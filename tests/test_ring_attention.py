"""Ring attention (sequence/context parallelism) on 8 virtual CPU devices.

Correctness bar: ring attention over a sharded sequence must match plain
XLA attention over the full sequence — forward AND gradients — because it
computes the exact same math, just blockwise around the ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.ops.attention import xla_attention
from nanosandbox_tpu.ops.ring_attention import ring_attention_sharded
from nanosandbox_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                           set_current_mesh)


def _qkv(B=2, H=4, T=64, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_xla_forward(sp):
    mesh = make_mesh(mesh_dp=1, mesh_sp=sp, devices=jax.devices()[:sp])
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_matches_xla_gradients():
    mesh = make_mesh(mesh_dp=2, mesh_sp=4)  # B=2 over dp=2, T over sp=4
    q, k, v = _qkv()

    def loss_ring(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh=mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_seq_axis_one_degenerates():
    mesh = make_mesh(mesh_dp=1, devices=jax.devices()[:1])  # seq axis size 1
    q, k, v = _qkv(T=32)
    ref = xla_attention(q, k, v, causal=True)
    out = ring_attention_sharded(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_rejects_indivisible_seq():
    mesh = make_mesh(mesh_dp=2, mesh_sp=4)
    q, k, v = _qkv(T=30)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention_sharded(q, k, v, mesh=mesh)


def test_ring_end_to_end_training(tiny_cfg):
    """Tiny GPT trains under mesh_sp=4 with ring attention; loss falls and
    the first-step loss matches the non-sequence-parallel run (same data)."""
    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(batch_size=8, mesh_dp=2, mesh_sp=4,
                           attention_impl="ring")
    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    losses = []
    rng = jax.random.key(0)
    for _ in range(8):
        xb, yb = next(loader)
        state, m = train_step(state, trainer.to_global(xb),
                              trainer.to_global(yb), rng)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    # parity with a plain-DP run on identical data
    cfg2 = tiny_cfg.replace(batch_size=8, mesh_dp=8)
    t2 = Trainer(cfg2)
    s2 = t2.init_state()
    step2, _ = t2.compiled_steps()
    loader2 = t2.make_loader("train", prefetch=False)
    xb, yb = next(loader2)
    _, m2 = step2(s2, t2.to_global(xb), t2.to_global(yb), jax.random.key(0))
    assert float(m2["loss"]) == pytest.approx(losses[0], rel=1e-4)


def test_ring_trainer_with_dp_and_coexisting_trainer(tiny_cfg):
    """Regressions: (a) Trainer init must work for ring configs whose
    data*fsdp shards exceed the old fixed dummy batch of 2; (b) a second
    Trainer must not silently steal the ring Trainer's mesh (the model
    binds its mesh explicitly)."""
    import jax

    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(batch_size=8, mesh_dp=4, mesh_sp=2,
                           attention_impl="ring")
    trainer = Trainer(cfg)
    state = trainer.init_state()  # dummy init batch respects the shardings

    # Constructing another trainer overwrites the *global* mesh...
    other = Trainer(tiny_cfg.replace(batch_size=8, mesh_dp=8))
    assert other.mesh is not trainer.mesh

    # ...but the ring trainer still traces with ITS OWN mesh afterwards.
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    xb, yb = next(loader)
    _, m = train_step(state, trainer.to_global(xb), trainer.to_global(yb),
                      jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_trainer_validates_ring_config(tiny_cfg):
    from nanosandbox_tpu.train import Trainer

    with pytest.raises(ValueError, match="requires attention_impl='ring'"):
        Trainer(tiny_cfg.replace(mesh_dp=4, mesh_sp=2))
    with pytest.raises(ValueError, match="block_size"):
        Trainer(tiny_cfg.replace(mesh_dp=1, mesh_sp=8, block_size=60,
                                 attention_impl="ring"))
    # dropout + ring is SUPPORTED as of round 5 (global-position hash
    # masks); construction must succeed.
    Trainer(tiny_cfg.replace(mesh_dp=4, mesh_sp=2, dropout=0.1,
                             attention_impl="ring"))


def teardown_module():
    set_current_mesh(None)


# -- zigzag layout (VERDICT.md round-1 stretch #10) -----------------------

def test_zigzag_permutation_inverse():
    from nanosandbox_tpu.ops.ring_attention import zigzag_permutation

    idx, inv = zigzag_permutation(64, 4)
    x = np.arange(64)
    assert (x[idx][inv] == x).all()
    # device 0's shard = first early + last late half-chunk
    h = 64 // 8
    assert (idx[:h] == np.arange(0, h)).all()
    assert (idx[h:2 * h] == np.arange(64 - h, 64)).all()


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("layout", ["zigzag", "contiguous"])
def test_ring_layouts_match_xla_forward(sp, layout):
    mesh = make_mesh(mesh_dp=1, mesh_sp=sp, devices=jax.devices()[:sp])
    q, k, v = _qkv(seed=3)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, layout=layout))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_matches_xla_gradients():
    mesh = make_mesh(mesh_dp=2, mesh_sp=4)
    q, k, v = _qkv(seed=4)

    def loss_zig(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh=mesh,
                                       layout="zigzag") ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g_zig = jax.jit(jax.grad(loss_zig, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_zig, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_zigzag_falls_back_when_T_not_2cp_divisible():
    """T=40 with sp=4: divisible by cp but not 2*cp — zigzag silently
    uses the (exact) contiguous path."""
    mesh = make_mesh(mesh_dp=2, mesh_sp=4)
    q, k, v = _qkv(T=40, seed=5)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, layout="zigzag"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_end_to_end_training_matches_dp(tiny_cfg):
    """Tiny GPT under mesh_sp=4 + zigzag ring: first-step loss matches a
    plain-DP run on identical data (layout is invisible to the math)."""
    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(batch_size=8, mesh_dp=2, mesh_sp=4,
                           attention_impl="ring", ring_layout="zigzag")
    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    xb, yb = next(loader)
    _, m = train_step(state, trainer.to_global(xb), trainer.to_global(yb),
                      jax.random.key(0))

    cfg2 = tiny_cfg.replace(batch_size=8, mesh_dp=8)
    t2 = Trainer(cfg2)
    s2 = t2.init_state()
    step2, _ = t2.compiled_steps()
    loader2 = t2.make_loader("train", prefetch=False)
    xb2, yb2 = next(loader2)
    _, m2 = step2(s2, t2.to_global(xb2), t2.to_global(yb2), jax.random.key(0))
    assert float(m2["loss"]) == pytest.approx(float(m["loss"]), rel=1e-4)


# -- Pallas flash blocks inside the ring (round-2 VERDICT weak #1) --------

@pytest.mark.parametrize("sp,T,layout", [
    (2, 512, "zigzag"),      # half-chunk h = 128
    (4, 1024, "zigzag"),     # h = 128 across 4 devices
    (2, 256, "contiguous"),  # full chunk Tc = 128
])
def test_ring_flash_blocks_match_xla(sp, T, layout):
    """Ring with the real flash kernel per block (interpret mode on CPU)
    must equal plain full-sequence attention, like the einsum body does."""
    mesh = make_mesh(mesh_dp=1, mesh_sp=sp, devices=jax.devices()[:sp])
    q, k, v = _qkv(B=1, H=2, T=T, D=16, seed=7)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, layout=layout,
        block_impl="pallas_interpret"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_flash_blocks_gradients():
    mesh = make_mesh(mesh_dp=1, mesh_sp=2, devices=jax.devices()[:2])
    q, k, v = _qkv(B=1, H=2, T=512, D=16, seed=8)

    def loss_ring(q, k, v):
        return (ring_attention_sharded(
            q, k, v, mesh=mesh, layout="zigzag",
            block_impl="pallas_interpret") ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_block_impl_auto_resolution():
    """'auto' resolves per backend: the einsum body wherever the Mosaic
    kernel can't compile (CPU), the flash body where it can (TPU);
    unaligned chunks force einsum regardless of backend."""
    from nanosandbox_tpu.ops.attention import pallas_compile_probe
    from nanosandbox_tpu.ops.ring_attention import _resolve_block_impl

    assert _resolve_block_impl("xla", 128) == "xla"
    with pytest.raises(ValueError, match="ring_block_impl"):
        _resolve_block_impl("pallas", 77)  # pinned + unaligned: loud error
    assert _resolve_block_impl("auto", 64) == "xla"       # unaligned
    expected = "pallas" if pallas_compile_probe() else "xla"
    assert _resolve_block_impl("auto", 128) == expected


def test_model_ring_attention_dropout_trains_directly():
    """Round 5: ring attention + dropout is supported (global-position
    hash masks). The direct model path must trace AND regularize — the
    non-deterministic forward must differ from the deterministic one."""
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT

    mesh = make_mesh(mesh_dp=1, mesh_sp=2, devices=jax.devices()[:2])
    cfg = GPTConfig(n_layer=1, n_head=2, n_embd=16, block_size=16,
                    vocab_size=32, dropout=0.1, attention_impl="ring",
                    compute_dtype="float32")
    model = GPT(cfg, mesh=mesh)
    x = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), x, deterministic=True)
    det = model.apply(variables, x, deterministic=True)
    reg = model.apply(variables, x, deterministic=False,
                      rngs={"dropout": jax.random.key(1)})
    assert np.isfinite(np.asarray(reg)).all()
    assert not np.allclose(np.asarray(det), np.asarray(reg))


def test_pinned_pallas_unaligned_chunk_raises_ring_level_error():
    """A pinned ring_block_impl='pallas' with a non-128-multiple per-device
    chunk must fail with an error naming ring_block_impl and the chunk
    (ADVICE r3) — not a block-divisibility ValueError deep in _pad_qkv."""
    mesh = make_mesh(mesh_dp=1, mesh_sp=2, devices=jax.devices()[:2])
    q, k, v = _qkv(T=64)  # 32 per device: unaligned
    with pytest.raises(ValueError, match="ring_block_impl.*multiple of 128"):
        jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh=mesh, block_impl="pallas"))(q, k, v)


# -- dropout in the ring (round-5 VERDICT next #5) -------------------------
#
# The keep-mask is a hash of GLOBAL (q_pos, k_pos), so a masked-XLA dense
# reference built from the same hash must match the ring output exactly —
# per layout (contiguous + zigzag) and per block impl (xla +
# pallas_interpret), at sp=2.


def _masked_dense_reference(q, k, v, seed, rate, hash_seq_len):
    """Full attention with the hash keep-mask applied to normalized
    probabilities — the ground truth every ring variant must reproduce."""
    from nanosandbox_tpu.ops.attention import hash_dropout_keep_mask

    B, H, T, D = q.shape
    sm_scale = D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale,
                   k.astype(jnp.float32))
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    keep = hash_dropout_keep_mask(seed, B, H, T, T,
                                  hash_seq_len=hash_seq_len, rate=rate)
    p = jnp.where(keep, p / (1.0 - rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
@pytest.mark.parametrize("block_impl", ["xla", "pallas_interpret"])
def test_ring_dropout_matches_masked_reference(layout, block_impl):
    sp = 2
    mesh = make_mesh(mesh_dp=1, mesh_sp=sp, devices=jax.devices()[:sp])
    # pallas blocks need 128-aligned per-call chunks; zigzag halves the
    # chunk (T / (2*sp)), so T=512 keeps both layouts aligned at sp=2.
    T = 512 if block_impl == "pallas_interpret" else 64
    q, k, v = _qkv(T=T)
    seed = jnp.asarray([1234], jnp.uint32)
    rate = 0.2
    ref = _masked_dense_reference(q, k, v, seed, rate, hash_seq_len=T)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, layout=layout, block_impl=block_impl,
        dropout_rate=rate, dropout_seed=seed))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5, rtol=5e-5)


def test_ring_dropout_gradients_flow_and_seed_matters():
    mesh = make_mesh(mesh_dp=2, mesh_sp=4)
    q, k, v = _qkv()
    s1 = jnp.asarray([7], jnp.uint32)
    s2 = jnp.asarray([8], jnp.uint32)

    def loss(q, k, v, seed):
        return (ring_attention_sharded(
            q, k, v, mesh=mesh, dropout_rate=0.2, dropout_seed=seed,
        ) ** 2).sum()

    val1, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        q, k, v, s1)
    val1b = jax.jit(loss)(q, k, v, s1)
    val2 = jax.jit(loss)(q, k, v, s2)
    assert np.isfinite(float(val1))
    assert float(val1) == pytest.approx(float(val1b))  # deterministic
    assert float(val1) != pytest.approx(float(val2))   # seed matters
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0


def test_ring_dropout_batch_shards_draw_distinct_masks():
    """With batch sharded over dp, each global row must draw its own
    dropout stream — two identical batch rows on different devices must
    NOT produce identical outputs."""
    mesh = make_mesh(mesh_dp=2, mesh_sp=2, devices=jax.devices()[:4])
    q, k, v = _qkv(B=2)
    # Duplicate row 0 into row 1: without per-shard b_off the two rows
    # (placed on different dp shards) would get identical masks.
    q = q.at[1].set(q[0]); k = k.at[1].set(k[0]); v = v.at[1].set(v[0])
    seed = jnp.asarray([42], jnp.uint32)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, dropout_rate=0.3, dropout_seed=seed))(q, k, v)
    assert not np.allclose(np.asarray(out[0]), np.asarray(out[1]))
