"""Tier-0 training smoke tests (the reference's CPU smoke, ipynb:69-80):
end-to-end loop on JAX-CPU, loss decreases, checkpoint/resume works."""

import numpy as np

from nanosandbox_tpu.train import Trainer, make_lr_schedule


def test_train_loss_decreases(tiny_cfg):
    trainer = Trainer(tiny_cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    import jax

    rng = jax.random.key(0)
    losses = []
    for i in range(20):
        xb, yb = next(loader)
        state, m = train_step(state, trainer.to_global(xb),
                              trainer.to_global(yb), rng)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert int(state["step"]) == 20


def test_run_end_to_end_and_resume(tiny_cfg):
    cfg = tiny_cfg.replace(max_iters=10, eval_interval=5, eval_iters=2,
                           always_save_checkpoint=True)
    result = Trainer(cfg).run()
    assert result["iter_num"] == 10
    assert np.isfinite(result["final_val_loss"])

    # Resume: picks up at iter 10 and runs to 15.
    cfg2 = cfg.replace(max_iters=15, init_from="resume")
    result2 = Trainer(cfg2).run()
    assert result2["iter_num"] == 15


def test_init_from_auto(tiny_cfg, tmp_path):
    """'auto' = scratch on first boot, resume after a crash/restart — the
    mode the k8s StatefulSet passes (k8s/statefulset/40-train-multipod.yaml)
    so restarted pods continue instead of silently starting over."""
    cfg = tiny_cfg.replace(out_dir=str(tmp_path / "auto_out"), max_iters=6,
                           eval_interval=3, eval_iters=1, init_from="auto")
    result = Trainer(cfg).run()
    assert result["iter_num"] == 6  # no checkpoint existed -> scratch

    cfg2 = cfg.replace(max_iters=12)
    result2 = Trainer(cfg2).run()
    assert result2["iter_num"] == 12  # checkpoint existed -> resumed at 6


def test_grad_accumulation_equivalence(tiny_cfg):
    """accum=2 with the same total tokens produces a finite, close loss."""
    cfg = tiny_cfg.replace(batch_size=8, gradient_accumulation_steps=2)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    import jax

    xb, yb = next(loader)
    state, m = train_step(state, trainer.to_global(xb), trainer.to_global(yb),
                          jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_lr_schedule_shape():
    from nanosandbox_tpu.config import TrainConfig

    cfg = TrainConfig(learning_rate=1e-3, min_lr=1e-4, warmup_iters=10,
                      lr_decay_iters=100, max_iters=100)
    sched = make_lr_schedule(cfg)
    assert float(sched(0)) < float(sched(10))
    assert abs(float(sched(10)) - 1e-3) < 1e-9
    assert float(sched(100)) <= float(sched(50))
    assert abs(float(sched(100)) - 1e-4) < 1e-6


def test_eval_only(tiny_cfg):
    cfg = tiny_cfg.replace(eval_only=True, eval_interval=1, max_iters=5)
    result = Trainer(cfg).run()
    assert result["iter_num"] == 0


def test_eval_batch_divisibility_validated(tiny_cfg, monkeypatch):
    """batch 8 / accum 2 / 16 processes passes the sequences_per_iter
    check (16 % 16 == 0) and the mesh check (8 % 8 == 0) but estimate_loss
    would build a 0-row eval batch and crash mid-run; the Trainer must
    reject it at construction instead (round-2 VERDICT weak #5)."""
    import pytest

    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 16)
    cfg = tiny_cfg.replace(batch_size=8, gradient_accumulation_steps=2)
    with pytest.raises(ValueError, match="num_processes"):
        Trainer(cfg)


def test_memory_report(char_dataset, tmp_path):
    """--memory_report: XLA's compile-time breakdown is exposed with sane
    invariants (state >= params; total covers the parts)."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    cfg = TrainConfig(
        out_dir=str(tmp_path / "o"), data_dir=char_dataset,
        dataset="shakespeare_char", n_layer=2, n_head=2, n_embd=64,
        block_size=64, batch_size=8, max_iters=1, eval_interval=0,
        warmup_iters=1, lr_decay_iters=1, compute_dtype="float32",
        tensorboard=False, device="cpu")
    trainer = Trainer(cfg)
    mem = trainer.memory_report()
    if not mem:
        return  # backend without memory analysis
    assert mem["params_bytes"] > 0
    # params (f32) + Adam m/v (2x) + batch live in the argument set.
    assert mem["state_bytes"] >= 3 * mem["params_bytes"]
    assert mem["total_bytes"] >= mem["state_bytes"] + mem["temp_bytes"]


def test_rng_impl_rbg_trains(char_dataset, tmp_path):
    """rng_impl='rbg' (the TPU-fast dropout-mask stream) composes with the
    full train loop + dropout; loss falls as with the default impl.

    Runs in a FRESH single-device subprocess: in-process it would share
    this session's 8-virtual-device backend, and XLA:CPU's collective
    rendezvous has a 40s watchdog that flakes late in a 200-test process
    (observed as a hard abort when this exact e2e ran as the last test
    of the full suite; isolated it reproduces never)."""
    import os
    import subprocess
    import sys

    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    # In-process: just the impl plumbing (no collectives involved).
    cfg = TrainConfig(rng_impl="rbg", device="cpu")
    import jax
    trainer_key = Trainer.train_rng(
        type("T", (), {"cfg": cfg})(), 0)  # unbound: no mesh construction
    assert str(jax.random.key_impl(trainer_key)) == "rbg"

    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
from nanosandbox_tpu.config import TrainConfig
from nanosandbox_tpu.train import Trainer
cfg = TrainConfig(
    out_dir={str(tmp_path / 'o')!r}, data_dir={char_dataset!r},
    dataset="shakespeare_char", n_layer=2, n_head=2, n_embd=64,
    block_size=64, batch_size=8, max_iters=8, eval_interval=0,
    eval_iters=2, log_interval=1, warmup_iters=1, lr_decay_iters=8,
    dropout=0.2, rng_impl="rbg", compute_dtype="float32",
    tensorboard=False, device="cpu")
result = Trainer(cfg).run()
assert result["final_loss"] < 3.5, result
print("RBG_OK", result["final_loss"])
"""
    env = os.environ.copy()
    env["XLA_FLAGS"] = ""  # single CPU device
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=root,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0 and "RBG_OK" in proc.stdout, (
        proc.stdout + proc.stderr)
