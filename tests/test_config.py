"""Configurator tests: the config-file + --key=value contract (ipynb:71)."""

import pytest

from nanosandbox_tpu.config import GPTConfig, TrainConfig, load_config


def test_defaults():
    cfg = load_config([])
    assert cfg.n_layer == 12 and cfg.block_size == 1024
    assert cfg.lr_decay_iters == cfg.max_iters or cfg.lr_decay_iters > 0


def test_cli_overrides():
    cfg = load_config(["--n_layer=3", "--learning_rate=1e-3",
                       "--compile=False", "--dataset=openwebtext"])
    assert cfg.n_layer == 3
    assert cfg.learning_rate == pytest.approx(1e-3)
    assert cfg.compile is False
    assert cfg.dataset == "openwebtext"


def test_config_file_then_cli(tmp_path):
    f = tmp_path / "cfg.py"
    f.write_text("n_layer = 4\nn_head = 4\nbatch_size = 32\n")
    cfg = load_config([str(f), "--batch_size=8"])
    assert cfg.n_layer == 4 and cfg.n_head == 4
    assert cfg.batch_size == 8  # CLI wins over file


def test_exercised_keys_all_exist():
    # The 14 keys the reference exercises (ipynb:71-78, 108-115) must all be
    # valid flags; --device/--compile map to JAX platform/jit.
    keys = ["out_dir", "eval_interval", "log_interval", "block_size",
            "batch_size", "n_layer", "n_head", "n_embd", "max_iters",
            "lr_decay_iters", "dropout", "device", "compile", "dataset"]
    argv = [f"--{k}=1" if k not in (
        "out_dir", "device", "compile", "dataset", "dropout") else
        {"out_dir": "--out_dir=o", "device": "--device=cpu",
         "compile": "--compile=True", "dataset": "--dataset=d",
         "dropout": "--dropout=0.5"}[k] for k in keys]
    cfg = load_config(argv)
    assert cfg.block_size == 1

def test_unknown_key_raises():
    with pytest.raises(ValueError, match="unknown config key"):
        load_config(["--nope=1"])


def test_bool_strictness():
    with pytest.raises(ValueError):
        load_config(["--compile=1"])


def test_tokens_per_iter():
    cfg = load_config(["--batch_size=4", "--block_size=8",
                       "--gradient_accumulation_steps=2"])
    assert cfg.tokens_per_iter == 2 * 4 * 8


def test_gpt_config_from_train_config():
    cfg = TrainConfig(n_layer=3, n_head=3, n_embd=48)
    g = GPTConfig.from_train_config(cfg, vocab_size=65)
    assert (g.n_layer, g.vocab_size) == (3, 65)


def test_every_shipped_config_parses():
    """load_config on every configs/*.py: every shipped config must
    exec cleanly under the strict file-binding check (a typo'd key in a
    config file raises at load, not silently trains with defaults)."""
    import glob
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "configs", "*.py")))
    assert len(paths) >= 9
    for p in paths:
        cfg = load_config([p])
        assert cfg.n_layer >= 1, p
        assert cfg.batch_size >= 1, p


def test_config_file_typo_key_raises(tmp_path):
    """File bindings get the same strictness as --key=value flags: a
    typo'd key must raise, not silently fall back to the default."""
    bad = tmp_path / "bad.py"
    bad.write_text("learning_rte = 3e-5\n")
    with pytest.raises(ValueError, match="learning_rte"):
        load_config([str(bad)])
    ok = tmp_path / "ok.py"
    ok.write_text("import math\n_helper = 2\nlearning_rate = math.e * 1e-4\n")
    cfg = load_config([str(ok)])
    assert abs(cfg.learning_rate - 2.718e-4) < 1e-6


def test_resolve_loss_chunk_size_policy():
    """Pins the -1 (auto) resolution (r3 VERDICT weak #2): full logits
    whenever the per-device (B, T, V) f32 tensor fits the HBM budget,
    chunk 512 when it doesn't or under sequence parallelism; explicit
    values always pass through."""
    from nanosandbox_tpu.config import resolve_loss_chunk_size as r

    assert r(-1, 16, 1024, 50304) == 0       # 3.3 GB fits -> full logits
    assert r(-1, 32, 1024, 50304) == 512     # 6.6 GB doesn't
    assert r(-1, 64, 1024, 50304) == 512
    assert r(-1, 1, 8192, 50304) == 0        # long ctx, tiny batch fits
    assert r(-1, 1, 8192, 50304, seq_shards=2) == 512  # ring: always chunk
    assert r(128, 64, 1024, 50304) == 128    # explicit passthrough
    assert r(0, 64, 1024, 50304) == 0        # explicit full logits
    # TrainConfig defaults to auto
    from nanosandbox_tpu.config import TrainConfig

    assert TrainConfig().loss_chunk_size == -1


def test_trainer_resolves_auto_loss_chunk(tmp_path):
    """End-to-end: a default (auto) config resolves to full logits at the
    CPU smoke shape and the trainer records the resolved value."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.data.prepare import prepare_char_dataset
    from nanosandbox_tpu.train import Trainer

    prepare_char_dataset(str(tmp_path / "shakespeare_char"),
                         url="http://invalid.localhost/offline")
    cfg = TrainConfig(device="cpu", data_dir=str(tmp_path),
                      out_dir=str(tmp_path / "out"), n_layer=1, n_head=1,
                      n_embd=32, block_size=32, batch_size=8, max_iters=1)
    tr = Trainer(cfg)
    assert tr.loss_chunk_size == 0  # tiny shape -> full logits
