"""Test harness: JAX-CPU with 8 virtual devices.

The reference's answer to "test multi-node without a cluster" is to simulate
N processes on one machine (README.md:5, ipynb:15 — torchrun
--nproc_per_node on a single VM). The JAX equivalent (SURVEY.md §4 Tier 1)
is the host-platform device-count spoof: 8 virtual CPU devices, so every
mesh/sharding/collective path compiles and executes in CI with no TPU.
Must run before jax initializes its backend, hence top of conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Site hooks (e.g. an out-of-process TPU plugin) may override the platform
# selection after env vars are read; the config API wins over both.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def char_dataset(tmp_path_factory):
    """A prepared synthetic char-level dataset (offline Tier-0 fixture)."""
    from nanosandbox_tpu.data.prepare import prepare_char_dataset

    root = tmp_path_factory.mktemp("data")
    out = root / "shakespeare_char"
    stats = prepare_char_dataset(str(out), allow_synthetic=True,
                                 url="http://invalid.localhost/nope")
    assert stats["train_tokens"] > 1000
    return str(root)


@pytest.fixture()
def tiny_cfg(char_dataset, tmp_path):
    from nanosandbox_tpu.config import TrainConfig

    return TrainConfig(
        out_dir=str(tmp_path / "out"),
        data_dir=char_dataset,
        dataset="shakespeare_char",
        n_layer=2, n_head=2, n_embd=64, block_size=64,
        batch_size=8, max_iters=20, lr_decay_iters=20,
        eval_interval=0, eval_iters=2, log_interval=5,
        warmup_iters=2, learning_rate=1e-3, min_lr=1e-4,
        dropout=0.0, compute_dtype="float32", device="auto",
        tensorboard=False, seed=0)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
