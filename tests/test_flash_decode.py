"""Flash-decode kernel + int8 KV cache tests (ISSUE 8).

The contract under test:
  * the Pallas kernel (interpret mode on CPU — the exact math CI ships)
    matches masked reference attention under FUZZED per-row frontiers,
    fp and int8 alike;
  * per-block int8 quantization round-trips within the analytic bound
    (|err| <= max|row| / 254 per element);
  * an int8-KV engine stays greedy-token-faithful to the fp engine on
    mixed batches (bounded logit drift -> bounded token divergence),
    with the SAME compile budget (the kernel must not widen the set);
  * speculative-decode acceptance does not regress under int8 KV;
  * the scalar-index (prefill) attention path is BOUNDED to the known
    frontier — no dot in the jaxpr touches the full max_len buffer;
  * the resolved decode impl + kv mode are exported (stats + /metrics
    gauges) and the auto->xla degrade on TPU warns once.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import (GPT, init_cache, normalize_kv_dtype,
                                        scatter_cache_rows)
from nanosandbox_tpu.ops import flash_decode as fd
from nanosandbox_tpu.serve import Engine, NGramDrafter


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


# ------------------------------------------------------------------ kernel

@pytest.mark.parametrize("B,H,L,D,block_k", [
    (3, 2, 100, 16, 32),    # padded D, padded L, multi-block walk
    (2, 2, 64, 64, 64),     # the verified-unpadded D=64, single block
    (1, 3, 257, 32, 128),   # L one past a block boundary
])
def test_flash_decode_frontier_fuzz_fp(B, H, L, D, block_k):
    """Random per-row frontiers vs reference attention — the per-row
    mask is the kernel's core claim (never attend past a row's own
    frontier, stale tail contributes nothing)."""
    rng = np.random.default_rng(hash((B, H, L, D)) % 2**32)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    # Poison the tail of every row past its frontier with huge values:
    # a masking bug becomes a gross error, not a rounding blip.
    k = rng.normal(size=(B, H, L, D)).astype(np.float32)
    v = rng.normal(size=(B, H, L, D)).astype(np.float32)
    lengths = rng.integers(1, L + 1, size=B).astype(np.int32)
    for b in range(B):
        k[b, :, lengths[b]:, :] = 1e4
        v[b, :, lengths[b]:, :] = -1e4
    k, v, lengths = jnp.asarray(k), jnp.asarray(v), jnp.asarray(lengths)
    ref = fd.xla_decode_attention(q, k, v, lengths)
    out = fd.flash_decode(q, k, v, lengths, block_k=block_k, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_flash_decode_int8_matches_xla_int8_exactly():
    """Kernel fused-dequant (scales folded into scores/probs) vs the
    XLA int8 reference: the two impls share one numeric contract, so
    they agree to float rounding — NOT just to quantization tolerance."""
    rng = np.random.default_rng(7)
    B, H, L, D = 4, 2, 96, 16
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, L + 1, size=B), jnp.int32)
    kq, ks = fd.quantize_kv_rows(k)
    vq, vs = fd.quantize_kv_rows(v)
    ref = fd.xla_decode_attention(q, kq, vq, lengths, k_scale=ks, v_scale=vs)
    out = fd.flash_decode(q, kq, vq, lengths, k_scale=ks, v_scale=vs,
                          block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=1e-5)
    # ...and both sit near the fp answer (quantization-bounded drift).
    fp = fd.xla_decode_attention(q, k, v, lengths)
    assert float(jnp.max(jnp.abs(out - fp))) < 0.05


def test_flash_decode_fp32_pool_keeps_precision_under_bf16_query():
    """A full-precision pool must not be silently truncated to the
    query's dtype on the flash path: with a bf16 q and an fp32 pool the
    kernel dots in fp32 (the wider type), matching the XLA reference to
    accumulation-order rounding rather than bf16 rounding."""
    rng = np.random.default_rng(13)
    B, H, L, D = 2, 2, 64, 16
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
    lengths = jnp.asarray([17, 64], jnp.int32)
    # f32 end-to-end oracle; the kernel's only rounding should be the
    # final bf16 output write (~1.6e-3 here). A kernel that truncated
    # the pool to bf16 before the dots measures ~5e-3 on this seed, so
    # the 2.5e-3 bound discriminates the regression.
    ref32 = fd.xla_decode_attention(q.astype(jnp.float32), k, v, lengths)
    out = fd.flash_decode(q, k, v, lengths, block_k=32, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref32), atol=2.5e-3)


def test_flash_decode_validates_scale_args():
    q = jnp.zeros((1, 1, 16))
    k = jnp.zeros((1, 1, 32, 16))
    s = jnp.ones((1, 1, 32))
    with pytest.raises(ValueError, match="together"):
        fd.flash_decode(q, k, k, jnp.ones(1, jnp.int32), k_scale=s)
    with pytest.raises(ValueError, match="non-quantized"):
        fd.flash_decode(q, k, k, jnp.ones(1, jnp.int32),
                        k_scale=s, v_scale=s)
    with pytest.raises(ValueError, match="unknown decode impl"):
        fd.resolve_decode_impl("mosaic")


# ------------------------------------------------------------ quantization

def test_quantize_roundtrip_error_bound():
    """Per-block (one scale per <=128-lane K/V row) symmetric int8:
    every element round-trips within scale/2 = max|row|/254, the bound
    the playbook's kv_dtype table quotes."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(3, 2, 40, 16)) * 5.0, jnp.float32)
    q, s = fd.quantize_kv_rows(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    deq = q.astype(jnp.float32) * s[..., None]
    bound = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 254.0
    assert bool(jnp.all(jnp.abs(deq - x) <= bound + 1e-7))
    # All-zero rows (parked slots, unwritten tail) are exact.
    zq, zs = fd.quantize_kv_rows(jnp.zeros((2, 4)))
    assert bool(jnp.all(zq == 0))


def test_init_cache_kv_dtype_modes():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    compute_dtype="float32")
    c8 = init_cache(cfg, 3, 16, kv_dtype="int8")
    assert len(c8) == 2 and len(c8[0]) == 4
    ck, cv, cks, cvs = c8[0]
    assert ck.dtype == cv.dtype == jnp.int8
    assert cks.shape == cvs.shape == (3, 2, 16) and cks.dtype == jnp.float32
    cbf = init_cache(cfg, 3, 16, kv_dtype="bf16")
    assert cbf[0][0].dtype == jnp.bfloat16 and len(cbf[0]) == 2
    cfp = init_cache(cfg, 3, 16, kv_dtype="fp32")
    assert cfp[0][0].dtype == jnp.float32
    assert normalize_kv_dtype("bfloat16") == "bf16"
    assert normalize_kv_dtype(None) is None
    with pytest.raises(ValueError, match="kv_dtype"):
        init_cache(cfg, 3, 16, kv_dtype="fp8")


def test_scatter_cache_rows_quantizes_into_int8_pool():
    """Prefill waves land already-quantized: fp rows scattered into an
    int8 pool match direct quantization, ladder-padding rows drop, and
    int8 rows into an fp pool refuse loudly."""
    cfg = GPTConfig(n_layer=1, n_head=2, n_embd=32, block_size=64,
                    compute_dtype="float32")
    pool = init_cache(cfg, 4, 32, kv_dtype="int8")
    rng = np.random.default_rng(3)
    ck = jnp.asarray(rng.normal(size=(2, 2, 16, 16)), jnp.float32)
    cv = jnp.asarray(rng.normal(size=(2, 2, 16, 16)), jnp.float32)
    slots = jnp.asarray([2, 4], jnp.int32)   # slot 4 is the drop row
    out = scatter_cache_rows(pool, [(ck, cv)], slots)
    pk, pv, pks, pvs = out[0]
    kq, ks = fd.quantize_kv_rows(ck)
    np.testing.assert_array_equal(np.asarray(pk[2, :, :16]),
                                  np.asarray(kq[0]))
    np.testing.assert_array_equal(np.asarray(pks[2, :, :16]),
                                  np.asarray(ks[0]))
    assert int(jnp.sum(jnp.abs(pk[3]))) == 0       # drop row untouched
    with pytest.raises(ValueError, match="full-precision pool"):
        scatter_cache_rows(init_cache(cfg, 4, 32),
                           [(kq, kq, ks, ks)], slots)


# ------------------------------------------------------------------ engine

def _run_mixed(engine, n=12, seed=0, temperature=0.0):
    rng = np.random.default_rng(seed)
    rids = []
    for _ in range(n):
        L = int(rng.integers(1, 40))
        rids.append(engine.submit(rng.integers(0, 50, L).tolist(),
                                  int(rng.integers(2, 12)),
                                  temperature=temperature, seed=7))
    res = {r.rid: r for r in engine.drain()}
    return [res[r].tokens for r in rids]


def test_engine_greedy_parity_fp32_vs_int8_mixed_batch(served_model):
    """The ISSUE-8 parity bar: int8 KV's logit drift is quantization-
    bounded, so greedy tokens on a mixed continuous batch stay near-
    identical to the fp engine — and the flash kernel (interpret) path
    emits EXACTLY what the int8 xla path emits, since they share one
    numeric contract."""
    cfg, model, params = served_model
    e_fp = Engine(model, params, num_slots=4, max_len=64)
    e_8 = Engine(model, params, num_slots=4, max_len=64, kv_dtype="int8")
    e_8k = Engine(model, params, num_slots=4, max_len=64, kv_dtype="int8",
                  decode_impl="pallas_interpret")
    a, b, c = _run_mixed(e_fp), _run_mixed(e_8), _run_mixed(e_8k)
    total = sum(len(t) for t in a)
    match_q = sum(sum(x == y for x, y in zip(p, q)) for p, q in zip(a, b))
    assert match_q / total >= 0.95, (match_q, total)
    assert b == c  # kernel vs xla int8: same tokens, not just close


def test_engine_int8_budget_not_widened(served_model):
    """The kernel must not widen the compile set: same max_programs()
    dict as the fp engine, trace counts within it after a full mixed
    drain, and the tracecheck postcondition holds."""
    cfg, model, params = served_model
    e_fp = Engine(model, params, num_slots=4, max_len=64)
    e_8 = Engine(model, params, num_slots=4, max_len=64, kv_dtype="int8",
                 decode_impl="pallas_interpret")
    assert e_8.max_programs() == e_fp.max_programs()
    _run_mixed(e_8)
    e_8.tracecheck.assert_within_budget()
    assert e_8.trace_counts["decode"] == 1


def test_engine_sampled_path_runs_under_int8(served_model):
    """Temperature > 0 rides the same per-row keyed streams; int8 only
    perturbs logits, so the sampled path must run (and complete) with
    the quantized pool + flash kernel."""
    cfg, model, params = served_model
    e = Engine(model, params, num_slots=4, max_len=64, kv_dtype="int8",
               decode_impl="pallas_interpret")
    toks = _run_mixed(e, n=6, seed=5, temperature=0.8)
    assert all(len(t) >= 2 for t in toks)


def test_spec_acceptance_non_regression_under_int8(served_model):
    """Spec verify reads the same quantized pool; on the repetitive
    workload (the drafter's favorable regime) acceptance under int8
    must stay within a point of fp32 — the ISSUE-8 'within 1%' bar,
    deterministic here (fixed seeds, greedy)."""
    cfg, model, params = served_model

    def run_rep(engine, n=10, seed=1):
        rng = np.random.default_rng(seed)
        for _ in range(n):
            motif = rng.integers(0, 50, 3)
            L = int(rng.integers(6, 40))
            engine.submit(np.tile(motif, L // 3 + 1)[:L].tolist(), 10)
        engine.drain()
        return engine.stats()["spec_acceptance_rate"]

    acc_fp = run_rep(Engine(model, params, num_slots=4, max_len=64,
                            spec=NGramDrafter(k=4)))
    acc_8 = run_rep(Engine(model, params, num_slots=4, max_len=64,
                           spec=NGramDrafter(k=4), kv_dtype="int8"))
    assert acc_fp is not None and acc_fp > 0.5   # the regime is favorable
    assert acc_8 >= acc_fp - 0.01, (acc_8, acc_fp)


def test_spec_greedy_parity_under_int8(served_model):
    """Verify and plain decode read one pool mode: spec-on int8 output
    equals spec-off int8 output token-for-token under greedy decoding
    (the Leviathan exactness argument is dtype-independent)."""
    cfg, model, params = served_model
    e_plain = Engine(model, params, num_slots=4, max_len=64,
                     kv_dtype="int8")
    e_spec = Engine(model, params, num_slots=4, max_len=64,
                    kv_dtype="int8", spec=NGramDrafter(k=4))
    assert _run_mixed(e_plain, n=8, seed=2) == _run_mixed(e_spec, n=8,
                                                          seed=2)


# ------------------------------------------------- bounded scalar prefill

def test_scalar_prefill_attention_bounded_to_frontier(served_model):
    """Satellite: with a STATIC cache_index the masked path slices the
    buffer to the known frontier — pinned structurally (no dot_general
    in the jaxpr touches the full max_len buffer) and numerically
    (bit-identical logits to an exactly-sized cache)."""
    cfg, model, params = served_model
    T, max_len = 8, 64
    prompt = jnp.asarray(np.random.default_rng(0).integers(0, 50, (2, T)),
                         jnp.int32)

    def prefill(params, prompt):
        cache = init_cache(cfg, 2, max_len)
        return model.apply({"params": params}, prompt, deterministic=True,
                           cache=cache, cache_index=0)[0]

    jaxpr = jax.make_jaxpr(prefill)(params, prompt)
    dot_dims = {d for eqn in jaxpr.jaxpr.eqns
                if eqn.primitive.name == "dot_general"
                for v in eqn.outvars for d in v.aval.shape}
    # Distinctive sentinel: nothing else in this config is 64-sized, so
    # any 64 in a dot output means the attention read the whole buffer.
    assert max_len not in dot_dims, sorted(dot_dims)
    # FLOP pin: bounded span = T columns instead of max_len, i.e. the
    # score dots shrank by max_len/T = 8x on this shape.
    assert T in dot_dims

    tight = init_cache(cfg, 2, T)
    tight_logits = model.apply({"params": params}, prompt,
                               deterministic=True, cache=tight,
                               cache_index=0)[0]
    np.testing.assert_array_equal(np.asarray(prefill(params, prompt)),
                                  np.asarray(tight_logits))


# -------------------------------------------------------- impl resolution

def test_resolve_decode_impl_ladder(monkeypatch):
    assert fd.resolve_decode_impl("xla") == "xla"
    assert fd.resolve_decode_impl("pallas_interpret") == "pallas_interpret"
    # CPU: auto degrades to xla silently (no TPU to warn about).
    assert fd.resolve_decode_impl("auto") == "xla"
    # TPU whose probe fails: the degrade must warn_once.
    from nanosandbox_tpu.utils import metrics as um
    um.reset_for_tests()
    monkeypatch.setattr(fd, "_backend", lambda: "tpu")
    monkeypatch.setattr(fd, "decode_compile_probe", lambda: False)
    assert fd.resolve_decode_impl("auto") == "xla"
    assert "flash-decode-xla-fallback" in um._WARNED_ONCE
    um.reset_for_tests()


def test_model_drafter_follows_engine_decode_impl(served_model):
    """The engine's --decode_impl pin reaches the drafter's own model:
    a drafter built under an engine pinned to the interpret kernel (or
    away from a broken one) drafts through the same ladder rung."""
    from nanosandbox_tpu.serve import ModelDrafter

    cfg, model, params = served_model
    dcfg = GPTConfig(n_layer=1, n_head=2, n_embd=32, block_size=64,
                     vocab_size=50, dropout=0.0, compute_dtype="float32",
                     attention_impl="xla")
    dmodel = GPT(dcfg)
    dparams = dmodel.init(jax.random.key(1),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    drafter = ModelDrafter(dmodel, dparams, k=3)
    Engine(model, params, num_slots=2, max_len=32, prefill_buckets=(16, 32),
           spec=drafter, kv_dtype="int8", decode_impl="pallas_interpret")
    assert drafter.model.cfg.decode_impl == "pallas_interpret"


def test_engine_warns_on_pad_copy_pool_shape(served_model):
    """A pool shape the kernel must pad-copy every step (max_len off
    the 32 quantum) warns at construction instead of silently doubling
    the hot path's HBM traffic; 32-multiples stay quiet."""
    from nanosandbox_tpu.utils import metrics as um

    cfg, model, params = served_model
    assert fd.decode_pad_copies(100, 16) and not fd.decode_pad_copies(64, 64)
    um.reset_for_tests()
    Engine(model, params, num_slots=2, max_len=60,
           decode_impl="pallas_interpret")
    assert "flash-decode-pad-copy-60" in um._WARNED_ONCE
    # Zero-copy shape (32-multiple max_len AND head_dim 64): quiet.
    cfg64 = GPTConfig(n_layer=1, n_head=1, n_embd=64, block_size=64,
                      vocab_size=50, dropout=0.0, compute_dtype="float32",
                      attention_impl="xla")
    m64 = GPT(cfg64)
    p64 = m64.init(jax.random.key(2), jnp.zeros((1, 8), jnp.int32))["params"]
    um.reset_for_tests()
    Engine(m64, p64, num_slots=2, max_len=64,
           decode_impl="pallas_interpret")
    assert not any(k.startswith("flash-decode-pad-copy")
                   for k in um._WARNED_ONCE)
    um.reset_for_tests()


def test_engine_exports_impl_and_kv_mode(served_model):
    cfg, model, params = served_model
    e = Engine(model, params, num_slots=2, max_len=64, kv_dtype="int8",
               decode_impl="pallas_interpret")
    s = e.stats()
    assert s["kv_dtype"] == "int8"
    assert s["decode_attention_impl"] == "pallas_interpret"
    snap = e.metrics.snapshot()
    assert snap["serve_decode_attention_impl"]["series"][0]["labels"] == \
        {"impl": "pallas_interpret"}
    assert snap["serve_kv_dtype"]["series"][0]["labels"] == \
        {"kv_dtype": "int8"}


def test_bench_decode_int8_mode_emits_comparison():
    """bench.py --mode=decode --kv_dtype=int8 runs the baseline twin in
    the same interleaved rounds and records ratio + parity + bytes/token
    (the ISSUE-8 acceptance numbers live in this JSON)."""
    import bench

    out = bench.main(["--quick", "--mode=decode", "--kv_dtype=int8",
                      "--requests=4", "--max_new_tokens=4",
                      "--num_slots=2"])
    extra = out["extra"]
    assert extra["kv_dtype"] == "int8"
    assert extra["baseline_kv_dtype"] in ("fp32", "bf16")
    assert extra["int8_vs_fp32"] == extra["kv_vs_baseline"] > 0
    assert 0.9 <= extra["kv_greedy_parity"] <= 1.0
    assert (extra["estimated_hbm_bytes_per_token"]
            < extra["estimated_hbm_bytes_per_token_baseline"])
    assert extra["decode_attention_impl"] == "xla"  # auto on CPU
    assert extra["decode_impl_status"]["pallas_interpret"] == "ok"


# ------------------------------------------- paged prefill kernel + int4

def _paged_reference(q, kf, vf, tbl, start):
    """Masked reference over the gathered chains: (B, H, T, D) output
    for (B, H, T, D) queries at positions start[b] + t."""
    B, H, T, D = q.shape
    N, _, page, _ = kf.shape
    nb = tbl.shape[1]
    kk = kf[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    vv = vf[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    qpos = start[:, None] + jnp.arange(T)[None, :]
    mask = (jnp.arange(nb * page)[None, None, None, :]
            <= qpos[:, None, :, None])
    s = jnp.einsum("bhtd,bhsd->bhts", q, kk) / D ** 0.5
    s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, -1), vv)


@pytest.mark.parametrize("start", [[0, 0, 0], [0, 7, 20]])
def test_flash_prefill_paged_matches_reference_fp(start):
    """The T>1 paged kernel vs the gathered masked reference — cold
    prefill (start 0) and prefix-hit offsets alike, with the split
    masked/unmasked loop exercised (start spanning block interiors)."""
    rng = np.random.default_rng(0)
    B, H, T, D, N, page, nb = 3, 2, 8, 32, 16, 16, 4
    kf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    tbl = jnp.asarray(rng.permutation(N)[:B * nb].reshape(B, nb),
                      jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    st = jnp.asarray(start, jnp.int32)
    out = fd.flash_prefill_paged(q, kf, vf, tbl, st, interpret=True)
    ref = _paged_reference(q, kf, vf, tbl, st)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kvd", ["int8", "int4"])
def test_flash_prefill_paged_quantized_matches_reference(kvd):
    """Quantized pools through the prefill kernel: the fused scale fold
    equals dequantize-then-attend within float rounding."""
    rng = np.random.default_rng(1)
    B, H, T, D, N, page, nb = 2, 2, 8, 32, 12, 16, 3
    kf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    tbl = jnp.asarray(rng.permutation(N)[:B * nb].reshape(B, nb),
                      jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    st = jnp.asarray([0, 9], jnp.int32)
    qfn = (fd.quantize_kv_rows_int4 if kvd == "int4"
           else fd.quantize_kv_rows)
    kq, ks = qfn(kf)
    vq, vs = qfn(vf)
    out = fd.flash_prefill_paged(q, kq, vq, tbl, st, k_scale=ks,
                                 v_scale=vs, interpret=True)
    if kvd == "int4":
        kd = fd.unpack_int4(kq).astype(jnp.float32) * ks[..., None]
        vd = fd.unpack_int4(vq).astype(jnp.float32) * vs[..., None]
    else:
        kd = kq.astype(jnp.float32) * ks[..., None]
        vd = vq.astype(jnp.float32) * vs[..., None]
    ref = _paged_reference(q, kd, vd, tbl, st)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5, rtol=5e-5)


def test_flash_prefill_paged_sentinel_entries_harmless():
    """Table entries at the engine's unallocated sentinel (>= N) clamp
    in the index_map and never contribute — rows whose chains end
    early produce the same output as a table padded with real blocks
    the mask hides anyway."""
    rng = np.random.default_rng(2)
    B, H, T, D, N, page, nb = 2, 2, 4, 32, 8, 16, 4
    kf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    st = jnp.asarray([0, 5], jnp.int32)       # frontiers inside block 0
    tbl_real = jnp.asarray([[0, 1, 2, 3], [4, 5, 6, 7]], jnp.int32)
    tbl_sent = jnp.asarray([[0, N, N, N], [4, N, N, N]], jnp.int32)
    out_r = fd.flash_prefill_paged(q, kf, vf, tbl_real, st,
                                   interpret=True)
    out_s = fd.flash_prefill_paged(q, kf, vf, tbl_sent, st,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_s),
                               atol=1e-6)


def test_flash_decode_paged_int4_matches_dequant_reference():
    """T=1 paged decode through packed int4: in-kernel nibble unpack +
    scale fold == dequantized reference."""
    rng = np.random.default_rng(3)
    B, H, D, N, page, nb = 3, 2, 32, 16, 16, 4
    kf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    tbl = jnp.asarray(rng.permutation(N)[:B * nb].reshape(B, nb),
                      jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    lens = jnp.asarray([3, 30, 63], jnp.int32)
    kq, ks = fd.quantize_kv_rows_int4(kf)
    vq, vs = fd.quantize_kv_rows_int4(vf)
    out = fd.flash_decode_paged(q, kq, vq, tbl, lens, k_scale=ks,
                                v_scale=vs, interpret=True)
    kd = fd.unpack_int4(kq).astype(jnp.float32) * ks[..., None]
    vd = fd.unpack_int4(vq).astype(jnp.float32) * vs[..., None]
    kk = kd[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    vv = vd[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    ref = fd.xla_decode_attention(q, kk, vv, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5, rtol=5e-5)


def test_xla_decode_attention_paged_matches_gathered_reference():
    """The gather-free XLA paged decode fast path == the gathered
    masked reference, fp and quantized (it replaced the chain-relayout
    copy on the CPU fallback hot path)."""
    rng = np.random.default_rng(4)
    B, H, D, N, page, nb = 3, 2, 32, 16, 16, 4
    kf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(N, H, page, D)), jnp.float32)
    tbl = jnp.asarray(rng.permutation(N)[:B * nb].reshape(B, nb),
                      jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    lens = jnp.asarray([1, 17, 64], jnp.int32)
    out = fd.xla_decode_attention_paged(q, kf, vf, tbl, lens)
    kk = kf[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    vv = vf[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    ref = fd.xla_decode_attention(q, kk, vv, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    kq, ks = fd.quantize_kv_rows(kf)
    vq, vs = fd.quantize_kv_rows(vf)
    out_q = fd.xla_decode_attention_paged(q, kq, vq, tbl, lens,
                                          k_scale=ks, v_scale=vs)
    kkq = kq[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    vvq = vq[tbl].transpose(0, 2, 1, 3, 4).reshape(B, H, nb * page, D)
    kks = ks[tbl].transpose(0, 2, 1, 3).reshape(B, H, nb * page)
    vvs = vs[tbl].transpose(0, 2, 1, 3).reshape(B, H, nb * page)
    ref_q = fd.xla_decode_attention(q, kkq, vvq, lens, k_scale=kks,
                                    v_scale=vvs)
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(ref_q),
                               atol=2e-6, rtol=2e-6)


def test_engine_paged_prefill_kernel_token_exact(served_model):
    """A paged interpret-kernel engine (prefill AND decode through the
    Pallas paths) emits exactly the XLA engine's greedy tokens on a
    mixed workload — the kernel swap is invisible to outputs."""
    cfg, model, params = served_model
    rng = np.random.default_rng(23)
    reqs = [(rng.integers(0, 50, int(rng.integers(2, 40))).tolist(),
             int(rng.integers(2, 8)), int(rng.integers(0, 99)))
            for _ in range(8)]

    def run(impl):
        e = Engine(model, params, num_slots=4, max_len=64,
                   decode_impl=impl)
        for prompt, mnt, seed in reqs:
            e.submit(prompt, mnt, seed=seed)
        return {r.rid: r.tokens for r in e.drain()}

    assert run("pallas_interpret") == run("xla")


def test_init_cache_int4_layout():
    """int4 cache layers: packed uint8 values at head_dim // 2, f32
    per-position scales, both layouts; odd head_dim rejected."""
    from nanosandbox_tpu.models.gpt import init_paged_cache

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32")
    cache = init_cache(cfg, 3, 32, kv_dtype="int4")
    k, v, ks, vs = cache[0]
    assert k.shape == (3, 2, 32, 16) and k.dtype == jnp.uint8
    assert ks.shape == (3, 2, 32) and ks.dtype == jnp.float32
    paged = init_paged_cache(cfg, 8, 16, kv_dtype="int4")
    pk = paged[0][0]
    assert pk.shape == (8, 2, 16, 16) and pk.dtype == jnp.uint8
    assert normalize_kv_dtype("int4") == "int4"
    odd = GPTConfig(n_layer=1, n_head=3, n_embd=9, block_size=8,
                    vocab_size=50, dropout=0.0, compute_dtype="float32")
    with pytest.raises(ValueError, match="even"):
        init_cache(odd, 1, 8, kv_dtype="int4")
