"""Speculative-decoding tests: drafters + fixed-shape batched verify.

The contract under test (ISSUE 4 acceptance bar):
  * exact greedy token-parity with the non-speculative engine (and so,
    transitively, with single-request sample.generate) for BOTH drafter
    backends, under mixed batches where speculating and non-speculating
    rows share one verify program;
  * rollback correctness: a drafter that is ALWAYS wrong still yields
    exact outputs (the rejected tail's K/V is overwritten before any
    query attends to it) and never slows a row below one token per
    verify;
  * mid-chunk eos truncates exactly where the non-spec loop would have
    stopped, and the freed slot's next occupant is unaffected;
  * the compile set stays closed: ONE verify program (+ the
    ModelDrafter's draft/draft_prefill grid), asserted via the engine's
    TraceBudgetRegistry and enforced under frozen();
  * temperature > 0 rejection sampling preserves the output
    distribution (seeded two-sided frequency check on a tiny vocab).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.sample import generate
from nanosandbox_tpu.serve import (Engine, ModelDrafter, NGramDrafter,
                                   drafter_from_flag)


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


@pytest.fixture(scope="module")
def draft_model(served_model):
    """A smaller GPT sharing the target's vocab + block size — the
    ModelDrafter contract."""
    cfg, _, _ = served_model
    dcfg = GPTConfig(n_layer=1, n_head=2, n_embd=16,
                     block_size=cfg.block_size, vocab_size=cfg.vocab_size,
                     dropout=0.0, compute_dtype="float32",
                     attention_impl="xla")
    dmodel = GPT(dcfg)
    dparams = dmodel.init(jax.random.key(1),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    return dcfg, dmodel, dparams


def _ref_greedy(model, params, prompt, max_new, block_size):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32), max_new,
                   temperature=0.0, top_k=0, rng=jax.random.key(0),
                   block_size=block_size)
    return [int(t) for t in out[0, len(prompt):]]


def _mixed_workload(cfg, seed, n):
    """Half repetitive prompts (the drafter's favorable regime), half
    independent-random (ngram mostly misses -> draft_len-0 rows), so
    speculating and non-speculating rows share verify batches."""
    rng = np.random.default_rng(seed)
    work = []
    for i in range(n):
        L = int(rng.integers(2, 30))
        if i % 2 == 0:
            motif = rng.integers(0, cfg.vocab_size, int(rng.integers(2, 4)))
            prompt = np.tile(motif, L // len(motif) + 1)[:L].tolist()
        else:
            prompt = [int(x) for x in rng.integers(0, cfg.vocab_size, L)]
        work.append((prompt, int(rng.integers(1, 16))))
    return work


def _spec_budget_ok(eng):
    eng.tracecheck.assert_within_budget()
    assert eng.tracecheck.budgets() == eng.max_programs()
    assert eng.max_programs()["verify"] == 1
    assert eng.trace_counts["verify"] <= 1


class _ScriptedDrafter:
    """Host drafter that proposes from a per-prompt script indexed by how
    many tokens the request has generated so far — lets a test pin the
    drafter to be exactly wrong (full-reject rollback) or exactly right
    (oracle) against a precomputed reference stream."""

    kind = "host"

    def __init__(self, scripts, k=4):
        # scripts: {prompt tuple: [token per generated position]}
        self.scripts = scripts
        self.k = k

    def propose(self, context, max_tokens=None):
        cap = self.k if max_tokens is None else min(self.k, max_tokens)
        ctx = tuple(int(t) for t in context)
        for prompt, script in self.scripts.items():
            if ctx[:len(prompt)] == prompt:
                done = len(ctx) - len(prompt)
                return script[done:done + cap]
        return []


class _ConstDrafter:
    """Propose a fixed token at every offset — acceptance probability is
    then exactly the target's p(token), the cleanest handle for the
    distribution-preservation test."""

    kind = "host"

    def __init__(self, token, k=2):
        self.token = int(token)
        self.k = k

    def propose(self, context, max_tokens=None):
        cap = self.k if max_tokens is None else min(self.k, max_tokens)
        return [self.token] * max(cap, 0)


# -------------------------------------------------------------- greedy parity

def test_ngram_greedy_parity_mixed_batch_and_budget(served_model):
    """10 mixed requests through 4 slots (backfill mid-flight), half
    repetitive / half random prompts: every output token-for-token equal
    to the non-spec reference, one verify program total."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64,
                 spec=NGramDrafter(k=4))
    reqs = [(eng.submit(p, m), p, m)
            for p, m in _mixed_workload(cfg, seed=7, n=10)]
    res = {r.rid: r for r in eng.drain()}
    assert len(res) == 10
    for rid, prompt, mnt in reqs:
        assert res[rid].tokens == _ref_greedy(model, params, prompt, mnt,
                                              cfg.block_size), rid
    _spec_budget_ok(eng)
    s = eng.stats()
    assert s["spec"]["enabled"] is True
    assert s["spec"]["verify_steps"] > 0
    # The repetitive half must actually speculate for this test to mean
    # anything (draft_len-0 rows alone would vacuously pass parity).
    assert s["spec"]["tokens_accepted"] > 0


def test_model_drafter_greedy_parity_and_budget(served_model, draft_model):
    """Same parity bar for the device drafter: a small same-tokenizer GPT
    drafting greedily against its own slot pool."""
    cfg, model, params = served_model
    _, dmodel, dparams = draft_model
    eng = Engine(model, params, num_slots=4, max_len=64,
                 spec=ModelDrafter(dmodel, dparams, k=3))
    reqs = [(eng.submit(p, m), p, m)
            for p, m in _mixed_workload(cfg, seed=13, n=8)]
    res = {r.rid: r for r in eng.drain()}
    assert len(res) == 8
    for rid, prompt, mnt in reqs:
        assert res[rid].tokens == _ref_greedy(model, params, prompt, mnt,
                                              cfg.block_size), rid
    _spec_budget_ok(eng)
    progs = eng.max_programs()
    assert progs["draft"] == 1
    assert progs["draft_prefill"] == progs["prefill"]
    assert eng.trace_counts["draft"] <= 1


def test_full_reject_rollback_exact(served_model):
    """A drafter that is wrong at EVERY position: every verify fully
    rejects, the cache frontier rolls back every step (the rejected
    tail's K/V sits in the pool until overwritten), and the output is
    still exact — at exactly one token per verify, never slower than
    plain decode."""
    cfg, model, params = served_model
    prompt = (5, 3, 1, 4)
    ref = _ref_greedy(model, params, list(prompt), 12, cfg.block_size)
    wrong = [(t + 1) % cfg.vocab_size for t in ref]
    eng = Engine(model, params, num_slots=2, max_len=64,
                 spec=_ScriptedDrafter({prompt: wrong}, k=4))
    rid = eng.submit(prompt, 12)
    res = {r.rid: r for r in eng.drain()}
    assert res[rid].tokens == ref
    s = eng.stats()
    assert s["spec"]["tokens_accepted"] == 0
    assert s["spec_acceptance_rate"] == 0.0
    _spec_budget_ok(eng)


def test_oracle_drafter_fewer_forwards(served_model):
    """The flip side: a drafter that is right at every position collapses
    max_new tokens into ~max_new/(k+1) verifies — the whole point of the
    subsystem, pinned here at the step-count level (CPU wall-clock is
    bench.py's job)."""
    cfg, model, params = served_model
    prompt = (2, 7, 2, 7)
    max_new = 13
    ref = _ref_greedy(model, params, list(prompt), max_new, cfg.block_size)
    eng = Engine(model, params, num_slots=2, max_len=64,
                 spec=_ScriptedDrafter({prompt: ref}, k=4))
    rid = eng.submit(prompt, max_new)
    res = {r.rid: r for r in eng.drain()}
    assert res[rid].tokens == ref
    s = eng.stats()
    assert s["spec_acceptance_rate"] == 1.0
    # 12 post-prefill tokens at up to 5/verify: 3 verifies suffice
    # (drafts are capped at remaining-1, so the last chunk is partial).
    assert s["spec"]["verify_steps"] <= 4


def test_spec_eos_mid_chunk_truncates_exactly(served_model):
    """An eos landing MID verify-chunk: the accepted tokens after it are
    dropped, finish_reason is eos, and the freed slot's next occupant
    decodes exactly as if the engine were fresh."""
    cfg, model, params = served_model
    prompt = ref = idx = None
    for cand in ([5, 3], [6, 6, 2], [42, 13, 27, 33], [49, 48, 47]):
        r = _ref_greedy(model, params, cand, 12, cfg.block_size)
        novel = [i for i in range(2, len(r) - 1) if r[i] not in r[:i]]
        if novel:
            prompt, ref, idx = cand, r, novel[0]
            break
    assert prompt is not None, "no candidate prompt with a mid-stream " \
        "novel greedy token; extend the candidate list"
    eos = ref[idx]
    # Oracle drafts guarantee the eos arrives inside an accepted chunk
    # (k=4 spans it) rather than as a lone bonus token.
    eng = Engine(model, params, num_slots=1, max_len=64,
                 spec=_ScriptedDrafter({tuple(prompt): ref}, k=4))
    rid_a = eng.submit(prompt, 12, eos_id=eos)
    rid_b = eng.submit([9, 9], 6)    # backfills the SAME slot afterwards
    res = {r.rid: r for r in eng.drain()}
    assert res[rid_a].tokens == ref[:idx + 1]
    assert res[rid_a].finish_reason == "eos"
    assert res[rid_b].tokens == _ref_greedy(model, params, [9, 9], 6,
                                            cfg.block_size)
    assert eng.stats()["free_slots"] == 1


# ------------------------------------------------------------- compile budget

def test_verify_budget_under_frozen_registry(served_model):
    """The post-warmup serving contract extends to the spec programs:
    once the verify (and prefill set) is compiled, a frozen registry
    admits any further speculative traffic without a single retrace —
    and a shape that WOULD need a new program still fails loudly."""
    from nanosandbox_tpu.utils.tracecheck import CompileBudgetExceeded

    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 spec=NGramDrafter(k=4))
    eng.submit([1, 2, 1, 2, 1, 2], 8)   # rung-1 wave
    eng.submit([5, 6, 5, 6], 8)
    eng.submit([6, 5, 6], 8)            # rung-2 wave (backfill pair)
    eng.drain()                         # verify + bucket-16 rungs compiled
    assert eng.trace_counts["verify"] == 1
    with eng.tracecheck.frozen():
        # Different draft lengths, mixed hit/miss rows, different
        # temperature mix: all the SAME verify shape — zero retraces.
        eng.submit([3, 4, 3, 4, 3], 6)
        eng.submit([9, 8, 7], 5, temperature=0.7, top_k=5, seed=11)
        eng.drain()
        eng.submit([9] * 20, 2)       # bucket 32: needs a NEW prefill
        with pytest.raises(CompileBudgetExceeded, match="frozen"):
            eng.drain()
    assert eng.trace_counts["verify"] == 1
    eng.tracecheck.assert_within_budget()


def test_spec_stats_surface(served_model):
    """Engine.stats() (and therefore serve's /stats) carries the
    acceptance signal: rate, mean accepted length, per-request accepted
    totals — and the non-spec engine reports enabled=False with null
    fields instead of omitting the keys."""
    cfg, model, params = served_model
    plain = Engine(model, params, num_slots=1, max_len=64)
    s0 = plain.stats()
    assert s0["spec"] == {"enabled": False}
    assert s0["spec_acceptance_rate"] is None

    eng = Engine(model, params, num_slots=2, max_len=64,
                 spec=NGramDrafter(k=4))
    rid = eng.submit([1, 2, 1, 2, 1, 2, 1, 2], 10)
    res = {r.rid: r for r in eng.drain()}
    assert len(res[rid].tokens) == 10
    s = eng.stats()
    assert s["spec"]["drafter"] == "NGramDrafter"
    assert s["spec"]["k"] == 4
    assert s["spec"]["tokens_drafted"] >= s["spec"]["tokens_accepted"] > 0
    assert s["spec_acceptance_rate"] == pytest.approx(
        s["spec"]["tokens_accepted"] / s["spec"]["tokens_drafted"])
    assert s["spec_accepted_len_mean"] is not None
    assert s["spec_req_accepted_tokens"]["p50"] is not None


def test_drafter_validation():
    """Bad drafter configs fail at construction, not mid-flight."""
    with pytest.raises(ValueError, match="k must be"):
        NGramDrafter(k=0)
    with pytest.raises(ValueError, match="max_ngram"):
        NGramDrafter(max_ngram=0)
    assert drafter_from_flag("off") is None
    assert drafter_from_flag("") is None
    assert isinstance(drafter_from_flag("ngram", k=3), NGramDrafter)
    with pytest.raises(ValueError, match="model:<out_dir>"):
        drafter_from_flag("model:")
    with pytest.raises(ValueError, match="unknown --spec"):
        drafter_from_flag("bogus")


def test_model_drafter_rejects_mismatched_model(served_model):
    """Vocabulary or context mismatch between drafter and target is a
    construction-time error — drafts are token ids, so the models must
    share one tokenizer and the drafter must reach every frontier."""
    cfg, model, params = served_model
    bad_vocab = GPTConfig(n_layer=1, n_head=2, n_embd=16, block_size=64,
                          vocab_size=cfg.vocab_size + 1, dropout=0.0,
                          compute_dtype="float32", attention_impl="xla")
    bmodel = GPT(bad_vocab)
    bparams = bmodel.init(jax.random.key(2),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="vocab_size"):
        Engine(model, params, num_slots=2, max_len=64,
               spec=ModelDrafter(bmodel, bparams, k=2))

    short_ctx = GPTConfig(n_layer=1, n_head=2, n_embd=16, block_size=32,
                          vocab_size=cfg.vocab_size, dropout=0.0,
                          compute_dtype="float32", attention_impl="xla")
    smodel = GPT(short_ctx)
    sparams = smodel.init(jax.random.key(3),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="block_size"):
        Engine(model, params, num_slots=2, max_len=64,
               spec=ModelDrafter(smodel, sparams, k=2))


def test_model_drafter_cache_consistent_after_full_accept(served_model,
                                                          draft_model):
    """The drafter pool must stay coherent through a FULL-accept round:
    when all k drafts are accepted the engine's frontier jumps k+1
    columns, so the k-th draft's K/V column is queried by every later
    draft — if the draft scan never wrote it (the k-step version of
    _draft_fn), round-2 drafts silently diverge from the draft model's
    true greedy predictions for the rest of the request. Pinned by
    exact parity against a cache-free dense re-run of the draft model
    over the full accepted sequence."""
    cfg, model, params = served_model
    _, dmodel, dparams = draft_model
    from nanosandbox_tpu.utils.tracecheck import TraceBudgetRegistry

    k = 3
    drafter = ModelDrafter(dmodel, dparams, k=k)
    drafter.build(target_cfg=cfg, num_slots=2, max_len=32,
                  n_prefill_programs=4, registry=TraceBudgetRegistry(),
                  on_accel=False)
    # A previous occupant fills slot 0's pool row first: prefill only
    # rewrites columns [0, L) (scatter_cache_rows), so its K/V survives
    # past the new prompt's length — the exact garbage the never-written
    # column would expose (an all-zero fresh pool is too benign to flip
    # a tiny model's argmax, a real stale row is not).
    junk = [int(x) for x in np.random.default_rng(3).integers(
        0, cfg.vocab_size, 24)]

    def meta(slots):
        # The engine's packed dense staging row ([slot | true_len |
        # top_k | seed]); the drafter prefill reads only the slot col.
        m = np.zeros((len(slots), 4), np.int32)
        m[:, 0] = slots
        return jnp.asarray(m)

    drafter.prefill_wave(jnp.asarray([junk, junk], jnp.int32),
                         meta([0, 1]))
    prompt = [1, 2, 3, 4, 5]
    L = len(prompt)
    drafter.prefill_wave(jnp.asarray([prompt, prompt], jnp.int32),
                         meta([0, 1]))

    def dense_greedy(seq, n):
        out = []
        for _ in range(n):
            logits = dmodel.apply({"params": dparams},
                                  jnp.asarray([seq], jnp.int32),
                                  deterministic=True)
            nxt = int(jnp.argmax(logits[0, -1]))
            out.append(nxt)
            seq = seq + [nxt]
        return out

    active = jnp.asarray([True, False])
    t0 = 7
    r1 = np.asarray(drafter.draft(jnp.asarray([t0, 0], jnp.int32),
                                  jnp.asarray([L, L], jnp.int32), active))
    assert r1[0].tolist() == dense_greedy(prompt + [t0], k)

    # Full accept: the engine advances pos by k+1, so the NEXT draft
    # call queries across column L+k — the k-th draft's K/V, which only
    # the scan's extra (k+1)-th step writes. Pin the invariant at the
    # cache level (argmax parity alone is too blunt on a tiny model):
    # slot 0's pool columns [0, L+k] must match a from-scratch prefill
    # of the full accepted sequence, every layer.
    from nanosandbox_tpu.models.gpt import init_cache

    seq_acc = prompt + [t0] + r1[0].tolist()          # columns 0..L+k
    _, ref_cache = dmodel.apply(
        {"params": dparams}, jnp.asarray([seq_acc], jnp.int32),
        deterministic=True, cache=init_cache(dmodel.cfg, 1, 32),
        cache_index=0)
    n_cols = len(seq_acc)
    for li, ((pk, pv), (rk, rv)) in enumerate(zip(drafter._pool,
                                                  ref_cache)):
        np.testing.assert_allclose(
            np.asarray(pk[0, :, :n_cols]), np.asarray(rk[0, :, :n_cols]),
            atol=1e-5, err_msg=f"K layer {li}")
        np.testing.assert_allclose(
            np.asarray(pv[0, :, :n_cols]), np.asarray(rv[0, :, :n_cols]),
            atol=1e-5, err_msg=f"V layer {li}")

    # And the round-2 drafts (queries spanning that column) still match
    # the cache-free dense reference.
    bonus = 9
    seq = seq_acc + [bonus]
    r2 = np.asarray(drafter.draft(jnp.asarray([bonus, 0], jnp.int32),
                                  jnp.asarray([L + k + 1, L], jnp.int32),
                                  active))
    assert r2[0].tolist() == dense_greedy(seq, k)


# ------------------------------------------------- distribution preservation

def test_temperature_rejection_sampling_preserves_distribution():
    """Leviathan-rule correctness at temperature > 0, empirically: on a
    tiny vocab, the per-position token frequencies of the speculative
    engine match the non-speculative engine across many seeded requests
    (two-sided max-abs-frequency check; each engine's run is fully
    deterministic given the seed set, so the tolerance is stable, not
    flaky). The constant drafter makes the accept probability exactly
    the target's p(token), so both the accept and the
    resample-with-mass-removed paths are exercised."""
    V = 13
    cfg = GPTConfig(n_layer=1, n_head=2, n_embd=16, block_size=16,
                    vocab_size=V, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(4),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    prompt, max_new, n_seeds = [1, 2, 1, 2], 3, 800

    def collect(drafter):
        eng = Engine(model, params, num_slots=8, max_len=16, spec=drafter)
        rids = [eng.submit(prompt, max_new, temperature=1.0, seed=s)
                for s in range(n_seeds)]
        res = {r.rid: r.tokens for r in eng.drain()}
        toks = np.asarray([res[r] for r in rids])     # (n_seeds, max_new)
        counts = np.stack([np.bincount(toks[:, j], minlength=V)
                           for j in range(max_new)])  # (max_new, V)
        return eng, counts / n_seeds

    base_eng, base_freq = collect(None)
    spec_eng, spec_freq = collect(_ConstDrafter(token=5, k=2))

    s = spec_eng.stats()
    # Both rejection paths ran: some drafts accepted, some rejected.
    assert 0.0 < s["spec_acceptance_rate"] < 1.0
    # Position 0 comes from the prefill in both engines — same seeded
    # stream, so the frequencies are IDENTICAL, a built-in control that
    # the comparison itself is sound.
    np.testing.assert_allclose(spec_freq[0], base_freq[0], atol=1e-12)
    # Positions 1..: verify-emitted (accept / resample / bonus). Two
    # independent N-sample draws from the same distribution: bound the
    # max per-token frequency gap. std of a freq diff is at most
    # sqrt(0.5/N) ~ 0.025 at N=800; 0.06 is ~2.4 sigma on the worst
    # token but the run is deterministic — this documents the margin.
    gap = np.abs(spec_freq[1:] - base_freq[1:]).max()
    assert gap < 0.06, f"frequency gap {gap:.4f} (spec vs base)"
    # And the drafted token's own frequency did not inflate (the classic
    # always-accept bug would push it toward 1.0).
    assert abs(spec_freq[1][5] - base_freq[1][5]) < 0.06


# ----------------------------------------------------------------- bench hook

def test_bench_decode_spec_mode():
    import bench

    result = bench.bench_decode(
        {"num_slots": "2", "max_new_tokens": "6", "requests": "4",
         "spec": "ngram", "spec_k": "3", "repetitive": "1"},
        quick=True, on_tpu=False)
    extra = result["extra"]
    assert extra["spec"] == "ngram"
    assert extra["spec_k"] == 3
    assert extra["spec_tokens_per_sec"] > 0
    assert extra["spec_vs_baseline"] == pytest.approx(
        extra["spec_tokens_per_sec"] / extra["pipelined_tokens_per_sec"])
    assert extra["spec_tokens_generated"] == extra["tokens_generated"]
    assert 0.0 <= extra["acceptance_rate"] <= 1.0

    with pytest.raises(SystemExit):
        bench.bench_decode({"spec": "model:/nope"}, quick=True,
                           on_tpu=False)
