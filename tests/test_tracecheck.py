"""utils.tracecheck tests: retrace budgets, freezing, the sync ledger.

The guard's whole premise is that jax calls a wrapped Python body once
per TRACE, so counting calls counts compiles — pinned here against a
real jax.jit (same shape twice -> one bump; new shape -> retrace ->
bump -> overflow raises).
"""

import jax
import jax.numpy as jnp
import pytest

from nanosandbox_tpu.utils import tracecheck
from nanosandbox_tpu.utils.tracecheck import (CompileBudgetExceeded,
                                              TraceBudgetRegistry,
                                              compile_budget)


def test_guard_counts_calls_and_raises_on_overflow():
    reg = TraceBudgetRegistry()

    @reg.guard("step", 2)
    def f(x):
        return x + 1

    assert f(1) == 2 and f(2) == 3
    assert reg.counts() == {"step": 2}
    with pytest.raises(CompileBudgetExceeded, match="'step' would trace 3"):
        f(3)
    # The rejected trace compiled nothing, so it consumed no counter:
    # counts() keeps describing the REAL compile set and the budget
    # postcondition stays healthy on an engine that survived the leak.
    assert reg.counts() == {"step": 2}
    reg.assert_within_budget()
    # The message points at the static-analysis companion.
    with pytest.raises(CompileBudgetExceeded, match="nanosandbox_tpu"):
        f(4)


def test_budget_zero_rejects_first_trace_and_negative_rejected():
    reg = TraceBudgetRegistry()
    with pytest.raises(ValueError, match="max_traces"):
        reg.guard("x", -1)

    @reg.guard("never", 0)
    def f():
        return None

    with pytest.raises(CompileBudgetExceeded):
        f()


def test_under_jit_counts_traces_not_calls():
    reg = TraceBudgetRegistry()
    f = jax.jit(reg.guard("decode", 1)(lambda x: x * 2))
    x = jnp.ones((4,))
    for _ in range(5):                      # one shape: one trace
        f(x)
    assert reg.counts() == {"decode": 1}
    with pytest.raises(CompileBudgetExceeded, match="'decode'"):
        f(jnp.ones((8,)))                   # shape leak: retrace


def test_frozen_context_rejects_any_new_trace():
    reg = TraceBudgetRegistry()
    f = jax.jit(reg.guard("step", 2)(lambda x: x + 1))
    f(jnp.ones((2,)))
    with reg.frozen():
        f(jnp.ones((2,)))                  # cached program: no trace, fine
        with pytest.raises(CompileBudgetExceeded, match="frozen"):
            f(jnp.ones((3,)))
    # The frozen rejection consumed NO budget (the trace was aborted
    # before compiling): with budget 2 the post-unfreeze compile fits.
    assert reg.counts()["step"] == 1
    f(jnp.ones((4,)))                      # unfrozen again: budget applies
    assert reg.counts()["step"] == 2


def test_assert_within_budget_reports_every_overflow():
    reg = TraceBudgetRegistry()
    reg.register("a", 1)
    reg.assert_within_budget()
    reg.bump("a")
    reg.assert_within_budget()
    with pytest.raises(CompileBudgetExceeded):
        reg.bump("a")
    reg.assert_within_budget()         # rejected bump consumed nothing
    # Tightening a budget BELOW the already-observed traces is the one
    # way counts can exceed it — the postcondition names the offender.
    reg.register("a", 0)
    with pytest.raises(CompileBudgetExceeded, match="'a'"):
        reg.assert_within_budget()
    assert reg.budgets() == {"a": 0}


def test_compile_budget_decorator_uses_global_registry_by_default():
    name = "test-global-budget-unique"

    @compile_budget(name, 1)
    def f():
        return 7

    assert f() == 7
    assert tracecheck.global_registry().counts()[name] == 1


def test_host_sync_reads_scalar_and_counts():
    before = tracecheck.sync_count("test-window")
    total_before = tracecheck.sync_count()
    val = tracecheck.host_sync("test-window", jnp.float32(2.5))
    assert isinstance(val, float) and val == 2.5
    assert tracecheck.host_sync("test-window") is None   # count-only form
    assert tracecheck.sync_count("test-window") == before + 2
    assert tracecheck.sync_count() == total_before + 2
    assert tracecheck.sync_counts()["test-window"] >= 2
