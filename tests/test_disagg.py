"""Disaggregated prefill/decode serving tests (ISSUE 16).

The contract under test:
  * Parity: greedy outputs are token-identical between disaggregated
    (DisaggPair: prefill tier -> block-chain migration -> decode tier)
    and colocated serving, across paged x {fp32, int8, int4} kv pools
    x scan_k {1, 4} — the adoption re-enters decode at pos = true_len
    with the same fold_in(seed, pos + 1) keys a colocated engine would
    have used.
  * Ledger: the decode tier dispatches ZERO prefill programs — ever —
    and its compiled set is a strict subset of a colocated engine's
    (no widening; max_programs() budgets identical).
  * Exactly-once: every pair rid resolves to exactly one terminal
    across the handoff, including a replica_down fired INSIDE the
    migration window (blocks reserved, nothing committed) — the
    adoption unwinds, the export falls back colocated, and the merged
    flight stream still carries one terminal per namespaced rid.
  * Limbo hygiene: a deadline that expires while an export is parked
    in migration limbo sheds with blocks released WITHOUT donation
    (nothing warms the cache on refused traffic) and the pool's
    partition/refcount invariants hold throughout.
  * Wire: export_to_wire / adopt_from_wire survive a JSON round trip
    with the same parity + zero-prefill guarantees, and adoption
    backpressure surfaces as None (503-retryable upstream), never a
    half-written pool.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.obs import TERMINAL_EVENTS
from nanosandbox_tpu.serve import (DisaggPair, Engine, FaultPlan,
                                   PrefixAffinityRouter, adopt_from_wire,
                                   export_to_wire)
from nanosandbox_tpu.serve.paged import BlockPool, blocks_for
from nanosandbox_tpu.serve.router import NoReadyReplicaError
from nanosandbox_tpu.serve.scheduler import SlotScheduler


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


ENGINE_KW = dict(num_slots=4, max_len=64, paged=True)


def _requests(vocab=50, n=6, seed=0):
    """Mixed greedy mix: varied lengths/budgets, some sharing a
    prefix (the migration must respect radix hits on BOTH tiers)."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, vocab, 18).tolist()
    out = []
    for i in range(n):
        if i % 3 == 0:
            prompt = shared + rng.integers(0, vocab, 1 + i).tolist()
        else:
            prompt = rng.integers(0, vocab, 5 + 7 * i % 40).tolist()
        out.append((prompt, 3 + (i % 4)))
    return out


def _colocated_reference(model, params, reqs, **kw):
    eng = Engine(model, params, **{**ENGINE_KW, **kw})
    rids = [eng.submit(p, m, temperature=0.0, seed=11 + i)
            for i, (p, m) in enumerate(reqs)]
    by_rid = {r.rid: r for r in eng.drain()}
    return [by_rid[r] for r in rids]


# ------------------------------------------------------------------ parity
@pytest.mark.parametrize("kv_dtype,scan_k", [
    (None, 1),
    (None, 4),
    ("int8", 1),
    ("int4", 1),
    pytest.param("int8", 4, marks=pytest.mark.slow),
    pytest.param("int4", 4, marks=pytest.mark.slow),
])
def test_greedy_parity_disagg_vs_colocated(served_model, kv_dtype,
                                           scan_k):
    cfg, model, params = served_model
    kw = dict(kv_dtype=kv_dtype, scan_k=scan_k)
    reqs = _requests()
    ref = _colocated_reference(model, params, reqs, **kw)

    pair = DisaggPair(model, params, **{**ENGINE_KW, **kw})
    pair_rids = [pair.submit(p, m, temperature=0.0, seed=11 + i)
                 for i, (p, m) in enumerate(reqs)]
    by_rid = {r.rid: r for r in pair.drain()}
    assert set(by_rid) == set(pair_rids)
    for i, pr in enumerate(pair_rids):
        assert by_rid[pr].tokens == ref[i].tokens, (
            f"req {i}: disagg {by_rid[pr].tokens} != "
            f"colocated {ref[i].tokens}")
        assert by_rid[pr].finish_reason == ref[i].finish_reason
    # Every request actually took the migration path.
    assert pair.migrations == len(reqs)
    assert pair.fallbacks == 0
    assert pair.decode.host_dispatches["prefill"] == 0


# ------------------------------------------------------------------ ledger
def test_decode_tier_zero_prefill_and_strict_subset(served_model):
    cfg, model, params = served_model
    reqs = _requests(n=8, seed=3)
    coloc = Engine(model, params, **ENGINE_KW)
    for i, (p, m) in enumerate(reqs):
        coloc.submit(p, m, temperature=0.0, seed=i)
    coloc.drain()

    pair = DisaggPair(model, params, **ENGINE_KW)
    for i, (p, m) in enumerate(reqs):
        pair.submit(p, m, temperature=0.0, seed=i)
    out = pair.drain()
    assert len(out) == len(reqs)

    d = pair.decode
    # The dispatch ledger: NOT ONE prefill dispatch on the decode tier.
    assert d.host_dispatches["prefill"] == 0
    # Compile set: strict subset of the colocated engine's — no
    # prefill programs at all, admit narrowed to the rung-1 adoption
    # scatter, decode/release no wider.
    assert d.trace_counts["prefill"] == 0 < coloc.trace_counts["prefill"]
    assert d.trace_counts["admit"] == 1 <= coloc.trace_counts["admit"]
    assert d.trace_counts["decode"] <= coloc.trace_counts["decode"]
    assert d.trace_counts["release"] <= coloc.trace_counts["release"]
    # ... and the guarded budgets did NOT widen to pay for it.
    assert d.max_programs() == coloc.max_programs()
    assert pair.prefill.max_programs() == coloc.max_programs()
    # Pool invariants hold on both tiers after the workload drains.
    pair.prefill.block_pool.check([])
    pair.decode.block_pool.check([])
    st = pair.stats()
    assert st["tiers"]["decode"]["adopted"] == len(reqs)
    assert st["tiers"]["prefill"]["migrated"] == len(reqs)


# ------------------------------------------------------------- exactly-once
@pytest.mark.parametrize("kill_step", [
    2,
    pytest.param(4, marks=pytest.mark.slow),
])
def test_replica_down_mid_migration_exactly_once(served_model,
                                                 kill_step):
    cfg, model, params = served_model
    reqs = _requests(n=8, seed=5)
    ref = _colocated_reference(model, params, reqs)

    plan = FaultPlan.parse(f"replica_down@{kill_step}")
    pair = DisaggPair(model, params, faults=plan, **ENGINE_KW)
    pair_rids = [pair.submit(p, m, temperature=0.0, seed=11 + i)
                 for i, (p, m) in enumerate(reqs)]
    by_rid = {}
    for _ in range(500):
        for r in pair.step():
            assert r.rid not in by_rid, f"duplicate terminal {r.rid}"
            by_rid[r.rid] = r
        if not pair.has_work():
            break
    assert set(by_rid) == set(pair_rids)
    assert pair.replica_downs == 1
    # The kill forces fallbacks, but greedy outputs stay identical:
    # the colocated re-admission is a pure prefix hit resampling the
    # same stream.
    for i, pr in enumerate(pair_rids):
        assert by_rid[pr].finish_reason == ref[i].finish_reason
        assert by_rid[pr].tokens == ref[i].tokens
    # Merged flight: exactly one terminal per namespaced engine rid.
    terminals = {}
    for ev in pair.merged_flight_events():
        if ev["ev"] in TERMINAL_EVENTS and ev.get("rid") is not None:
            assert ev["rid"] not in terminals, (
                f"rid {ev['rid']} got two terminals")
            terminals[ev["rid"]] = ev["ev"]
    assert terminals, "no terminals recorded"
    pair.prefill.block_pool.check([])


def test_fallback_off_surfaces_failed(served_model):
    cfg, model, params = served_model
    plan = FaultPlan.parse("replica_down@0")
    pair = DisaggPair(model, params, faults=plan, fallback=False,
                      **ENGINE_KW)
    rid = pair.submit([1, 2, 3, 4, 5], 4, temperature=0.0, seed=1)
    out = pair.drain()
    assert [r.rid for r in out].count(rid) == 1
    res = next(r for r in out if r.rid == rid)
    assert res.finish_reason == "failed"
    # The sampled first token is salvaged into the failure.
    assert len(res.tokens) >= 1


# ------------------------------------------------------------------- limbo
def test_limbo_deadline_shed_releases_without_donation(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, role="prefill", **ENGINE_KW)
    free0 = eng.block_pool.free_blocks
    rid = eng.submit([7] * 20, 5, temperature=0.0, seed=2,
                     deadline_s=0.05, migrate=True)
    # Step until the export parks in limbo; nobody pumps it.
    for _ in range(50):
        eng.step()
        if eng.sched.limbo:
            break
    assert eng.sched.limbo == 1
    time.sleep(0.08)
    out = []
    for _ in range(20):
        out.extend(eng.step())
        if out:
            break
    assert [r.rid for r in out] == [rid]
    assert out[0].finish_reason == "shed"
    assert eng.sched.limbo == 0
    # Blocks came back WITHOUT donation: pool fully free, no cached
    # chain left behind by traffic the engine refused to serve.
    assert eng.block_pool.free_blocks == free0
    assert eng.block_pool.stats()["trie_blocks"] == 0
    eng.block_pool.check([])
    # Exactly one terminal in the flight ledger.
    evs = [e for e in eng.flight.events(rid=rid)
           if e["ev"] in TERMINAL_EVENTS]
    assert [e["ev"] for e in evs] == ["shed"]


def test_scheduler_drain_expired_sweeps_limbo_unit():
    class Item:
        def __init__(self, rid, expired):
            self.rid, self._expired = rid, expired

    sched = SlotScheduler(2, [16, 32, 64])
    sched.park_limbo(Item(1, False))
    sched.park_limbo(Item(2, True))
    sched.park_limbo(Item(3, True))
    sched.park_limbo_front(Item(0, False))
    swept = sched.drain_expired(lambda it: it._expired)
    assert sorted(it.rid for it in swept) == [2, 3]
    # Survivors keep order, head repark included.
    assert [it.rid for it in sched.limbo_items()] == [0, 1]
    assert sched.pop_limbo().rid == 0
    assert sched.limbo == 1


# -------------------------------------------------------------------- wire
def test_wire_roundtrip_parity_and_json(served_model):
    cfg, model, params = served_model
    reqs = _requests(n=3, seed=9)
    ref = _colocated_reference(model, params, reqs)

    src = Engine(model, params, role="prefill", **ENGINE_KW)
    dst = Engine(model, params, role="decode", **ENGINE_KW)
    rids = [src.submit(p, m, temperature=0.0, seed=11 + i, migrate=True)
            for i, (p, m) in enumerate(reqs)]
    adopted = {}
    for _ in range(200):
        src.step()
        while True:
            exp = src.pop_export()
            if exp is None:
                break
            wire = json.loads(json.dumps(export_to_wire(src, exp)))
            got = adopt_from_wire(dst, wire, src="src")
            assert got is not None
            new_rid, done = got
            adopted[new_rid] = rids.index(exp.req.rid)
            src.complete_export(exp, dst="dst")
            if done is not None:
                pytest.fail("tiny budgets should not finish at adopt")
        if len(adopted) == len(reqs) and not src.has_work():
            break
    assert len(adopted) == len(reqs)
    by_rid = {r.rid: r for r in dst.drain()}
    for new_rid, i in adopted.items():
        assert by_rid[new_rid].tokens == ref[i].tokens
    assert dst.host_dispatches["prefill"] == 0
    assert dst.trace_counts["prefill"] == 0
    assert src.migrated == len(reqs) and dst.adopted == len(reqs)
    src.block_pool.check([])
    dst.block_pool.check([])


def test_wire_adopt_backpressure_returns_none(served_model):
    cfg, model, params = served_model
    src = Engine(model, params, role="prefill", **ENGINE_KW)
    # A decode tier with ONE slot, already occupied: begin_adopt has
    # no slot to reserve, adoption must refuse cleanly.
    dst = Engine(model, params, num_slots=1, max_len=64, paged=True,
                 role="decode")
    src.submit([5] * 12, 6, temperature=0.0, seed=1, migrate=True)
    exp = None
    for _ in range(50):
        src.step()
        exp = src.pop_export()
        if exp is not None:
            break
    assert exp is not None
    wire = export_to_wire(src, exp)
    got1 = adopt_from_wire(dst, wire, src="src")
    assert got1 is not None            # first adoption takes the slot
    got2 = adopt_from_wire(dst, wire, src="src")
    assert got2 is None                # backpressure: no slot left
    # The refused adoption left no blocks behind.
    dst.drain()
    dst.block_pool.check([])
    src.repark_export(exp)
    assert src.sched.limbo == 1


# ------------------------------------------------------------- block pool
def test_adopt_chain_ledger_and_refcounts():
    bp = BlockPool(16, 4, prefix_cache=True)
    prompt = list(range(10))               # 3 chain blocks
    got = bp.adopt_chain(prompt, 4)
    assert got is not None
    alloc, copy = got
    # Cold pool: every chain block must be copied.
    assert copy == list(range(blocks_for(len(prompt), 4)))
    bp.check([alloc])
    bp.release(alloc, generated=(), donate=True)
    bp.check([])
    # Warm pool: the FULL blocks are a radix hit; only the partial
    # tail block (10 % 4 = 2 positions — never donated) still copies.
    got2 = bp.adopt_chain(prompt, 4)
    assert got2 is not None
    alloc2, copy2 = got2
    assert copy2 == [2]
    assert alloc2.n_hit == 2
    bp.release(alloc2, donate=False)
    bp.check([])
    st = bp.stats()
    assert st["adoptions"] == 2
    assert st["adopted_blocks"] == len(copy) + len(copy2)


# ------------------------------------------------------------------ router
def test_router_phase_dimension():
    r = PrefixAffinityRouter(["p0", "d0", "c0"], page=4,
                             roles={"p0": "prefill", "d0": "decode"})
    for name in ("p0", "d0", "c0"):
        r.update_replica(name, ready=True)
    assert r.replicas["c0"].role == "both"
    assert r.route([], phase="prefill").replica in ("p0", "c0")
    assert r.route([], phase="decode").replica in ("d0", "c0")
    # Roles are sticky across health updates that do not mention them.
    r.update_replica("p0", ready=True, queued=3)
    assert r.replicas["p0"].role == "prefill"
    # Phase exclusion: with the only decode-capable replicas excluded,
    # the error names the phase.
    with pytest.raises(NoReadyReplicaError) as ei:
        r.route([], phase="decode", exclude={"d0", "c0"})
    assert "decode" in str(ei.value)
    with pytest.raises(ValueError):
        r.route([], phase="verify")
    with pytest.raises(ValueError):
        r.add_replica("x", role="nonsense")
    # A colocated fleet (all "both") serves either phase — graceful
    # degradation during mixed rollouts.
    r2 = PrefixAffinityRouter(["a", "b"], page=4)
    for name in ("a", "b"):
        r2.update_replica(name, ready=True)
    assert r2.route([], phase="prefill").replica in ("a", "b")
    assert r2.route([], phase="decode").replica in ("a", "b")


# ----------------------------------------------------------------- metrics
def test_pair_metrics_and_debug_views(served_model):
    from nanosandbox_tpu.obs import render_prometheus

    cfg, model, params = served_model
    pair = DisaggPair(model, params, **ENGINE_KW)
    pair.submit([3, 1, 4, 1, 5, 9, 2, 6], 3, temperature=0.0, seed=4)
    pair.drain()
    text = render_prometheus(pair.metrics)
    assert 'serve_migrations_total{outcome="ok"} 1' in text
    assert "serve_migration_seconds" in text
    assert "serve_migration_limbo_depth" in text
    ptext = render_prometheus(pair.prefill.metrics)
    assert 'serve_engine_role{role="prefill"} 1' in ptext
    assert "serve_migrated_out_total 1" in ptext
    dtext = render_prometheus(pair.decode.metrics)
    assert 'serve_engine_role{role="decode"} 1' in dtext
    assert "serve_adopted_in_total 1" in dtext
    dbg = pair.prefill.debug_scheduler()
    assert dbg["role"] == "prefill"
    assert "limbo_queue" in dbg and dbg["limbo"] == 0
    st = pair.stats()
    assert st["migrations"] == 1 and st["limbo"] == 0
    assert st["migration_s"]["p50"] is not None


# ------------------------------------------------------------------- http
def test_http_two_tier_end_to_end(served_model):
    """Prefill pod + decode pod + RouterFrontend: the migrate-flagged
    /generate answers 202 at the source, the frontend carries the
    chain to /internal/adopt, confirms via /internal/export_done, and
    the client's tokens are identical to colocated serving."""
    from nanosandbox_tpu.serve.http import (EngineLoop, RouterFrontend,
                                            _http_json, make_server)

    cfg, model, params = served_model

    def enc(s):
        return [ord(c) % 50 for c in s] or [0]

    def dec(toks):
        return "".join(chr(97 + (t % 26)) for t in toks)

    pods = []

    def pod(role, **kw):
        eng = Engine(model, params, role=role, **{**ENGINE_KW, **kw})
        loop = EngineLoop(eng)
        loop.start()
        srv = make_server("127.0.0.1", 0, loop, enc, dec,
                          request_timeout=60.0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        pods.append((eng, loop, srv))
        return eng, url

    p_eng, p_url = pod("prefill")
    d_eng, d_url = pod("decode")
    fe = RouterFrontend([p_url, d_url], host="127.0.0.1", port=0,
                        page=p_eng.kv_page_size,
                        health_interval_s=0.2).start()
    fe_url = f"http://127.0.0.1:{fe.port}"
    try:
        for _ in range(100):
            _, body, _ = _http_json(f"{fe_url}/debug/router")
            reps = body["router"]["replicas"]
            if (all(r["ready"] for r in reps.values())
                    and {r.get("role") for r in reps.values()}
                    == {"prefill", "decode"}):
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"role discovery failed: {reps}")

        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
        st, body, _ = _http_json(
            f"{fe_url}/generate", method="POST",
            body={"prompt_tokens": prompt, "max_new_tokens": 6,
                  "temperature": 0.0, "seed": 7}, timeout=60.0)
        assert st == 200, (st, body)
        assert body["adopted"] is True
        assert body["migrated_from"] == p_url
        assert body["replica"] == d_url

        coloc = Engine(model, params, **ENGINE_KW)
        coloc.submit(prompt, 6, temperature=0.0, seed=7)
        assert body["tokens"] == coloc.drain()[0].tokens

        _, ps, _ = _http_json(f"{p_url}/stats")
        _, ds, _ = _http_json(f"{d_url}/stats")
        assert ps["role"] == "prefill" and ps["migrated"] == 1
        assert ds["role"] == "decode" and ds["adopted"] == 1
        assert ds["host_dispatches"]["prefill"] == 0
    finally:
        fe.stop()
        for _, loop, srv in pods:
            srv.shutdown()
            srv.server_close()
            loop.stop()
