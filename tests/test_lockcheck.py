"""lockcheck + schedcheck tests (ISSUE 18).

The contract under test:
  * Rules: each of the five concurrency rules catches its known-bad
    fixture and stays quiet on the good twin; the committed tier
    ordering in budgets/lock_order.json drives the inversion rule;
    guarded-by declarations are enforced at every access.
  * Suppressions: `# lockcheck: disable=<rule> -- <why>` semantics are
    identical to jaxlint's — reason mandatory, standalone covers the
    next statement only, typos flagged, string literals inert, unused
    reasoned disables reported (findings under --strict-suppressions).
  * Report/CLI: stable JSON schema (version/tool/summary), exit codes
    0/1/2, --out artifact, --changed-only pre-commit path, and the tool
    runs on a bare Python (no jax import).
  * Self-clean gate: lockcheck exits 0 on nanosandbox_tpu/ under
    --strict-suppressions with the committed lock order — the CI bar.
  * schedcheck: the runtime half DETECTS planted order inversions and
    crashed driver threads (the harness has teeth), then passes clean
    over Engine/Fleet/DisaggPair across many seeds; instrumentation
    adds zero compiled programs and zero audited host syncs.
"""

import json
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from nanosandbox_tpu.analysis.lockcheck import export_report_metrics
from nanosandbox_tpu.analysis.lockcheck.cli import main as cli_main
from nanosandbox_tpu.analysis.lockcheck.core import (
    DEFAULT_LOCK_ORDER, LockOrder, all_rules, analyze_paths,
    analyze_source, drain_unused_suppressions, load_lock_order,
    render_text)
from nanosandbox_tpu.utils import schedcheck
from nanosandbox_tpu.utils.schedcheck import (SchedCheck, _InstrumentedLock,
                                              _run_threads, fuzz_router)

PACKAGE_ROOT = Path(__file__).resolve().parent.parent / "nanosandbox_tpu"
REPO_ROOT = PACKAGE_ROOT.parent
ORDER_FILE = REPO_ROOT / DEFAULT_LOCK_ORDER


def rules_of(src, select=None, lock_order=None):
    findings, suppressed = analyze_source(src, "mod.py", select=select,
                                          lock_order=lock_order)
    return [f.rule for f in findings], findings, suppressed


# ----------------------------------------------------------- rule fixtures
# The bad twin must trip EXACTLY its rule; the good twin must be clean
# under that rule.

FIXTURES = {
    "unguarded-shared-write": (
        # `hits` written from the worker thread (Thread-subclass run)
        # AND from the unreached main-context reset, no lock anywhere.
        """
import threading


class Worker(threading.Thread):
    def __init__(self):
        super().__init__()
        self.hits = 0

    def run(self):
        self.hits += 1

    def reset(self):
        self.hits = 0
""",
        # Same shape, every write under one lock.
        """
import threading


class Worker(threading.Thread):
    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self.hits = 0

    def run(self):
        with self._lock:
            self.hits += 1

    def reset(self):
        with self._lock:
            self.hits = 0
""",
    ),
    "lock-order-inversion": (
        # A-while-B in one method, B-while-A in another: a module-local
        # cycle, no ordering file needed.
        """
import threading


class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def backward(self):
        with self._b_lock:
            with self._a_lock:
                pass
""",
        # Consistent nesting order everywhere.
        """
import threading


class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def backward(self):
        with self._a_lock:
            with self._b_lock:
                pass
""",
    ),
    "blocking-under-lock": (
        """
import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def refresh(self, compute):
        with self._lock:
            time.sleep(0.1)
            self.value = compute()
""",
        # Slow work hoisted out of the lock region.
        """
import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def refresh(self, compute):
        time.sleep(0.1)
        fresh = compute()
        with self._lock:
            self.value = fresh
""",
    ),
    "asyncio-blocking-call": (
        """
import urllib.request


async def fetch(url):
    return urllib.request.urlopen(url)
""",
        # Routed through the executor: the await is a coroutine, the
        # urlopen runs on the executor thread inside the lambda.
        """
import urllib.request


async def fetch(loop, url):
    return await loop.run_in_executor(
        None, lambda: urllib.request.urlopen(url))
""",
    ),
    "leaked-acquire": (
        """
import threading

_lock = threading.Lock()


def grab(work):
    _lock.acquire()
    work()
    _lock.release()
""",
        """
import threading

_lock = threading.Lock()


def grab(work):
    with _lock:
        work()
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_catches_bad_and_passes_good(rule):
    bad, good = FIXTURES[rule]
    bad_rules, findings, _ = rules_of(bad)
    assert rule in bad_rules, \
        f"{rule} missed its known-bad fixture: {findings}"
    assert all(r == rule for r in bad_rules), \
        f"unexpected extra rules on the {rule} bad fixture: {findings}"
    good_rules, findings, _ = rules_of(good)
    assert rule not in good_rules, \
        f"{rule} false-positived on its known-good twin: {findings}"


def test_bad_fixture_messages_name_the_context_or_function():
    _, findings, _ = rules_of(FIXTURES["unguarded-shared-write"][0])
    assert any("thread" in f.message and "main" in f.message
               for f in findings)
    _, findings, _ = rules_of(FIXTURES["asyncio-blocking-call"][0])
    assert any("fetch" in f.message for f in findings)


def test_guarded_by_declaration_enforced_on_every_access():
    src = """
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self.items.append(x)

    def peek(self):
        return len(self.items)
"""
    rules, findings, _ = rules_of(src)
    assert rules == ["unguarded-shared-write"]
    assert any("peek" in f.message and "guarded-by" in f.message
               for f in findings)
    # Holding the declared lock everywhere silences it.
    fixed = src.replace("return len(self.items)",
                        "with self._lock:\n"
                        "            return len(self.items)")
    rules, findings, _ = rules_of(fixed)
    assert rules == [], findings


def test_blocking_under_lock_is_transitive():
    src = """
import threading
import time


class C:
    def __init__(self):
        self._lock = threading.Lock()

    def _slow(self):
        time.sleep(1)

    def tick(self):
        with self._lock:
            self._slow()
"""
    rules, findings, _ = rules_of(src)
    assert rules == ["blocking-under-lock"]
    assert any("_slow" in f.message for f in findings)


def test_committed_tier_ordering_drives_inversion_rule():
    """Acquiring an engine-tier lock while holding a recorder-tier one
    inverts the canonical engine -> scheduler -> pool -> recorder
    order; the SAME nesting is silent without the ordering file (no
    module-local cycle)."""
    order = load_lock_order(str(ORDER_FILE))
    src = """
import threading


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()

    def bad(self):
        with self._lock:
            with self._cond:
                pass
"""
    rules, findings, _ = rules_of(src, lock_order=order)
    assert rules == ["lock-order-inversion"]
    assert any("recorder" in f.message and "engine" in f.message
               for f in findings)
    rules, _, _ = rules_of(src)              # no order file: no cycle
    assert rules == []
    # The canonical direction (engine-tier outermost) is clean.
    good = src.replace("with self._lock:\n            with self._cond:",
                       "with self._cond:\n            with self._lock:")
    rules, findings, _ = rules_of(good, lock_order=order)
    assert rules == [], findings


def test_lock_order_file_is_valid_and_loader_rejects_bad_tiers(tmp_path):
    order = load_lock_order(str(ORDER_FILE))
    assert order.tiers == ("engine", "scheduler", "pool", "recorder")
    assert order.locks, "no locks pinned to tiers"
    assert order.tier_index("EngineLoop._cond") == 0
    assert order.tier_index("FlightRecorder._lock") == 3
    assert order.tier_index("not-a-lock") is None
    bad = tmp_path / "order.json"
    bad.write_text(json.dumps({"order": ["engine"],
                               "locks": {"X._lock": "mystery"}}))
    with pytest.raises(ValueError, match="unknown tier"):
        load_lock_order(str(bad))


def test_select_restricts_rules():
    bad = FIXTURES["leaked-acquire"][0]
    rules, _, _ = rules_of(bad, select=["blocking-under-lock"])
    assert rules == []
    with pytest.raises(ValueError, match="unknown rule"):
        analyze_source(bad, select=["not-a-rule"])


def test_rule_catalogue_is_exactly_the_five():
    assert sorted(all_rules()) == [
        "asyncio-blocking-call", "blocking-under-lock", "leaked-acquire",
        "lock-order-inversion", "unguarded-shared-write"]


# -------------------------------------------------------------- suppressions

def test_suppression_with_reason_is_honored():
    src = FIXTURES["blocking-under-lock"][0].replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)"
        "  # lockcheck: disable=blocking-under-lock -- test rig")
    rules, _, suppressed = rules_of(src)
    assert rules == []
    assert suppressed == 1


def test_standalone_suppression_covers_next_statement():
    src = FIXTURES["blocking-under-lock"][0].replace(
        "            time.sleep(0.1)",
        "            # lockcheck: disable=blocking-under-lock -- rig\n"
        "            # (prose between stacked disables is fine)\n"
        "            time.sleep(0.1)")
    rules, _, suppressed = rules_of(src)
    assert rules == []
    assert suppressed == 1


def test_standalone_suppression_does_not_reach_past_code():
    src = FIXTURES["blocking-under-lock"][0].replace(
        "        with self._lock:",
        "        # lockcheck: disable=blocking-under-lock -- audits with\n"
        "        with self._lock:")
    # The finding anchors at the sleep BELOW the (clean) with line:
    # not covered.
    rules, _, suppressed = rules_of(src)
    assert "blocking-under-lock" in rules and suppressed == 0


def test_suppression_without_reason_is_void_and_flagged():
    src = FIXTURES["blocking-under-lock"][0].replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # lockcheck: disable=blocking-under-lock")
    rules, _, suppressed = rules_of(src)
    assert suppressed == 0
    assert "blocking-under-lock" in rules
    assert "bad-suppression" in rules


def test_unknown_rule_id_in_suppression_is_flagged():
    src = FIXTURES["blocking-under-lock"][0].replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)"
        "  # lockcheck: disable=blocking-under-locks -- typo'd id")
    rules, findings, suppressed = rules_of(src)
    assert suppressed == 0
    assert "blocking-under-lock" in rules    # the real finding survives
    assert "bad-suppression" in rules
    assert any("unknown rule id" in f.message for f in findings)


def test_suppression_in_string_literal_is_inert():
    src = FIXTURES["blocking-under-lock"][0].replace(
        "            time.sleep(0.1)",
        "            s = '# lockcheck: disable=blocking-under-lock -- x'\n"
        "            time.sleep(0.1)")
    rules, _, suppressed = rules_of(src)
    assert "blocking-under-lock" in rules and suppressed == 0


def test_jaxlint_disable_does_not_suppress_lockcheck():
    """The two tools keep separate suppression namespaces — a jaxlint
    audit must not silence a concurrency finding."""
    src = FIXTURES["blocking-under-lock"][0].replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # jaxlint: disable=host-sync -- wrong tool")
    rules, _, suppressed = rules_of(src)
    assert "blocking-under-lock" in rules and suppressed == 0


def test_unused_reasoned_suppression_reported_and_strict():
    drain_unused_suppressions()
    src = "x = 1  # lockcheck: disable=leaked-acquire -- stale audit\n"
    findings, suppressed = analyze_source(src, "mod.py")
    assert findings == [] and suppressed == 0
    unused = drain_unused_suppressions()
    assert len(unused) == 1
    assert unused[0]["rules"] == ["leaked-acquire"]
    assert unused[0]["reason"] == "stale audit"
    findings, _ = analyze_source(src, "mod.py", strict_suppressions=True)
    assert [f.rule for f in findings] == ["unused-suppression"]
    drain_unused_suppressions()

    # A USED suppression is never reported unused.
    used = FIXTURES["blocking-under-lock"][0].replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)"
        "  # lockcheck: disable=blocking-under-lock -- test rig")
    findings, suppressed = analyze_source(used, "mod.py",
                                          strict_suppressions=True)
    assert findings == [] and suppressed == 1
    assert drain_unused_suppressions() == []


# ------------------------------------------------------------ report + CLI

def test_parse_error_is_a_finding_not_a_crash():
    rules, findings, _ = rules_of("def broken(:\n")
    assert rules == ["parse-error"]


def test_json_schema(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(FIXTURES["leaked-acquire"][0])
    report = analyze_paths([str(tmp_path)])
    assert report["version"] == 1
    assert report["tool"] == "lockcheck"
    assert report["summary"]["files_scanned"] == 1
    assert report["summary"]["findings"] == len(report["findings"]) > 0
    assert report["summary"]["by_rule"] == {"leaked-acquire": 1}
    for item in report["findings"]:
        assert set(item) == {"file", "line", "col", "rule", "message"}
        assert isinstance(item["line"], int) and item["line"] > 0
    assert "lockcheck:" in render_text(report)


def test_cli_exit_codes_and_artifact(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["leaked-acquire"][0])
    good = tmp_path / "good.py"
    good.write_text(FIXTURES["leaked-acquire"][1])
    out = tmp_path / "report.json"

    assert cli_main([str(good)]) == 0
    assert cli_main(["--format=json", f"--out={out}", str(bad)]) == 1
    report = json.loads(out.read_text())
    assert report["summary"]["by_rule"] == {"leaked-acquire": 1}
    # The human summary still reached stdout next to the artifact.
    assert "lockcheck:" in capsys.readouterr().out
    assert cli_main([str(tmp_path / "nowhere")]) == 2
    assert cli_main(["--select=bogus", str(good)]) == 2
    assert cli_main(["--list-rules"]) == 0
    # A malformed ordering file is a usage error, not a crash.
    badorder = tmp_path / "order.json"
    badorder.write_text('{"order": [], "locks": {"X._l": "ghost"}}')
    assert cli_main([f"--lock-order={badorder}", str(good)]) == 2


def test_cli_changed_only_pre_commit_path(tmp_path, monkeypatch):
    """The fast pre-commit run: `lockcheck --changed-only --base=REF`
    lints exactly the git-diff set, sharing jaxlint's resolver."""
    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       capture_output=True,
                       env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t",
                            "HOME": str(tmp_path), "PATH": "/usr/bin:/bin"})

    git("init", "-q")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("x = 1\n")
    (pkg / "b.py").write_text("y = 1\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    monkeypatch.chdir(tmp_path)

    # Nothing changed -> nothing to lint, exit 0.
    assert cli_main(["--changed-only", "--base=HEAD", "pkg"]) == 0
    (pkg / "a.py").write_text(FIXTURES["leaked-acquire"][0])
    assert cli_main(["--changed-only", "--base=HEAD", "pkg"]) == 1
    assert cli_main(["--changed-only", "--base=no-such-ref", "pkg"]) == 2


def test_cli_runs_without_jax_importable():
    """The CI lint job runs lockcheck on a bare Python: make the 'no
    jax needed' contract executable by poisoning jax at import time —
    through the real `python -m nanosandbox_tpu.analysis lockcheck`
    dispatch."""
    code = (
        "import sys; sys.modules['jax'] = None\n"
        "from nanosandbox_tpu.analysis.__main__ import main\n"
        "raise SystemExit(main(['lockcheck', '--list-rules']))\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True,
                          cwd=str(REPO_ROOT), timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "unguarded-shared-write" in proc.stdout


def test_report_metrics_export():
    report = {"summary": {"files_scanned": 3, "suppressed": 2,
                          "findings": 1,
                          "by_rule": {"leaked-acquire": 1}}}
    from nanosandbox_tpu.obs import MetricRegistry, render_prometheus
    reg = MetricRegistry()
    export_report_metrics(report, reg)
    page = render_prometheus(reg)
    assert 'lockcheck_findings_total{rule="leaked-acquire"} 1' in page
    assert "lockcheck_files_scanned 3" in page
    assert "lockcheck_suppressed_total 2" in page
    # Clean report still renders a findings sample to scrape.
    clean = {"summary": {"files_scanned": 3, "suppressed": 2,
                         "findings": 0, "by_rule": {}}}
    reg = MetricRegistry()
    export_report_metrics(clean, reg)
    assert 'lockcheck_findings_total{rule="none"} 0' in render_prometheus(reg)


# ------------------------------------------------------------ self-clean gate

def test_package_tree_is_clean():
    """The acceptance bar CI enforces: lockcheck exits 0 on the
    nanosandbox_tpu/ tree under --strict-suppressions with the
    committed lock order — every deliberate concurrency call-out is a
    reasoned suppression, and none of those audits has rotted."""
    report = analyze_paths([str(PACKAGE_ROOT)], strict_suppressions=True,
                           lock_order=load_lock_order(str(ORDER_FILE)))
    assert report["summary"]["files_scanned"] > 30
    msgs = [f"{f['file']}:{f['line']} {f['rule']}: {f['message']}"
            for f in report["findings"]]
    assert not msgs, "lockcheck findings on the package tree:\n" + \
        "\n".join(msgs)
    # The deliberate exceptions (watchdog dump serialization, build-once
    # double-checked locking, publish-before-barrier fields) are
    # suppressed WITH reasons, not invisible.
    assert report["summary"]["suppressed"] >= 5
    assert report["unused_suppressions"] == []


# ------------------------------------------------- schedcheck: the harness

def _order():
    return schedcheck.load_order(str(ORDER_FILE))


def test_schedcheck_detects_planted_order_inversion():
    """The runtime half has teeth: acquiring an earlier-tier lock while
    holding a later-tier one is recorded and assert_clean raises."""
    check = SchedCheck(seed=0, order={"A._l": 0, "B._l": 1})
    a = _InstrumentedLock(threading.Lock(), "A._l", check)
    b = _InstrumentedLock(threading.Lock(), "B._l", check)
    with b:
        with a:
            pass
    assert [v.kind for v in check.violations] == ["order"]
    with pytest.raises(AssertionError, match="inverts the committed"):
        check.assert_clean()
    # The canonical direction is silent, including RLock re-entry.
    check = SchedCheck(seed=0, order={"A._l": 0, "B._l": 1})
    r = _InstrumentedLock(threading.RLock(), "A._l", check)
    with _InstrumentedLock(threading.Lock(), "A._l", check):
        pass
    with r, r:
        with _InstrumentedLock(threading.Lock(), "B._l", check):
            pass
    check.assert_clean()


def test_schedcheck_records_driver_crash_as_violation():
    """A dead driver thread is DATA (the dynamic signature of an
    unguarded structure), never a test-framework accident."""
    check = SchedCheck(seed=0)

    def boom():
        raise ValueError("planted")

    _run_threads(check, [("boom", boom), ("calm", lambda: None)])
    assert [v.kind for v in check.violations] == ["crash"]
    assert "planted" in check.violations[0].detail
    assert check.violations[0].thread == "boom"


def test_schedcheck_catches_a_real_iterate_while_mutate_race():
    """Detection power on the exact race class the router fix closed:
    an UNLOCKED dict iterated by one thread while another inserts and
    deletes crashes under the tightened switch interval within a few
    seeds — proving the fuzz drivers would catch a lock regression."""
    class Racy:
        def __init__(self):
            self.d = {i: i for i in range(64)}

        def writer(self):
            for i in range(40000):
                self.d[64 + (i % 67)] = i
                self.d.pop(64 + ((i * 7) % 67), None)

        def reader(self):
            for _ in range(40000):
                for _k in self.d:
                    pass

    for seed in range(10):
        check = SchedCheck(seed=seed)
        racy = Racy()
        _run_threads(check, [("w", racy.writer), ("r", racy.reader)])
        if check.violations:
            break
    assert check.violations, \
        "planted iterate-while-mutate race never crashed — the fuzz " \
        "harness has lost its detection power"
    assert check.violations[0].kind == "crash"
    assert "RuntimeError" in check.violations[0].detail


def test_schedcheck_wrap_lock_idempotent_and_tolerant():
    check1 = SchedCheck(seed=0)
    check2 = SchedCheck(seed=1)

    class Owner:
        pass

    o = Owner()
    o._lock = threading.Lock()
    schedcheck.wrap_lock(o, "_lock", "O._lock", check1)
    wrapped = o._lock
    assert isinstance(wrapped, _InstrumentedLock)
    # Re-wrapping (a fixture reused across seeds) keeps the wrapper but
    # re-points the collector at the new run.
    schedcheck.wrap_lock(o, "_lock", "O._lock", check2)
    assert o._lock is wrapped and wrapped._check is check2
    with o._lock:
        pass
    assert check2.acquires == 1 and check1.acquires == 0
    # A missing attribute is skipped, not an error — the drivers must
    # still run against an object that LOST its lock.
    schedcheck.wrap_lock(o, "_ghost", "O._ghost", check2)


def test_schedcheck_metrics_export():
    from nanosandbox_tpu.obs import MetricRegistry, render_prometheus
    check = fuzz_router(0, order=_order())
    check.assert_clean()
    reg = MetricRegistry()
    check.export_metrics(reg)
    page = render_prometheus(reg)
    assert "schedcheck_violations_total 0" in page
    assert "schedcheck_acquires_total" in page
    assert check.acquires > 0


# ------------------------------------------ schedcheck: fuzz the serve host

@pytest.mark.parametrize("seed", range(20))
def test_fuzz_router_clean(seed):
    """ISSUE 18 TP-1 regression: pre-lock this crashed with
    'dictionary changed size during iteration' within a handful of
    seeds; the locked router survives every seed."""
    fuzz_router(seed, order=_order()).assert_clean()


@pytest.fixture(scope="module")
def served_model():
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


@pytest.fixture(scope="module")
def engine_loop(served_model):
    from nanosandbox_tpu.serve import Engine
    from nanosandbox_tpu.serve.http import EngineLoop

    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, paged=True)
    loop = EngineLoop(eng)
    loop.start()
    yield loop
    loop.stop()
    loop.join(30)


@pytest.fixture(scope="module")
def fleet(served_model):
    from nanosandbox_tpu.serve import Fleet

    _, model, params = served_model
    return Fleet(model, params, n_replicas=2, num_slots=2, max_len=64)


@pytest.fixture(scope="module")
def pair(served_model):
    from nanosandbox_tpu.serve import DisaggPair

    _, model, params = served_model
    return DisaggPair(model, params, num_slots=4, max_len=64, paged=True)


# Quick CI subset runs in tier-1; the full >=20-seed sweeps ride the
# slow lane (same drivers, same shared fixture, more seeds).
QUICK_SEEDS = range(3)
FULL_SEEDS = range(3, 20)


@pytest.mark.parametrize("seed", QUICK_SEEDS)
def test_fuzz_engine_loop_clean(engine_loop, seed):
    schedcheck.fuzz_engine_loop(engine_loop, seed,
                                order=_order()).assert_clean()


@pytest.mark.slow
@pytest.mark.parametrize("seed", FULL_SEEDS)
def test_fuzz_engine_loop_clean_full(engine_loop, seed):
    schedcheck.fuzz_engine_loop(engine_loop, seed,
                                order=_order()).assert_clean()


@pytest.mark.parametrize("seed", QUICK_SEEDS)
def test_fuzz_fleet_clean(fleet, seed):
    schedcheck.fuzz_fleet(fleet, seed, order=_order()).assert_clean()


@pytest.mark.slow
@pytest.mark.parametrize("seed", FULL_SEEDS)
def test_fuzz_fleet_clean_full(fleet, seed):
    schedcheck.fuzz_fleet(fleet, seed, order=_order()).assert_clean()


@pytest.mark.parametrize("seed", QUICK_SEEDS)
def test_fuzz_disagg_clean(pair, seed):
    schedcheck.fuzz_disagg(pair, seed, order=_order()).assert_clean()


@pytest.mark.slow
@pytest.mark.parametrize("seed", FULL_SEEDS)
def test_fuzz_disagg_clean_full(pair, seed):
    schedcheck.fuzz_disagg(pair, seed, order=_order()).assert_clean()


def test_schedcheck_cli_router_smoke():
    assert schedcheck.main(["--target=router", "--seeds=3"]) == 0


# ------------------------------------------------ budgets stay untouched

def test_compile_set_and_sync_ledger_unchanged_by_instrumentation(
        served_model):
    """ISSUE 18 acceptance: schedcheck instrumentation is pure host
    Python — the compile set and the audited host-sync ledger of an
    instrumented engine are IDENTICAL to a plain one's on the same
    workload."""
    from nanosandbox_tpu.serve import Engine
    from nanosandbox_tpu.utils import tracecheck as _tracecheck

    _, model, params = served_model

    def run(instrument):
        mark = _tracecheck.sync_counts()
        eng = Engine(model, params, num_slots=2, max_len=64, paged=True)
        if instrument:
            schedcheck.instrument_engine(
                eng, SchedCheck(seed=0, order=_order()))
        for i in range(4):
            eng.submit([1 + i, 2], 5)
        eng.drain()
        return (eng.max_programs(), dict(eng.trace_counts),
                _tracecheck.sync_delta(mark))

    assert run(False) == run(True)
