"""Flight recorder, SLO ledger, deadline shedding and watchdog tests.

The contract under test (ISSUE 10):
  * every submitted request leaves a lifecycle track (submit -> queue ->
    [block events] -> admit -> prefill[hit|miss] -> retire* -> evict ->
    terminal) and EXACTLY ONE terminal event (finish | reject | shed)
    under fuzzed mixed workloads — the no-orphan pin, mirroring the
    PR 5 eviction/backfill zero-orphan span pin;
  * recording overhead < 50 us/event (the PR 5 tracer budget style) and
    zero new host syncs / zero new compiled programs with the recorder,
    SLO ledger and watchdogs all armed;
  * deadlines: a queued request whose deadline expires is shed (terminal
    'shed' Result, SLO outcome shed), finished requests land in the
    attainment/goodput ledger with deadline margins by class and
    prefix outcome;
  * watchdogs trip on forced anomalies, count on
    watchdog_trips_total{kind=}, and dump flight + trace + meta
    snapshots.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.obs import (TERMINAL_EVENTS, FlightRecorder, SLOLedger,
                                 MetricRegistry)
from nanosandbox_tpu.serve import Engine


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


# ------------------------------------------------------------- recorder

def test_recorder_ring_rid_filter_and_jsonl():
    rec = FlightRecorder(capacity=8)
    rec.record("submit", rid=1, step=0, prompt_len=3)
    rec.record("submit", rid=2, step=0, prompt_len=5)
    rec.record("finish", rid=1, step=4, reason="length", tokens=4)
    evs = rec.events(rid=1)
    assert [e["ev"] for e in evs] == ["submit", "finish"]
    assert evs[0]["prompt_len"] == 3 and evs[1]["reason"] == "length"
    # wall + relative timestamps ride every exported event
    assert all("wall" in e and e["t"] >= 0 for e in evs)
    assert rec.terminals(1) == ["finish"] and rec.terminals(2) == []
    # JSONL: one parseable object per line, schema keys present
    lines = rec.to_jsonl().splitlines()
    assert len(lines) == 3
    for ln in lines:
        e = json.loads(ln)
        assert {"t", "ev", "rid", "wall"} <= set(e)
    # bounded: old events rotate out, `recorded` keeps the true total
    for i in range(20):
        rec.record("retire", rid=9, n=1)
    assert len(rec.events()) == 8
    assert rec.stats()["recorded"] == 23
    assert rec.stats()["dropped"] == 15
    rec.clear()
    assert rec.events() == [] and rec.counts() == {}


def test_recorder_disabled_and_dump(tmp_path):
    rec = FlightRecorder(enabled=False)
    rec.record("submit", rid=1)
    assert rec.events() == []
    rec = FlightRecorder()
    rec.record("submit", rid=1)
    rec.record("finish", rid=1)
    p = str(tmp_path / "flight.jsonl")
    assert rec.dump(p) == 2
    with open(p) as f:
        assert [json.loads(ln)["ev"] for ln in f] == ["submit", "finish"]


def test_recorder_overhead_pinned():
    """< 50 us/event, median of 5 — the engine records ~1 event per
    retired token per row plus a handful per request lifecycle, so at
    this ceiling the ledger cannot move a tokens/sec bench by the 3%
    bar (the acceptance-criteria budget, same style as the PR 5 tracer
    pin)."""
    rec = FlightRecorder(capacity=4096)
    n = 2000
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        for i in range(n):
            rec.record("retire", rid=i & 7, step=i, n=1)
        runs.append((time.perf_counter() - t0) / n)
    runs.sort()
    assert runs[2] < 50e-6, f"record {runs[2] * 1e6:.1f}us/event"


# ------------------------------------------------------------ SLO ledger

def test_slo_ledger_attainment_goodput_and_reset():
    reg = MetricRegistry()
    led = SLOLedger(reg)
    assert led.record_finish("interactive", tokens=10, elapsed_s=0.5,
                             deadline_s=1.0, prefix="hit") is True
    assert led.record_finish("interactive", tokens=7, elapsed_s=2.0,
                             deadline_s=1.0, prefix="miss") is False
    led.record_shed("interactive")
    # deadline-less requests are not SLO-tracked at all
    assert led.record_finish("batch", tokens=3, elapsed_s=9.9,
                             deadline_s=None) is None
    st = led.stats()
    cls = st["classes"]["interactive"]
    assert (cls["met"], cls["missed"], cls["shed"]) == (1, 1, 1)
    assert cls["goodput_tokens"] == 10 and cls["late_tokens"] == 7
    assert cls["attainment"] == pytest.approx(1 / 3)
    assert "batch" not in st["classes"]
    assert st["overall"]["goodput_tokens"] == 10
    # mirrored families land on the scrape with real children only
    text = reg.prometheus_text()
    assert ('serve_slo_requests_total{slo_class="interactive",'
            'outcome="met"} 1') in text
    assert 'serve_goodput_tokens_total{slo_class="interactive"} 10' in text
    assert 'serve_slo_attainment{slo_class="interactive"}' in text
    assert ('serve_deadline_margin_seconds_bucket{slo_class='
            '"interactive",prefix="hit"') in text
    led.reset()
    assert led.stats()["overall"]["met"] == 0
    assert 'outcome="met"} 1' not in reg.prometheus_text()


def test_slo_class_validation(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    with pytest.raises(ValueError, match="slo_class"):
        eng.submit([1, 2], 2, slo_class="bad class!")
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit([1, 2], 2, deadline_s=-1.0)
    assert eng.rejected == {"bad_slo_class": 1, "bad_deadline": 1}
    rejects = [e for e in eng.flight.events() if e["ev"] == "reject"]
    assert [e["reason"] for e in rejects] == ["bad_slo_class",
                                              "bad_deadline"]


# -------------------------------------------------- engine lifecycle

def test_engine_lifecycle_track_order(served_model):
    """The canonical paged track: submit -> queue -> block_reserve ->
    admit -> prefill -> retire* -> evict -> finish, in order, with the
    retire count matching the generated tokens (first token comes from
    the prefill, so retires = tokens - 1)."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    rid = eng.submit([1, 2, 3], 5, deadline_s=60.0)
    res = {r.rid: r for r in eng.drain()}[rid]
    evs = [e["ev"] for e in eng.flight.events(rid=rid)]
    assert evs[:5] == ["submit", "queue", "block_reserve", "admit",
                       "prefill"]
    assert evs[-2:] == ["evict", "finish"]
    assert evs.count("retire") == len(res.tokens) - 1
    fin = [e for e in eng.flight.events(rid=rid) if e["ev"] == "finish"][0]
    assert fin["reason"] == "length" and fin["tokens"] == 5
    assert fin["deadline_met"] is True and fin["e2e_s"] > 0
    pre = [e for e in eng.flight.events(rid=rid) if e["ev"] == "prefill"][0]
    assert pre["prefix"] == "miss" and pre["suffix_tokens"] == 3


def test_engine_dense_track_and_zero_token(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, paged=False)
    rid = eng.submit([1, 2], 3)
    rid0 = eng.submit([1, 2], 0)           # zero-token fast path
    eng.drain()
    evs = [e["ev"] for e in eng.flight.events(rid=rid)]
    assert "block_reserve" not in evs and "block_stall" not in evs
    assert evs[:4] == ["submit", "queue", "admit", "prefill"]
    assert eng.flight.terminals(rid0) == ["finish"]


def test_deadline_shed_exactly_once(served_model):
    """A queued request whose deadline expires is shed with a terminal
    Result + flight event + SLO outcome, and never admitted."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    long = [eng.submit([5] * 10, 20) for _ in range(2)]   # occupy slots
    shed_rid = eng.submit([9, 9], 20, deadline_s=1e-6,
                          slo_class="interactive")
    time.sleep(0.005)
    out = {r.rid: r for r in eng.drain()}
    assert out[shed_rid].finish_reason == "shed"
    assert out[shed_rid].tokens == []
    assert eng.flight.terminals(shed_rid) == ["shed"]
    evs = [e["ev"] for e in eng.flight.events(rid=shed_rid)]
    assert "admit" not in evs and "block_reserve" not in evs
    assert eng.shed == 1
    assert eng.stats()["slo"]["classes"]["interactive"]["shed"] == 1
    for rid in long:                         # bystanders unaffected
        assert out[rid].finish_reason == "length"
        assert eng.flight.terminals(rid) == ["finish"]
    # the shed queued-span closed: no orphans
    assert eng.tracer.open_count() == 0
    # counted on the scrape
    text = eng.metrics.prometheus_text()
    assert "serve_requests_shed_total 1" in text


def test_no_deadline_never_sheds(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=64)
    rids = [eng.submit([3, 4], 6) for _ in range(6)]
    out = {r.rid: r for r in eng.drain()}
    assert all(out[r].finish_reason == "length" for r in rids)
    assert eng.shed == 0


def test_default_deadline_applies(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 default_deadline_s=60.0)
    rid = eng.submit([1, 2], 3)
    eng.drain()
    sub = [e for e in eng.flight.events(rid=rid) if e["ev"] == "submit"][0]
    assert sub["deadline_s"] == 60.0
    assert eng.stats()["slo"]["classes"]["default"]["met"] == 1
    with pytest.raises(ValueError, match="default_deadline_s"):
        Engine(model, params, num_slots=2, max_len=64,
               default_deadline_s=0.0)


# --------------------------------------------------- no-orphan fuzzing

@pytest.mark.parametrize("paged", [True, False])
def test_every_outcome_exactly_once_fuzzed(served_model, paged):
    """The acceptance pin: under a fuzzed mixed workload — valid
    requests with and without deadlines, zero-token fast paths, eos
    finishes, rejects, tiny deadlines that shed, more requests than
    slots (eviction + backfill) — every rid gets EXACTLY one terminal
    flight event, rejects are ledgered, and no span leaks open."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, paged=paged,
                 flight=None)
    rng = np.random.default_rng(7)
    rids, n_rejects = [], 0
    results = []
    for i in range(40):
        kind = rng.random()
        try:
            if kind < 0.08:                      # invalid: empty prompt
                eng.submit([], 4)
            elif kind < 0.16:                    # invalid: over budget
                eng.submit([1] * 10, 100)
            elif kind < 0.24:                    # zero-token fast path
                rids.append(eng.submit([1, 2], 0))
            elif kind < 0.34:                    # sheddable deadline
                rids.append(eng.submit(
                    rng.integers(0, 50, 4).tolist(), 12,
                    deadline_s=1e-6, slo_class="tight"))
            elif kind < 0.5:                     # eos-prone (tiny vocab)
                rids.append(eng.submit(
                    rng.integers(0, 4, 3).tolist(), 10,
                    temperature=1.0, seed=i, eos_id=2, deadline_s=30.0))
            else:                                # plain mixed
                rids.append(eng.submit(
                    rng.integers(0, 50,
                                 int(rng.integers(1, 20))).tolist(),
                    int(rng.integers(1, 10)),
                    deadline_s=30.0 if rng.random() < 0.5 else None))
        except ValueError:
            n_rejects += 1
        if rng.random() < 0.4:
            results.extend(eng.step())
    results.extend(eng.drain())
    assert {r.rid for r in results} == set(rids)
    for rid in rids:
        terms = eng.flight.terminals(rid)
        assert len(terms) == 1, (rid, terms)
        assert terms[0] in TERMINAL_EVENTS
    by_rid = {r.rid: r for r in results}
    for rid in rids:
        want = {"shed": "shed"}.get(by_rid[rid].finish_reason, "finish")
        assert eng.flight.terminals(rid) == [want]
    reject_events = [e for e in eng.flight.events()
                     if e["ev"] == "reject"]
    assert len(reject_events) == n_rejects == sum(eng.rejected.values())
    assert eng.tracer.open_count() == 0
    # SLO ledger totals agree with the results list
    slo = eng.stats()["slo"]["overall"]
    n_shed = sum(1 for r in results if r.finish_reason == "shed")
    assert slo["shed"] == n_shed == eng.shed
    if paged:
        eng.block_pool.check([])                 # pool partition intact


def test_spec_engine_outcomes_exactly_once(served_model):
    """The spec verify path records per-retire accepted counts and the
    same exactly-once terminals."""
    from nanosandbox_tpu.serve.drafters import NGramDrafter

    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 spec=NGramDrafter(k=3))
    rids = [eng.submit([1, 2, 3, 1, 2, 3, 1, 2], 8, deadline_s=30.0)
            for _ in range(4)]
    eng.drain()
    for rid in rids:
        assert eng.flight.terminals(rid) == ["finish"]
        retires = [e for e in eng.flight.events(rid=rid)
                   if e["ev"] == "retire"]
        assert retires and all("accepted" in e for e in retires)
        assert sum(e["n"] for e in retires) == 7   # 8 minus prefill token
    assert eng.tracer.open_count() == 0


# ------------------------------------------------------------ watchdogs

def test_watchdog_ttft_spike_trips_and_dumps(served_model, tmp_path):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 watchdog_dir=str(tmp_path))
    wd = eng.watchdog
    wd.ttft_min_samples = 4
    wd.ttft_min_s = 0.0
    eng.submit([1, 2], 2)
    eng.drain()                                    # real traffic first
    for _ in range(8):
        wd.on_ttft(0.010)
    wd.on_ttft(0.500)                              # 50x the baseline
    assert wd.trips == {"ttft_spike": 1}
    assert eng.stats()["watchdog"]["trips"]["ttft_spike"] == 1
    text = eng.metrics.prometheus_text()
    assert 'watchdog_trips_total{kind="ttft_spike"} 1' in text
    dump = wd.last_trip["dump"]
    assert dump is not None and dump.startswith(str(tmp_path))
    # Files carry the trip kind (the ISSUE-11 dump-race fix): two
    # near-simultaneous trips of different kinds can never claim each
    # other's snapshot files.
    with open(os.path.join(dump, "flight-ttft_spike.jsonl")) as f:
        lines = [json.loads(ln) for ln in f]
    assert any(e["ev"] == "finish" for e in lines)
    with open(os.path.join(dump, "trace-ttft_spike.json")) as f:
        assert "traceEvents" in json.load(f)
    with open(os.path.join(dump, "meta-ttft_spike.json")) as f:
        meta = json.load(f)
    assert meta["trip"]["kind"] == "ttft_spike"
    # cooldown: an immediate second trip counts but does not re-dump
    wd.on_ttft(0.500)
    assert wd.trips["ttft_spike"] == 2
    assert "dump" not in wd.last_trip


def test_watchdog_stuck_slot_and_stall(served_model, tmp_path):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 kv_pool_blocks=5, watchdog_dir=str(tmp_path))
    wd = eng.watchdog
    wd.stuck_slot_s = 0.0                     # any active slot is "stuck"
    wd.check_interval_steps = 1
    wd.stall_trip_steps = 1                   # first stalled poll trips
    eng.submit([1] * 16, 40)                  # needs 4 of 5 blocks
    eng.submit([2] * 16, 40)                  # stalls on blocks
    eng.step()
    eng.step()
    assert wd.trips.get("stuck_slot", 0) >= 1
    assert wd.trips.get("admission_stall", 0) >= 1
    eng.drain()


def test_watchdog_post_steady_retrace(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    eng.submit([1, 2], 3)
    eng.drain()
    eng.watchdog.check_interval_steps = 1
    eng.watchdog.mark_steady()
    eng.step()
    assert "post_freeze_retrace" not in eng.watchdog.trips
    # a NEW shape (bigger admission wave) compiles post-steady -> page
    eng.submit([1, 2], 4)
    eng.submit([3, 4], 4)
    eng.drain()
    assert eng.watchdog.trips.get("post_freeze_retrace", 0) >= 1


def test_obs_off_engine_matches_budgets(served_model):
    """Observability adds ZERO compiled programs: max_programs() and
    the observed trace counts are identical with the recorder +
    watchdogs fully disabled."""
    _, model, params = served_model

    def run(**kw):
        eng = Engine(model, params, num_slots=2, max_len=64, **kw)
        for i in range(4):
            eng.submit([1 + i, 2], 5, deadline_s=30.0)
        eng.drain()
        return eng.max_programs(), dict(eng.trace_counts)

    on = run()
    off = run(flight=FlightRecorder(enabled=False), watchdogs=False)
    assert on == off


# --------------------------------------------------------- debug views

def test_debug_slots_kvpool_scheduler_shapes(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    eng.submit([1, 2, 3], 8, deadline_s=30.0, slo_class="interactive")
    eng.submit([4, 5], 8)
    eng.submit([6] * 5, 8, deadline_s=45.0)        # queued (slots full)
    eng.step()
    slots = eng.debug_slots()
    json.dumps(slots)
    assert slots["active"] == 2 and slots["num_slots"] == 2
    active = [s for s in slots["slots"] if s["state"] == "active"]
    assert {s["rid"] for s in active} == {0, 1}
    assert active[0]["slo_class"] == "interactive"
    assert active[0]["tokens"] >= 1 and active[0]["age_s"] >= 0
    sched = eng.debug_scheduler()
    json.dumps(sched)
    assert sched["queued"] == 1
    q = sched["queue"][0]
    assert q["rid"] == 2 and q["deadline_s"] == 45.0
    assert q["expired"] is False and q["waited_s"] >= 0
    pool = eng.debug_kvpool()
    json.dumps(pool)
    assert pool["paged"] is True
    frag = pool["fragmentation"]
    assert 0.0 <= frag["internal"] <= 1.0
    assert frag["reserved_positions"] >= frag["used_positions"] > 0
    assert len(pool["live_requests"]) == 2
    assert pool["trie"]["enabled"] is True
    eng.drain()
    pool = eng.debug_kvpool()
    assert pool["trie"]["nodes"] >= 0
    dense = Engine(model, params, num_slots=2, max_len=64, paged=False)
    assert dense.debug_kvpool() == {"paged": False}


def test_debug_kvpool_trie_occupancy_after_donation(served_model):
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    prompt = list(np.random.default_rng(1).integers(0, 50, 40))
    eng.submit([int(t) for t in prompt], 4)
    eng.drain()
    pool = eng.debug_kvpool()
    assert pool["trie"]["nodes"] == 2              # 40 // 16 donated
    assert pool["trie"]["cached_tokens"] == 32
    assert pool["trie"]["max_depth"] >= 1
    assert sum(pool["trie"]["depth_histogram"].values()) == 2


def test_watchdog_detectors_survive_ledger_reset(served_model):
    """reset_latency_stats() zeros the pool's stall/eviction counters;
    the watchdog marks must resync (counter moved backwards) instead of
    staying stale-high and blinding the detectors from the moment
    production measurement begins."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, kv_pool_blocks=5)
    wd = eng.watchdog
    wd.check_interval_steps = 1
    wd.stall_trip_steps = 1
    wd.stuck_slot_s = 1e9                    # isolate the stall detector
    eng.submit([1] * 16, 40)
    eng.submit([2] * 16, 40)                 # stalls on blocks
    eng.step()
    eng.step()
    trips_before = wd.trips.get("admission_stall", 0)
    assert trips_before >= 1
    eng.drain()
    eng.reset_latency_stats()                # zeros pool.stall_steps
    assert eng.block_pool.stall_steps == 0
    eng.submit([1] * 16, 40)
    eng.submit([2] * 16, 40)                 # stalls again, from zero
    eng.step()
    eng.step()
    assert wd.trips["admission_stall"] > trips_before
    eng.drain()
