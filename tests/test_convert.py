"""Pretrained GPT-2 import (the reference's `--init_from=gpt2*` path).

Zero-egress testing strategy: build a RANDOMLY initialized HF
GPT2LMHeadModel (transformers + torch-cpu are in the image), convert its
state_dict, and demand logits parity between the HF forward and this
model's forward — which pins every mapping detail at once (packing order,
kernel orientation, gelu variant, LayerNorm eps, tied head). The real
pretrained weights flow through the identical code path.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from nanosandbox_tpu.models.convert import (gpt_config_from_hf,  # noqa: E402
                                            params_from_hf_state_dict,
                                            resolve_init_from)
from nanosandbox_tpu.models.gpt import GPT  # noqa: E402


def _hf_model(n_layer=2, n_head=2, n_embd=64, vocab=128, n_positions=64,
              seed=0):
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(seed)
    cfg = GPT2Config(n_layer=n_layer, n_head=n_head, n_embd=n_embd,
                     vocab_size=vocab, n_positions=n_positions,
                     resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    return GPT2LMHeadModel(cfg).eval()


def test_logits_match_hf_forward():
    hf = _hf_model()
    cfg = gpt_config_from_hf(hf.config, compute_dtype="float32")
    params = params_from_hf_state_dict(hf.state_dict(), cfg.n_layer)

    rng = np.random.default_rng(0)
    x = rng.integers(0, hf.config.vocab_size, size=(2, 48))
    with torch.no_grad():
        ref = hf(torch.from_numpy(x)).logits.numpy()
    ours = GPT(cfg).apply({"params": params}, jnp.asarray(x, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4, rtol=2e-4)


def test_resolve_init_from():
    assert resolve_init_from("gpt2") == "gpt2"
    assert resolve_init_from("gpt2-xl") == "gpt2-xl"
    assert resolve_init_from("hf:/data/models/gpt2") == "/data/models/gpt2"
    assert resolve_init_from("scratch") is None
    assert resolve_init_from("resume") is None
    assert resolve_init_from("auto") is None


def test_trainer_finetunes_from_local_hf_dir(char_dataset, tmp_path):
    """init_from=hf:<path>: the Trainer adopts the pretrained
    architecture, starts from the converted weights, and the loss
    decreases — the fine-tune workflow end-to-end, offline."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    hf = _hf_model(vocab=128)  # >= the char dataset's vocab
    hf_dir = tmp_path / "hf_gpt2"
    hf.save_pretrained(hf_dir, safe_serialization=True)

    cfg = TrainConfig(
        data_dir=char_dataset, dataset="shakespeare_char",
        out_dir=str(tmp_path / "out"), init_from=f"hf:{hf_dir}",
        # deliberately different from the HF config: must be overridden
        n_layer=5, n_head=3, n_embd=48, block_size=32,  # block cropped
        batch_size=8, max_iters=8, lr_decay_iters=8, warmup_iters=1,
        eval_interval=0, log_interval=4, learning_rate=3e-4,
        dropout=0.0, compute_dtype="float32", device="cpu",
        tensorboard=False)
    trainer = Trainer(cfg)
    # architecture forced from the pretrained config (nanoGPT behavior)
    assert trainer.model_cfg.n_layer == 2
    assert trainer.model_cfg.n_embd == 64
    assert trainer.model_cfg.vocab_size == 128
    assert trainer.model_cfg.bias is True
    assert trainer.model_cfg.block_size == 32  # cropped wpe

    state = trainer.pretrained_state()
    # the state really is the converted weights, sharded
    wte = np.asarray(jax.device_get(state["params"]["wte"]["embedding"]))
    np.testing.assert_allclose(
        wte, hf.state_dict()["transformer.wte.weight"].numpy(), atol=1e-6)

    step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    losses = []
    for _ in range(8):
        xb, yb = next(loader)
        state, m = step(state, trainer.to_global(xb), trainer.to_global(yb),
                        jax.random.key(0))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_block_size_growth_rejected(char_dataset, tmp_path):
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    hf = _hf_model(n_positions=64)
    hf_dir = tmp_path / "hf_gpt2"
    hf.save_pretrained(hf_dir, safe_serialization=True)
    cfg = TrainConfig(data_dir=char_dataset, dataset="shakespeare_char",
                      out_dir=str(tmp_path / "out"),
                      init_from=f"hf:{hf_dir}", block_size=128,
                      device="cpu", tensorboard=False)
    with pytest.raises(ValueError, match="pretrained context"):
        Trainer(cfg)


def test_variant_configs_rejected():
    """hf: paths accept arbitrary GPT2Configs — numerics this model does
    not implement must fail at conversion, not corrupt the forward."""
    from transformers import GPT2Config

    exact_gelu = GPT2Config(n_layer=1, n_head=1, n_embd=32,
                            activation_function="gelu")
    with pytest.raises(ValueError, match="gelu_new"):
        gpt_config_from_hf(exact_gelu)
    odd_eps = GPT2Config(n_layer=1, n_head=1, n_embd=32,
                         layer_norm_epsilon=1e-6)
    with pytest.raises(ValueError, match="layer_norm_epsilon"):
        gpt_config_from_hf(odd_eps)


def test_empty_hf_path_is_not_pretrained(char_dataset, tmp_path):
    """init_from='hf:' (malformed empty path) must not half-enter the
    pretrained flow."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    cfg = TrainConfig(data_dir=char_dataset, dataset="shakespeare_char",
                      out_dir=str(tmp_path / "out"), init_from="hf:",
                      n_layer=1, n_head=2, n_embd=32, block_size=16,
                      batch_size=8, device="cpu", tensorboard=False)
    trainer = Trainer(cfg)
    assert trainer._pretrained is False
    assert trainer.model_cfg.n_layer == 1  # user dims kept


def test_export_roundtrip_logits_parity(tmp_path):
    """Our params -> export_hf_gpt2 -> GPT2LMHeadModel.from_pretrained:
    torch forward must reproduce our logits. Covers the bias=True path
    (import-shaped params) AND the vocab-crop."""
    from nanosandbox_tpu.models.convert import export_hf_gpt2
    from transformers import GPT2LMHeadModel

    hf = _hf_model(vocab=128)
    cfg = gpt_config_from_hf(hf.config, compute_dtype="float32")
    params = params_from_hf_state_dict(hf.state_dict(), cfg.n_layer)

    dest = export_hf_gpt2(params, cfg, str(tmp_path / "hf"), vocab_size=120)
    back = GPT2LMHeadModel.from_pretrained(dest).eval()
    assert back.config.vocab_size == 120

    rng = np.random.default_rng(3)
    x = rng.integers(0, 120, size=(2, 32))
    with torch.no_grad():
        theirs = back(torch.from_numpy(x)).logits.numpy()
    ours = GPT(cfg).apply({"params": params}, jnp.asarray(x, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours)[..., :120], theirs,
                               atol=2e-4, rtol=2e-4)


def test_export_biasfree_checkpoint(tmp_path):
    """The DEFAULT config trains bias=False; export writes zero biases
    (mathematically identical) and the HF model still reproduces logits."""
    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.convert import export_hf_gpt2
    from transformers import GPT2LMHeadModel

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=64,
                    vocab_size=128, bias=False, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    dest = export_hf_gpt2(params, cfg, str(tmp_path / "hf"))
    back = GPT2LMHeadModel.from_pretrained(dest).eval()

    rng = np.random.default_rng(4)
    x = rng.integers(0, 128, size=(2, 32))
    with torch.no_grad():
        theirs = back(torch.from_numpy(x)).logits.numpy()
    ours = model.apply({"params": params}, jnp.asarray(x, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), theirs,
                               atol=2e-4, rtol=2e-4)


def test_export_cli_from_checkpoint(char_dataset, tmp_path):
    """End to end: train 2 iters -> checkpoint -> module CLI -> HF dir ->
    re-import through our own `hf:` path (the fully-offline round trip)."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.models import convert as convert_mod
    from nanosandbox_tpu.train import Trainer

    out = str(tmp_path / "run")
    cfg = TrainConfig(
        out_dir=out, data_dir=char_dataset, dataset="shakespeare_char",
        n_layer=2, n_head=2, n_embd=64, block_size=64, batch_size=8,
        max_iters=2, eval_interval=0, eval_iters=2, log_interval=1,
        warmup_iters=1, lr_decay_iters=2, compute_dtype="float32",
        tensorboard=False, device="cpu")
    Trainer(cfg).run()

    dest = convert_mod.main(["--out_dir", out, "--to",
                             str(tmp_path / "hf_export")])
    cfg2, params2 = __import__(
        "nanosandbox_tpu.models.convert", fromlist=["load_hf_gpt2"]
    ).load_hf_gpt2(dest)
    assert cfg2.n_layer == 2 and cfg2.n_embd == 64
    assert params2["wte"]["embedding"].shape[0] == cfg2.vocab_size
