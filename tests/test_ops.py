"""Ops-layer tests: gh_sync dry run, issue templates, CI workflow.

The reference's ops layer is gh_sync.ps1 + three issue forms (SURVEY.md
§2.1 #3-6). gh_sync.sh is the bash port; DRY_RUN=1 exercises its full
control flow — slug fallback, 27 labels, 11 issues — without the gh CLI.
"""

import os
import subprocess

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_gh_sync_dry_run():
    out = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "gh_sync.sh")],
        env={**os.environ, "DRY_RUN": "1"},
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    lines = out.stdout.splitlines()
    label_posts = [l for l in lines if "repos/" in l and "/labels" in l]
    issue_creates = [l for l in lines if "issue create" in l]
    # 24+ labels (reference had 24; we add TPU-specific ones), 11 issues
    assert len(label_posts) >= 24, f"only {len(label_posts)} label ops"
    assert len(issue_creates) == 11, f"{len(issue_creates)} issues"
    assert "Done." in out.stdout
    # TPU retargeting: no GPU-flavored labels survive
    assert "area:tpu" in out.stdout
    assert "area:gpu" not in out.stdout


def _load(rel):
    with open(os.path.join(REPO, rel)) as f:
        return yaml.safe_load(f)


def test_issue_templates_valid():
    for name in ("task", "bug_report", "feature_request"):
        doc = _load(f".github/ISSUE_TEMPLATE/{name}.yml")
        assert doc["name"]
        assert isinstance(doc["body"], list) and doc["body"]
        ids = [b.get("id") for b in doc["body"] if b.get("id")]
        assert len(ids) == len(set(ids)), f"duplicate ids in {name}"


def test_task_template_requires_acceptance_criteria():
    """The acceptance-criteria requirement is the reference's
    verification-as-process mechanism (task.yml:12-21) — keep it required."""
    doc = _load(".github/ISSUE_TEMPLATE/task.yml")
    acc = next(b for b in doc["body"] if b.get("id") == "acceptance")
    assert acc["validations"]["required"] is True


def test_feature_template_area_taxonomy():
    doc = _load(".github/ISSUE_TEMPLATE/feature_request.yml")
    area = next(b for b in doc["body"] if b.get("id") == "area")
    opts = area["attributes"]["options"]
    assert "area:tpu" in opts and "area:gpu" not in opts
    assert {"area:k8s", "area:data", "area:training", "area:monitoring",
            "area:ci", "area:docker"} <= set(opts)


def test_ci_workflow_valid():
    doc = _load(".github/workflows/ci.yml")
    # yaml parses the `on:` key as boolean True
    assert "jobs" in doc and ("on" in doc or True in doc)
    assert {"lint", "test"} <= set(doc["jobs"])
    steps = " ".join(str(s) for j in doc["jobs"].values()
                     for s in j.get("steps", []))
    assert "pytest" in steps and "shellcheck" in steps
