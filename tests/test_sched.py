"""Overload-robust scheduling tests (ISSUE 13).

The contract under test:
  * Priority queue: higher Request.priority admits first, FIFO within a
    class; priorities default from slo_class (interactive > default >
    batch) and an explicit priority overrides.
  * Chunked prefill: with prefill_chunk set, per-step prefill work is
    budgeted and long (suffix) prompts split into bucket-shaped chunks
    interleaved with decode steps — outputs token-identical to the
    unchunked engine, compile set NOT widened (max_programs identical,
    every chunk rides the existing (rung, bucket) grid), prefix hits
    shrink the chunk pipeline, and a crash mid-chunk recovers through
    the normal requeue path.
  * Preemption-by-eviction: a deadline-pressed higher-priority head
    evicts the lowest-priority victim; the victim's prompt+generated
    blocks donate to the radix cache, it requeues as prompt' = prompt +
    tokens-so-far, and its final greedy output is token-identical to an
    unpreempted twin — across paged/dense x spec on/off, including a
    victim preempted twice and a victim shed before re-admission.
    Preemption leaves a `preempt` flight event (salvaged tokens +
    donated blocks) and never a terminal.
  * Brownout ladder: sustained SLO burn steps through shrink_scan ->
    no_spec -> shed_batch -> interactive_only with hysteresis; each
    transition is a flight/metrics event; effects reverse on clearing.
  * retry_after_s is priority-aware and the scheduling machinery adds
    zero compiled programs and zero audited host syncs.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.obs import render_prometheus
from nanosandbox_tpu.serve import (PRIORITY_BY_CLASS, Engine,
                                   EngineSupervisor, FaultPlan,
                                   NGramDrafter, SlotScheduler)
from nanosandbox_tpu.utils import tracecheck as _tracecheck


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _mixed(eng, vocab, n=6, seed=3, long_every=2, budget=None,
           long_len=60):
    """Deterministic greedy mix with some long prompts (the chunked
    lane's food) — same stream for every engine fed the same seed."""
    rng = np.random.default_rng(seed)
    rids = []
    for i in range(n):
        L = long_len if i % long_every == 0 else int(rng.integers(1, 40))
        mnt = budget if budget is not None else int(rng.integers(2, 5))
        cls = "interactive" if i % 2 == 0 else "batch"
        rids.append(eng.submit(rng.integers(0, vocab, L).tolist(), mnt,
                               slo_class=cls))
    return rids


def _drive(stepper, engine, limit=5000):
    got = {}
    n = 0
    while engine.has_work() and n < limit:
        for r in stepper.step():
            got[r.rid] = r
        n += 1
    assert n < limit, "engine failed to drain"
    return got


# Greedy outputs are invariant across paged/dense/spec/chunked engines
# (each pinned in its own suite), so every twin comparison here can
# share ONE reference run per workload — computed on a plain default
# engine and cached for the module.
_WANT_CACHE: dict = {}


def _want(served_model, **kw):
    key = tuple(sorted(kw.items()))
    if key not in _WANT_CACHE:
        cfg, model, params = served_model
        eng = Engine(model, params, num_slots=4, max_len=64)
        _mixed(eng, cfg.vocab_size, **kw)
        _WANT_CACHE[key] = {
            r.rid: (r.prompt, r.tokens, r.finish_reason)
            for r in eng.drain()}
    return _WANT_CACHE[key]


# --------------------------------------------------------- priority queue

def test_priority_queue_ordering_fifo_within_class():
    class Item:
        def __init__(self, rid, priority):
            self.rid, self.priority, self.prompt = rid, priority, (0,) * 3

    s = SlotScheduler(4, [16, 32])
    for rid, p in [(0, 0), (1, 2), (2, 1), (3, 2), (4, 0), (5, 1)]:
        s.enqueue(Item(rid, p))
    assert [it.rid for it in s.queued_items()] == [1, 3, 2, 5, 0, 4]
    # requeue_front jumps the victim's own CLASS but not higher
    # priorities (the recovery contract: no FIFO inversion within the
    # class, no head-of-line blocking of more urgent traffic — the
    # queue stays priority-sorted so peek_head is the most urgent item)
    s.requeue_front([Item(9, 0)])
    assert [it.rid for it in s.queued_items()] == [1, 3, 2, 5, 9, 0, 4]
    s.requeue_front([Item(8, 1)])
    assert [it.rid for it in s.queued_items()] == [1, 3, 8, 2, 5, 9, 0, 4]
    # items without .priority share one class (plain FIFO)
    s2 = SlotScheduler(2, [16])
    for rid in (0, 1, 2):
        class Bare:
            def __init__(self, rid):
                self.rid, self.prompt = rid, (0,)
        s2.enqueue(Bare(rid))
    assert [it.rid for it in s2.queued_items()] == [0, 1, 2]


def test_priority_defaults_from_class_and_override(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=64)
    eng.submit([1, 2], 30)                 # occupy the only slot
    eng.step()
    eng.submit([1, 3], 2, slo_class="batch")
    eng.submit([1, 4], 2, slo_class="interactive")
    eng.submit([1, 5], 2, slo_class="batch", priority=9)   # override
    q = eng.sched.queued_items()
    assert [it.priority for it in q] == [9, 2, 0]
    assert PRIORITY_BY_CLASS["interactive"] > PRIORITY_BY_CLASS["batch"]
    # unknown classes land on the default priority
    eng.submit([1, 6], 2, slo_class="bulk9")
    assert eng.sched.queued_items()[-2].priority == 1
    eng.drain()


# -------------------------------------------------------- chunked prefill

@pytest.mark.parametrize("spec", [False, True])
def test_chunked_prefill_parity_and_closed_compile_set(served_model,
                                                       spec):
    """Chunked vs unchunked twins on the same stream (incl. max-length
    prompts): token-identical outputs, identical max_programs(), chunk
    events in the ledger, and trace counts inside the published
    budget."""
    cfg, model, params = served_model

    def build(chunk):
        kw = dict(num_slots=4, max_len=64, prefill_chunk=chunk)
        if spec:
            kw["spec"] = NGramDrafter(k=3)
        return Engine(model, params, **kw)

    want = {rid: w[1:] for rid, w in
            _want(served_model, n=8).items()}
    chunked = build(16)
    _mixed(chunked, cfg.vocab_size, n=8)
    got = {r.rid: (r.tokens, r.finish_reason) for r in chunked.drain()}
    assert got == want
    assert chunked.max_programs() == build(None).max_programs()
    chunks = [e for e in chunked.flight.events()
              if e["ev"] == "prefill_chunk"]
    assert chunks and all(e["n"] <= 16 for e in chunks)
    budget = chunked.max_programs()
    for kind, n in chunked.trace_counts.items():
        assert n <= budget[kind], (kind, n, budget)


def test_chunked_prefill_interleaves_decode(served_model):
    """THE point of chunking: while a max-length prompt chunk-prefills,
    an active decoder keeps retiring tokens BETWEEN its chunks instead
    of stalling for the whole wave."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 prefill_chunk=16)
    dec = eng.submit([1, 2, 3], 30)
    for _ in range(4):
        eng.step()
    storm = eng.submit(list(np.random.default_rng(0).integers(
        0, cfg.vocab_size, 60)), 2)
    eng.drain()
    evs = eng.flight.events()
    chunk_ts = [e["t"] for e in evs
                if e["ev"] == "prefill_chunk" and e["rid"] == storm]
    assert len(chunk_ts) >= 3, "long prompt did not chunk"
    dec_retires = [e["t"] for e in evs
                   if e["ev"] == "retire" and e.get("rid") == dec]
    between = [t for t in dec_retires
               if chunk_ts[0] < t < chunk_ts[-1]]
    assert between, "decode never interleaved with the chunk pipeline"


def test_chunked_prefix_hit_shrinks_pipeline(served_model):
    """A resident prefix skips its chunks: the second submission of a
    long prompt chunk-prefills only the suffix (fewer chunk events) and
    produces the identical output (hit == cold, chunked or not)."""
    cfg, model, params = served_model
    prompt = np.random.default_rng(7).integers(
        0, cfg.vocab_size, 60).tolist()
    eng = Engine(model, params, num_slots=2, max_len=64,
                 prefill_chunk=16)
    eng.submit(prompt, 3)
    cold = eng.drain()[0].tokens
    n_cold = len([e for e in eng.flight.events()
                  if e["ev"] == "prefill_chunk"])
    eng.flight.clear()
    eng.submit(prompt, 3)
    hit = eng.drain()[0].tokens
    n_hit = len([e for e in eng.flight.events()
                 if e["ev"] == "prefill_chunk"])
    assert hit == cold
    assert n_hit < n_cold
    hits = [e for e in eng.flight.events()
            if e["ev"] == "prefill" and e["prefix"] == "hit"]
    assert hits and hits[0]["hit_tokens"] > 0


def test_chunk_must_be_a_bucket(served_model):
    cfg, model, params = served_model
    with pytest.raises(ValueError, match="prefill_chunk"):
        Engine(model, params, num_slots=2, max_len=64, prefill_chunk=17)


def test_recovery_mid_chunk_restitches(served_model):
    """A prefill crash landing INSIDE the chunk pipeline unwinds like
    mid-wave limbo — blocks freed without donation, the request
    re-chunks from scratch — and every output matches the clean twin
    token for token."""
    cfg, model, params = served_model
    want = {rid: w[1] for rid, w in
            _want(served_model, n=6, seed=11).items()}
    # Fire prefill_exc on a mid-pipeline chunk dispatch (visit-counted,
    # so the schedule is deterministic for this stream).
    plan = FaultPlan.parse("prefill_exc@2")
    eng = Engine(model, params, num_slots=2, max_len=64,
                 prefill_chunk=16, faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    _mixed(eng, cfg.vocab_size, n=6, seed=11)
    got = {rid: r.tokens for rid, r in _drive(sup, eng).items()}
    assert plan.fired_log and eng.recoveries >= 1
    assert got == want
    eng.block_pool.check([st.alloc for st in eng._active.values()
                          if st.alloc is not None])
    for rid in got:
        assert eng.flight.terminals(rid) == ["finish"], rid


# ------------------------------------------------------------- preemption

@pytest.mark.parametrize("paged,spec", [(True, False), (False, False),
                                        (True, True), (False, True)])
def test_preempt_resume_parity_incl_double(served_model, paged, spec):
    """preempt_storm evicts the same victim twice mid-decode; outputs
    stay token-identical to a clean twin (resume = re-prefill of
    prompt + tokens-so-far under position-keyed sampling), with one
    terminal per request and zero orphaned evicts."""
    cfg, model, params = served_model

    def build(faults=None):
        kw = dict(num_slots=4, max_len=64, paged=paged, faults=faults)
        if spec:
            kw["spec"] = NGramDrafter(k=3)
        return Engine(model, params, **kw)

    # n == num_slots: the preempted victim re-admits into the slot it
    # just freed before the storm's next firing, so the SAME victim is
    # deterministically evicted twice.
    want = {rid: w[:2] for rid, w in
            _want(served_model, n=4, seed=5, budget=12,
                  long_len=48).items()}
    plan = FaultPlan.parse("preempt_storm@2x2")
    eng = build(faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    rids = _mixed(eng, cfg.vocab_size, n=4, seed=5, budget=12,
                  long_len=48)
    got = {rid: (r.prompt, r.tokens)
           for rid, r in _drive(sup, eng).items()}
    assert plan.fired_log and eng.preemptions >= 2
    assert got == want
    pre = [e for e in eng.flight.events() if e["ev"] == "preempt"]
    assert pre and all("salvaged_tokens" in e and "donated_blocks" in e
                       for e in pre)
    per_rid: dict = {}
    for e in pre:
        per_rid[e["rid"]] = per_rid.get(e["rid"], 0) + 1
    if not spec:
        # Plain decode retires one token/step, so the first victim is
        # still mid-flight at the second firing: the SAME victim is
        # evicted twice. (Spec rounds retire up to k+1 tokens/step and
        # may finish the first victim in between — two single-victim
        # evictions are equally valid there.)
        assert max(per_rid.values()) >= 2, per_rid
    for rid in rids:
        assert eng.flight.terminals(rid) == ["finish"], rid
        evicts = [e for e in eng.flight.events()
                  if e.get("rid") == rid and e["ev"] == "evict"]
        assert len(evicts) <= 1


def test_natural_deadline_preemption_and_donation(served_model):
    """The policy path: a deadline-carrying interactive head blocked on
    slots evicts the lowest-priority batch victim; the victim's
    generated blocks donate (the preempt event says how many), both
    finish, and the victim's stitched output matches an unpreempted
    twin."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, kv_page_size=4)
    v1 = eng.submit(list(range(1, 9)), 40, slo_class="batch")
    v2 = eng.submit(list(range(2, 10)), 40, slo_class="batch")
    for _ in range(8):
        eng.step()
    hi = eng.submit([7, 8, 9], 4, slo_class="interactive",
                    deadline_s=0.05)
    res = _drive(eng, eng, limit=8000)
    assert eng.preemptions >= 1
    pre = [e for e in eng.flight.events() if e["ev"] == "preempt"][0]
    assert pre["cause"] == "deadline"
    # 8-token prompt + >=8 generated at page 4 -> donated full blocks
    assert pre["donated_blocks"] >= 1
    twin = Engine(model, params, num_slots=4, max_len=64)
    t1 = twin.submit(list(range(1, 9)), 40)
    t2 = twin.submit(list(range(2, 10)), 40)
    t3 = twin.submit([7, 8, 9], 4)
    tw = {r.rid: r.tokens for r in twin.drain()}
    assert res[v1].tokens == tw[t1]
    assert res[v2].tokens == tw[t2]
    assert res[hi].tokens == tw[t3]
    # the victim's resume was a prefix HIT on its own donated blocks
    hits = [e for e in eng.flight.events()
            if e["ev"] == "prefill" and e["prefix"] == "hit"]
    assert hits, "preemption resume was not a prefix hit"


def test_preempted_victim_shed_before_readmission(served_model):
    """A preempted victim whose deadline expires waiting for
    re-admission sheds with the ORIGINAL prompt and the salvaged
    tokens, one terminal, no leaked _Resume."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=64)
    prompt = [3, 4, 5]
    victim = eng.submit(prompt, 30, slo_class="batch", deadline_s=0.25)
    for _ in range(6):
        eng.step()
    pre_tokens = list(next(iter(eng._active.values())).tokens)
    assert pre_tokens
    hi = eng.submit([6, 7], 24, slo_class="interactive",
                    deadline_s=0.05)
    # drive until the preemption lands, then let the victim expire
    n = 0
    while eng.preemptions == 0 and n < 4000:
        eng.step()
        n += 1
    assert eng.preemptions >= 1
    assert victim in eng._resumed
    time.sleep(0.3)
    res = _drive(eng, eng)
    assert res[victim].finish_reason == "shed"
    assert res[victim].prompt == tuple(prompt)
    assert len(res[victim].tokens) >= len(pre_tokens)
    assert eng._resumed == {}
    assert eng.flight.terminals(victim) == ["shed"]
    assert res[hi].finish_reason in ("length", "shed")




# ---------------------------------------------------------- brownout ladder

def test_brownout_escalates_sheds_and_clears(served_model):
    """Sustained deadline burn climbs the ladder to shed_batch: batch
    submissions shed at submit AND queued batch drains; healthy windows
    walk it back down; every transition is a flight event and the
    level/transition metrics are on the registry."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, scan_k=4,
                 brownout=True)
    ctl = eng.brownout
    ctl.check_interval_steps = 2
    ctl.min_window_events = 1
    rng = np.random.default_rng(0)
    for i in range(12):
        eng.submit(rng.integers(0, cfg.vocab_size, 5).tolist(), 6,
                   deadline_s=1e-4, slo_class="interactive")
        eng.drain()
        if ctl.level >= 3:
            break
    assert ctl.level >= 3, ctl.stats()
    assert eng.scan_cap == 2               # shrink_scan: scan_k // 2
    assert eng.spec_suspended is True
    assert eng.brownout_min_priority == 1
    # shed at submit
    rid = eng.submit([1, 2], 4, slo_class="batch")
    out = eng.step()
    assert any(r.rid == rid and r.finish_reason == "shed" for r in out)
    assert eng.flight.terminals(rid) == ["shed"]
    # queued below-floor traffic drains too: queue one while the floor
    # is down, then re-raise it
    ctl._set(0)
    blocker = eng.submit([1, 2], 20)       # hold the engine busy
    eng.step()
    queued_batch = eng.submit([2, 3], 4, slo_class="batch")
    ctl._set(3)
    res = _drive(eng, eng)
    assert res[queued_batch].finish_reason == "shed"
    shed_ev = [e for e in eng.flight.events()
               if e["ev"] == "shed" and e.get("rid") == queued_batch]
    assert shed_ev and shed_ev[0]["reason"] == "brownout"
    assert res[blocker].finish_reason == "length"
    # healthy windows clear back to normal (hysteresis: clear_checks
    # consecutive windows per step down)
    for _ in range(80):
        eng.submit([3, 4], 2, slo_class="interactive", deadline_s=30.0)
        eng.drain()
        if ctl.level == 0:
            break
    assert ctl.level == 0, ctl.stats()
    assert eng.scan_cap is None and eng.spec_suspended is False
    assert eng.brownout_min_priority is None
    bevs = [e for e in eng.flight.events() if e["ev"] == "brownout"]
    assert bevs and {e["direction"] for e in bevs} == {"up", "down"}
    text = render_prometheus(eng.metrics)
    assert "serve_brownout_level 0" in text
    assert 'serve_brownout_transitions_total{direction="up"}' in text
    assert eng.stats()["brownout"]["name"] == "normal"


def test_brownout_suspends_and_resumes_spec(served_model):
    """Level 2 suspends speculative decoding reversibly: verify
    dispatches stop, outputs stay correct (greedy spec == greedy
    non-spec by construction), and clearing resumes them."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 spec=NGramDrafter(k=3), brownout=True)
    eng.submit([1, 2, 3], 6)
    eng.drain()
    assert eng.host_dispatches["verify"] > 0
    eng.brownout._set(2)
    mark = eng.host_dispatches["verify"]
    rid = eng.submit([1, 2, 3], 6)
    res = eng.drain()
    assert eng.host_dispatches["verify"] == mark, "spec not suspended"
    twin = Engine(model, params, num_slots=2, max_len=64)
    twin.submit([1, 2, 3], 6)
    assert res[0].tokens == twin.drain()[0].tokens
    eng.brownout._set(0)
    eng.submit([1, 2, 3], 6)
    eng.drain()
    assert eng.host_dispatches["verify"] > mark, "spec did not resume"


# ------------------------------------------------- retry-after & budgets

def test_retry_after_debug_views_and_equal_priority(served_model):
    """One single-slot engine, three contracts: (a) retry_after_s is
    priority-aware — a batch request behind a deep interactive queue
    gets a LONGER hint than an interactive one, the classless call
    keeps the legacy estimate; (b) /debug/scheduler surfaces per-class
    depths, priorities, chunk/brownout posture; (c) a single-class
    deadline head with no strictly-lower-priority victim never preempts
    (the pre-ISSUE-13 behavior)."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=64,
                 prefill_chunk=16, brownout=True)
    assert eng.retry_after_s() == 1.0                    # cold
    eng.submit([1, 2], 20)
    eng.step()
    eng.submit([3, 4], 2, slo_class="batch")
    for i in range(5):
        eng.submit([1, 2 + i], 8, slo_class="interactive")
    base = eng.retry_after_s()
    assert eng.retry_after_s(slo_class="batch") \
        > eng.retry_after_s(slo_class="interactive") >= base
    d = eng.debug_scheduler()
    assert d["queue_by_class"]["batch"]["queued"] == 1
    assert d["queue_by_class"]["interactive"]["queued"] == 5
    assert d["queue_by_class"]["interactive"]["priorities"] == {2: 5}
    assert d["queue"][0]["slo_class"] == "interactive"   # priority order
    assert d["brownout"]["name"] == "normal"
    assert d["prefill_chunk"] == 16
    assert d["preemptions"] == 0
    eng.drain()
    # (c) equal priority never preempts — warm engine, same slot
    eng.submit([1, 2], 20)
    eng.step()
    eng.submit([3, 4], 4, deadline_s=0.05)    # same default class
    eng.drain()
    assert eng.preemptions == 0


def test_scheduling_adds_no_programs_and_no_syncs(served_model):
    """The acceptance pin: preemption + chunked prefill + brownout all
    ride host-side bookkeeping and the existing compiled grid — the
    published compile set and the audited host-sync ledger are
    IDENTICAL to a plain engine's on the same workload."""
    cfg, model, params = served_model

    def run(**kw):
        mark = _tracecheck.sync_counts()
        eng = Engine(model, params, num_slots=2, max_len=64, **kw)
        _mixed(eng, cfg.vocab_size, n=6, seed=9)
        eng.drain()
        return eng.max_programs(), _tracecheck.sync_delta(mark)

    plain_progs, plain_sync = run()
    sched_progs, sched_sync = run(
        prefill_chunk=16, brownout=True,
        faults=FaultPlan.parse("preempt_storm@2x2"))
    assert sched_progs == plain_progs
    assert sched_sync == plain_sync


