"""Data pipeline tests: prepare, memmap loader, per-host sharding, native gather."""

import numpy as np
import pytest

from nanosandbox_tpu.data.loader import BatchLoader, BinDataset
from nanosandbox_tpu.data.prepare import prepare_bpe_dataset
from nanosandbox_tpu.utils import native


def test_prepare_and_meta(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    assert ds.vocab_size > 10
    assert ds.tokens("train") > ds.tokens("val") > 0
    assert ds.meta["kind"] == "char"


def test_sample_batch_shapes_and_shift(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    x, y = ds.sample_batch("train", step=0, batch_size=4, block_size=32)
    assert x.shape == y.shape == (4, 32)
    # y is x shifted by one (same window).
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    assert x.max() < ds.vocab_size


def test_determinism_and_host_disjointness(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    a = ds.sample_batch("train", 5, 4, 32, seed=7, process_index=0)
    b = ds.sample_batch("train", 5, 4, 32, seed=7, process_index=0)
    np.testing.assert_array_equal(a[0], b[0])
    c = ds.sample_batch("train", 5, 4, 32, seed=7, process_index=1)
    assert not np.array_equal(a[0], c[0])
    d = ds.sample_batch("train", 6, 4, 32, seed=7, process_index=0)
    assert not np.array_equal(a[0], d[0])
    e = ds.sample_batch("val", 5, 4, 32, seed=7, process_index=0)
    assert not np.array_equal(a[0], e[0])


def test_batch_loader_prefetch(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    loader = BatchLoader(ds, "train", batch_size=8, block_size=16,
                         num_processes=2, process_index=0)
    try:
        x, y = next(loader)
        assert x.shape == (4, 16)  # local batch = global / num_processes
        x2, _ = next(loader)
        assert not np.array_equal(x, x2)
    finally:
        loader.close()


def test_batch_loader_divisibility(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    with pytest.raises(ValueError, match="divisible"):
        BatchLoader(ds, "train", batch_size=7, block_size=16,
                    num_processes=2, prefetch=False)


def test_native_gather_matches_numpy(tmp_path):
    data = np.arange(1000, dtype=np.uint16)
    offsets = np.asarray([0, 10, 500, 991], dtype=np.int64)
    got = native.gather_windows(data, offsets, 9)
    want = np.stack([data[o:o + 9] for o in offsets])
    np.testing.assert_array_equal(got, want)


def test_native_gather_clamps_overrun():
    data = np.arange(100, dtype=np.uint16)
    got = native.gather_windows(data, np.asarray([98], dtype=np.int64), 5)
    np.testing.assert_array_equal(got[0], data[95:100])


def test_sample_offsets_in_range():
    offs = native.sample_offsets(seed=1, stream=2, n_tokens=1000, width=65,
                                 batch=256)
    assert offs.shape == (256,)
    assert offs.min() >= 0 and offs.max() <= 1000 - 65
    offs2 = native.sample_offsets(seed=1, stream=2, n_tokens=1000, width=65,
                                  batch=256)
    np.testing.assert_array_equal(offs, offs2)
    offs3 = native.sample_offsets(seed=1, stream=3, n_tokens=1000, width=65,
                                  batch=256)
    assert not np.array_equal(offs, offs3)


def test_bpe_prepare_offline(tmp_path):
    out = tmp_path / "owt"
    stats = prepare_bpe_dataset(str(out), text="hello world " * 2000,
                                tokenizer="byte")
    assert stats["vocab_size"] == 256
    ds = BinDataset(str(tmp_path), "owt")
    assert ds.tokens("train") > 0


def test_bpe_prepare_strict_raises_offline(tmp_path):
    """A real-corpus prep must FAIL, not silently train on synthetic data,
    when the download is unavailable and synthetic isn't allowed (the k8s
    OWT Job's posture, k8s/jobs/21-download-openwebtext.yaml)."""
    import pytest

    with pytest.raises(Exception):
        prepare_bpe_dataset(str(tmp_path / "owt2"), allow_synthetic=False,
                            download=False)


def test_bpe_prepare_synthetic_fallback_warns(tmp_path, capfd):
    stats = prepare_bpe_dataset(str(tmp_path / "owt3"), tokenizer="byte",
                                num_chars=5000, download=False,
                                allow_synthetic=True)
    assert stats["train_tokens"] > 0
    assert "SYNTHETIC" in capfd.readouterr().err
