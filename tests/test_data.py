"""Data pipeline tests: prepare, memmap loader, per-host sharding, native gather."""

import os

import numpy as np
import pytest

from nanosandbox_tpu.data.loader import BatchLoader, BinDataset
from nanosandbox_tpu.data.prepare import prepare_bpe_dataset
from nanosandbox_tpu.utils import native


def test_prepare_and_meta(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    assert ds.vocab_size > 10
    assert ds.tokens("train") > ds.tokens("val") > 0
    assert ds.meta["kind"] == "char"


def test_sample_batch_shapes_and_shift(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    x, y = ds.sample_batch("train", step=0, batch_size=4, block_size=32)
    assert x.shape == y.shape == (4, 32)
    # y is x shifted by one (same window).
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    assert x.max() < ds.vocab_size


def test_determinism_and_host_disjointness(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    a = ds.sample_batch("train", 5, 4, 32, seed=7, process_index=0)
    b = ds.sample_batch("train", 5, 4, 32, seed=7, process_index=0)
    np.testing.assert_array_equal(a[0], b[0])
    c = ds.sample_batch("train", 5, 4, 32, seed=7, process_index=1)
    assert not np.array_equal(a[0], c[0])
    d = ds.sample_batch("train", 6, 4, 32, seed=7, process_index=0)
    assert not np.array_equal(a[0], d[0])
    e = ds.sample_batch("val", 5, 4, 32, seed=7, process_index=0)
    assert not np.array_equal(a[0], e[0])


def test_batch_loader_prefetch(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    loader = BatchLoader(ds, "train", batch_size=8, block_size=16,
                         num_processes=2, process_index=0)
    try:
        x, y = next(loader)
        assert x.shape == (4, 16)  # local batch = global / num_processes
        x2, _ = next(loader)
        assert not np.array_equal(x, x2)
    finally:
        loader.close()


def test_batch_loader_divisibility(char_dataset):
    ds = BinDataset(char_dataset, "shakespeare_char")
    with pytest.raises(ValueError, match="divisible"):
        BatchLoader(ds, "train", batch_size=7, block_size=16,
                    num_processes=2, prefetch=False)


def test_batch_loader_prefetch_worker_error_propagates(char_dataset):
    """An exception in the prefetch worker (e.g. a truncated .bin
    mid-run) used to kill the thread silently and leave __next__ blocked
    forever on an empty queue; it must surface in the consumer — on the
    first __next__ after the failure AND on every later one."""
    ds = BinDataset(char_dataset, "shakespeare_char")

    class Boom(BatchLoader):
        def _load(self, step):
            raise OSError("truncated .bin")

    loader = Boom(ds, "train", batch_size=4, block_size=16)
    try:
        with pytest.raises(RuntimeError, match="prefetch worker"):
            next(loader)
        with pytest.raises(RuntimeError, match="truncated"):
            next(loader)   # repeat call re-raises, never deadlocks
    finally:
        loader.close()


def test_batch_loader_prefetch_error_after_good_batches(char_dataset):
    """Batches staged before the failure are still delivered in order;
    the error surfaces exactly where the stream breaks."""
    ds = BinDataset(char_dataset, "shakespeare_char")

    class Boom(BatchLoader):
        def _load(self, step):
            if step >= 1:
                raise ValueError(f"bad step {step}")
            return super()._load(step)

    loader = Boom(ds, "train", batch_size=4, block_size=16)
    try:
        x, y = next(loader)       # step 0 staged fine
        assert x.shape == (4, 16)
        with pytest.raises(RuntimeError, match="bad step 1"):
            next(loader)
    finally:
        loader.close()


def test_native_gather_matches_numpy(tmp_path):
    data = np.arange(1000, dtype=np.uint16)
    offsets = np.asarray([0, 10, 500, 991], dtype=np.int64)
    got = native.gather_windows(data, offsets, 9)
    want = np.stack([data[o:o + 9] for o in offsets])
    np.testing.assert_array_equal(got, want)


def test_native_gather_clamps_overrun():
    data = np.arange(100, dtype=np.uint16)
    got = native.gather_windows(data, np.asarray([98], dtype=np.int64), 5)
    np.testing.assert_array_equal(got[0], data[95:100])


def test_sample_offsets_in_range():
    offs = native.sample_offsets(seed=1, stream=2, n_tokens=1000, width=65,
                                 batch=256)
    assert offs.shape == (256,)
    assert offs.min() >= 0 and offs.max() <= 1000 - 65
    offs2 = native.sample_offsets(seed=1, stream=2, n_tokens=1000, width=65,
                                  batch=256)
    np.testing.assert_array_equal(offs, offs2)
    offs3 = native.sample_offsets(seed=1, stream=3, n_tokens=1000, width=65,
                                  batch=256)
    assert not np.array_equal(offs, offs3)


def test_bpe_prepare_offline(tmp_path):
    out = tmp_path / "owt"
    stats = prepare_bpe_dataset(str(out), text="hello world " * 2000,
                                tokenizer="byte")
    assert stats["vocab_size"] == 256
    ds = BinDataset(str(tmp_path), "owt")
    assert ds.tokens("train") > 0


def test_bpe_prepare_strict_raises_offline(tmp_path):
    """A real-corpus prep must FAIL, not silently train on synthetic data,
    when the download is unavailable and synthetic isn't allowed (the k8s
    OWT Job's posture, k8s/jobs/21-download-openwebtext.yaml)."""
    import pytest

    with pytest.raises(Exception):
        prepare_bpe_dataset(str(tmp_path / "owt2"), allow_synthetic=False,
                            download=False)


def test_bpe_prepare_synthetic_fallback_warns(tmp_path, capfd):
    stats = prepare_bpe_dataset(str(tmp_path / "owt3"), tokenizer="byte",
                                num_chars=5000, download=False,
                                allow_synthetic=True)
    assert stats["train_tokens"] > 0
    assert "SYNTHETIC" in capfd.readouterr().err


def test_gpt2_tokenizer_offline_error_message():
    """Offline with no vendored vocabulary, get_tokenizer('gpt2') must
    fail with remediation steps (round-4 VERDICT missing #1), not an
    opaque network traceback."""
    from nanosandbox_tpu.data import tokenizer as tok

    if os.path.exists(os.path.join(tok._REPO_ROOT, tok.GPT2_LOCAL_ASSET)):
        pytest.skip("vendored gpt2 vocabulary present")
    try:
        import tiktoken

        tiktoken.get_encoding("gpt2")
        pytest.skip("tiktoken gpt2 available (online or cached)")
    except Exception:
        pass
    with pytest.raises(RuntimeError, match="tokenizer.json"):
        tok.get_tokenizer("gpt2")


def test_gpt2_vendored_asset_validated(tmp_path, monkeypatch):
    """A WRONG file dropped at the gpt2 asset path must be rejected — the
    whole point of the vendored path is to never tokenize into a
    mismatched id space."""
    from nanosandbox_tpu.data import tokenizer as tok

    # The committed english_prose BPE vocab has the right FORMAT but the
    # wrong content (different merges, no 50257/50256 structure match).
    wrong = os.path.join(tok._REPO_ROOT, tok.DEFAULT_BPE_ASSET)
    fake_root = tmp_path
    dst = fake_root / tok.GPT2_LOCAL_ASSET
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    import shutil

    shutil.copy(wrong, dst)
    monkeypatch.setattr(tok, "_REPO_ROOT", str(fake_root))

    def no_tiktoken(name):
        raise ConnectionError("offline")

    import tiktoken

    monkeypatch.setattr(tiktoken, "get_encoding", no_tiktoken)
    with pytest.raises(ValueError, match="not the real GPT-2"):
        tok.GPT2Tokenizer()


def test_init_from_gpt2_rejects_mismatched_tokenizer(tmp_path):
    """--init_from=gpt2 + a dataset whose meta.pkl was written by a
    non-gpt2 tokenizer must hard-error BEFORE any weight download
    (round-4 VERDICT missing #1: the silent-mismatch fine-tune path)."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.data.prepare import prepare_char_dataset
    from nanosandbox_tpu.train import Trainer

    data_dir = tmp_path / "data"
    prepare_char_dataset(str(data_dir / "shakespeare_char"),
                         allow_synthetic=True,
                         url="http://invalid.localhost/offline")
    cfg = TrainConfig(out_dir=str(tmp_path / "out"), data_dir=str(data_dir),
                      dataset="shakespeare_char", init_from="gpt2",
                      device="cpu", tensorboard=False)
    with pytest.raises(ValueError, match="not GPT-2 BPE"):
        Trainer(cfg)
