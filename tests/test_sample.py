"""Generation tests: jit-compiled scan decode."""

import jax
import jax.numpy as jnp

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.sample import generate


def test_generate_shapes_and_range():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=16,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    idx = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(model, params, idx, 40, temperature=1.0, top_k=10,
                   rng=jax.random.key(1), block_size=cfg.block_size)
    assert out.shape == (1, 43)
    assert int(out.max()) < 50 and int(out.min()) >= 0
    # prompt preserved
    assert out[0, :3].tolist() == [1, 2, 3]


def test_generate_deterministic_given_rng():
    cfg = GPTConfig(n_layer=1, n_head=1, n_embd=16, block_size=8,
                    vocab_size=20, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    idx = jnp.asarray([[5]], jnp.int32)
    a = generate(model, params, idx, 12, temperature=0.8, top_k=5,
                 rng=jax.random.key(7), block_size=cfg.block_size)
    b = generate(model, params, idx, 12, temperature=0.8, top_k=5,
                 rng=jax.random.key(7), block_size=cfg.block_size)
    assert a.tolist() == b.tolist()
