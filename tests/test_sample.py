"""Generation tests: KV-cached decode + windowed fallback parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT, init_cache
from nanosandbox_tpu.sample import _generate_windowed, generate


def test_generate_shapes_and_range():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=16,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    idx = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(model, params, idx, 40, temperature=1.0, top_k=10,
                   rng=jax.random.key(1), block_size=cfg.block_size)
    assert out.shape == (1, 43)
    assert int(out.max()) < 50 and int(out.min()) >= 0
    # prompt preserved
    assert out[0, :3].tolist() == [1, 2, 3]


def test_generate_deterministic_given_rng():
    cfg = GPTConfig(n_layer=1, n_head=1, n_embd=16, block_size=8,
                    vocab_size=20, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    idx = jnp.asarray([[5]], jnp.int32)
    a = generate(model, params, idx, 12, temperature=0.8, top_k=5,
                 rng=jax.random.key(7), block_size=cfg.block_size)
    b = generate(model, params, idx, 12, temperature=0.8, top_k=5,
                 rng=jax.random.key(7), block_size=cfg.block_size)
    assert a.tolist() == b.tolist()


def _tiny_model(block_size=32, vocab=50):
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=block_size,
                    vocab_size=vocab, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def test_cached_logits_match_full_forward():
    """Prefill + per-token cached steps reproduce the full forward's logits
    at every position — the correctness contract of the KV-cache path."""
    cfg, model, params = _tiny_model()
    idx = jax.random.randint(jax.random.key(3), (2, 12), 0, 50, jnp.int32)

    ref = model.apply({"params": params}, idx, deterministic=True)

    T0 = 5
    cache = init_cache(cfg, 2, 12)
    logits, cache = model.apply({"params": params}, idx[:, :T0],
                                deterministic=True, cache=cache,
                                cache_index=0)
    got = [logits]  # (2, T0, V)
    for i in range(T0, 12):
        logits, cache = model.apply({"params": params}, idx[:, i:i + 1],
                                    deterministic=True, cache=cache,
                                    cache_index=i)
        got.append(logits)
    got = jnp.concatenate(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_cached_greedy_matches_windowed():
    """temperature=0 decode is identical between the KV-cache path and the
    sliding-window full-forward fallback (VERDICT r3 next #3 done-bar)."""
    cfg, model, params = _tiny_model()
    idx = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    a = generate(model, params, idx, 20, temperature=0.0, top_k=0,
                 rng=jax.random.key(1), block_size=cfg.block_size)
    b = _generate_windowed(model, params, idx, 20, temperature=0.0, top_k=0,
                           rng=jax.random.key(1), block_size=cfg.block_size)
    assert a.shape == (1, 24)
    assert a.tolist() == b.tolist()


def test_cached_path_shapes_and_edges():
    cfg, model, params = _tiny_model()
    idx = jnp.asarray([[7, 8]], jnp.int32)
    # Single new token (scan length 0).
    out = generate(model, params, idx, 1, temperature=0.0, top_k=0,
                   rng=jax.random.key(0), block_size=cfg.block_size)
    assert out.shape == (1, 3)
    assert out[0, :2].tolist() == [7, 8]
    # Zero new tokens returns the prompt.
    out = generate(model, params, idx, 0, temperature=0.0, top_k=0,
                   rng=jax.random.key(0), block_size=cfg.block_size)
    assert out.tolist() == idx.tolist()
    # Exactly filling block_size stays on the cached path.
    out = generate(model, params, idx, cfg.block_size - 2, temperature=0.0,
                   top_k=0, rng=jax.random.key(0), block_size=cfg.block_size)
    assert out.shape == (1, cfg.block_size)


def test_init_cache_rejects_beyond_block_size():
    cfg, _, _ = _tiny_model(block_size=16)
    import pytest
    with pytest.raises(ValueError, match="block_size"):
        init_cache(cfg, 1, 17)


def test_cache_and_return_hidden_conflict_raises():
    cfg, model, params = _tiny_model()
    cache = init_cache(cfg, 1, 8)
    import pytest
    with pytest.raises(ValueError, match="return_hidden"):
        model.apply({"params": params}, jnp.zeros((1, 4), jnp.int32),
                    deterministic=True, return_hidden=True,
                    cache=cache, cache_index=0)


def test_top_p_nucleus_filter():
    """top_p keeps exactly the smallest prefix of the sorted distribution
    whose mass reaches p: probs (.5, .3, .15, .05) @ p=0.6 -> tokens
    {0, 1} only (mass before token 2 is already 0.8)."""
    from nanosandbox_tpu.sample import _sample_token

    probs = jnp.asarray([[0.5, 0.3, 0.15, 0.05]])
    logits = jnp.log(probs)
    seen = set()
    rng = jax.random.key(0)
    for _ in range(200):
        tok, rng = _sample_token(logits, rng, temperature=1.0, top_k=0,
                                 top_p=0.6)
        seen.add(int(tok[0]))
    assert seen == {0, 1}, seen
    # p=1.0 disables the filter: the tail tokens reappear.
    seen = set()
    for _ in range(400):
        tok, rng = _sample_token(logits, rng, temperature=1.0, top_k=0,
                                 top_p=1.0)
        seen.add(int(tok[0]))
    assert seen == {0, 1, 2, 3}, seen


def test_top_p_composes_with_cached_generate():
    cfg, model, params = _tiny_model()
    idx = jnp.asarray([[1, 2]], jnp.int32)
    out = generate(model, params, idx, 10, temperature=0.9, top_k=0,
                   rng=jax.random.key(2), block_size=cfg.block_size,
                   top_p=0.9)
    assert out.shape == (1, 12)


def test_top_p_zero_keeps_top1():
    """top_p<=0 must degrade to near-greedy (top-1 survives), never to the
    all-masked uniform-categorical failure mode."""
    from nanosandbox_tpu.sample import _sample_token

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    rng = jax.random.key(1)
    for _ in range(50):
        tok, rng = _sample_token(logits, rng, temperature=1.0, top_k=0,
                                 top_p=0.0)
        assert int(tok[0]) == 0


# ------------------------------------------------- per-row sampling (serve)

def test_sample_token_per_row_greedy_and_topk1():
    """Vector params: a temperature=0 row takes argmax of the RAW logits;
    a top_k=1 row is argmax via filtering — both deterministic, each row
    governed only by its own settings."""
    from nanosandbox_tpu.sample import _sample_token

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05],
                                  [0.05, 0.15, 0.3, 0.5]]))
    for _ in range(20):
        tok, _ = _sample_token(logits, jax.random.key(0),
                               temperature=jnp.asarray([0.0, 1.0]),
                               top_k=jnp.asarray([0, 1]),
                               top_p=jnp.asarray([1.0, 1.0]))
        assert int(tok[0]) == 0   # greedy row
        assert int(tok[1]) == 3   # top-1-filtered row


def test_sample_token_per_row_top_p_masks_per_row():
    """Row 0 (p=0.6) may only emit tokens {0, 1}; row 1 (p=1.0) of the
    same distribution eventually emits the tail too."""
    from nanosandbox_tpu.sample import _sample_token

    row = [0.5, 0.3, 0.15, 0.05]
    logits = jnp.log(jnp.asarray([row, row]))
    seen0, seen1 = set(), set()
    rng = jax.random.key(0)
    for _ in range(300):
        rng, sub = jax.random.split(rng)
        tok, _ = _sample_token(logits, sub,
                               temperature=jnp.asarray([1.0, 1.0]),
                               top_k=jnp.asarray([0, 0]),
                               top_p=jnp.asarray([0.6, 1.0]))
        seen0.add(int(tok[0]))
        seen1.add(int(tok[1]))
    assert seen0 == {0, 1}, seen0
    assert seen1 == {0, 1, 2, 3}, seen1


def test_sample_token_per_row_key_batch_isolates_rows():
    """With a (B,) key batch, each row samples from its own stream: the
    same key must yield the same token no matter what other rows ride
    along — the engine's batch-composition-independence anchor."""
    from nanosandbox_tpu.sample import _sample_token

    row = [0.25, 0.25, 0.25, 0.25]
    keys1 = jnp.stack([jax.random.key(5)])
    keys3 = jnp.stack([jax.random.key(5), jax.random.key(6),
                       jax.random.key(7)])
    t1, _ = _sample_token(jnp.log(jnp.asarray([row])), keys1,
                          temperature=jnp.asarray([1.0]),
                          top_k=jnp.asarray([0]), top_p=jnp.asarray([1.0]))
    t3, _ = _sample_token(jnp.log(jnp.asarray([row] * 3)), keys3,
                          temperature=jnp.ones(3), top_k=jnp.zeros(3, jnp.int32),
                          top_p=jnp.ones(3))
    assert int(t1[0]) == int(t3[0])


def test_sample_token_scalar_path_unchanged_by_vector_dispatch():
    """A (B,)-broadcast of identical scalar params filters identically to
    the scalar path: with top_k=2 both paths can only emit {0, 1}."""
    from nanosandbox_tpu.sample import _sample_token

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    seen = set()
    rng = jax.random.key(3)
    for _ in range(100):
        rng, sub = jax.random.split(rng)
        tok, _ = _sample_token(logits, sub,
                               temperature=jnp.asarray([1.0]),
                               top_k=jnp.asarray([2]),
                               top_p=jnp.asarray([1.0]))
        seen.add(int(tok[0]))
    assert seen == {0, 1}, seen


# ------------------------------------------------------ CLI parity (main)

def test_main_rejects_num_samples_below_one(tmp_path):
    """--num_samples=0 must fail fast (argparse error), BEFORE any
    checkpoint restore is attempted — the bogus out_dir would raise a
    different error if validation ran late."""
    from nanosandbox_tpu.sample import main

    with pytest.raises(SystemExit) as ei:
        main(["--num_samples=0", f"--out_dir={tmp_path}/definitely-missing"])
    assert ei.value.code == 2  # argparse error exit, not FileNotFoundError


def test_resolve_start_file_convention(tmp_path):
    """nanoGPT's --start=FILE:<path> reads the prompt from a file."""
    from nanosandbox_tpu.sample import resolve_start

    p = tmp_path / "prompt.txt"
    p.write_text("To be, or not to be\n")
    assert resolve_start(f"FILE:{p}") == "To be, or not to be\n"
    assert resolve_start("plain text") == "plain text"
    with pytest.raises(FileNotFoundError):
        resolve_start(f"FILE:{tmp_path}/nope.txt")
