"""Checkpoint round-trip tests (Orbax, /data layout contract)."""

import jax
import numpy as np

from nanosandbox_tpu.checkpoint import Checkpointer, abstract_like
from nanosandbox_tpu.train import Trainer


def test_roundtrip(tiny_cfg):
    trainer = Trainer(tiny_cfg)
    state = trainer.init_state()
    ckpt = Checkpointer(tiny_cfg.out_dir, keep=2)
    ckpt.save(3, state, {"iter_num": 3, "best_val_loss": 1.5}, wait=True)
    assert ckpt.latest_step() == 3

    restored, extra = ckpt.restore(trainer.abstract_state)
    assert extra["iter_num"] == 3
    assert extra["best_val_loss"] == 1.5
    for a, b in zip(jax.tree.leaves(state["params"]),
                    jax.tree.leaves(restored["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_keep_limit(tiny_cfg):
    trainer = Trainer(tiny_cfg)
    state = trainer.init_state()
    ckpt = Checkpointer(tiny_cfg.out_dir, keep=2)
    for s in (1, 2, 3):
        ckpt.save(s, state, wait=True)
    steps = ckpt.mgr.all_steps()
    assert 3 in steps and len(steps) <= 2
    ckpt.close()


def test_duplicate_step_is_noop(tiny_cfg):
    trainer = Trainer(tiny_cfg)
    state = trainer.init_state()
    ckpt = Checkpointer(tiny_cfg.out_dir, keep=2)
    ckpt.save(1, state, {"iter_num": 1}, wait=True)
    ckpt.save(1, state, {"iter_num": 1}, wait=True)  # must not raise
    ckpt.close()


def test_duplicate_step_save_logs_skip_once(tmp_path, capsys):
    """A skipped re-save (resume re-evals at the restored step) must say
    so ONCE on stderr — a resumed run that never logs a save otherwise
    looks like checkpointing silently stopped — and must not repeat on
    every subsequent eval_interval hit."""
    state = {"w": np.zeros((2, 2), np.float32)}
    ckpt = Checkpointer(str(tmp_path / "out"), keep=2)
    ckpt.save(1, state, wait=True)
    capsys.readouterr()  # drop orbax's own chatter from the first save
    ckpt.save(1, state, wait=True)
    ckpt.save(1, state, wait=True)
    err = capsys.readouterr().err
    assert err.count("already exists") == 1, err
    assert "skipping save" in err
    ckpt.close()


def test_abstract_like(tiny_cfg):
    trainer = Trainer(tiny_cfg)
    state = trainer.init_state()
    ab = abstract_like(state)
    leaf = jax.tree.leaves(ab)[0]
    assert isinstance(leaf, jax.ShapeDtypeStruct)


def test_restore_into_sharded(tiny_cfg):
    """Save replicated, restore into an FSDP-sharded abstract state."""
    t1 = Trainer(tiny_cfg)
    state = t1.init_state()
    ckpt = Checkpointer(tiny_cfg.out_dir, keep=2)
    ckpt.save(5, state, wait=True)

    cfg2 = tiny_cfg.replace(mesh_dp=1, mesh_fsdp=8, shard_params=True)
    t2 = Trainer(cfg2)
    restored, _ = ckpt.restore(t2.abstract_state, 5)
    k = restored["params"]["h_0"]["attn"]["c_attn"]["kernel"]
    assert k.sharding.is_fully_replicated is False
    np.testing.assert_allclose(
        np.asarray(jax.device_get(k)),
        np.asarray(jax.device_get(
            state["params"]["h_0"]["attn"]["c_attn"]["kernel"])))
    ckpt.close()
