"""shardcheck tests: grammar, axis attribution, rules, budgets, fleets.

The ISSUE 7 acceptance bar:
  * a manifest for >= 6 distinct compiled programs (train step, eval,
    decode, >= 2 prefill rungs, spec verify) on the 8-device CPU mesh;
  * the deliberately-injected unconstrained output (the frontier_slice
    fixture dropping its with_sharding_constraint) is caught as an
    accidental-all-gather finding with nonzero byte attribution;
  * the committed budgets pass clean at zero findings.

Layered like the tool: the HLO grammar and budget checker are pure
stdlib (no compile in the loop), the rule layer is fed synthetic
manifests, and ONE module-scoped fleet fixture pays the compile cost
for every integration assertion.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from nanosandbox_tpu.analysis.shardcheck.budget import (budget_from_manifest,
                                                        check_budget)
from nanosandbox_tpu.analysis.shardcheck.hlo import (parse_hlo_collectives,
                                                     parse_replica_groups)
from nanosandbox_tpu.analysis.shardcheck.manifest import (Expectations,
                                                          agg_key,
                                                          attribute_axes,
                                                          axis_groups)
from nanosandbox_tpu.analysis.shardcheck.rules import check_program

REPO_ROOT = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------- HLO grammar

HLO_SAMPLE = """\
HloModule jit_f, entry_computation_layout={...}

%region_0.6 (a: f32[], b: f32[]) -> f32[] {
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main.10 {
  %param.1 = f32[128,32]{1,0} parameter(0)
  %param.2 = f32[2,256]{1,0} parameter(1), sharding={replicated}
  %all-gather = f32[256,32]{1,0} all-gather(f32[128,32]{1,0} %param.1), \
channel_id=1, replica_groups={{0,2},{4,6},{1,3},{5,7}}, dimensions={0}, \
use_global_device_ids=true, metadata={op_name="jit(f)/dot_general"}
  %all-reduce = f32[] all-reduce(f32[] %fusion), channel_id=2, \
replica_groups=[4,2]<=[8], use_global_device_ids=true, to_apply=%region_0.6
  ROOT %all-reduce.1 = f32[] all-reduce(f32[] %all-reduce), channel_id=3, \
replica_groups=[2,4]<=[4,2]T(1,0), use_global_device_ids=true, \
to_apply=%region_0.6
  %cp = f32[8,16]{1,0} collective-permute(f32[8,16]{1,0} %x), channel_id=4, \
source_target_pairs={{0,2},{2,0},{1,3},{3,1}}
  %aa = (f32[64,8]{1,0}, f32[64,8]{1,0}) all-to-all(f32[64,8]{1,0} %y, \
f32[64,8]{1,0} %z), channel_id=5, replica_groups={{0,1},{2,3}}
}
"""


def test_hlo_parser_extracts_collectives():
    parsed = parse_hlo_collectives(HLO_SAMPLE)
    by_kind = {c.kind: c for c in parsed.collectives}
    assert set(by_kind) == {"all-gather", "all-reduce",
                            "collective-permute", "all-to-all"}
    assert len(parsed.collectives) == 5  # two all-reduces

    ag = by_kind["all-gather"]
    assert ag.bytes_in == 128 * 32 * 4
    assert ag.bytes_out == 256 * 32 * 4
    assert ag.bytes_moved == ag.bytes_out       # gathers charge the result
    assert ag.groups == frozenset({frozenset({0, 2}), frozenset({4, 6}),
                                   frozenset({1, 3}), frozenset({5, 7})})
    assert ag.operand_params == (0,)            # fed by parameter(0)

    cp = by_kind["collective-permute"]
    assert cp.pairs == ((0, 2), (2, 0), (1, 3), (3, 1))
    assert cp.bytes_moved == 8 * 16 * 4

    aa = by_kind["all-to-all"]
    assert aa.bytes_out == 2 * 64 * 8 * 4       # tuple result summed
    assert parsed.params == {"param.1": 0, "param.2": 1}


def test_iota_replica_groups():
    # [4,2]<=[8]: iota(8) -> rows of 2.
    assert parse_replica_groups("replica_groups=[4,2]<=[8]") == frozenset(
        frozenset(p) for p in [(0, 1), (2, 3), (4, 5), (6, 7)])
    # [2,4]<=[4,2]T(1,0): transpose interleaves -> stride-2 groups.
    assert parse_replica_groups(
        "replica_groups=[2,4]<=[4,2]T(1,0)") == frozenset(
        frozenset(p) for p in [(0, 2, 4, 6), (1, 3, 5, 7)])


def test_parser_skips_async_done_and_metadata_strings():
    text = """
  %ags = f32[8]{0} all-gather-start(f32[4]{0} %p), replica_groups={{0,1}}
  %agd = f32[8]{0} all-gather-done(f32[8]{0} %ags)
  %fusion = f32[] fusion(f32[] %q), metadata={op_name="fake all-reduce(x)"}
"""
    parsed = parse_hlo_collectives(text)
    assert len(parsed.collectives) == 1
    assert parsed.collectives[0].kind == "all-gather"


def test_async_start_tuple_result_counts_output_only():
    """The TPU async form returns (operand, output[, contexts]); the
    operand echo must not double-charge bytes_out or break the
    full-input-gather byte match."""
    text = """
  %ags = (f32[128,32]{1,0}, f32[256,32]{1,0}) all-gather-start(\
f32[128,32]{1,0} %p), channel_id=1, replica_groups={{0,1}}, dimensions={0}
  %cps = (f32[8,16]{1,0}, f32[8,16]{1,0}, u32[], u32[]) \
collective-permute-start(f32[8,16]{1,0} %x), source_target_pairs={{0,1}}
"""
    parsed = parse_hlo_collectives(text)
    ag, cp = parsed.collectives
    assert ag.kind == "all-gather"
    assert ag.bytes_in == 128 * 32 * 4
    assert ag.bytes_out == 256 * 32 * 4      # NOT operand + output
    assert ag.bytes_moved == 256 * 32 * 4
    assert cp.kind == "collective-permute"
    assert cp.bytes_out == 8 * 16 * 4        # context scalars excluded


# --------------------------------------------------------- axis attribution

MESH_SIZES = {"data": 1, "fsdp": 2, "seq": 2, "model": 2}


def _groups(*sets):
    return frozenset(frozenset(s) for s in sets)


def test_axis_groups_cover_and_attribution():
    gi = axis_groups(MESH_SIZES)
    # model is the innermost axis (stride 1), fsdp outermost live axis
    # (stride 4) — matching make_mesh's (data, fsdp, seq, model) layout.
    import dataclasses

    from nanosandbox_tpu.analysis.shardcheck.hlo import Collective

    def coll(groups=None, pairs=()):
        return Collective(kind="x", name="x", bytes_in=0, bytes_out=0,
                          groups=groups, pairs=pairs)

    assert attribute_axes(
        coll(groups=_groups((0, 1), (2, 3), (4, 5), (6, 7))),
        MESH_SIZES, gi) == ("model",)
    assert attribute_axes(
        coll(groups=_groups((0, 2), (1, 3), (4, 6), (5, 7))),
        MESH_SIZES, gi) == ("seq",)
    assert attribute_axes(
        coll(groups=_groups((0, 4), (1, 5), (2, 6), (3, 7))),
        MESH_SIZES, gi) == ("fsdp",)
    assert attribute_axes(
        coll(groups=_groups((0, 2, 4, 6), (1, 3, 5, 7))),
        MESH_SIZES, gi) == ("fsdp", "seq")
    assert attribute_axes(
        coll(groups=_groups(tuple(range(8)))),
        MESH_SIZES, gi) == ("fsdp", "seq", "model")
    # permute pairs stepping one axis
    assert attribute_axes(coll(pairs=((0, 2), (2, 0), (1, 3), (3, 1))),
                          MESH_SIZES, gi) == ("seq",)
    # a group structure matching no axis subset
    assert attribute_axes(coll(groups=_groups((0, 3), (1, 2), (4, 7),
                                              (5, 6))),
                          MESH_SIZES, gi) == ("unknown",)
    # size-1 groups move nothing
    assert attribute_axes(coll(groups=_groups((0,), (1,))),
                          MESH_SIZES, gi) == ()
    assert dataclasses.is_dataclass(coll())


def test_registered_axes_match_static_rule_mirror():
    # The jaxlint axis-mismatch rule mirrors parallel.mesh.AXES without
    # importing jax; this is the pin that keeps the mirror honest.
    from nanosandbox_tpu.analysis.rules_sharding import REGISTERED_AXIS_NAMES
    from nanosandbox_tpu.parallel.mesh import AXES, REGISTERED_AXES

    assert tuple(REGISTERED_AXIS_NAMES) == tuple(AXES)
    assert REGISTERED_AXES == frozenset(AXES)


# ----------------------------------------------------------- manifest rules


def _entry(collectives=None, full_gathers=(), donated=()):
    colls = {}
    for kind, axes, count, bytes_ in (collectives or []):
        colls[agg_key(kind, axes)] = {
            "kind": kind, "axes": list(axes), "count": count,
            "bytes_moved": bytes_, "max_bytes_out": bytes_}
    return {"collectives": colls,
            "full_input_gathers": list(full_gathers),
            "donated_param_comms": list(donated)}


def test_rule_comms_free_violation():
    entry = _entry([("all-gather", ("fsdp",), 2, 1024)])
    found = check_program("decode", entry, Expectations(comms_free=True))
    assert [f["rule"] for f in found] == ["comms-free-violation"]
    assert found[0]["bytes"] == 1024
    assert not check_program("decode", _entry(),
                             Expectations(comms_free=True))


def test_rule_accidental_all_gather_gated_by_expected_axes():
    fg = {"axes": ["fsdp"], "bytes": 65536, "materializes": "arg0/w",
          "instr": "all-gather.1"}
    entry = _entry([("all-gather", ("fsdp",), 1, 65536)], full_gathers=[fg])
    # ZeRO-3 declares fsdp gathers expected -> clean.
    assert not check_program("train_step", entry,
                             Expectations(gather_ok_axes=("fsdp",)))
    # Undeclared -> accidental, bytes attributed.
    found = check_program("train_step", entry, Expectations())
    assert [f["rule"] for f in found] == ["accidental-all-gather"]
    assert found[0]["bytes"] == 65536
    assert "arg0/w" in found[0]["message"]


def test_rule_dp_axis_and_fusion_bound():
    entry = _entry([("all-gather", ("data",), 1, 512),
                    ("all-reduce", ("data",), 9, 4096)])
    found = check_program(
        "train_step", entry,
        Expectations(allreduce_only_axes=("data",), max_axis_allreduces=4))
    rules = sorted(f["rule"] for f in found)
    assert rules == ["unexpected-dp-collective", "unfused-grad-allreduce"]
    # Within the bound, all-reduce on dp is the expected gradient sync.
    entry = _entry([("all-reduce", ("data",), 3, 4096)])
    assert not check_program(
        "train_step", entry,
        Expectations(allreduce_only_axes=("data",), max_axis_allreduces=4))


def test_rule_donated_reshard():
    entry = _entry(donated=[{"kind": "all-gather", "axes": ["model"],
                             "bytes": 2048, "params": [0]}])
    found = check_program("step", entry, Expectations())
    assert [f["rule"] for f in found] == ["donated-reshard"]


# ------------------------------------------------------------ budget checks


def _manifest(programs):
    return {"version": 1, "tool": "shardcheck",
            "provenance": {"jax": "0.0", "jaxlib": "0.0"},
            "mesh": dict(MESH_SIZES),
            "programs": {
                name: {"collectives": _entry(colls)["collectives"],
                       "totals": {}, "full_input_gathers": [],
                       "donated_param_comms": [], "findings": []}
                for name, colls in programs.items()}}


def test_budget_roundtrip_clean_and_violations():
    manifest = _manifest({
        "train_step": [("all-gather", ("fsdp",), 4, 1000),
                       ("all-reduce", ("model",), 2, 500)],
        "decode": []})
    budget = budget_from_manifest(manifest, tolerance=0.10)
    violations, notes = check_budget(manifest, budget)
    assert violations == [] and notes == []

    # bytes growth past tolerance
    grown = _manifest({
        "train_step": [("all-gather", ("fsdp",), 4, 1200),
                       ("all-reduce", ("model",), 2, 500)],
        "decode": []})
    violations, _ = check_budget(grown, budget)
    assert [v["kind"] for v in violations] == ["bytes-growth"]
    # within tolerance: clean
    ok = _manifest({
        "train_step": [("all-gather", ("fsdp",), 4, 1050),
                       ("all-reduce", ("model",), 2, 500)],
        "decode": []})
    assert check_budget(ok, budget)[0] == []

    # a NEW collective kind/axes pair
    new_kind = _manifest({
        "train_step": [("all-gather", ("fsdp",), 4, 1000),
                       ("all-reduce", ("model",), 2, 500),
                       ("all-gather", ("data",), 1, 8)],
        "decode": []})
    violations, _ = check_budget(new_kind, budget)
    assert [v["kind"] for v in violations] == ["new-collective"]

    # count growth (same key)
    more = _manifest({
        "train_step": [("all-gather", ("fsdp",), 5, 1000),
                       ("all-reduce", ("model",), 2, 500)],
        "decode": []})
    violations, _ = check_budget(more, budget)
    assert [v["kind"] for v in violations] == ["count-growth"]

    # unbudgeted / missing programs
    extra = _manifest({
        "train_step": [("all-gather", ("fsdp",), 4, 1000),
                       ("all-reduce", ("model",), 2, 500)],
        "decode": [], "new_prog": []})
    violations, _ = check_budget(extra, budget)
    assert [v["kind"] for v in violations] == ["unbudgeted-program"]
    gone = _manifest({"decode": []})
    violations, _ = check_budget(gone, budget)
    assert [v["kind"] for v in violations] == ["missing-program"]

    # shrinkage is a stale note, never a violation
    less = _manifest({
        "train_step": [("all-gather", ("fsdp",), 3, 700),
                       ("all-reduce", ("model",), 2, 500)],
        "decode": []})
    violations, notes = check_budget(less, budget)
    assert violations == [] and any("ratchet" in n or "stale" in n
                                    for n in notes)

    # mesh mismatch is terminal
    other = _manifest({"decode": []})
    other["mesh"] = {"data": 8, "fsdp": 1, "seq": 1, "model": 1}
    violations, _ = check_budget(other, budget)
    assert [v["kind"] for v in violations] == ["mesh-mismatch"]


# ------------------------------------------------- compile-level integration


@pytest.fixture(scope="module")
def mesh():
    from nanosandbox_tpu.analysis.shardcheck.fleet import build_mesh

    return build_mesh()


@pytest.fixture(scope="module")
def serve_manifest(mesh):
    from nanosandbox_tpu.analysis.shardcheck.fleet import serve_programs
    from nanosandbox_tpu.analysis.shardcheck.manifest import build_manifest

    return build_manifest(serve_programs(mesh), mesh)


def test_fixture_pair_pins_the_accidental_all_gather(mesh):
    """The acceptance fixture: dropping the with_sharding_constraint
    turns a bounded all-to-all into a full-pool all-gather, and
    shardcheck names it with nonzero bytes."""
    from nanosandbox_tpu.analysis.shardcheck.fleet import (
        frontier_slice_programs)
    from nanosandbox_tpu.analysis.shardcheck.manifest import build_manifest

    good = build_manifest(frontier_slice_programs(mesh, True), mesh)
    bad = build_manifest(frontier_slice_programs(mesh, False), mesh)

    assert good["findings"] == []
    assert len(bad["findings"]) == 1
    f = bad["findings"][0]
    assert f["rule"] == "accidental-all-gather"
    assert f["bytes"] == 256 * 64 * 4        # the FULL sharded pool
    entry = bad["programs"]["frontier_slice_unconstrained"]
    assert entry["full_input_gathers"][0]["axes"] == ["fsdp"]
    # The constrained twin exchanges strictly fewer bytes.
    good_bytes = good["programs"]["frontier_slice"]["totals"]["bytes_moved"]
    assert 0 < good_bytes < entry["totals"]["bytes_moved"]


def test_serve_fleet_manifest_and_committed_budget(serve_manifest):
    """>= 6 distinct programs incl. decode, >=2 prefill rungs, spec
    verify + drafter — all pinned comms-free, committed budget clean."""
    programs = serve_manifest["programs"]
    assert "decode" in programs
    assert "spec_verify" in programs
    assert "drafter_draft" in programs
    rungs = {name for name in programs if name.startswith("prefill_k")}
    assert len(rungs) >= 2
    assert len(programs) >= 6
    # Today's single-chip contract, stated on the mesh: zero collectives.
    for name, entry in programs.items():
        assert entry["collectives"] == {}, (name, entry["collectives"])
    assert serve_manifest["findings"] == []
    # replicated accounting: the params went in replicated
    assert programs["decode"]["replicated_input_bytes"] > 0
    assert programs["decode"]["sharded_input_bytes_per_device"] == 0

    budget = json.loads(
        (REPO_ROOT / "budgets" / "serve_cpu8.json").read_text())
    violations, _ = check_budget(serve_manifest, budget)
    assert violations == []


def test_serve_manifest_provenance_and_memory(serve_manifest):
    prov = serve_manifest["provenance"]
    assert prov["device_count"] == 8
    assert prov["jax"] and prov["jaxlib"]
    mem = serve_manifest["programs"]["decode"]["memory"]
    if mem:  # backend-dependent; CPU provides it today
        assert mem["argument_bytes"] > 0


def test_train_fleet_manifest_and_committed_budget(mesh):
    """Train + eval on the full dp/fsdp/sp/tp mesh: real collectives on
    the expected axes, zero accidental findings, committed budget
    clean."""
    from nanosandbox_tpu.analysis.shardcheck.fleet import train_programs
    from nanosandbox_tpu.analysis.shardcheck.manifest import build_manifest

    manifest = build_manifest(train_programs(mesh), mesh)
    programs = manifest["programs"]
    assert set(programs) == {"train_step", "eval_step"}
    assert manifest["findings"] == []
    train = programs["train_step"]
    # ZeRO-3 gathers on fsdp, ring permutes on seq, TP reduces on model.
    kinds = {(s["kind"], tuple(s["axes"]))
             for s in train["collectives"].values()}
    assert any(k == ("all-gather", ("fsdp",)) for k in kinds)
    assert any(k[0] == "collective-permute" for k in kinds)
    assert any(k[0] == "all-reduce" and "model" in k[1] for k in kinds)
    assert train["totals"]["bytes_moved"] > 0
    assert train["sharded_input_bytes_per_device"] > 0

    budget = json.loads(
        (REPO_ROOT / "budgets" / "train_cpu8.json").read_text())
    violations, notes = check_budget(manifest, budget)
    assert violations == [], (violations, notes)


def test_export_manifest_metrics_gauges(serve_manifest):
    from nanosandbox_tpu.analysis.shardcheck import (budget_from_manifest,
                                                     export_manifest_metrics)
    from nanosandbox_tpu.obs import MetricRegistry, render_prometheus

    reg = MetricRegistry()
    export_manifest_metrics(budget_from_manifest(serve_manifest), reg)
    text = render_prometheus(reg)
    assert "shardcheck_collectives_total" in text
    assert 'program="decode"' in text
    assert 'kind="none"' in text       # comms-free programs pin zero
    reg2 = MetricRegistry()
    export_manifest_metrics(
        _manifest({"train_step": [("all-gather", ("fsdp",), 4, 1000)]}),
        reg2)
    text2 = render_prometheus(reg2)
    assert 'kind="all-gather"' in text2 and "4" in text2


def test_shardcheck_cli_badge_usage_errors():
    from nanosandbox_tpu.analysis.shardcheck.cli import main as sc_main

    assert sc_main(["--mesh", "nope"]) == 2
    assert sc_main(["--fleet", "bogus"]) == 2


def test_shardcheck_cli_subcommand_dispatch(tmp_path):
    """End-to-end through `python -m nanosandbox_tpu.analysis
    shardcheck` in a fresh process (the CI invocation), on the cheap
    serve fleet, against the committed budget."""
    out = tmp_path / "manifest.json"
    proc = subprocess.run(
        [sys.executable, "-m", "nanosandbox_tpu.analysis", "shardcheck",
         "--fleet=serve", "--format=json", f"--out={out}",
         "--budget=budgets/serve_cpu8.json"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**__import__("os").environ, "XLA_FLAGS": "",
             "JAX_PLATFORMS": ""},
        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    manifest = json.loads(out.read_text())
    assert manifest["tool"] == "shardcheck"
    assert manifest["budget"]["violations"] == []
    assert "budget budgets/serve_cpu8.json OK" in proc.stdout
