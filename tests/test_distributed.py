"""Real multi-process jax.distributed.initialize (SURVEY.md §2.6).

Round 1 tested rank derivation and 8 virtual devices in ONE process, but
jax.distributed.initialize never actually executed (VERDICT.md missing
#4). This spawns 2 OS processes that rendezvous over a localhost
coordinator — the CPU-backend analogue of the reference's 2-process
torchrun tier (/root/reference/notebooks/colab_nanoGPT_companion.ipynb:108)
— with identity plumbed exactly as container/entrypoint.sh exports it
(COORDINATOR_ADDRESS/NUM_PROCESSES env, PROCESS_ID from the HOSTNAME
ordinal).
"""

import os
import re
import socket
import subprocess
import sys

import pytest


WORKER = os.path.join(os.path.dirname(__file__), "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(char_dataset, tmp_path, mode: str, local_devices: int):
    port = _free_port()
    procs = []
    try:
        for i in range(2):
            env = os.environ.copy()
            # Exactly the identity surface container/entrypoint.sh
            # exports: ordinal comes from the StatefulSet hostname, not
            # an explicit id.
            env.update({
                "HOSTNAME": f"train-multipod-{i}",
                "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "NUM_PROCESSES": "2",
            })
            env.pop("PROCESS_ID", None)
            # local_devices CPU devices per process (replacing the
            # 8-device spoof the parent test session uses) -> global mesh
            # of 2 real processes x local_devices.
            env["XLA_FLAGS"] = (
                "" if local_devices == 1 else
                f"--xla_force_host_platform_device_count={local_devices}")
            procs.append(subprocess.Popen(
                [sys.executable, WORKER, char_dataset,
                 str(tmp_path / f"o{i}"), mode],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))

        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        # A rendezvous hang leaves live workers holding the coordinator
        # port; never leak them past the test.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    # Every process reports the same globally-reduced loss & grad norm:
    # the gradient collective crossed the process boundary.
    losses = {re.search(r"DIST_LOSS (\S+)", o).group(1) for o in outs}
    gnorms = {re.search(r"DIST_GRADNORM (\S+)", o).group(1) for o in outs}
    assert len(losses) == 1, f"losses diverged across processes: {losses}"
    assert len(gnorms) == 1, f"grad norms diverged: {gnorms}"
    n_global = 2 * local_devices
    for out in outs:
        assert re.search(
            rf"devices={n_global} local={local_devices}", out), out
    return outs, float(losses.pop()), float(gnorms.pop())


def test_two_process_rendezvous_and_dp_step(char_dataset, tmp_path):
    _run_workers(char_dataset, tmp_path, "dp", local_devices=1)


def _single_process_reference(mode: str, char_dataset, tmp_path):
    """Replay the worker's exact global batch on the parent's own
    8-device single-process session with the same mesh/config."""
    import jax

    from nanosandbox_tpu.train import Trainer
    from tests._dist_worker import worker_config

    cfg = worker_config(mode, char_dataset, str(tmp_path / "ref"))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    step, _ = trainer.compiled_steps()
    xg, yg = trainer.dataset.sample_batch(
        "train", 0, cfg.batch_size, cfg.block_size, seed=cfg.seed)
    _, m = step(state, trainer.to_global(xg), trainer.to_global(yg),
                jax.random.key(0))
    return float(m["loss"]), float(m["grad_norm"])


@pytest.mark.parametrize("mode", ["fsdp8", "fsdp4sp2"])
def test_two_process_nontrivial_mesh(char_dataset, tmp_path, mode):
    """Round-2 VERDICT weak #6: a mesh axis must actually SPAN the
    process boundary. 2 processes x 4 local devices, fsdp sharding the
    params across both processes (and, in fsdp4sp2, ring attention's
    ppermute crossing it too); the globally-reduced loss must equal a
    single-process run of the identical mesh on the identical batch."""
    outs, loss, gnorm = _run_workers(char_dataset, tmp_path, mode,
                                     local_devices=4)
    for out in outs:
        assert re.search(r"FSDP_SPAN local_shards=4 global_devices=8", out), out
    ref_loss, ref_gnorm = _single_process_reference(mode, char_dataset,
                                                    tmp_path)
    assert loss == pytest.approx(ref_loss, rel=1e-4), (loss, ref_loss)
    assert gnorm == pytest.approx(ref_gnorm, rel=1e-4), (gnorm, ref_gnorm)
