"""Real multi-process jax.distributed.initialize (SURVEY.md §2.6).

Round 1 tested rank derivation and 8 virtual devices in ONE process, but
jax.distributed.initialize never actually executed (VERDICT.md missing
#4). This spawns 2 OS processes that rendezvous over a localhost
coordinator — the CPU-backend analogue of the reference's 2-process
torchrun tier (/root/reference/notebooks/colab_nanoGPT_companion.ipynb:108)
— with identity plumbed exactly as container/entrypoint.sh exports it
(COORDINATOR_ADDRESS/NUM_PROCESSES env, PROCESS_ID from the HOSTNAME
ordinal).
"""

import os
import re
import socket
import subprocess
import sys
import time

import pytest


WORKER = os.path.join(os.path.dirname(__file__), "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(char_dataset, tmp_path, mode: str, local_devices: int,
                 n_procs: int = 2):
    port = _free_port()
    procs = []
    try:
        for i in range(n_procs):
            env = os.environ.copy()
            # Exactly the identity surface container/entrypoint.sh
            # exports: ordinal comes from the StatefulSet hostname, not
            # an explicit id.
            env.update({
                "HOSTNAME": f"train-multipod-{i}",
                "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "NUM_PROCESSES": str(n_procs),
            })
            env.pop("PROCESS_ID", None)
            # local_devices CPU devices per process (replacing the
            # 8-device spoof the parent test session uses) -> global mesh
            # of 2 real processes x local_devices.
            env["XLA_FLAGS"] = (
                "" if local_devices == 1 else
                f"--xla_force_host_platform_device_count={local_devices}")
            procs.append(subprocess.Popen(
                [sys.executable, WORKER, char_dataset,
                 str(tmp_path / f"o{i}"), mode],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))

        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        # A rendezvous hang leaves live workers holding the coordinator
        # port; never leak them past the test.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    # Every process reports the same globally-reduced loss & grad norm:
    # the gradient collective crossed the process boundary.
    losses = {re.search(r"DIST_LOSS (\S+)", o).group(1) for o in outs}
    gnorms = {re.search(r"DIST_GRADNORM (\S+)", o).group(1) for o in outs}
    assert len(losses) == 1, f"losses diverged across processes: {losses}"
    assert len(gnorms) == 1, f"grad norms diverged: {gnorms}"
    n_global = n_procs * local_devices
    for out in outs:
        assert re.search(
            rf"devices={n_global} local={local_devices}", out), out
    return outs, float(losses.pop()), float(gnorms.pop())


def test_two_process_rendezvous_and_dp_step(char_dataset, tmp_path):
    _run_workers(char_dataset, tmp_path, "dp", local_devices=1)


def _single_process_reference(mode: str, char_dataset, tmp_path):
    """Replay the worker's exact global batch on the parent's own
    8-device single-process session with the same mesh/config."""
    import jax

    from nanosandbox_tpu.train import Trainer
    from tests._dist_worker import worker_config

    cfg = worker_config(mode, char_dataset, str(tmp_path / "ref"))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    step, _ = trainer.compiled_steps()
    xg, yg = trainer.dataset.sample_batch(
        "train", 0, cfg.batch_size, cfg.block_size, seed=cfg.seed)
    _, m = step(state, trainer.to_global(xg), trainer.to_global(yg),
                jax.random.key(0))
    return float(m["loss"]), float(m["grad_norm"])


def _launch_faulttol(char_dataset, out_dir: str, max_iters: int,
                     n_procs: int = 2):
    """N Trainer.run() workers against a SHARED out_dir (the RWX-PV
    layout), identity from the StatefulSet hostname ordinal."""
    port = _free_port()
    procs = []
    for i in range(n_procs):
        env = os.environ.copy()
        env.update({
            "HOSTNAME": f"train-multipod-{i}",
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "NUM_PROCESSES": str(n_procs),
            "FT_MAX_ITERS": str(max_iters),
        })
        env.pop("PROCESS_ID", None)
        env["XLA_FLAGS"] = ""
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, char_dataset, out_dir, "faulttol"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    return procs


def _committed_ckpt_steps(out_dir: str) -> set[int]:
    """Committed Orbax steps: local-FS commit is an atomic rename from a
    '<step>.orbax-checkpoint-tmp-*' dir to a bare '<step>' dir, so a
    digit-named directory existing == the checkpoint is complete."""
    ckpt_dir = os.path.join(out_dir, "ckpt")
    if not os.path.isdir(ckpt_dir):
        return set()
    return {int(d) for d in os.listdir(ckpt_dir) if d.isdigit()}


def _drain(procs, timeout=600):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    return outs


def test_fault_injection_kill_and_resume(char_dataset, tmp_path):
    """The reference's failure catalogue is pod-death-with-stable-identity
    (/root/reference/README.md:116-120): a worker dies mid-run, the
    StatefulSet restarts it under the SAME hostname ordinal, and the job
    must resume from the shared-PV checkpoint. Here: SIGKILL worker 1
    after the iter-3 Orbax checkpoint commits, restart BOTH workers (a
    dead collective peer takes the whole SPMD job down — same as NCCL)
    with identical env, and require the resumed run's final loss to EQUAL
    the uninterrupted reference — the loader is step-indexed and the
    trajectory deterministic, so recovery is exact, not approximate."""
    iters = 24
    ref_dir = str(tmp_path / "ref")
    procs = _launch_faulttol(char_dataset, ref_dir, iters)
    outs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"ref worker {i} failed:\n{out}"
    m = re.search(r"RUN_RESULT iter=(\d+) final_loss=(\S+)", outs[0])
    assert m and int(m.group(1)) == iters, outs[0]
    ref_loss = float(m.group(2))

    shared = str(tmp_path / "shared")
    procs = _launch_faulttol(char_dataset, shared, iters)
    try:
        deadline = time.time() + 300
        while not _committed_ckpt_steps(shared):
            assert time.time() < deadline, "no checkpoint appeared in 300s"
            assert procs[1].poll() is None, (
                "worker 1 exited before any checkpoint committed:\n"
                + procs[1].communicate()[0])
            time.sleep(0.2)
        # Fault: kill worker 1 the instant a checkpoint is committed —
        # mid-run by construction (24 iters + 7 more eval/ckpt blocks
        # remain at this point).
        assert procs[1].poll() is None, "worker 1 finished too early"
        procs[1].kill()
        killed_after = max(_committed_ckpt_steps(shared))
        # Worker 0 now has a dead collective peer; it can only hang or
        # crash, never finish (assert it did not race to completion).
        time.sleep(2.0)
        procs[0].kill()
    finally:
        _drain(procs, timeout=60)
    assert killed_after < iters

    # Restart with the SAME ordinal identity; init_from=auto must resume
    # from the committed step, not restart from scratch.
    procs = _launch_faulttol(char_dataset, shared, iters)
    outs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"restarted worker {i} failed:\n{out}"
    resumed = re.search(r"resumed from iter (\d+)", outs[0])
    assert resumed, f"restart did not resume from checkpoint:\n{outs[0]}"
    assert int(resumed.group(1)) >= killed_after >= 3
    m = re.search(r"RUN_RESULT iter=(\d+) final_loss=(\S+)", outs[0])
    assert m and int(m.group(1)) == iters, outs[0]
    assert float(m.group(2)) == pytest.approx(ref_loss, rel=1e-6), (
        f"resumed trajectory diverged: {m.group(2)} vs {ref_loss}")


@pytest.mark.parametrize("mode", ["fsdp8", "fsdp4sp2"])
def test_two_process_nontrivial_mesh(char_dataset, tmp_path, mode):
    """Round-2 VERDICT weak #6: a mesh axis must actually SPAN the
    process boundary. 2 processes x 4 local devices, fsdp sharding the
    params across both processes (and, in fsdp4sp2, ring attention's
    ppermute crossing it too); the globally-reduced loss must equal a
    single-process run of the identical mesh on the identical batch."""
    outs, loss, gnorm = _run_workers(char_dataset, tmp_path, mode,
                                     local_devices=4)
    for out in outs:
        assert re.search(r"FSDP_SPAN local_shards=4 global_devices=8", out), out
    ref_loss, ref_gnorm = _single_process_reference(mode, char_dataset,
                                                    tmp_path)
    assert loss == pytest.approx(ref_loss, rel=1e-4), (loss, ref_loss)
    assert gnorm == pytest.approx(ref_gnorm, rel=1e-4), (gnorm, ref_gnorm)


# -- 4-process tier (round-5 VERDICT next #3) ------------------------------
#
# The shipped StatefulSet is replicas: 4 / NUM_PROCESSES=4
# (k8s/statefulset/40-train-multipod.yaml:26,55), but until round 5 no
# test ever spawned more than 2 OS processes. This tier proves the
# shipped replica count: 4-process rendezvous, an fsdp mesh whose axis
# spans ALL FOUR processes with single-process loss parity, and a
# mid-ordinal SIGKILL/restart with exact resume.


def test_four_process_rendezvous_and_dp_step(char_dataset, tmp_path):
    _run_workers(char_dataset, tmp_path, "dp", local_devices=1, n_procs=4)


def test_four_process_fsdp_span_and_parity(char_dataset, tmp_path):
    """mesh fsdp=4 over 4 processes x 1 device: every param shard lives
    on a DIFFERENT process; the globally-reduced loss must equal a
    single-process run of the identical config on the identical batch."""
    outs, loss, gnorm = _run_workers(char_dataset, tmp_path, "fsdp4x1",
                                     local_devices=1, n_procs=4)
    for out in outs:
        assert re.search(r"FSDP_SPAN local_shards=1 global_devices=4", out), out
    ref_loss, ref_gnorm = _single_process_reference("fsdp4x1", char_dataset,
                                                    tmp_path)
    assert loss == pytest.approx(ref_loss, rel=1e-4), (loss, ref_loss)
    assert gnorm == pytest.approx(ref_gnorm, rel=1e-4), (gnorm, ref_gnorm)


def test_four_process_midordinal_kill_and_resume(char_dataset, tmp_path):
    """SIGKILL ordinal 2 (a MID ordinal — not first, not last) after a
    checkpoint commits; restart all four with the same identities;
    init_from=auto must resume and reach the uninterrupted run's exact
    final loss."""
    iters = 12
    ref_dir = str(tmp_path / "ref4")
    procs = _launch_faulttol(char_dataset, ref_dir, iters, n_procs=4)
    outs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"ref worker {i} failed:\n{out}"
    m = re.search(r"RUN_RESULT iter=(\d+) final_loss=(\S+)", outs[0])
    assert m and int(m.group(1)) == iters, outs[0]
    ref_loss = float(m.group(2))

    shared = str(tmp_path / "shared4")
    procs = _launch_faulttol(char_dataset, shared, iters, n_procs=4)
    try:
        deadline = time.time() + 300
        while not _committed_ckpt_steps(shared):
            assert time.time() < deadline, "no checkpoint appeared in 300s"
            assert procs[2].poll() is None, (
                "worker 2 exited before any checkpoint committed:\n"
                + procs[2].communicate()[0])
            time.sleep(0.2)
        assert procs[2].poll() is None, "worker 2 finished too early"
        procs[2].kill()
        killed_after = max(_committed_ckpt_steps(shared))
        time.sleep(2.0)
        for p in procs:
            if p.poll() is None:
                p.kill()
    finally:
        _drain(procs, timeout=60)
    assert killed_after < iters

    procs = _launch_faulttol(char_dataset, shared, iters, n_procs=4)
    outs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"restarted worker {i} failed:\n{out}"
    resumed = re.search(r"resumed from iter (\d+)", outs[0])
    assert resumed, f"restart did not resume from checkpoint:\n{outs[0]}"
    assert int(resumed.group(1)) >= killed_after >= 3
    m = re.search(r"RUN_RESULT iter=(\d+) final_loss=(\S+)", outs[0])
    assert m and int(m.group(1)) == iters, outs[0]
    assert float(m.group(2)) == pytest.approx(ref_loss, rel=1e-6), (
        f"resumed trajectory diverged: {m.group(2)} vs {ref_loss}")
