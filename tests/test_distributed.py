"""Real multi-process jax.distributed.initialize (SURVEY.md §2.6).

Round 1 tested rank derivation and 8 virtual devices in ONE process, but
jax.distributed.initialize never actually executed (VERDICT.md missing
#4). This spawns 2 OS processes that rendezvous over a localhost
coordinator — the CPU-backend analogue of the reference's 2-process
torchrun tier (/root/reference/notebooks/colab_nanoGPT_companion.ipynb:108)
— with identity plumbed exactly as container/entrypoint.sh exports it
(COORDINATOR_ADDRESS/NUM_PROCESSES env, PROCESS_ID from the HOSTNAME
ordinal).
"""

import os
import re
import socket
import subprocess
import sys


WORKER = os.path.join(os.path.dirname(__file__), "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_dp_step(char_dataset, tmp_path):
    port = _free_port()
    procs = []
    try:
        for i in range(2):
            env = os.environ.copy()
            # Exactly the identity surface container/entrypoint.sh
            # exports: ordinal comes from the StatefulSet hostname, not
            # an explicit id.
            env.update({
                "HOSTNAME": f"train-multipod-{i}",
                "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "NUM_PROCESSES": "2",
            })
            env.pop("PROCESS_ID", None)
            # One local CPU device per process (drop the 8-device spoof
            # the parent test session uses) -> global mesh of 2 real
            # processes.
            env["XLA_FLAGS"] = ""
            procs.append(subprocess.Popen(
                [sys.executable, WORKER, char_dataset,
                 str(tmp_path / f"o{i}")],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))

        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        # A rendezvous hang leaves live workers holding the coordinator
        # port; never leak them past the test.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    # Every process reports the same globally-reduced loss & grad norm:
    # the gradient allreduce crossed the process boundary.
    losses = {re.search(r"DIST_LOSS (\S+)", o).group(1) for o in outs}
    gnorms = {re.search(r"DIST_GRADNORM (\S+)", o).group(1) for o in outs}
    assert len(losses) == 1, f"losses diverged across processes: {losses}"
    assert len(gnorms) == 1, f"grad norms diverged: {gnorms}"
    # And each worker really saw 2 global devices / 1 local device.
    for out in outs:
        assert re.search(r"devices=2 local=1", out), out
