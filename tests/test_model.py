"""GPT model tests: shapes, tying, causality, init scale, param count."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT, count_params, cross_entropy_loss


def tiny(**kw):
    base = dict(n_layer=2, n_head=2, n_embd=32, block_size=16, vocab_size=65,
                dropout=0.0, compute_dtype="float32", attention_impl="xla")
    base.update(kw)
    return GPTConfig(**base)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = tiny()
    model = GPT(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"]
    return model, params, cfg


def test_forward_shape(model_and_params):
    model, params, cfg = model_and_params
    x = jnp.zeros((3, 16), jnp.int32)
    logits = model.apply({"params": params}, x)
    assert logits.shape == (3, 16, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_weight_tying(model_and_params):
    _, params, _ = model_and_params
    assert "lm_head" not in params  # head reuses wte.attend


def test_causality(model_and_params):
    model, params, _ = model_and_params
    rng = np.random.default_rng(0)
    x = rng.integers(0, 65, (1, 16))
    x2 = x.copy()
    x2[0, 10:] = rng.integers(0, 65, 6)  # perturb the future
    l1 = model.apply({"params": params}, jnp.asarray(x, jnp.int32))
    l2 = model.apply({"params": params}, jnp.asarray(x2, jnp.int32))
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_gpt2_124m_param_count():
    cfg = GPTConfig(n_layer=12, n_head=12, n_embd=768, block_size=1024,
                    vocab_size=50304, bias=False)
    model = GPT(cfg)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
    n = count_params(abstract["params"])
    # nanoGPT reports 124.34M for GPT-2 with wpe included at vocab 50304.
    assert 120e6 < n < 130e6


def test_cross_entropy_matches_manual():
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, 7)),
                         jnp.float32)
    targets = jnp.asarray([[1, 2, 3, -1], [0, 6, -1, -1]])
    loss = cross_entropy_loss(logits, targets)
    logp = jax.nn.log_softmax(logits, -1)
    manual = []
    for b in range(2):
        for t in range(4):
            if int(targets[b, t]) != -1:
                manual.append(-float(logp[b, t, int(targets[b, t])]))
    assert float(loss) == pytest.approx(np.mean(manual), rel=1e-5)


def test_dropout_requires_rng_and_varies():
    cfg = tiny(dropout=0.5)
    model = GPT(cfg)
    x = jnp.zeros((1, 8), jnp.int32)
    params = model.init({"params": jax.random.key(0),
                         "dropout": jax.random.key(1)}, x,
                        deterministic=False)["params"]
    a = model.apply({"params": params}, x, deterministic=False,
                    rngs={"dropout": jax.random.key(2)})
    b = model.apply({"params": params}, x, deterministic=False,
                    rngs={"dropout": jax.random.key(3)})
    assert not np.allclose(np.asarray(a), np.asarray(b))
    c = model.apply({"params": params}, x, deterministic=True)
    d = model.apply({"params": params}, x, deterministic=True)
    np.testing.assert_allclose(np.asarray(c), np.asarray(d))


def test_remat_matches(model_and_params):
    model, params, cfg = model_and_params
    rcfg = tiny(remat=True)
    rmodel = GPT(rcfg)
    x = jnp.zeros((2, 16), jnp.int32)
    a = model.apply({"params": params}, x)
    b = rmodel.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_block_size_overflow_raises(model_and_params):
    model, params, _ = model_and_params
    with pytest.raises(ValueError, match="block_size"):
        model.apply({"params": params}, jnp.zeros((1, 17), jnp.int32))


# -- chunked_cross_entropy_loss parity (ADVICE.md round-1 items 2+3) ------

def _chunk_case(B=2, T=12, C=32, V=65, seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.normal(size=(B, T, C)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(V, C)) * 0.1, jnp.float32)
    targets = rng.integers(0, V, (B, T))
    targets[0, :3] = -1  # ignore_index rows
    targets[1, -1] = -1
    return hidden, emb, jnp.asarray(targets, jnp.int32)


@pytest.mark.parametrize("chunk_size", [5, 4, 128])  # 5 does not divide 12
def test_chunked_loss_matches_full_f32(chunk_size):
    from nanosandbox_tpu.models.gpt import chunked_cross_entropy_loss

    hidden, emb, targets = _chunk_case()
    logits = hidden @ emb.T
    full = cross_entropy_loss(logits, targets)
    chunked = chunked_cross_entropy_loss(
        hidden, emb, targets, chunk_size=chunk_size,
        compute_dtype="float32")
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=1e-6, atol=1e-6)


def test_chunked_loss_grads_match_full_f32():
    from nanosandbox_tpu.models.gpt import chunked_cross_entropy_loss

    hidden, emb, targets = _chunk_case(seed=1)

    def full_fn(h, e):
        return cross_entropy_loss(h @ e.T, targets)

    def chunk_fn(h, e):
        return chunked_cross_entropy_loss(h, e, targets, chunk_size=4,
                                          compute_dtype="float32")

    gh_f, ge_f = jax.grad(full_fn, argnums=(0, 1))(hidden, emb)
    gh_c, ge_c = jax.grad(chunk_fn, argnums=(0, 1))(hidden, emb)
    np.testing.assert_allclose(np.asarray(gh_c), np.asarray(gh_f),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ge_c), np.asarray(ge_f),
                               rtol=1e-5, atol=1e-6)


def test_chunked_loss_bf16_within_rounding_of_full():
    """Documented tradeoff: chunked feeds the MXU bf16 inputs while the
    full path casts to f32 — under bf16 they agree to bf16 rounding."""
    from nanosandbox_tpu.models.gpt import chunked_cross_entropy_loss

    hidden, emb, targets = _chunk_case(seed=2)
    full = cross_entropy_loss(hidden @ emb.T, targets)
    chunked = chunked_cross_entropy_loss(
        hidden, emb, targets, chunk_size=4, compute_dtype="bfloat16")
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=2e-2, atol=2e-2)


def test_chunked_loss_all_ignored_is_zero():
    from nanosandbox_tpu.models.gpt import chunked_cross_entropy_loss

    hidden, emb, _ = _chunk_case()
    targets = jnp.full((2, 12), -1, jnp.int32)
    out = chunked_cross_entropy_loss(hidden, emb, targets, chunk_size=4,
                                     compute_dtype="float32")
    assert float(out) == 0.0


@pytest.mark.parametrize("policy", ["save_attention", "full"])
def test_remat_policies_match(model_and_params, policy):
    """Selective remat changes what's saved, never the math: outputs and
    gradients agree with the non-remat model."""
    model, params, cfg = model_and_params
    rmodel = GPT(tiny(remat=True, remat_policy=policy))
    x = jnp.zeros((2, 16), jnp.int32) + jnp.arange(16)[None, :] % 5
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": params}, x)),
        np.asarray(rmodel.apply({"params": params}, x)), atol=1e-5)

    def loss(m, p):
        return (m.apply({"params": p}, x).astype(jnp.float32) ** 2).mean()

    g1 = jax.grad(lambda p: loss(model, p))(params)
    g2 = jax.grad(lambda p: loss(rmodel, p))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_remat_policy_unknown_raises(model_and_params):
    _, params, _ = model_and_params
    bad = GPT(tiny(remat=True, remat_policy="nope"))
    with pytest.raises(ValueError, match="remat_policy"):
        bad.apply({"params": params}, jnp.zeros((1, 16), jnp.int32))


def test_save_attention_policy_elides_kernel_recompute():
    """The policy's reason to exist, pinned by counting pallas_calls in
    the grad jaxpr: a remat region discards custom_vjp residuals, so
    without the checkpoint_name tags on (o, lse) the flash forward runs
    TWICE in the backward (3 calls/layer); with them it runs once
    (2 = fwd + fused one-pass bwd), same as no remat."""

    def count_calls(remat, policy):
        cfg = tiny(block_size=128, attention_impl="pallas_interpret",
                   remat=remat, remat_policy=policy)
        model = GPT(cfg)
        x = jnp.zeros((1, 128), jnp.int32)
        params = model.init(jax.random.key(0), x)["params"]

        def loss(p):
            return (model.apply({"params": p}, x)
                    .astype(jnp.float32) ** 2).mean()

        return str(jax.make_jaxpr(jax.grad(loss))(params)).count(
            "pallas_call")

    assert count_calls(False, "full") == 2 * tiny().n_layer
    assert count_calls(True, "full") == 3 * tiny().n_layer
    assert count_calls(True, "save_attention") == 2 * tiny().n_layer
