"""jaxlint tests: fixture pairs per rule, suppressions, schema, CLI.

The ISSUE 3 acceptance bar:
  * each of the 5 rules catches its known-bad snippet while passing the
    known-good twin;
  * suppressions are honored ONLY with a reason (a bare disable is void
    and itself a finding);
  * the JSON report is schema-stable (CI uploads it as an artifact);
  * the tool exits 0 on the cleaned package tree (the self-clean gate —
    the same invocation CI runs).

Pure-ast tests: no jax import anywhere on this path, mirroring the CI
lint job, which runs jaxlint without installing jax.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from nanosandbox_tpu.analysis import analyze_paths, analyze_source
from nanosandbox_tpu.analysis.__main__ import main as cli_main

PACKAGE_ROOT = Path(__file__).resolve().parent.parent / "nanosandbox_tpu"


def rules_of(src, name="fixture.py", select=None):
    findings, suppressed = analyze_source(src, name, select=select)
    return [f.rule for f in findings], findings, suppressed


# ------------------------------------------------------------- rule fixtures
# One (known-bad, known-good) source pair per rule. The bad twin must
# trip EXACTLY its rule; the good twin must be clean under that rule.

FIXTURES = {
    "host-sync": (
        # float()/print() on values produced by a compiled callable,
        # inside the host loop that drives it.
        """
import jax

@jax.jit
def step(x):
    return x * 2

def serve_loop(batches):
    total = 0.0
    for b in batches:
        y = step(b)
        total += float(y)        # readback every iteration
        print(y)                 # and a device print
    return total
""",
        # Same loop, one deliberate readback through the blessed wrapper.
        """
import jax
from nanosandbox_tpu.utils import tracecheck

@jax.jit
def step(x):
    return x * 2

def serve_loop(batches):
    ys = [step(b) for b in batches]
    return tracecheck.host_sync("drain", ys[-1])
""",
    ),
    "tracer-leak": (
        # Python control flow on a traced array inside a jitted body.
        """
import jax
import jax.numpy as jnp

@jax.jit
def clamp(x):
    y = jnp.sum(x)
    if y > 0:
        return y
    while y < 0:
        y = y + 1
    return bool(y)
""",
        # Static introspection and lax-style selects stay silent.
        """
import jax
import jax.numpy as jnp

@jax.jit
def clamp(x, scale=None):
    y = jnp.sum(x)
    if scale is None:            # pytree-structure check: static
        scale = 1.0
    if x.shape[0] > 2:           # shapes are static under trace
        y = y * scale
    return jnp.where(y > 0, y, -y)
""",
    ),
    "nonstatic-shape": (
        # A raw len() of runtime data reaching a compiled call's shape:
        # one fresh XLA program per distinct queue length.
        """
import jax
import numpy as np

prefill = jax.jit(lambda p: p)

def admit(reqs, bucket):
    prompts = np.zeros((len(reqs), bucket), np.int32)
    return prefill(prompts)
""",
        # The engine's discipline: pad the wave size up a ladder first.
        """
import jax
import numpy as np

prefill = jax.jit(lambda p: p)

def rung_for(n):
    return 1 << max(n - 1, 0).bit_length()

def admit(reqs, bucket):
    k = rung_for(len(reqs))
    prompts = np.zeros((k, bucket), np.int32)
    return prefill(prompts)
""",
    ),
    "donation-misuse": (
        # Unguarded donation AND reuse of the donated buffer.
        """
import jax

def build(fn):
    step = jax.jit(fn, donate_argnums=(0,))
    return step

def run(step, state, batch):
    new_state = step(state, batch)
    print(state["step"])         # donated buffer: garbage on TPU
    return new_state
""",
        # Accelerator-gated donation, result rebound over the operand.
        """
import jax

def build(fn):
    on_accel = jax.default_backend() != "cpu"
    step = jax.jit(fn, donate_argnums=(0,) if on_accel else ())
    return step

def run(step, state, batch):
    state = step(state, batch)
    return state
""",
    ),
    "impure-trace": (
        # Trace-time randomness, clocks, and host-state mutation.
        """
import time

import jax
import numpy as np

class Engine:
    def _step_fn(self, x):
        self.trace_counts["step"] += 1
        noise = np.random.randn(4)
        t0 = time.time()
        return x + noise + t0

    def compile(self):
        import jax
        self._step = jax.jit(self._step_fn)
""",
        # Functional: randomness/time enter as operands, counters live
        # OUTSIDE the traced body (utils.tracecheck.compile_budget).
        """
import jax
import jax.numpy as jnp

class Engine:
    def _step_fn(self, x, key, t0):
        noise = jax.random.normal(key, (4,))
        return x + noise + t0

    def compile(self, budget):
        import jax
        self._step = jax.jit(budget("step", 1)(self._step_fn))
""",
    ),
    "unconstrained-output": (
        # in_shardings declared, output layout left to the partitioner.
        """
import jax

def step_fn(state, batch):
    return state

def build(state_shardings):
    return jax.jit(step_fn, in_shardings=(state_shardings, None))
""",
        # Pinned output layout (out_shardings); a second root constrains
        # its intermediate instead — both spellings are clean.
        """
import jax
from jax.lax import with_sharding_constraint

def step_fn(state, batch):
    return state

def frontier_fn(pool, start, sharding):
    pool = with_sharding_constraint(pool, sharding)
    return pool

def build(state_shardings, rep):
    a = jax.jit(step_fn, in_shardings=(state_shardings, None),
                out_shardings=state_shardings)
    b = jax.jit(frontier_fn, in_shardings=(state_shardings, None, None))
    return a, b
""",
    ),
    "implicit-replication": (
        # Placement-less device_put in a module that builds meshes.
        """
import jax
from jax.sharding import NamedSharding

def place(params):
    return jax.device_put(params)
""",
        # Spelled-out placement (positional or keyword).
        """
import jax
from jax.sharding import NamedSharding

def place(params, sharding):
    a = jax.device_put(params, sharding)
    b = jax.device_put(params, device=sharding)
    return a, b
""",
    ),
    "unconstrained-frontier-slice": (
        # A traced-offset slice of a pool in a mesh-aware module with
        # no constraint in sight — if the pool is sharded along dim 0,
        # GSPMD all-gathers ALL of it on every device (the shardcheck
        # frontier_slice fixture's accident, KV-pool edition). The
        # keyword-spelled offset must be caught too, and a
        # discarded-result constraint launders nothing (the functional
        # result is what carries the sharding).
        """
from jax.lax import dynamic_slice_in_dim, with_sharding_constraint
from jax.sharding import NamedSharding


def frontier(pool, start):
    return dynamic_slice_in_dim(pool, start, 8, axis=0)


def frontier_kw(pool, start):
    return dynamic_slice_in_dim(pool, start_index=start, slice_size=8,
                                axis=0)


def frontier_discarded(pool, start, sh):
    with_sharding_constraint(pool, sh)
    return dynamic_slice_in_dim(pool, start, 8, axis=0)
""",
        # The idiom: reshard OFF the sliced dim first — in place or as
        # a rebind to a NEW name; static-offset windows are fine (GSPMD
        # partitions fixed slices without materializing anything).
        """
from jax.lax import dynamic_slice_in_dim, with_sharding_constraint
from jax.sharding import NamedSharding, PartitionSpec as P


def frontier(pool, start, mesh):
    pool = with_sharding_constraint(
        pool, NamedSharding(mesh, P(None, "fsdp")))
    return dynamic_slice_in_dim(pool, start, 8, axis=0)


def frontier_rebound(pool, start, mesh):
    pool_c = with_sharding_constraint(
        pool, NamedSharding(mesh, P(None, "fsdp")))
    return dynamic_slice_in_dim(pool_c, start, 8, axis=0)


def static_window(pool):
    return dynamic_slice_in_dim(pool, 0, 8, axis=0)
""",
    ),
    "axis-mismatch": (
        # 'sequence' is not a registered mesh axis (it's 'seq').
        """
from jax.sharding import PartitionSpec as P

BATCH = P(("data", "fsdp"), "sequence")
""",
        # Registered names only — including inside tuple groups.
        """
from jax.sharding import PartitionSpec as P

BATCH = P(("data", "fsdp"), "seq")
PARAM = P(None, "model")
REPL = P()
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_catches_bad_and_passes_good(rule):
    bad, good = FIXTURES[rule]
    bad_rules, findings, _ = rules_of(bad)
    assert rule in bad_rules, \
        f"{rule} missed its known-bad fixture: {findings}"
    assert all(r == rule for r in bad_rules), \
        f"unexpected extra rules on the {rule} bad fixture: {findings}"
    good_rules, findings, _ = rules_of(good)
    assert rule not in good_rules, \
        f"{rule} false-positived on its known-good twin: {findings}"


def test_bad_fixture_messages_name_the_function():
    _, findings, _ = rules_of(FIXTURES["host-sync"][0])
    assert any("serve_loop" in f.message for f in findings)


def test_select_restricts_rules():
    bad = FIXTURES["donation-misuse"][0]
    rules, _, _ = rules_of(bad, select=["host-sync"])
    assert rules == []
    with pytest.raises(ValueError, match="unknown rule"):
        analyze_source(bad, select=["not-a-rule"])


# -------------------------------------------------------------- suppressions

def test_suppression_with_reason_is_honored():
    # nonstatic-shape findings anchor at the compiled CALL site — the
    # disable goes there.
    src = FIXTURES["nonstatic-shape"][0].replace(
        "return prefill(prompts)",
        "return prefill(prompts)"
        "  # jaxlint: disable=nonstatic-shape -- test rig, one shape")
    rules, _, suppressed = rules_of(src)
    assert rules == []
    assert suppressed == 1


def test_standalone_suppression_covers_next_statement():
    src = FIXTURES["nonstatic-shape"][0].replace(
        "    return prefill(prompts)",
        "    # jaxlint: disable=nonstatic-shape -- test rig, one shape\n"
        "    # (prose between stacked disables is fine)\n"
        "    return prefill(prompts)")
    rules, _, suppressed = rules_of(src)
    assert rules == []
    assert suppressed == 1


def test_standalone_suppression_does_not_reach_past_code():
    """A code line between a standalone disable and a violation keeps
    the violation live — the disable must sit ON or directly ABOVE the
    offending statement, so later edits can't inherit an old audit."""
    src = FIXTURES["nonstatic-shape"][0].replace(
        "    prompts = np.zeros((len(reqs), bucket), np.int32)",
        "    # jaxlint: disable=nonstatic-shape -- audits the zeros only\n"
        "    prompts = np.zeros((len(reqs), bucket), np.int32)")
    # The finding anchors at `return prefill(prompts)`, which sits
    # BELOW the (clean) constructor line: not covered.
    rules, _, suppressed = rules_of(src)
    assert "nonstatic-shape" in rules and suppressed == 0


def test_unknown_rule_id_in_suppression_is_flagged():
    """A typo'd disable must not sit inert while the author believes
    the violation is audited."""
    src = FIXTURES["nonstatic-shape"][0].replace(
        "return prefill(prompts)",
        "return prefill(prompts)"
        "  # jaxlint: disable=nonstatic-shapes -- typo'd rule id")
    rules, findings, suppressed = rules_of(src)
    assert suppressed == 0
    assert "nonstatic-shape" in rules       # the real finding survives
    assert "bad-suppression" in rules
    assert any("unknown rule id" in f.message for f in findings)


def test_reasonless_suppression_matching_nothing_still_flagged():
    src = "x = 1  # jaxlint: disable=host-sync\n"
    rules, _, _ = rules_of(src)
    assert rules == ["bad-suppression"]


def test_suppression_without_reason_is_void_and_flagged():
    src = FIXTURES["nonstatic-shape"][0].replace(
        "return prefill(prompts)",
        "return prefill(prompts)"
        "  # jaxlint: disable=nonstatic-shape")
    rules, _, suppressed = rules_of(src)
    assert suppressed == 0
    assert "nonstatic-shape" in rules      # the disable did NOT apply
    assert "bad-suppression" in rules      # and is itself a finding


def test_suppression_in_string_literal_is_inert():
    src = FIXTURES["nonstatic-shape"][0].replace(
        "    return prefill(prompts)",
        "    s = '# jaxlint: disable=nonstatic-shape -- nope'\n"
        "    return prefill(prompts)")
    rules, _, suppressed = rules_of(src)
    assert "nonstatic-shape" in rules and suppressed == 0


def test_suppression_for_other_rule_does_not_apply():
    src = FIXTURES["nonstatic-shape"][0].replace(
        "prompts = np.zeros((len(reqs), bucket), np.int32)",
        "prompts = np.zeros((len(reqs), bucket), np.int32)"
        "  # jaxlint: disable=host-sync -- wrong rule")
    rules, _, _ = rules_of(src)
    assert "nonstatic-shape" in rules


def test_unused_reasoned_suppression_reported_and_strict():
    """ISSUE 7 satellite: a reasoned disable whose line no longer trips
    its rule is reported (and --strict-suppressions makes it a
    finding), so audits can't rot in place."""
    from nanosandbox_tpu.analysis.core import drain_unused_suppressions

    drain_unused_suppressions()
    src = "x = 1  # jaxlint: disable=host-sync -- stale audit\n"
    findings, suppressed = analyze_source(src, "mod.py")
    assert findings == [] and suppressed == 0
    unused = drain_unused_suppressions()
    assert len(unused) == 1
    assert unused[0]["rules"] == ["host-sync"]
    assert unused[0]["reason"] == "stale audit"

    # strict: the rot becomes a finding (and the CI gate trips).
    findings, _ = analyze_source(src, "mod.py", strict_suppressions=True)
    assert [f.rule for f in findings] == ["unused-suppression"]
    drain_unused_suppressions()

    # A USED suppression is never reported unused.
    used = FIXTURES["nonstatic-shape"][0].replace(
        "return prefill(prompts)",
        "return prefill(prompts)"
        "  # jaxlint: disable=nonstatic-shape -- test rig, one shape")
    findings, suppressed = analyze_source(used, "mod.py",
                                          strict_suppressions=True)
    assert findings == [] and suppressed == 1
    assert drain_unused_suppressions() == []


def test_unused_suppression_not_judged_under_select():
    """--select runs a rule subset; a suppression for an unselected
    rule never got a chance to match and must not be called unused."""
    from nanosandbox_tpu.analysis.core import drain_unused_suppressions

    drain_unused_suppressions()
    src = "x = 1  # jaxlint: disable=host-sync -- audited elsewhere\n"
    findings, _ = analyze_source(src, "mod.py", select=["tracer-leak"],
                                 strict_suppressions=True)
    assert findings == []
    assert drain_unused_suppressions() == []
    # `disable=all` may suppress ANY rule, so it is only judged under a
    # full run — an unselected rule could be what it audits.
    src = "y = 2  # jaxlint: disable=all -- audited readback\n"
    findings, _ = analyze_source(src, "mod.py", select=["tracer-leak"],
                                 strict_suppressions=True)
    assert findings == []
    assert drain_unused_suppressions() == []
    findings, _ = analyze_source(src, "mod.py", strict_suppressions=True)
    assert [f.rule for f in findings] == ["unused-suppression"]
    drain_unused_suppressions()


def test_report_carries_unused_suppressions(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text("y = 2  # jaxlint: disable=tracer-leak -- old fix\n")
    report = analyze_paths([str(tmp_path)])
    assert report["summary"]["findings"] == 0
    assert len(report["unused_suppressions"]) == 1
    assert report["unused_suppressions"][0]["line"] == 1
    from nanosandbox_tpu.analysis import render_text

    assert "unused suppression" in render_text(report)
    # strict run: same tree now fails.
    report = analyze_paths([str(tmp_path)], strict_suppressions=True)
    assert report["summary"]["by_rule"] == {"unused-suppression": 1}


# ------------------------------------------------------------ report + CLI

def test_parse_error_is_a_finding_not_a_crash():
    rules, findings, _ = rules_of("def broken(:\n")
    assert rules == ["parse-error"]


def test_json_schema(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(FIXTURES["host-sync"][0])
    report = analyze_paths([str(tmp_path)])
    assert report["version"] == 1
    assert report["tool"] == "jaxlint"
    assert report["summary"]["files_scanned"] == 1
    assert report["summary"]["findings"] == len(report["findings"]) > 0
    assert report["summary"]["by_rule"] == {"host-sync": 2}
    for item in report["findings"]:
        assert set(item) == {"file", "line", "col", "rule", "message"}
        assert isinstance(item["line"], int) and item["line"] > 0


def test_cli_exit_codes_and_artifact(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["tracer-leak"][0])
    good = tmp_path / "good.py"
    good.write_text(FIXTURES["tracer-leak"][1])
    out = tmp_path / "report.json"

    assert cli_main([str(good)]) == 0
    assert cli_main(["--format=json", f"--out={out}", str(bad)]) == 1
    report = json.loads(out.read_text())
    assert report["summary"]["by_rule"] == {"tracer-leak": 3}
    # The human summary still reached stdout next to the artifact.
    assert "jaxlint:" in capsys.readouterr().out
    assert cli_main([str(tmp_path / "nowhere")]) == 2
    assert cli_main(["--select=bogus", str(good)]) == 2
    assert cli_main(["--list-rules"]) == 0


def test_changed_only_resolves_from_git_diff(tmp_path):
    """ISSUE 7 satellite: --changed-only lints the `git diff
    --name-only <base>` set — the fast pre-commit run."""
    from nanosandbox_tpu.analysis.__main__ import changed_only_paths

    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       capture_output=True,
                       env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t",
                            "HOME": str(tmp_path), "PATH": "/usr/bin:/bin"})

    git("init", "-q")
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "a.py").write_text("x = 1\n")
    (tmp_path / "pkg" / "b.py").write_text("y = 1\n")
    (tmp_path / "other.py").write_text("z = 1\n")
    (tmp_path / "notes.txt").write_text("n\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")

    # Nothing changed -> empty set.
    assert changed_only_paths(["pkg"], "HEAD", cwd=tmp_path) == []

    (tmp_path / "pkg" / "a.py").write_text(FIXTURES["tracer-leak"][0])
    (tmp_path / "other.py").write_text("z = 2\n")   # outside pkg/
    (tmp_path / "notes.txt").write_text("m\n")      # not .py
    changed = changed_only_paths(["pkg"], "HEAD", cwd=tmp_path)
    assert [Path(p).name for p in changed] == ["a.py"]
    # The resolved set feeds the ordinary analyzer and finds the leak.
    report = analyze_paths(changed)
    assert report["summary"]["by_rule"] == {"tracer-leak": 3}

    # Invoked from a subdirectory, git paths still resolve against the
    # repo ROOT (git prints root-relative names) — and a lint root that
    # does not exist from the invocation dir fails loudly instead of
    # silently matching nothing.
    sub = tmp_path / "pkg"
    changed = changed_only_paths(["."], "HEAD", cwd=sub)
    assert [Path(p).name for p in changed] == ["a.py"]
    with pytest.raises(RuntimeError, match="do not exist"):
        changed_only_paths(["pkg"], "HEAD", cwd=sub)

    # A bad base ref is a usage error, not a crash.
    with pytest.raises(RuntimeError, match="git diff"):
        changed_only_paths(["pkg"], "no-such-ref", cwd=tmp_path)


def test_cli_runs_without_jax_importable():
    """The CI lint job runs jaxlint on a bare Python: make the 'no jax
    needed' contract executable by poisoning jax at import time."""
    code = (
        "import sys; sys.modules['jax'] = None\n"
        "from nanosandbox_tpu.analysis.__main__ import main\n"
        f"raise SystemExit(main(['--list-rules']))\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True,
                          cwd=str(PACKAGE_ROOT.parent), timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "host-sync" in proc.stdout


# ------------------------------------------------------------ self-clean gate

def test_package_tree_is_clean():
    """The acceptance bar CI enforces: jaxlint exits 0 on the cleaned
    nanosandbox_tpu/ tree (deliberate syncs are all reason-suppressed)."""
    report = analyze_paths([str(PACKAGE_ROOT)])
    assert report["summary"]["files_scanned"] > 30
    msgs = [f"{f['file']}:{f['line']} {f['rule']}: {f['message']}"
            for f in report["findings"]]
    assert not msgs, "jaxlint findings on the package tree:\n" + \
        "\n".join(msgs)
    # The deliberate syncs (engine readbacks, benchmarking fences...)
    # are suppressed WITH reasons, not invisible.
    assert report["summary"]["suppressed"] >= 5
    # And none of those audits has rotted: every reasoned disable in
    # the tree still matches a live finding (the CI gate runs
    # --strict-suppressions, so rot would fail there too).
    assert report["unused_suppressions"] == []
