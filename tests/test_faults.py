"""Fault injection, crash-safe recovery and graceful degradation tests.

The contract under test (ISSUE 11):
  * FaultPlan is deterministic: same plan + same workload -> same
    failure at the same site, every run; plans parse from the flag
    syntax and canned names; zero cost / zero compile-set change when
    no plan is attached (pinned against a never-firing plan).
  * Recovery correctness: a fault injected mid-decode on a mixed greedy
    batch quarantines, rebuilds device state, re-admits every in-flight
    request through the normal admission path, and the recovered engine
    finishes ALL of them with outputs token-identical to a no-fault run
    (row keys derive from fold_in(seed, absolute position), so the
    resumed stream continues exactly where the fault cut it) — paged
    AND dense, poison path AND exception path.
  * Exactly-once terminals: a request admitted, interrupted, re-admitted
    and finished emits exactly one terminal flight event and zero
    orphaned evicts (fuzzed across spec/paged/dense mixes).
  * Graceful degradation: drafter faults degrade a step to plain decode
    and a streak disables spec (outputs unchanged); allocation failures
    are backpressure, not crashes; permanent failure drains cleanly
    (terminal 'failed' Results with salvaged partial tokens,
    submissions refused) instead of crash-looping.
  * Watchdog dump race regression: concurrent trips of different kinds
    serialize and write kind-suffixed files.
  * HTTP status hygiene: shed -> 429 + Retry-After; drain/quarantine ->
    503; readiness flips on drain; flight records the returned status.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.obs import TERMINAL_EVENTS, render_prometheus
from nanosandbox_tpu.serve import (Engine, EngineFailedError,
                                   EngineSupervisor, FaultInjected,
                                   FaultPlan, NGramDrafter, SlotScheduler)
from nanosandbox_tpu.utils import tracecheck as _tracecheck


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _mixed_workload(eng, vocab, n=6, seed=3, budget=None, eos_id=None):
    """Deterministic greedy mix: varied prompt lengths and budgets, the
    same stream for every engine fed the same seed."""
    rng = np.random.default_rng(seed)
    rids = []
    for i in range(n):
        L = int(rng.integers(1, 24))
        mnt = budget if budget is not None else int(rng.integers(4, 10))
        kw = {}
        if eos_id is not None and i % 3 == 0:
            kw["eos_id"] = eos_id
        rids.append(eng.submit(rng.integers(0, vocab, L).tolist(), mnt,
                               **kw))
    return rids


def _drive(sup, limit=5000):
    """Run a supervised engine to idle, collecting results by rid."""
    got = {}
    n = 0
    while sup.engine.has_work() and n < limit:
        for r in sup.step():
            got[r.rid] = r
        n += 1
    assert n < limit, "supervised engine failed to drain"
    return got


# ------------------------------------------------------------ fault plan

def test_fault_plan_parse_fire_and_rearm():
    plan = FaultPlan.parse("nan_logits@4x2,slow_step@10:0.25,"
                           "alloc_fail@0x3")
    # before step 4: nothing fires at the nan site
    assert plan.fire("nan_logits", 3) is None
    f = plan.fire("nan_logits", 4)
    assert f is not None and f.site == "nan_logits"
    assert plan.fire("nan_logits", 5) is not None   # count=2
    assert plan.fire("nan_logits", 6) is None       # drained
    # count-based firing drains even with a frozen step counter (an
    # admission stall dispatches nothing, steps never advance)
    assert sum(plan.fire("alloc_fail", 0) is not None
               for _ in range(5)) == 3
    s = plan.fire("slow_step", 10)
    assert s is not None and s.stall_s == 0.25
    assert len(plan.fired_log) == 6
    # rearm: firing state resets, steps re-base
    plan.rearm(100)
    assert plan.fire("nan_logits", 100) is None     # rel 0 < 4
    assert plan.fire("nan_logits", 104) is not None
    # canned names expand; unknown sites refuse
    assert FaultPlan.parse("chaos-smoke").describe()
    with pytest.raises(ValueError):
        FaultPlan.parse("warp_core_breach@3")
    with pytest.raises(ValueError):
        FaultPlan.parse("nan_logits")


def test_fault_plan_probabilistic_is_deterministic():
    a = FaultPlan.parse("drafter_fault@p0.3", seed=7)
    b = FaultPlan.parse("drafter_fault@p0.3", seed=7)
    fa = [a.fire("drafter_fault", i) is not None for i in range(50)]
    fb = [b.fire("drafter_fault", i) is not None for i in range(50)]
    assert fa == fb
    # "each visit flips the coin": multiple fires across visits (a
    # count=1 default would stop after the first hit), and both
    # outcomes occur
    assert 1 < sum(fa) < 50


def test_disabled_plan_never_fires():
    plan = FaultPlan.parse("nan_logits@0x99")
    plan.enabled = False
    assert plan.fire("nan_logits", 10) is None
    assert plan.fired_log == []


# --------------------------------------------------- recovery correctness

@pytest.mark.parametrize("paged", [True, False])
def test_poisoned_step_recovery_token_identical(served_model, paged):
    """THE acceptance pin: a fault mid-decode on a mixed greedy batch ->
    quarantine, rebuild, re-admit; every request finishes with outputs
    token-identical to a no-fault run, and the recovery metrics appear
    on the engine registry (/metrics)."""
    cfg, model, params = served_model

    def build(faults=None):
        return Engine(model, params, num_slots=4, max_len=64,
                      paged=paged, faults=faults)

    clean = build()
    _mixed_workload(clean, cfg.vocab_size)
    want = {r.rid: (r.prompt, r.tokens, r.finish_reason)
            for r in clean.drain()}

    plan = FaultPlan.parse("nan_logits@4")
    eng = build(faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    _mixed_workload(eng, cfg.vocab_size)
    got = {rid: (r.prompt, r.tokens, r.finish_reason)
           for rid, r in _drive(sup).items()}
    assert plan.fired_log, "fault never fired — the pin tested nothing"
    assert eng.recoveries >= 1 and sup.recoveries >= 1
    assert got == want
    assert not eng.quarantined and sup.state == "ok"
    text = render_prometheus(eng.metrics)
    assert 'serve_engine_recoveries_total{cause="poisoned_step"} 1' \
        in text
    assert "serve_engine_recovery_seconds" in text
    assert "serve_recovery_ttfrt_seconds" in text
    assert eng.stats()["recovery"]["recoveries"] == eng.recoveries


def test_prefill_exception_recovery_flushes_and_matches(served_model):
    """A dispatch crash mid-admission (blocks committed, wave in limbo)
    recovers on the exception path — cache flushed, pool rebuilt — and
    still finishes everything token-identically."""
    cfg, model, params = served_model
    clean = Engine(model, params, num_slots=4, max_len=64)
    _mixed_workload(clean, cfg.vocab_size, n=10)
    want = {r.rid: (r.tokens, r.finish_reason) for r in clean.drain()}

    plan = FaultPlan.parse("prefill_exc@2")
    eng = Engine(model, params, num_slots=4, max_len=64, faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    _mixed_workload(eng, cfg.vocab_size, n=10)
    got = {rid: (r.tokens, r.finish_reason)
           for rid, r in _drive(sup).items()}
    assert plan.fired_log and eng.recoveries >= 1
    assert got == want
    # the block pool survived the unwind intact
    eng.block_pool.check([st.alloc for st in eng._active.values()
                          if st.alloc is not None])


def test_scatter_corrupt_detected_at_wave_readback(served_model):
    cfg, model, params = served_model
    clean = Engine(model, params, num_slots=4, max_len=64)
    _mixed_workload(clean, cfg.vocab_size)
    want = {r.rid: r.tokens for r in clean.drain()}
    plan = FaultPlan.parse("scatter_corrupt@1")
    eng = Engine(model, params, num_slots=4, max_len=64, faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    _mixed_workload(eng, cfg.vocab_size)
    got = {rid: r.tokens for rid, r in _drive(sup).items()}
    assert plan.fired_log and eng.recoveries >= 1
    assert got == want


def test_stalled_step_watchdog_triggers_recovery(served_model, tmp_path):
    """A slow (stalled) decode step trips the stalled_step watchdog and
    the supervisor treats it as recoverable — the no-exception wedge
    class."""
    cfg, model, params = served_model
    clean = Engine(model, params, num_slots=4, max_len=64)
    _mixed_workload(clean, cfg.vocab_size)
    want = [r.tokens for r in sorted(clean.drain(), key=lambda r: r.rid)]
    plan = FaultPlan.parse("slow_step@3:0.12")
    plan.enabled = False
    eng = Engine(model, params, num_slots=4, max_len=64, faults=plan,
                 watchdog_dir=str(tmp_path))
    eng.watchdog.stalled_step_s = 0.05
    # Warm the compile set first: a step that COMPILES is legitimately
    # slow and deliberately does NOT feed the stalled_step detector, so
    # the stall must be injected into a steady-state step.
    _mixed_workload(eng, cfg.vocab_size)
    eng.drain()
    eng.reset_prefix_cache()      # cold cache: run 2 sees run 1's shapes
    plan.rearm(eng.steps)
    plan.enabled = True
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    _mixed_workload(eng, cfg.vocab_size)
    got = [r.tokens for r in
           sorted(_drive(sup).values(), key=lambda r: r.rid)]
    assert plan.fired_log
    assert eng.watchdog.trips.get("stalled_step", 0) >= 1
    assert eng.recoveries >= 1
    assert got == want


def test_double_fault_resume_stitches_once(served_model):
    """TWO faults interrupting the same requests still yield one
    terminal each and token-identical stitched outputs (the _Resume
    record accumulates across recoveries)."""
    cfg, model, params = served_model
    clean = Engine(model, params, num_slots=4, max_len=64)
    _mixed_workload(clean, cfg.vocab_size, budget=16)
    want = {r.rid: (r.prompt, r.tokens) for r in clean.drain()}
    plan = FaultPlan.parse("nan_logits@3,nan_logits@9")
    eng = Engine(model, params, num_slots=4, max_len=64, faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    _mixed_workload(eng, cfg.vocab_size, budget=16)
    got = {rid: (r.prompt, r.tokens) for rid, r in _drive(sup).items()}
    assert len(plan.fired_log) == 2 and eng.recoveries == 2
    assert got == want
    for rid in got:
        assert eng.flight.terminals(rid) == ["finish"]


def test_requeued_victim_shed_unstitches_and_does_not_leak(served_model):
    """Regression: a recovery-requeued victim whose deadline expires
    before re-admission must shed with the ORIGINAL prompt, the
    salvaged pre-fault tokens, one terminal, and no leaked _Resume
    record."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    prompt = [3, 4, 5]
    rid = eng.submit(prompt, 12, deadline_s=0.2)
    for _ in range(4):
        eng.step()
    pre = list(next(iter(eng._active.values())).tokens)
    assert pre, "victim never generated — scenario broken"
    eng.quarantine("poisoned_step")
    eng.recover("poisoned_step")
    assert rid in eng._resumed
    time.sleep(0.25)                     # deadline expires in the queue
    results = eng.step()
    assert [r.rid for r in results] == [rid]
    r = results[0]
    assert r.finish_reason == "shed"
    assert r.prompt == tuple(prompt)     # NOT prompt + generated tokens
    assert r.tokens == pre               # salvaged partial output
    assert eng._resumed == {}            # no leak
    assert eng.flight.terminals(rid) == ["shed"]


def test_recover_handles_active_admitting_overlap(served_model):
    """Regression: a crash INSIDE the wave-commit loop leaves a request
    in BOTH _active and _admitting; recover() must release its slot and
    blocks exactly once (a double release used to crash the recovery
    itself) and the victim still finishes normally."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    rid = eng.submit([1, 2, 3], 6)
    eng.step()                                 # admitted into a slot
    st = next(iter(eng._active.values()))
    eng._admitting = [(st.req, st.slot, st.alloc)]   # the crash window
    eng.quarantine("test_overlap")
    eng.recover("test_overlap")                # must not raise
    eng.block_pool.check([])
    results = eng.drain()
    assert [(r.rid, r.finish_reason) for r in results] == [(rid, "length")]
    assert len(results[0].tokens) == 6
    assert eng.flight.terminals(rid) == ["finish"]


# ------------------------------------------------ exactly-once terminals

def test_exactly_once_terminals_under_recovery_fuzz(served_model):
    """Fuzz the no-orphan contract across paged/dense/spec mixes with
    faults landing mid-flight: every request reaches EXACTLY one
    terminal, and no evict is orphaned (every evicted rid finishes,
    exactly once — interrupted requests are requeued, not evicted).
    The plan includes a preempt_storm burst (ISSUE 13): preempted-then-
    finished requests must emit one terminal and zero orphaned evicts
    too — a preemption is a requeue, never a terminal."""
    cfg, model, params = served_model
    cases = [
        dict(paged=True),
        dict(paged=False),
        dict(paged=True, spec=NGramDrafter(k=3)),
    ]
    preempts = 0
    for i, case in enumerate(cases):
        plan = FaultPlan.parse("nan_logits@3,prefill_exc@9,"
                               "preempt_storm@12x2,nan_logits@15")
        eng = Engine(model, params, num_slots=4, max_len=64,
                     faults=plan, **case)
        sup = EngineSupervisor(eng, backoff_base_s=0.0)
        rids = _mixed_workload(eng, cfg.vocab_size, n=10, seed=20 + i,
                               eos_id=1)
        rids.append(eng.submit([2, 3], 0))          # zero-token terminal
        got = _drive(sup)
        assert plan.fired_log, case
        preempts += eng.preemptions
        events = eng.flight.events()
        for rid in rids:
            terms = [e for e in events if e.get("rid") == rid
                     and e["ev"] in TERMINAL_EVENTS]
            assert len(terms) == 1, (case, rid, terms)
            evicts = [e for e in events if e.get("rid") == rid
                      and e["ev"] == "evict"]
            assert len(evicts) <= 1, (case, rid)
            if evicts:
                assert terms[0]["ev"] == "finish", (case, rid)
        assert set(got) == set(rids)
    assert preempts >= 1, "preempt_storm never fired — the extension " \
                          "pinned nothing"


# --------------------------------------------------- graceful degradation

def test_drafter_fault_streak_disables_spec_not_engine(served_model):
    """Drafter faults degrade the step to plain decode; a streak
    disables spec for good — outputs stay token-identical to the
    non-spec engine throughout (greedy spec == greedy non-spec is the
    existing invariant)."""
    cfg, model, params = served_model
    clean = Engine(model, params, num_slots=4, max_len=64)
    _mixed_workload(clean, cfg.vocab_size, budget=20)
    want = {r.rid: r.tokens for r in clean.drain()}
    eng = Engine(model, params, num_slots=4, max_len=64,
                 spec=NGramDrafter(k=3),
                 faults=FaultPlan.parse("drafter_fault@2x99"),
                 spec_fault_tolerance=3)
    _mixed_workload(eng, cfg.vocab_size, budget=20)
    got = {r.rid: r.tokens for r in eng.drain()}
    assert got == want
    assert eng.drafter_faults == 3           # disabled after tolerance
    assert eng.spec_disabled_reason is not None
    assert eng._spec is None
    assert eng.stats()["recovery"]["spec_disabled"] is not None
    assert any(e["ev"] == "spec_disabled" for e in eng.flight.events())


def test_transient_drafter_fault_only_degrades_one_step(served_model):
    """A single drafter blip below the tolerance keeps spec ENABLED
    (the streak resets on the next healthy draft)."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64,
                 spec=NGramDrafter(k=3),
                 faults=FaultPlan.parse("drafter_fault@2"),
                 spec_fault_tolerance=3)
    _mixed_workload(eng, cfg.vocab_size, budget=20)
    eng.drain()
    assert eng.drafter_faults == 1
    assert eng.spec_disabled_reason is None and eng._spec is not None


def test_alloc_fail_is_backpressure_not_a_crash(served_model):
    cfg, model, params = served_model
    clean = Engine(model, params, num_slots=4, max_len=64)
    _mixed_workload(clean, cfg.vocab_size)
    want = {r.rid: r.tokens for r in clean.drain()}
    eng = Engine(model, params, num_slots=4, max_len=64,
                 faults=FaultPlan.parse("alloc_fail@0x12"))
    _mixed_workload(eng, cfg.vocab_size)
    got = {r.rid: r.tokens for r in eng.drain()}
    assert got == want
    assert eng.block_pool.stall_steps >= 12
    assert eng.recoveries == 0               # no rebuild needed


def test_permanent_failure_drains_cleanly(served_model):
    """Recovery that never converges escalates: terminal 'failed'
    Results with salvaged partial tokens, exactly one terminal per rid,
    submissions refused with EngineFailedError — no crash loop."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64,
                 faults=FaultPlan.parse("nan_logits@0x99"))
    sup = EngineSupervisor(eng, max_consecutive=2, backoff_base_s=0.0)
    rids = _mixed_workload(eng, cfg.vocab_size, n=8)
    results = []
    for _ in range(500):
        results.extend(sup.step())
        if sup.state == "failed" and not eng.has_work():
            break
    assert sup.state == "failed" and eng.failed
    assert sorted(r.rid for r in results) == sorted(rids)
    by_rid = {r.rid: r for r in results}
    for rid in rids:
        assert by_rid[rid].finish_reason == "failed"
        terms = eng.flight.terminals(rid)
        assert terms == ["failed"], (rid, terms)
    # partial output salvaged: the admitted wave kept its pre-failure
    # tokens (still-queued victims legitimately drain with none)
    assert any(len(r.tokens) >= 1 for r in results)
    with pytest.raises(EngineFailedError):
        eng.submit([1, 2], 3)
    assert eng.rejected.get("engine_failed") == 1
    # a failed supervisor keeps flushing pending results, never raises
    assert sup.step() == []
    text = render_prometheus(eng.metrics)
    assert 'serve_supervisor_state{state="failed"} 1' in text


@pytest.mark.parametrize("spec", [False, True])
def test_real_nan_logits_detected_in_program(served_model, spec):
    """The in-program isfinite sentinel catches REAL non-finite logits
    (not just injected poison) in both the decode/prefill samplers and
    the spec verify: with NaN-poisoned params nothing plausible is ever
    emitted — rows terminate 'failed' via the strike backstop instead
    of silently returning argmax-over-NaN garbage."""
    cfg, model, params = served_model
    bad = jax.tree_util.tree_map(
        lambda x: (x * jnp.nan).astype(x.dtype), params)
    eng = Engine(model, bad, num_slots=2, max_len=64,
                 spec=NGramDrafter(k=3) if spec else None)
    rid = eng.submit([1, 2, 3], 6)
    results = eng.drain()                 # terminates via the backstop
    assert [r.rid for r in results] == [rid]
    assert results[0].finish_reason == "failed"
    assert results[0].tokens == []        # no garbage ever surfaced
    assert eng.poisoned_steps >= 1
    assert eng.flight.terminals(rid) == ["failed"]


def test_unsupervised_persistent_poison_fails_rows_not_wedges(
        served_model):
    """Liveness backstop: WITHOUT a supervisor, persistently poisoned
    rows terminate 'failed' after POISON_STRIKE_LIMIT strikes (clean
    tokens salvaged, slot freed, one terminal) — drain() returns
    instead of wedging the slot forever."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64,
                 faults=FaultPlan.parse("nan_logits@0x99999"))
    rids = _mixed_workload(eng, cfg.vocab_size, n=6)
    results = eng.drain()                 # must terminate
    assert sorted(r.rid for r in results) == sorted(rids)
    for r in results:
        assert r.finish_reason == "failed"
        assert len(r.tokens) >= 1         # the clean prefill token
        assert eng.flight.terminals(r.rid) == ["failed"]
    assert eng.sched.free_slots == eng.num_slots   # nothing leaked
    assert not eng.failed                 # rows failed, engine did not


def test_supervisor_backoff_ladder_and_settle(served_model):
    """Backoff doubles per consecutive recovery (capped) and a clean
    settle window resets the ladder."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    sleeps = []
    sup = EngineSupervisor(eng, backoff_base_s=0.1, backoff_max_s=0.5,
                           settle_s=0.05, sleep=sleeps.append)
    for expect in (0.1, 0.2, 0.4, 0.5):
        sup._last_fault_t = time.monotonic()  # inside the settle window
        sup._handle_fault("poisoned_step", flush_cache=False)
        assert sleeps[-1] == pytest.approx(expect)
    # a quiet stretch longer than settle_s resets the ladder
    sup._last_fault_t = time.monotonic() - 1.0
    sup._handle_fault("poisoned_step", flush_cache=False)
    assert sleeps[-1] == pytest.approx(0.1)


# ------------------------------------------------ budgets stay untouched

def test_compile_set_and_sync_ledger_unchanged_by_fault_hooks(
        served_model):
    """ISSUE-11 acceptance: with faults disabled (no plan, or a plan
    that never fires) the compile set and the audited host-sync ledger
    are IDENTICAL to a plain engine's — the hooks are pure host-side
    branches."""
    cfg, model, params = served_model

    def run(**kw):
        mark = _tracecheck.sync_counts()
        eng = Engine(model, params, num_slots=2, max_len=64, **kw)
        for i in range(4):
            eng.submit([1 + i, 2], 5)
        eng.drain()
        return (eng.max_programs(), dict(eng.trace_counts),
                _tracecheck.sync_delta(mark))

    plain = run()
    armed = run(faults=FaultPlan.parse("nan_logits@100000"))
    assert plain == armed


# ---------------------------------------------- watchdog dump race (fix)

def test_watchdog_dump_serialized_and_kind_suffixed(served_model,
                                                    tmp_path):
    """Regression: concurrent trips of different kinds used to be able
    to interleave writes into one snapshot. Dumps now serialize under a
    lock and every file carries its trip kind — each dump dir holds
    exactly its own three parseable files."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 watchdog_dir=str(tmp_path))
    eng.submit([1, 2], 2)
    eng.drain()
    wd = eng.watchdog
    wd.cooldown_s = 0.0                       # dump on every trip

    def trip(kind):
        for _ in range(4):
            wd._trip(kind, {"forced": True})

    threads = [threading.Thread(target=trip, args=(k,))
               for k in ("ttft_spike", "stuck_slot", "stalled_step")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dirs = os.listdir(tmp_path)
    assert len(dirs) == 12                    # one dir per dumped trip
    for d in dirs:
        kind = d.rsplit("-", 2)[0]
        files = sorted(os.listdir(tmp_path / d))
        assert files == sorted([f"flight-{kind}.jsonl",
                                f"meta-{kind}.json",
                                f"trace-{kind}.json"]), (d, files)
        with open(tmp_path / d / f"meta-{kind}.json") as f:
            assert json.load(f)["trip"]["kind"] == kind
        with open(tmp_path / d / f"trace-{kind}.json") as f:
            assert "traceEvents" in json.load(f)
        with open(tmp_path / d / f"flight-{kind}.jsonl") as f:
            for ln in f:
                json.loads(ln)
    assert wd.dump_errors == 0


# --------------------------------------------------------- scheduler unit

def test_requeue_front_preserves_order():
    class Item:
        def __init__(self, rid, n):
            self.rid, self.prompt = rid, (0,) * n

    s = SlotScheduler(4, [16, 32])
    s.enqueue(Item(10, 3))
    s.enqueue(Item(11, 3))
    s.requeue_front([Item(1, 3), Item(2, 3), Item(3, 3)])
    assert [it.rid for it in s.queued_items()] == [1, 2, 3, 10, 11]


# ------------------------------------------------------ HTTP status layer

def _start_server(eng, supervisor=None):
    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    loop = EngineLoop(eng, supervisor=supervisor)
    loop.start()
    encode = lambda s: [min(ord(c), 49) for c in s]       # noqa: E731
    decode = lambda ids: " ".join(str(i) for i in ids)    # noqa: E731
    srv = make_server("127.0.0.1", 0, loop, encode, decode)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, loop, srv.server_address[1]


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.status, json.loads(r.read())


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode())
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def test_http_drain_readiness_and_status_hygiene(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    sup = EngineSupervisor(eng)
    srv, loop, port = _start_server(eng, supervisor=sup)
    try:
        # healthy: liveness AND readiness green, liveness shape frozen
        assert _get(port, "/healthz")[1] == {"ok": True}
        code, body = _get(port, "/healthz?ready=1")
        assert code == 200 and body["ready"] is True
        code, body, _ = _post(port, "/generate",
                              {"prompt": "ab", "max_new_tokens": 3,
                               "temperature": 0.0})
        assert code == 200 and len(body["tokens"]) == 3
        # drain: readiness flips red, liveness stays green, /generate
        # gets 503 + Retry-After, the flight ledger records both codes
        code, body, _ = _post(port, "/drain", {})
        assert code == 200 and body["draining"] is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, "/generate", {"prompt": "ab",
                                      "max_new_tokens": 2})
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert "retry against another replica" in \
            json.loads(ei.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/healthz?ready=1")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["reason"] == "draining"
        assert _get(port, "/healthz")[1] == {"ok": True}
        # idempotent + reports drained once idle
        code, body, _ = _post(port, "/drain", {})
        assert body["drained"] is True
        statuses = [e["status"] for e in eng.flight.events()
                    if e["ev"] == "http"]
        assert 200 in statuses and 503 in statuses
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


def test_http_shed_returns_429_with_retry_after(served_model):
    """A queue-expired (shed) request returns 429 + Retry-After derived
    from the queue-wait p50 — not a generic error, and not a 200."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=64)
    srv, loop, port = _start_server(eng)
    try:
        out = {}

        def blocker():
            out["b"] = _post(port, "/generate",
                             {"prompt": "ab", "max_new_tokens": 56,
                              "temperature": 0.0})

        def shed_client():
            try:
                out["s"] = _post(port, "/generate",
                                 {"prompt": "cd", "max_new_tokens": 8,
                                  "deadline_s": 0.01})
            except urllib.error.HTTPError as e:
                out["s"] = (e.code, json.loads(e.read()),
                            dict(e.headers))

        tb = threading.Thread(target=blocker)
        tb.start()
        time.sleep(0.25)          # blocker owns the only slot
        ts = threading.Thread(target=shed_client)
        ts.start()
        tb.join(60)
        ts.join(60)
        code, body, headers = out["s"]
        assert code == 429, out["s"]
        assert body["finish_reason"] == "shed"
        assert int(headers["Retry-After"]) >= 1
        assert out["b"][0] == 200
        assert 429 in [e["status"] for e in eng.flight.events()
                       if e["ev"] == "http"]
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


def test_http_recovery_invisible_to_clients(served_model):
    """Clients riding through a quarantine+recovery see only their
    (token-identical) 200s — the loop never dies, waiters never fail."""
    cfg, model, params = served_model
    plan = FaultPlan.parse("nan_logits@4")
    eng = Engine(model, params, num_slots=4, max_len=64, faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0.0)
    srv, loop, port = _start_server(eng, supervisor=sup)
    try:
        out = {}

        def client(i):
            out[i] = _post(port, "/generate",
                           {"prompt": "ab" * (i + 1),
                            "max_new_tokens": 6, "temperature": 0.0})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert all(out[i][0] == 200 for i in range(5))
        assert all(len(out[i][1]["tokens"]) == 6 for i in range(5))
        assert eng.recoveries >= 1, plan.stats()
        assert loop.dead is None
        # recovery posture is visible in /stats
        stats = _get(port, "/stats")[1]
        assert stats["recovery"]["recoveries"] >= 1
        assert stats["loop"]["supervisor"]["state"] == "ok"
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


def test_bench_serve_fault_mode(served_model):
    """bench.py --mode=serve --faults wires the chaos point end to end:
    recoveries happen, the fault block lands in the JSON, the flight
    JSONL dumps."""
    import bench

    out = bench.main(["--mode=serve", "--quick", "--num_slots=2",
                      "--requests=6", "--load=1", "--burst=0",
                      "--faults=nan_logits@2",
                      "--flight_out=/tmp/test-fault-flight.jsonl"])
    f = out["extra"]["fault"]
    assert f["recoveries"] >= 1
    assert f["supervisor_state"] == "ok"
    assert f["goodput_under_fault_ratio"] is None \
        or f["goodput_under_fault_ratio"] > 0
    pt = out["extra"]["sweep"]["fault"]
    assert pt["finished"] + pt["shed"] == pt["requests"]
    with open("/tmp/test-fault-flight.jsonl") as fh:
        evs = [json.loads(ln) for ln in fh]
    assert any(e["ev"] == "recover" for e in evs)
