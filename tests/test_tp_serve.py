"""Tensor-parallel serving (ISSUE 14).

The sharded-engine contract under test:
  * greedy token PARITY: a tp=2 engine emits exactly the tp=1 engine's
    tokens across paged/dense pools, fp32/int8/int4 KV modes,
    scan_k in {1, 4} and spec on/off — the sharding is a layout
    choice, not sampling state (same fold_in keys, same per-row math,
    deterministic collectives);
  * the kernel dispatch layer: interpret-mode flash kernels run
    per-shard over local heads inside shard_map and agree token-exactly
    with the gather-free XLA paths under the same mesh;
  * recovery and preemption rebuild the SHARDED slot state: a poisoned
    step (and a forced preemption) under tp=2 restitches
    token-identically to a clean tp=2 run through the _Resume path;
  * the compile set does NOT widen: max_programs() is identical to the
    tp=1 engine's and trace counts stay within it;
  * the committed TP comms budget (budgets/serve_tp_cpu8.json) matches
    the live fleet: nonzero pinned collectives on the ``model`` axis
    for decode/prefill/verify, ZERO on every other axis, zero
    accidental full-pool all-gathers;
  * /metrics carries serve_tp_degree, and the startup budget export
    yields serve_collective_bytes_per_token{program=}.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.serve import Engine, EngineSupervisor, NGramDrafter
from nanosandbox_tpu.serve.faults import FaultPlan

REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _mixed_reqs(n=8, seed=0, vocab=50, eos=None):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, vocab, int(rng.integers(2, 40))).tolist(),
             int(rng.integers(2, 10)), int(rng.integers(0, 99)), eos)
            for _ in range(n)]


def _run(model, params, reqs, *, spec=False, **kw):
    eng = Engine(model, params, num_slots=4, max_len=64,
                 spec=NGramDrafter(k=3) if spec else None, **kw)
    for prompt, mnt, seed, eos in reqs:
        eng.submit(prompt, mnt, seed=seed, eos_id=eos)
    out = {r.rid: (r.tokens, r.finish_reason) for r in eng.drain()}
    assert len(out) == len(reqs)
    return eng, out


# One case per matrix dimension of the ISSUE-14 parity bar —
# paged/dense x fp32/int8/int4 x scan_k {1,4} x spec on/off — without
# paying the full 24-engine cross product in CI wall time.
PARITY_CASES = {
    "paged-fp32": dict(paged=True),
    "paged-int8": dict(paged=True, kv_dtype="int8"),
    "paged-int4": dict(paged=True, kv_dtype="int4"),
    "dense-fp32": dict(paged=False, kv_dtype="fp32"),
    "paged-fp32-scan4": dict(paged=True, scan_k=4),
    "dense-int8-scan4": dict(paged=False, kv_dtype="int8", scan_k=4),
    "paged-spec": dict(paged=True, spec=True),
    "dense-spec": dict(paged=False, spec=True),
}


@pytest.mark.parametrize("case", sorted(PARITY_CASES))
def test_tp_greedy_parity(served_model, case):
    """tp=2 vs tp=1: token-identical greedy outputs on a mixed
    continuous-batching workload — the issue's == 1.0 pin."""
    _, model, params = served_model
    reqs = _mixed_reqs(seed=3)
    kw = dict(PARITY_CASES[case])
    _, base = _run(model, params, reqs, tp=1, **kw)
    _, out = _run(model, params, reqs, tp=2, **kw)
    assert out == base, f"tp=2 diverged from tp=1 under {case}"


def test_tp_sampled_parity(served_model):
    """Sampled decode too: the per-row fold_in streams are placement-
    independent and the categorical draw sees bit-identically filtered
    logits, so even temperature > 0 outputs match across tp."""
    _, model, params = served_model

    def sampled(tp):
        eng = Engine(model, params, num_slots=4, max_len=64, tp=tp)
        rng = np.random.default_rng(5)
        for i in range(6):
            eng.submit(rng.integers(0, 50,
                                    int(rng.integers(2, 30))).tolist(),
                       6, temperature=0.9, top_k=20, top_p=0.95, seed=i)
        return {r.rid: r.tokens for r in eng.drain()}

    assert sampled(2) == sampled(1)


def test_tp_kernel_interpret_matches_xla(served_model):
    """The shard_map kernel dispatch: interpret-mode flash decode +
    paged-prefill over LOCAL heads equals the partitioned XLA path
    token-exactly under the same tp=2 mesh (fp and int8 pools)."""
    _, model, params = served_model
    reqs = _mixed_reqs(n=6, seed=9)
    for kvd in (None, "int8"):
        _, kern = _run(model, params, reqs, tp=2, kv_dtype=kvd,
                       decode_impl="pallas_interpret")
        _, xla = _run(model, params, reqs, tp=2, kv_dtype=kvd,
                      decode_impl="xla")
        assert kern == xla, f"kernel vs xla diverged under tp=2 ({kvd})"


def test_tp_recovery_restitches_sharded_state(served_model):
    """A poisoned step under tp=2 recovers through the supervisor: the
    rebuilt pool/slot state lands back on its SHARDED placements and
    the resumed streams are token-identical to a clean tp=2 run."""
    _, model, params = served_model
    reqs = _mixed_reqs(n=6, seed=7)
    _, clean = _run(model, params, reqs, tp=2)
    plan = FaultPlan.parse("nan_logits@3")
    eng = Engine(model, params, num_slots=4, max_len=64, tp=2,
                 faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0)
    for prompt, mnt, seed, eos in reqs:
        eng.submit(prompt, mnt, seed=seed, eos_id=eos)
    out = []
    while eng.has_work() and sup.state != "failed":
        out.extend(sup.step())
    assert sup.state == "ok"
    assert eng.recoveries >= 1
    assert {r.rid: (r.tokens, r.finish_reason) for r in out} == clean
    # The rebuilt arrays must sit on the mesh, heads-sharded, not on
    # one device: a replicated rebuild would silently reshard (or
    # gather) at the first post-recovery dispatch.
    from jax.sharding import PartitionSpec as P

    # (jax normalizes trailing Nones off the spec)
    assert eng._pool[0][0].sharding.spec == P(None, "model")


def test_tp_preemption_restitches(served_model):
    """A forced preemption (preempt_storm) under tp=2: the victim's
    slot parks on device, it requeues through _Resume, and the final
    outputs equal an unpreempted tp=2 run's."""
    _, model, params = served_model
    reqs = [(list(range(2, 2 + 8)), 10, s, None) for s in range(5)]
    _, clean = _run(model, params, reqs, tp=2)
    plan = FaultPlan.parse("preempt_storm@4x2")
    eng = Engine(model, params, num_slots=4, max_len=64, tp=2,
                 faults=plan)
    for prompt, mnt, seed, eos in reqs:
        eng.submit(prompt, mnt, seed=seed, eos_id=eos)
    out = {r.rid: (r.tokens, r.finish_reason) for r in eng.drain()}
    assert eng.preemptions >= 1
    assert out == clean


def test_tp_budget_not_widened(served_model):
    """tp is a placement, not a shape: max_programs() is identical to
    the tp=1 engine's and the observed traces stay within it."""
    _, model, params = served_model
    reqs = _mixed_reqs(seed=13)
    e1, _ = _run(model, params, reqs, tp=1)
    e2, _ = _run(model, params, reqs, tp=2)
    assert e2.max_programs() == e1.max_programs()
    for name, n in e2.trace_counts.items():
        assert n <= e2.max_programs()[name], (name, n)


def test_tp_validation(served_model):
    """Constructor contracts: tp must divide n_head; device drafters
    are rejected (their second model has no sharded pool yet); tp=1
    builds no mesh at all."""
    _, model, params = served_model
    with pytest.raises(ValueError, match="n_head"):
        Engine(model, params, num_slots=2, max_len=32, tp=3)

    class FakeDeviceDrafter:
        kind = "device"
        k = 3

    with pytest.raises(ValueError, match="host drafters"):
        Engine(model, params, num_slots=2, max_len=32, tp=2,
               spec=FakeDeviceDrafter())
    eng = Engine(model, params, num_slots=2, max_len=32)
    assert eng.tp == 1 and eng.mesh is None


def test_tp_degree_on_metrics_and_stats(served_model):
    """The posture is observable: stats()['tp'] and the
    serve_tp_degree gauge both read the shard count."""
    from nanosandbox_tpu.obs import render_prometheus

    _, model, params = served_model
    eng, _ = _run(model, params, _mixed_reqs(n=2, seed=1), tp=2)
    assert eng.stats()["tp"] == 2
    text = render_prometheus(eng.metrics)
    assert "serve_tp_degree 2" in text


def test_collective_bytes_per_token_export():
    """The committed TP budget exports per-program bytes/token gauges:
    nonzero for every program, and a k4 prefill wave normalizes by its
    4 first tokens (no compile — pure budget-file math)."""
    from nanosandbox_tpu.analysis.shardcheck import (
        export_collective_bytes_per_token)
    from nanosandbox_tpu.obs import MetricRegistry, render_prometheus

    budget = json.loads(
        (REPO_ROOT / "budgets" / "serve_tp_cpu8.json").read_text())
    reg = MetricRegistry()
    export_collective_bytes_per_token(budget, reg)
    text = render_prometheus(reg)
    assert "serve_collective_bytes_per_token" in text
    assert 'program="decode_kv8_tp2"' in text
    k1 = budget["programs"]["prefill_kv8_tp2_k1_L16"]
    k4 = budget["programs"]["prefill_kv8_tp2_k4_L16"]
    b1 = sum(s["bytes"] for s in k1.values())
    b4 = sum(s["bytes"] for s in k4.values())
    assert f'program="prefill_kv8_tp2_k4_L16"}} {b4 / 4}' in text \
        or f'program="prefill_kv8_tp2_k4_L16"}} {b4 / 4:g}' in text
    assert b1 > 0 and b4 > 0
    # A scan rung's collectives live in a lax.scan body the manifest
    # counts ONCE but the dispatch executes r times while emitting r
    # tokens — the r's cancel, so its bytes/token gauge must equal the
    # STATIC body bytes (== rung-1 decode's wire cost), NOT static/r:
    # scan amortizes host dispatch, not collectives.
    b_dec = sum(s["bytes"] for s in
                budget["programs"]["decode_kv8_tp2"].values())
    b_s4 = sum(s["bytes"] for s in
               budget["programs"]["decode_scan4_kv8_tp2"].values())
    assert b_s4 == b_dec > 0
    assert (f'program="decode_scan4_kv8_tp2"}} {float(b_s4)}' in text
            or f'program="decode_scan4_kv8_tp2"}} {b_s4}' in text)


def test_tp_fleet_manifest_vs_committed_budget():
    """The live serve_tp fleet against budgets/serve_tp_cpu8.json: no
    violations, no findings (zero accidental all-gathers of the
    sharded pool), nonzero model-axis collectives on decode, every
    prefill rung x bucket, spec verify and both scan rungs — and ZERO
    collectives attributed to any other axis. This is the rewrite of
    the all-zero serve comms contract, pinned."""
    from nanosandbox_tpu.analysis.shardcheck.budget import check_budget
    from nanosandbox_tpu.analysis.shardcheck.fleet import (
        SERVE_TP_MESH, build_mesh, serve_tp_programs)
    from nanosandbox_tpu.analysis.shardcheck.manifest import build_manifest

    mesh = build_mesh(SERVE_TP_MESH)
    manifest = build_manifest(serve_tp_programs(mesh), mesh)
    assert manifest["findings"] == []
    programs = manifest["programs"]
    expected = {"decode_kv8_tp2", "spec_verify_kv8_tp2",
                "decode_scan2_kv8_tp2", "decode_scan4_kv8_tp2"}
    assert expected <= set(programs)
    assert any(name.startswith("prefill_kv8_tp2_k") for name in programs)
    for name, entry in programs.items():
        assert entry["collectives"], f"{name} lost its TP collectives"
        for slot in entry["collectives"].values():
            assert slot["axes"] == ["model"], (name, slot)
        # The pool went in sharded: per-device input bytes are real.
        assert entry["sharded_input_bytes_per_device"] > 0, name

    budget = json.loads(
        (REPO_ROOT / "budgets" / "serve_tp_cpu8.json").read_text())
    violations, _ = check_budget(manifest, budget)
    assert violations == []
