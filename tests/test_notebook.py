"""Colab companion notebook validation (SURVEY.md §3.4: the reference's
notebook is its de-facto integration test; ours must at least be
well-formed, reference only real CLI flags, and keep the cell roles)."""

import ast
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NB = os.path.join(REPO, "notebooks", "colab_tpu_companion.ipynb")


def _nb():
    with open(NB) as f:
        return json.load(f)


def test_notebook_well_formed():
    nb = _nb()
    assert nb["nbformat"] == 4
    kinds = [c["cell_type"] for c in nb["cells"]]
    assert kinds.count("code") >= 5
    assert kinds.count("markdown") >= 2


def test_code_cells_are_valid_python():
    for i, cell in enumerate(_nb()["cells"]):
        if cell["cell_type"] != "code":
            continue
        src = "".join(cell["source"])
        # strip notebook magics before parsing
        src = "\n".join(l for l in src.splitlines()
                        if not l.lstrip().startswith(("%", "!")))
        ast.parse(src, filename=f"cell_{i}")


def test_notebook_flags_exist_in_config():
    """Every --key= flag passed to train_main must be a real config field —
    the notebook pins the CLI contract (reference ipynb role)."""
    from nanosandbox_tpu.config import field_names

    import re

    names = field_names()
    found = 0
    for cell in _nb()["cells"]:
        if cell["cell_type"] != "code":
            continue
        src = "".join(cell["source"])
        if "train_main" not in src:
            continue
        for key in re.findall(r"--([A-Za-z_][A-Za-z0-9_]*)=", src):
            assert key in names, f"unknown flag --{key} in notebook"
            found += 1
    assert found > 10, "flag extraction matched suspiciously few flags"


def test_notebook_covers_reference_cells():
    """Cell-role parity with the reference notebook: probe, dataset, CPU
    smoke, accelerator-gated run, sampling, tensorboard."""
    text = json.dumps(_nb())
    for needle in ("jax.devices", "prepare_char_dataset", "--device=cpu",
                   "HAS_TPU", "sample_main", "tensorboard"):
        assert needle in text, f"missing {needle}"
