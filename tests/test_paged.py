"""Paged KV block pool + radix prefix cache (ISSUE 9).

The contract under test:
  * block allocate/free/refcount invariants survive fuzzed
    admit/evict/release sequences (free | cached | live partitions the
    pool at every step, refcounts equal live holders);
  * the paged engine is greedy-token-IDENTICAL to the dense engine on
    the same workload, in fp32 and int8, through the XLA fallback and
    the interpret-mode paged flash kernel;
  * a prefix-cache hit skips prefill chunks but produces exactly the
    tokens a from-scratch prefill would (same sampling keys by
    construction);
  * two requests sharing a resident prefix diverge safely after it —
    refcounted copy-on-write blocks: the shared chain is never written,
    divergence lands in private blocks;
  * admission is block-aware: a full pool defers (never deadlocks) and
    an impossible request rejects at submit;
  * the compile set is NOT widened: paged max_programs() ==
    dense max_programs(), trace counts within budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.serve import BlockPool, Engine, blocks_for
from nanosandbox_tpu.serve.paged import RadixPrefixCache


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _run(model, params, reqs, **kw):
    eng = Engine(model, params, num_slots=4, max_len=64, **kw)
    for prompt, mnt, seed, temp in reqs:
        eng.submit(prompt, mnt, seed=seed, temperature=temp)
    out = {r.rid: (r.tokens, r.finish_reason) for r in eng.drain()}
    assert len(out) == len(reqs)
    return eng, out


def _mixed_reqs(n=10, seed=0, vocab=50, greedy=True):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, vocab, int(rng.integers(2, 40))).tolist(),
             int(rng.integers(2, 10)), int(rng.integers(0, 99)),
             0.0 if greedy else 0.8)
            for _ in range(n)]


# ------------------------------------------------------------ block pool

def test_blocks_for():
    assert blocks_for(1, 16) == 1
    assert blocks_for(16, 16) == 1
    assert blocks_for(17, 16) == 2


def test_block_pool_admit_release_roundtrip():
    bp = BlockPool(8, 4)
    a = bp.admit(list(range(10)), 5)     # 15 positions -> 4 blocks
    assert a is not None and len(a.table) == 4 and a.n_hit == 0
    bp.check([a])
    assert bp.stats()["free"] == 4 and bp.stats()["live"] == 4
    bp.release(a)
    bp.check([])
    # Full prompt blocks (10 // 4 = 2) were donated, the rest freed.
    st = bp.stats()
    assert st["cached"] == 2 and st["free"] == 6 and st["live"] == 0


def test_block_pool_prefix_hit_and_refcount():
    bp = BlockPool(16, 4)
    prompt = list(range(20))             # 5 full blocks
    a = bp.admit(prompt, 4)
    bp.release(a)                        # donates blocks 0..4
    b = bp.admit(prompt + [77, 78], 4)
    # Hit capped one token short of the prompt never applies here (the
    # prompt grew); all 5 donated blocks of the 22-token prompt match.
    assert b.n_hit == 5
    assert all(n.refs == 1 for n in b.nodes)
    c = bp.admit(prompt + [88], 4)       # same chain, second holder
    assert c.n_hit == 5
    assert all(n.refs == 2 for n in c.nodes)
    bp.check([b, c])
    bp.release(b)
    assert all(n.refs == 1 for n in c.nodes)
    bp.release(c)
    bp.check([])


def test_block_pool_hit_capped_one_token_short():
    """A fully-cached block-aligned prompt still re-prefills >= 1 token
    (the suffix forward needs a position to sample the first token)."""
    bp = BlockPool(8, 4)
    prompt = list(range(8))              # exactly 2 blocks
    bp.release(bp.admit(prompt, 2))
    a = bp.admit(prompt, 2)
    assert a.n_hit == 1                  # NOT 2: (8 - 1) // 4 == 1
    bp.release(a)


def test_block_pool_lru_eviction():
    bp = BlockPool(4, 4, prefix_cache=True)
    a = bp.admit([1] * 8, 1)             # 3 blocks (9 positions)
    bp.release(a)                        # donates 2, frees 1
    b = bp.admit([2] * 8, 1)
    bp.release(b)                        # donating 2 more must evict
    assert bp.evicted_blocks >= 1
    bp.check([])


def test_block_pool_defers_when_short():
    bp = BlockPool(4, 4, prefix_cache=False)
    a = bp.admit([1] * 10, 6)            # 4 blocks: pool exhausted
    assert a is not None
    assert bp.admit([2] * 10, 2) is None
    assert bp.stall_steps == 1
    bp.release(a)
    assert bp.admit([2] * 10, 2) is not None


def test_admit_never_evicts_its_own_hit_chain():
    """Regression: admit() pins (acquires) its matched chain BEFORE the
    private allocation. Unpinned, _take's shortfall eviction could
    reclaim the just-matched refs-0 chain and hand the same block out
    as both 'shared prefix' and 'fresh private' — an aliased table. The
    correct behavior when a request fits ONLY by sacrificing its own
    hit is to defer, chain intact."""
    bp = BlockPool(6, 2)
    prompt = [1, 2, 3, 4, 9]
    a = bp.admit(prompt, 7)            # 12 positions: the whole pool
    assert a is not None and len(a.table) == 6
    bp.release(a)                      # donates 2, frees 4
    c = bp.admit([7, 7, 7], 1)         # 2 blocks -> free 2, cached 2
    b = bp.admit(prompt, 7)            # hit 2 + need 4 > free 2: defer
    assert b is None
    bp.check([c])
    assert len(bp.cache) == 2
    assert all(n.refs == 0 for n in bp.cache._nodes)
    bp.release(c)
    b = bp.admit(prompt, 7)
    assert b is not None and b.n_hit == 2
    assert len(set(b.table)) == len(b.table)   # no aliasing
    bp.check([b])
    bp.release(b)
    bp.check([])


def test_block_pool_fuzzed_invariants():
    """Random admit/release interleavings with overlapping prompts:
    the partition + refcount audit holds after EVERY operation."""
    rng = np.random.default_rng(7)
    bp = BlockPool(24, 4)
    shared = rng.integers(0, 9, 12).tolist()
    live = []
    for _ in range(300):
        if live and (rng.random() < 0.45 or len(live) > 6):
            bp.release(live.pop(int(rng.integers(0, len(live)))))
        else:
            if rng.random() < 0.5:
                prompt = shared + rng.integers(0, 9, int(
                    rng.integers(1, 8))).tolist()
            else:
                prompt = rng.integers(0, 9, int(
                    rng.integers(1, 20))).tolist()
            a = bp.admit(prompt, int(rng.integers(1, 6)))
            if a is not None:
                live.append(a)
        bp.check(live)
    for a in live:
        bp.release(a)
    bp.check([])


def test_radix_insert_dedup_frees_duplicates():
    c = RadixPrefixCache(4)
    prompt = list(range(8))
    assert c.insert_chain(prompt, [3, 4], 0) == []
    # A second donor of the same chain gets its blocks back to free.
    assert c.insert_chain(prompt, [5, 6], 0) == [5, 6]
    assert sorted(c.cached_blocks()) == [3, 4]


# ------------------------------------------------------- engine parity

@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_paged_vs_dense_greedy_parity(served_model, kv_dtype):
    cfg, model, params = served_model
    reqs = _mixed_reqs(12, seed=3)
    _, dense = _run(model, params, reqs, paged=False, kv_dtype=kv_dtype)
    _, paged = _run(model, params, reqs, paged=True, kv_dtype=kv_dtype)
    assert dense == paged


def test_paged_kernel_vs_xla_token_exact(served_model):
    """The interpret-mode paged flash kernel (block-table indirection,
    fused int8 dequant) agrees token-for-token with the gather + masked
    XLA fallback, in both kv modes."""
    cfg, model, params = served_model
    reqs = _mixed_reqs(8, seed=5)
    for kvd in (None, "int8"):
        _, ker = _run(model, params, reqs, paged=True, kv_dtype=kvd,
                      decode_impl="pallas_interpret")
        _, xla = _run(model, params, reqs, paged=True, kv_dtype=kvd,
                      decode_impl="xla")
        assert ker == xla, kvd


def test_paged_sampled_parity(served_model):
    """Per-row keyed sampling is layout-independent: temperature > 0
    outputs match dense exactly (same keys, same filtered logits)."""
    cfg, model, params = served_model
    reqs = _mixed_reqs(8, seed=11, greedy=False)
    _, dense = _run(model, params, reqs, paged=False)
    _, paged = _run(model, params, reqs, paged=True)
    assert dense == paged


# ---------------------------------------------------------- prefix cache

def test_prefix_hit_skips_prefill_and_matches_cold(served_model):
    cfg, model, params = served_model
    rng = np.random.default_rng(2)
    base = rng.integers(0, 50, 40).tolist()
    warm = Engine(model, params, num_slots=4, max_len=64)
    warm.submit(base, 6)
    warm.drain()
    assert len(warm.block_pool.cache) == 2          # 40 // 16 donated
    rid = warm.submit(base[:35] + [7, 8, 9], 6)
    hot = {r.rid: r.tokens for r in warm.drain()}[rid]
    assert warm.block_pool.hit_tokens == 32         # 2 full blocks
    cold = Engine(model, params, num_slots=4, max_len=64,
                  prefix_cache=False)
    rid2 = cold.submit(base[:35] + [7, 8, 9], 6)
    assert hot == {r.rid: r.tokens for r in cold.drain()}[rid2]
    # The hit is visible in stats() and the labeled TTFT series.
    ps = warm.stats()["kv_pool"]
    assert ps["prefix_hit_tokens"] == 32
    assert ps["ttft_hit_s"] is not None


def test_copy_on_write_divergence_after_shared_prefix(served_model):
    """Two CONCURRENT requests sharing a resident prefix diverge after
    it: the shared chain is refcounted (never written — its nodes stay
    refs=2 while both fly) and each request's divergent tail matches an
    independent cold engine's output exactly."""
    cfg, model, params = served_model
    rng = np.random.default_rng(9)
    base = rng.integers(0, 50, 36).tolist()         # 2 full blocks
    eng = Engine(model, params, num_slots=4, max_len=64)
    eng.submit(base, 4)
    eng.drain()
    ra = eng.submit(base[:33] + [1, 2], 6, seed=1)
    rb = eng.submit(base[:33] + [3, 4, 5], 6, seed=2)
    # Both admitted and in flight before either finishes: step once to
    # admit, then audit the shared chain's refcounts mid-flight.
    eng.step()
    shared_nodes = [st.alloc.nodes for st in eng._active.values()]
    assert all(len(n) == 2 for n in shared_nodes)
    ids = {id(n) for chain in shared_nodes for n in chain}
    assert len(ids) == 2                            # SAME two nodes
    for chain in shared_nodes:
        assert all(n.refs == 2 for n in chain)
    out = {r.rid: r.tokens for r in eng.drain()}
    eng.block_pool.check([])
    for rid, prompt, seed in ((ra, base[:33] + [1, 2], 1),
                              (rb, base[:33] + [3, 4, 5], 2)):
        solo = Engine(model, params, num_slots=4, max_len=64,
                      prefix_cache=False)
        srid = solo.submit(prompt, 6, seed=seed)
        assert out[rid] == {r.rid: r.tokens
                            for r in solo.drain()}[srid], rid


def test_no_deadlock_under_full_pool(served_model):
    """More demand than the pool holds: admissions defer (counted) and
    every request still completes as earlier ones release blocks."""
    cfg, model, params = served_model
    # 8 blocks of 16 = 2 full-size requests at a time.
    eng = Engine(model, params, num_slots=4, max_len=64,
                 kv_pool_blocks=8, prefix_cache=False)
    rng = np.random.default_rng(4)
    for i in range(8):
        eng.submit(rng.integers(0, 50, 40).tolist(), 8)
    results = eng.drain()
    assert len(results) == 8
    assert all(len(r.tokens) == 8 for r in results)
    assert eng.block_pool.stall_steps > 0
    eng.block_pool.check([])


def test_submit_rejects_impossible_request(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64,
                 kv_pool_blocks=2)
    with pytest.raises(ValueError, match="KV blocks"):
        eng.submit([1] * 40, 8)


# ------------------------------------------------------- compile budget

def test_compile_budget_not_widened(served_model):
    """Paged engines publish EXACTLY the dense compile set — the block
    table varies as data, never as shape — and a prefix-hit workload
    (small-suffix waves) stays inside it."""
    cfg, model, params = served_model
    dense = Engine(model, params, num_slots=4, max_len=64, paged=False)
    paged = Engine(model, params, num_slots=4, max_len=64, paged=True)
    assert dense.max_programs() == paged.max_programs()
    rng = np.random.default_rng(6)
    base = rng.integers(0, 50, 40).tolist()
    paged.submit(base, 4)
    paged.drain()
    for i in range(6):                      # hits -> suffix-bucket waves
        paged.submit(base[:33 + i] + [i], 4)
    for _, _, s, _ in _mixed_reqs(6, seed=8):
        paged.submit(rng.integers(0, 50, 20).tolist(), 4, seed=s)
    paged.drain()
    assert paged.block_pool.hit_tokens > 0
    paged.tracecheck.assert_within_budget()
    assert paged.tracecheck.budgets() == paged.max_programs()


def test_pool_gauges_partition(served_model):
    cfg, model, params = served_model
    eng, _ = _run(model, params, _mixed_reqs(6, seed=12), paged=True)
    st = eng.stats()["kv_pool"]
    assert st["free"] + st["live"] + st["cached"] == eng.kv_pool_blocks
    text = eng.metrics.prometheus_text()
    assert 'serve_kv_pool_blocks{state="free"}' in text
    assert "serve_prefix_hit_tokens_total" in text
    assert "serve_prefix_miss_tokens_total" in text


def test_bench_paged_prefix_smoke():
    """bench.py --mode=decode --paged=on --prefix_share emits the ISSUE-9
    fields: hit rate, ttft hit-vs-miss, paged-vs-dense ratio, capacity."""
    import bench

    res = bench.main(["--quick", "--mode=decode", "--mixed",
                      "--prefix_share=0.8", "--requests=12"])
    e = res["extra"]
    assert e["paged"] is True
    assert e["paged_greedy_parity"] == 1.0
    assert e["prefix_hit_rate"] is not None and e["prefix_hit_rate"] > 0
    assert e["ttft_hit_vs_miss"]["hit_p50_s"] is not None
    assert e["ttft_hit_vs_miss"]["miss_p50_s"] is not None
    assert e["paged_vs_dense_toks"] > 0
    assert e["effective_slot_capacity"] > 0
