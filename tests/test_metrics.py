"""MetricsWriter: JSONL + TensorBoard event-file contract.

The reference's artifact contract delivers TB event files under /data/runs
(/root/reference/README.md:74-87). Round 1 imported only torch's writer,
which the shipped image lacks, so events silently never appeared
(VERDICT.md missing #5) — these tests pin the contract: a writer in the
image (tensorboardX) produces real events.out.tfevents* files.
"""

import glob
import json
import os

import pytest

from nanosandbox_tpu.utils.metrics import MetricsWriter


def test_jsonl_written(tmp_path):
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=False)
    w.log(0, {"train/loss": 1.5})
    w.log(1, {"train/loss": 1.25, "perf/mfu": 0.4})
    w.close()
    lines = [json.loads(x) for x in
             open(tmp_path / "r" / "metrics.jsonl")]
    assert lines[0]["train/loss"] == 1.5
    assert lines[1]["step"] == 1 and lines[1]["perf/mfu"] == 0.4


def test_tensorboard_event_files_appear(tmp_path):
    pytest.importorskip("tensorboardX")
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=True)
    assert w.tb is not None, (
        "TB writer must construct without torch installed")
    w.log(0, {"train/loss": 2.0})
    w.log(1, {"train/loss": 1.0})
    w.close()
    events = glob.glob(str(tmp_path / "r" / "events.out.tfevents*"))
    assert events, "no TB event files written"
    assert os.path.getsize(events[0]) > 0


def test_tensorboard_events_after_training_run(tiny_cfg):
    """End-to-end: a 2-iter training run leaves event files in
    resolved_log_dir (the /data/runs deployment contract)."""
    pytest.importorskip("tensorboardX")
    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(max_iters=2, tensorboard=True, log_interval=1,
                           eval_interval=0)
    Trainer(cfg).run()
    events = glob.glob(os.path.join(cfg.resolved_log_dir, "*",
                                    "events.out.tfevents*"))
    assert events, f"no event files under {cfg.resolved_log_dir}"


def test_disabled_writer_is_inert(tmp_path):
    w = MetricsWriter(str(tmp_path), enabled=False)
    w.log(0, {"x": 1})
    w.close()
    assert not os.listdir(tmp_path)


def test_warn_once_dedupes_by_key(capsys):
    from nanosandbox_tpu.utils.metrics import warn_once

    warn_once("test-metrics-key-a", "message A")
    warn_once("test-metrics-key-a", "message A again")
    warn_once("test-metrics-key-b", "message B")
    err = capsys.readouterr().err
    assert err.count("message A") == 1
    assert "again" not in err
    assert "message B" in err


def test_ring_stat_percentiles_and_bound():
    from nanosandbox_tpu.utils.metrics import RingStat

    r = RingStat(maxlen=4)
    assert r.mean() is None and r.percentiles() is None
    for x in (1.0, 2.0, 3.0, 4.0):
        r.record(x)
    assert r.mean() == 2.5
    assert r.percentiles((50, 90, 99)) == {"p50": 2.0, "p90": 4.0, "p99": 4.0}
    r.record(10.0)           # evicts the 1.0 — bounded window
    assert len(r) == 4
    assert r.percentiles((99,)) == {"p99": 10.0}
    assert r.mean() == (2 + 3 + 4 + 10) / 4
