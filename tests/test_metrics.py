"""MetricsWriter: JSONL + TensorBoard event-file contract.

The reference's artifact contract delivers TB event files under /data/runs
(/root/reference/README.md:74-87). Round 1 imported only torch's writer,
which the shipped image lacks, so events silently never appeared
(VERDICT.md missing #5) — these tests pin the contract: a writer in the
image (tensorboardX) produces real events.out.tfevents* files.
"""

import glob
import json
import os

import pytest

from nanosandbox_tpu.utils.metrics import MetricsWriter


def test_jsonl_written(tmp_path):
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=False)
    w.log(0, {"train/loss": 1.5})
    w.log(1, {"train/loss": 1.25, "perf/mfu": 0.4})
    w.close()
    lines = [json.loads(x) for x in
             open(tmp_path / "r" / "metrics.jsonl")]
    assert lines[0]["train/loss"] == 1.5
    assert lines[1]["step"] == 1 and lines[1]["perf/mfu"] == 0.4


def test_tensorboard_event_files_appear(tmp_path):
    pytest.importorskip("tensorboardX")
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=True)
    assert w.tb is not None, (
        "TB writer must construct without torch installed")
    w.log(0, {"train/loss": 2.0})
    w.log(1, {"train/loss": 1.0})
    w.close()
    events = glob.glob(str(tmp_path / "r" / "events.out.tfevents*"))
    assert events, "no TB event files written"
    assert os.path.getsize(events[0]) > 0


def test_tensorboard_events_after_training_run(tiny_cfg):
    """End-to-end: a 2-iter training run leaves event files in
    resolved_log_dir (the /data/runs deployment contract)."""
    pytest.importorskip("tensorboardX")
    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(max_iters=2, tensorboard=True, log_interval=1,
                           eval_interval=0)
    Trainer(cfg).run()
    events = glob.glob(os.path.join(cfg.resolved_log_dir, "*",
                                    "events.out.tfevents*"))
    assert events, f"no event files under {cfg.resolved_log_dir}"


def test_disabled_writer_is_inert(tmp_path):
    w = MetricsWriter(str(tmp_path), enabled=False)
    w.log(0, {"x": 1})
    w.close()
    assert not os.listdir(tmp_path)


def test_header_deferred_until_first_log(tmp_path):
    """Regression (ISSUE 5 satellite): a writer that takes a header but
    is closed without ever logging must leave metrics.jsonl EMPTY — a
    lone header line used to masquerade as a run that produced
    metrics."""
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=False)
    w.write_header({"rng_impl": "rbg"})
    w.close()
    assert open(tmp_path / "r" / "metrics.jsonl").read() == ""


def test_header_lands_before_first_scalar(tmp_path):
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=False)
    w.write_header({"rng_impl": "rbg"})
    w.log(0, {"train/loss": 1.5})
    w.log(1, {"train/loss": 1.0})
    w.close()
    lines = [json.loads(x) for x in open(tmp_path / "r" / "metrics.jsonl")]
    assert len(lines) == 3
    assert lines[0]["header"] == {"rng_impl": "rbg"}  # still line 1
    assert lines[1]["step"] == 0 and lines[2]["step"] == 1


def test_multiple_pending_headers_all_land_in_order(tmp_path):
    """Two provenance records before the first scalar both survive the
    deferral, in write order — the pending slot must be a queue, not a
    last-writer-wins cell."""
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=False)
    w.write_header({"a": 1})
    w.write_header({"b": 2})
    w.log(0, {"x": 0.5})
    w.close()
    lines = [json.loads(x) for x in open(tmp_path / "r" / "metrics.jsonl")]
    assert [ln.get("header", {"step": True})
            for ln in lines] == [{"a": 1}, {"b": 2}, {"step": True}]


def test_header_after_scalars_writes_immediately(tmp_path):
    """A late header (scalars already flowing) appends in stream order
    — deferring it would only push it further from the top."""
    w = MetricsWriter(str(tmp_path), run_name="r", tensorboard=False)
    w.log(0, {"train/loss": 2.0})
    w.write_header({"note": "late"})
    w.close()
    lines = [json.loads(x) for x in open(tmp_path / "r" / "metrics.jsonl")]
    assert [("step" in ln, "header" in ln) for ln in lines] == \
        [(True, False), (False, True)]


def test_warn_once_dedupes_by_key(capsys):
    from nanosandbox_tpu.utils.metrics import warn_once

    warn_once("test-metrics-key-a", "message A")
    warn_once("test-metrics-key-a", "message A again")
    warn_once("test-metrics-key-b", "message B")
    err = capsys.readouterr().err
    assert err.count("message A") == 1
    assert "again" not in err
    assert "message B" in err


def test_warn_once_reset_for_tests_and_counter_family(capsys):
    """ISSUE 5 satellite: the dedup registry is resettable so tests can
    assert a warning fires without ordering against the whole process,
    and every firing lands as warn_once_fired_total{key=...} in the
    process-global metric registry (which reset does NOT clear — it is
    a monotonic process-lifetime ledger)."""
    from nanosandbox_tpu.obs import global_registry
    from nanosandbox_tpu.utils.metrics import reset_for_tests, warn_once

    def fired(key):
        snap = global_registry().snapshot()
        return sum(s["value"]
                   for s in snap["warn_once_fired_total"]["series"]
                   if s["labels"]["key"] == key)

    warn_once("test-metrics-reset-key", "once")
    warn_once("test-metrics-reset-key", "suppressed")
    assert fired("test-metrics-reset-key") == 1
    reset_for_tests()
    warn_once("test-metrics-reset-key", "fires again after reset")
    err = capsys.readouterr().err
    assert err.count("once") == 1 and "suppressed" not in err
    assert "fires again after reset" in err
    assert fired("test-metrics-reset-key") == 2


def test_ring_stat_percentiles_and_bound():
    from nanosandbox_tpu.utils.metrics import RingStat

    r = RingStat(maxlen=4)
    assert r.mean() is None and r.percentiles() is None
    for x in (1.0, 2.0, 3.0, 4.0):
        r.record(x)
    assert r.mean() == 2.5
    assert r.percentiles((50, 90, 99)) == {"p50": 2.0, "p90": 4.0, "p99": 4.0}
    r.record(10.0)           # evicts the 1.0 — bounded window
    assert len(r) == 4
    assert r.percentiles((99,)) == {"p99": 10.0}
    assert r.mean() == (2 + 3 + 4 + 10) / 4
