"""Multi-token decode scan + int4 KV + paged-prefill kernel (ISSUE 12).

The lag-k contract under test:
  * greedy token PARITY: a scan_k in {2, 4, 8} engine emits exactly the
    scan_k=1 engine's tokens across paged/dense pools and
    fp32/int8/int4 KV modes — chunks are dispatch boundaries, not
    sampling state;
  * a mid-chunk eos truncates exactly where the single-step loop would
    have stopped, with no leaked slots or KV blocks;
  * a poisoned MID-SCAN chunk recovers through the supervisor and the
    resumed stream restitches token-identically to a no-fault run
    (clean pre-poison prefix kept, downstream-of-garbage tokens
    discarded);
  * the compile set widens ONLY by the declared scan-rung ladder:
    max_programs()['decode'] == len(scan_rungs), trace counts within
    budget, everything else identical to a scan_k=1 engine;
  * the dispatch ledger: decode dispatches drop by the chunking factor
    (tokens_per_dispatch > 1) and the serve_host_dispatches_total /
    serve_tokens_per_dispatch families land on /metrics;
  * int4 quantization round-trips within max|row|/7.5 per block of
    lanes (the per-(row, head, position) residual-scale format).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.serve import Engine, EngineSupervisor
from nanosandbox_tpu.serve.faults import FaultPlan


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _mixed_reqs(n=10, seed=0, vocab=50, eos=None):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, vocab, int(rng.integers(2, 40))).tolist(),
             int(rng.integers(2, 12)), int(rng.integers(0, 99)), eos)
            for _ in range(n)]


def _run(model, params, reqs, **kw):
    eng = Engine(model, params, num_slots=4, max_len=64, **kw)
    for prompt, mnt, seed, eos in reqs:
        eng.submit(prompt, mnt, seed=seed, eos_id=eos)
    out = {r.rid: (r.tokens, r.finish_reason) for r in eng.drain()}
    assert len(out) == len(reqs)
    return eng, out


@pytest.mark.parametrize("paged", [True, False])
@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
def test_scan_greedy_parity_all_modes(served_model, paged, kv_dtype):
    """scan_k in {2, 4, 8} vs single-step: token-identical outputs on a
    mixed continuous-batching workload, per pool layout and KV mode."""
    _, model, params = served_model
    reqs = _mixed_reqs(seed=3)
    _, base = _run(model, params, reqs, paged=paged, kv_dtype=kv_dtype)
    for k in (2, 4, 8):
        _, out = _run(model, params, reqs, paged=paged,
                      kv_dtype=kv_dtype, scan_k=k)
        assert out == base, f"scan_k={k} diverged"


def test_scan_parity_survives_sync_loop(served_model):
    """scan composes with pipeline=False too (chunked sync loop)."""
    _, model, params = served_model
    reqs = _mixed_reqs(seed=5)
    _, base = _run(model, params, reqs)
    _, out = _run(model, params, reqs, pipeline=False, scan_k=4)
    assert out == base


def test_mid_chunk_eos_truncates_exactly_no_leaks(served_model):
    """An eos landing mid-chunk cuts the stream exactly where the
    single-step loop would; afterwards no slot or block is leaked."""
    from collections import Counter

    _, model, params = served_model
    # Self-calibrating eos: run once eos-free, pick the most common
    # MID-stream token — per-row keyed sampling means re-running with
    # that token as eos truncates those rows exactly there, so the
    # workload is guaranteed to exercise the mid-chunk eos path.
    reqs0 = _mixed_reqs(n=12, seed=11)
    _, free = _run(model, params, reqs0, paged=True)
    cnt = Counter(t for toks, _ in free.values() for t in toks[:-1])
    eos = cnt.most_common(1)[0][0]
    reqs = [(p, m, s, eos) for (p, m, s, _) in reqs0]
    _, base = _run(model, params, reqs, paged=True)
    eng, out = _run(model, params, reqs, paged=True, scan_k=8)
    assert out == base
    assert any(r[1] == "eos" for r in out.values()), \
        "workload never hit eos — the test lost its subject"
    assert not eng._active and eng.sched.free_slots == eng.num_slots
    ps = eng.block_pool.stats()
    assert ps["live"] == 0, ps


def test_mid_scan_poison_recovery_restitches(served_model):
    """A nan_logits fault poisoning a whole scan chunk recovers via the
    supervisor and the final outputs equal a no-fault run's — the
    clean pre-poison tokens are kept, downstream garbage discarded,
    victims requeued with prompt' = prompt + tokens-so-far."""
    _, model, params = served_model
    reqs = _mixed_reqs(n=8, seed=7)
    _, clean = _run(model, params, reqs, scan_k=4)
    plan = FaultPlan.parse("nan_logits@3")
    eng = Engine(model, params, num_slots=4, max_len=64, scan_k=4,
                 faults=plan)
    sup = EngineSupervisor(eng, backoff_base_s=0)
    for prompt, mnt, seed, eos in reqs:
        eng.submit(prompt, mnt, seed=seed, eos_id=eos)
    out = []
    while eng.has_work() and sup.state != "failed":
        out.extend(sup.step())
    assert sup.state == "ok"
    assert eng.recoveries >= 1
    assert {r.rid: (r.tokens, r.finish_reason) for r in out} == clean


def test_scan_budget_pinned_not_widened(served_model):
    """The compile set grows by EXACTLY the scan-rung ladder (decode
    programs), nothing else; trace counts stay within the published
    budget."""
    _, model, params = served_model
    reqs = _mixed_reqs(seed=13)
    e1, _ = _run(model, params, reqs)
    e8, _ = _run(model, params, reqs, scan_k=8)
    p1, p8 = e1.max_programs(), e8.max_programs()
    assert e8.scan_rungs == [1, 2, 4, 8]
    assert p8["decode"] == len(e8.scan_rungs)
    assert {k: v for k, v in p8.items() if k != "decode"} == \
        {k: v for k, v in p1.items() if k != "decode"}
    for name, n in e8.trace_counts.items():
        assert n <= p8[name], (name, n, p8)


def test_scan_dispatch_ledger_and_metrics(served_model):
    """Chunked decode amortizes dispatches: tokens_per_dispatch well
    above 1, and the ledger lands on /metrics as
    serve_host_dispatches_total{kind=} + serve_tokens_per_dispatch."""
    _, model, params = served_model
    reqs = [(list(range(2, 10)), 16, s, None) for s in range(6)]
    eng, _ = _run(model, params, reqs, scan_k=8)
    st = eng.stats()
    assert st["scan_k"] == 8
    assert st["tokens_per_dispatch"] is not None
    assert st["tokens_per_dispatch"] > 2.0
    assert eng.host_dispatches["decode"] * 2 < eng.tokens_generated
    from nanosandbox_tpu.obs import render_prometheus

    text = render_prometheus(eng.metrics)
    assert 'serve_host_dispatches_total{kind="decode"}' in text
    assert "serve_tokens_per_dispatch" in text
    # The single-step twin must retire ~one token per row per dispatch.
    eng1, _ = _run(model, params, reqs)
    assert eng1.host_dispatches["decode"] >= eng.host_dispatches["decode"]


def test_flight_retire_events_carry_chunk_index(served_model):
    """Under lag-k every retire event records n tokens + its scan-chunk
    index, so per-token TPOT stays derivable from the flight JSONL."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64, scan_k=4)
    eng.submit(list(range(2, 8)), 10, seed=1)
    eng.drain()
    retires = [e for e in eng.flight.events() if e["ev"] == "retire"]
    assert retires
    chunked = [e for e in retires if e.get("n", 0) > 1]
    assert chunked, "scan_k=4 never retired a multi-token chunk"
    assert all("chunk" in e for e in chunked)
    total = sum(e["n"] for e in retires)
    finishes = [e for e in eng.flight.events() if e["ev"] == "finish"]
    # Each request's FIRST token comes from its prefill wave, not a
    # decode retire — the ledger splits them by design.
    assert sum(f["tokens"] for f in finishes) == total + len(finishes)


def test_scan_forced_to_one_under_spec(served_model):
    """spec keeps the synchronous loop: scan_k silently collapses to 1
    (the verify readback gates the next frontier)."""
    from nanosandbox_tpu.serve import NGramDrafter

    _, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64, scan_k=8,
                 spec=NGramDrafter(k=3))
    assert eng.scan_k == 1 and eng.scan_rungs == [1]


def test_scan_k_validation(served_model):
    _, model, params = served_model
    with pytest.raises(ValueError, match="scan_k"):
        Engine(model, params, num_slots=2, max_len=64, scan_k=0)


def test_int4_round_trip_error_bound():
    """Per-block-of-lanes int4 residual scales: round-trip error is
    bounded by max|row| / 7.5 (the nibble grid's worst case), and
    all-zero rows survive exactly."""
    from nanosandbox_tpu.ops.flash_decode import (quantize_kv_rows_int4,
                                                  unpack_int4)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 2, 17, 32)) * 9.0, jnp.float32)
    x = x.at[1, 0, 4].set(0.0)                      # an all-zero row
    packed, scale = quantize_kv_rows_int4(x)
    assert packed.dtype == jnp.uint8
    assert packed.shape == (3, 2, 17, 16)
    back = unpack_int4(packed).astype(jnp.float32) * scale[..., None]
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    err = jnp.abs(back - x)
    assert bool(jnp.all(err <= amax / 7.5 + 1e-7))
    assert bool(jnp.all(back[1, 0, 4] == 0.0))


def test_int4_sentinel_rows_skip_scale_chain():
    """The valid-mask fast path: sentinel rows quantize to zero scale
    and zero values without feeding the amax/divide chain."""
    from nanosandbox_tpu.ops.flash_decode import (quantize_kv_rows,
                                                  quantize_kv_rows_int4,
                                                  unpack_int4)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
    valid = jnp.asarray([True, False, True, False])[:, None]
    p4, s4 = quantize_kv_rows_int4(x, valid=valid)
    assert bool(jnp.all(s4[1] == 0)) and bool(jnp.all(s4[3] == 0))
    assert bool(jnp.all(unpack_int4(p4)[1] == 0))
    q8, s8 = quantize_kv_rows(x, valid=valid)
    assert bool(jnp.all(s8[1] == 0)) and bool(jnp.all(q8[1] == 0))
    # valid rows match the unmasked quantization exactly
    p4u, s4u = quantize_kv_rows_int4(x)
    assert bool(jnp.all(p4[0] == p4u[0])) and bool(jnp.all(s4[0] == s4u[0]))


@pytest.mark.parametrize("paged", [True, False])
def test_int4_vs_fp32_greedy_agreement(served_model, paged):
    """int4 is a lossy mode: require >= 90% greedy token agreement with
    the fp32 pool on the mixed workload (the ISSUE-12 parity floor),
    and identical agreement paged vs dense (same quantizer, same
    positions)."""
    _, model, params = served_model
    reqs = _mixed_reqs(n=10, seed=17)
    _, fp = _run(model, params, reqs, paged=paged)
    _, q4 = _run(model, params, reqs, paged=paged, kv_dtype="int4")
    total = matched = 0
    for rid, (toks, _) in fp.items():
        qtoks = q4[rid][0]
        total += max(len(toks), len(qtoks))
        matched += sum(a == b for a, b in zip(toks, qtoks))
    assert matched / total >= 0.9, f"int4 greedy agreement {matched/total}"


def test_int4_paged_equals_dense_token_exact(served_model):
    """Paged int4 reads/writes the same quantized values at the same
    positions as dense int4 — token-identical outputs."""
    _, model, params = served_model
    reqs = _mixed_reqs(n=10, seed=19)
    _, dense = _run(model, params, reqs, paged=False, kv_dtype="int4")
    _, paged = _run(model, params, reqs, paged=True, kv_dtype="int4")
    assert paged == dense


def test_int4_doubles_pool_capacity_at_equal_value_bytes(served_model):
    """The capacity story: an int4 pool holds 2x the blocks of an int8
    pool at equal value bytes, and admission need per request is
    dtype-independent — so effective capacity doubles."""
    cfg, model, params = served_model
    e8 = Engine(model, params, num_slots=4, max_len=64, kv_dtype="int8")
    e4 = Engine(model, params, num_slots=4, max_len=64, kv_dtype="int4",
                kv_pool_blocks=2 * e8.kv_pool_blocks)
    # per-block value bytes: int4 stores head_dim // 2 uint8 lanes
    k8 = e8._pool[0][0]
    k4 = e4._pool[0][0]
    assert k4.shape[-1] * 2 == k8.shape[-1]
    assert k4.dtype == jnp.uint8 and k8.dtype == jnp.int8
    assert (k4.size * k4.dtype.itemsize
            == k8.size * k8.dtype.itemsize)      # equal value bytes
    need8 = e8.block_pool.blocks_needed(20, 10)
    need4 = e4.block_pool.blocks_needed(20, 10)
    assert need8 == need4
    assert e4.kv_pool_blocks == 2 * e8.kv_pool_blocks


def test_scan_bench_smoke():
    """bench.py --mode=decode --scan_k wiring: scan twin fields land in
    the JSON with parity 1.0 and a sane dispatch ledger."""
    import bench

    out = bench.main(["--quick", "--mode=decode", "--mixed",
                      "--scan_k=4", "--repeat=2", "--requests=8"])
    extra = out["extra"]
    assert extra["scan_k"] == 4
    assert extra["scan_rungs"] == [1, 2, 4]
    assert extra["scan_greedy_parity"] == 1.0
    assert extra["scan_vs_single_toks"] > 0
    assert extra["dispatches_per_token"] <= 0.5
    assert extra["tokens_per_dispatch"] > 1.0


@pytest.mark.parametrize("max_len", [64, 10])
def test_scan_rung_warmup_is_freeze_safe(served_model, max_len):
    """Engine.warm_scan_rungs() (the serve __main__ / bench warmup)
    compiles the ENTIRE ladder — including rungs only reachable through
    tie-breaks or mixed-row budget profiles — so a frozen registry
    survives arbitrary post-warmup traffic. max_len=10 pins the
    short-context case where a budget-capped warmup heuristic used to
    skip the top rung and the first max-budget request retraced
    post-freeze."""
    _, model, params = served_model
    e = Engine(model, params, num_slots=4, max_len=max_len, scan_k=8)
    lo = 1
    for bucket in e.sched.buckets:
        length = min(bucket, e.max_len - 2)
        lo, prev_lo = bucket + 1, lo
        if length < prev_lo:
            continue
        for k in e.admit_buckets:
            for _ in range(k):
                e.submit([0] * length, 2)
            e.drain()
            e.reset_prefix_cache()
    e.warm_scan_rungs()
    e.reset_prefix_cache()
    assert e.trace_counts["decode"] == len(e.scan_rungs)
    with e.tracecheck.frozen():
        rng = np.random.default_rng(0)
        for i in range(40):
            L = int(rng.integers(1, min(50, max_len - 1)))
            mnt = int(rng.integers(1, max_len - L + 1))
            e.submit(rng.integers(0, 50, L).tolist(), mnt, seed=i)
        e.drain()
