"""The REAL-data evidence chain (round-2 VERDICT missing #2 / #4).

Pins the committed real-English fixture and the prep paths that consume
it, so every recorded loss number traces back to verifiable non-synthetic
text: the fixture's natural-language statistics, the char prep's exact
token counts, and the BPE prep run on the same real text.
"""

from __future__ import annotations

import collections
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "data", "fixtures", "english_prose.txt")


@pytest.fixture(scope="module")
def corpus() -> str:
    assert os.path.exists(FIXTURE), (
        "real-text fixture missing — run scripts/make_real_corpus.py")
    with open(FIXTURE, "r", encoding="utf-8") as f:
        return f.read()


def test_fixture_is_real_english(corpus):
    """Natural-language sanity: size, vocab, and Zipf-head words. A
    synthetic corpus (data/prepare.py _synthetic_corpus: 20-word
    vocabulary) cannot pass the unique-word bound."""
    assert len(corpus) == 4_000_000
    vocab = set(corpus)
    assert len(vocab) == 96 and all(ord(c) < 128 for c in vocab)
    words = [w.lower().strip(".,;:()\"'") for w in corpus.split()]
    counts = collections.Counter(words)
    assert len(counts) > 20_000, "real English has a large vocabulary"
    head = [w for w, _ in counts.most_common(12)]
    # The most frequent English function words must dominate.
    assert "the" == head[0]
    assert {"of", "to", "a", "is"} & set(head[:8])


def test_char_prep_token_counts(corpus, tmp_path):
    from nanosandbox_tpu.data.prepare import prepare_english_prose_dataset

    stats = prepare_english_prose_dataset(str(tmp_path), source_file=FIXTURE)
    assert stats == {"train_tokens": 3_600_000, "val_tokens": 400_000,
                     "vocab_size": 96}
    # Bins must roundtrip to the source text through meta.pkl.
    import pickle

    from nanosandbox_tpu.data.tokenizer import CharTokenizer
    with open(tmp_path / "meta.pkl", "rb") as f:
        meta = pickle.load(f)
    tok = CharTokenizer.from_meta(meta)
    train = np.fromfile(tmp_path / "train.bin", dtype=np.uint16)
    assert tok.decode(train[:512]) == corpus[:512]


def test_char_prep_missing_fixture_fails_loudly(tmp_path):
    from nanosandbox_tpu.data.prepare import prepare_english_prose_dataset

    with pytest.raises(FileNotFoundError, match="make_real_corpus"):
        prepare_english_prose_dataset(str(tmp_path),
                                      source_file=str(tmp_path / "no.txt"))


def test_bpe_prep_on_real_text(corpus, tmp_path):
    """prepare_bpe_dataset on REAL text (round-2 VERDICT missing #4):
    token counts pinned for whichever tokenizer resolves. Offline (no
    tiktoken vocab) the byte fallback must reproduce the corpus bytes
    exactly; with tiktoken available, the gpt2 counts are sanity-bounded
    by BPE's known ~4 chars/token compression on English."""
    from nanosandbox_tpu.data.prepare import prepare_bpe_dataset

    text = corpus[:500_000]
    stats = prepare_bpe_dataset(str(tmp_path), text=text, download=False,
                                allow_synthetic=False)
    if stats["vocab_size"] == 256:  # byte fallback (offline image)
        assert stats["train_tokens"] == 450_000
        assert stats["val_tokens"] == 50_000
        train = np.fromfile(tmp_path / "train.bin", dtype=np.uint16)
        assert bytes(train[:256].astype(np.uint8)) == text.encode()[:256]
    else:  # real gpt2 BPE
        assert stats["vocab_size"] == 50257
        total = stats["train_tokens"] + stats["val_tokens"]
        assert 90_000 < total < 170_000  # ~3-5.5 chars/token on English


def test_manifest_accounts_for_every_corpus_byte():
    """The provenance manifest's bytes_contributed column must sum to the
    emitted corpus size exactly (the final document is cut by the
    max_bytes truncation and must be recorded post-cut), and every
    site-packages path must belong to the pinned allowlist that makes
    the PROVENANCE.md redistribution claim auditable."""
    manifest = FIXTURE + ".manifest"
    assert os.path.exists(manifest)
    import sys
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from make_real_corpus import _DIST_NAMES, DOCSTRING_PACKAGES

    allowed = set(DOCSTRING_PACKAGES) | set(_DIST_NAMES.values())

    total = 0
    with open(manifest) as f:
        for line in f:
            if line.startswith("#") or not line.strip():
                continue
            _, path, nbytes = line.rsplit("\t", 2)[-3:]
            total += int(nbytes)
            if "/site-packages/" in path:
                pkg = path.split("/site-packages/")[1].split("/")[0]
                pkg = pkg.split("-")[0]  # foo-1.2.dist-info -> foo
                assert pkg in allowed, (
                    f"unpinned package in corpus provenance: {path}")
    assert total == os.path.getsize(FIXTURE)
