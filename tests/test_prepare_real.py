"""The REAL-data evidence chain (round-2 VERDICT missing #2 / #4).

Pins the committed real-English fixture and the prep paths that consume
it, so every recorded loss number traces back to verifiable non-synthetic
text: the fixture's natural-language statistics, the char prep's exact
token counts, and the BPE prep run on the same real text.
"""

from __future__ import annotations

import collections
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "data", "fixtures", "english_prose.txt")


@pytest.fixture(scope="module")
def corpus() -> str:
    assert os.path.exists(FIXTURE), (
        "real-text fixture missing — run scripts/make_real_corpus.py")
    with open(FIXTURE, "r", encoding="utf-8") as f:
        return f.read()


def test_fixture_is_real_english(corpus):
    """Natural-language sanity: size, vocab, and Zipf-head words. A
    synthetic corpus (data/prepare.py _synthetic_corpus: 20-word
    vocabulary) cannot pass the unique-word bound."""
    assert len(corpus) == 4_000_000
    vocab = set(corpus)
    assert len(vocab) == 96 and all(ord(c) < 128 for c in vocab)
    words = [w.lower().strip(".,;:()\"'") for w in corpus.split()]
    counts = collections.Counter(words)
    assert len(counts) > 20_000, "real English has a large vocabulary"
    head = [w for w, _ in counts.most_common(12)]
    # The most frequent English function words must dominate.
    assert "the" == head[0]
    assert {"of", "to", "a", "is"} & set(head[:8])


def test_char_prep_token_counts(corpus, tmp_path):
    from nanosandbox_tpu.data.prepare import prepare_english_prose_dataset

    stats = prepare_english_prose_dataset(str(tmp_path), source_file=FIXTURE)
    assert stats == {"train_tokens": 3_600_000, "val_tokens": 400_000,
                     "vocab_size": 96}
    # Bins must roundtrip to the source text through meta.pkl.
    import pickle

    from nanosandbox_tpu.data.tokenizer import CharTokenizer
    with open(tmp_path / "meta.pkl", "rb") as f:
        meta = pickle.load(f)
    tok = CharTokenizer.from_meta(meta)
    train = np.fromfile(tmp_path / "train.bin", dtype=np.uint16)
    assert tok.decode(train[:512]) == corpus[:512]


def test_char_prep_missing_fixture_fails_loudly(tmp_path):
    from nanosandbox_tpu.data.prepare import prepare_english_prose_dataset

    with pytest.raises(FileNotFoundError, match="make_real_corpus"):
        prepare_english_prose_dataset(str(tmp_path),
                                      source_file=str(tmp_path / "no.txt"))


def test_bpe_prep_on_real_text(corpus, tmp_path):
    """prepare_bpe_dataset on REAL text with the COMMITTED offline BPE
    vocab (round-3 VERDICT next #1): 50,257-entry GPT-2-shape vocabulary,
    counts sanity-bounded by BPE's known ~4 chars/token on English."""
    from nanosandbox_tpu.data.prepare import prepare_bpe_dataset

    text = corpus[:500_000]
    stats = prepare_bpe_dataset(str(tmp_path), text=text, download=False,
                                allow_synthetic=False, tokenizer="bpe")
    assert stats["vocab_size"] == 50257
    total = stats["train_tokens"] + stats["val_tokens"]
    assert 90_000 < total < 170_000  # ~3-5.5 chars/token on English
    # bins decode back to the original text (uint16 ids, lossless BPE)
    from nanosandbox_tpu.data.tokenizer import get_tokenizer

    tok = get_tokenizer("bpe")
    train = np.fromfile(tmp_path / "train.bin", dtype=np.uint16)
    assert tok.decode(train[:2000]) == text[:len(tok.decode(train[:2000]))]


def test_bpe_prep_byte_downgrade_is_opt_in(tmp_path):
    """An unavailable tokenizer must FAIL the prep, not silently emit
    vocab-256 bins for a run configured at 50k vocab (round-3 VERDICT
    weak #6); the downgrade happens only with allow_byte_fallback."""
    from nanosandbox_tpu.data.prepare import prepare_bpe_dataset

    # 'gpt2' (tiktoken) is genuinely unavailable in this zero-egress image.
    with pytest.raises(RuntimeError, match="allow_byte_fallback"):
        prepare_bpe_dataset(str(tmp_path / "strict2"), text="hello " * 5000,
                            download=False, allow_synthetic=False,
                            tokenizer="gpt2")
    stats = prepare_bpe_dataset(str(tmp_path / "fb"), text="hello " * 5000,
                                download=False, allow_synthetic=False,
                                tokenizer="gpt2", allow_byte_fallback=True)
    assert stats["vocab_size"] == 256


def test_english_prose_bpe_prep_small_source(tmp_path):
    """The english_prose_bpe dataset prep on a small source file: real
    BPE ids, meta records kind='bpe' + the asset path so sample.py can
    reconstruct the tokenizer."""
    import pickle

    from nanosandbox_tpu.data.prepare import prepare_english_prose_bpe_dataset

    src = tmp_path / "src.txt"
    src.write_text("The quick brown fox jumps over the lazy dog. " * 2000)
    out = tmp_path / "ds"
    stats = prepare_english_prose_bpe_dataset(str(out),
                                              source_file=str(src))
    assert stats["vocab_size"] == 50257
    meta = pickle.loads((out / "meta.pkl").read_bytes())
    assert meta["kind"] == "bpe" and "asset" in meta


def test_manifest_accounts_for_every_corpus_byte():
    """The provenance manifest's bytes_contributed column must sum to the
    emitted corpus size exactly (the final document is cut by the
    max_bytes truncation and must be recorded post-cut), and every
    site-packages path must belong to the pinned allowlist that makes
    the PROVENANCE.md redistribution claim auditable."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from make_real_corpus import (_DIST_NAMES, DOCSTRING_PACKAGES,
                                  XL_EXTRA_PACKAGES)

    base_allowed = set(DOCSTRING_PACKAGES) | set(_DIST_NAMES.values())
    cases = [
        (FIXTURE, base_allowed),
        (os.path.join(REPO, "data", "fixtures", "english_prose_xl.txt"),
         base_allowed | set(XL_EXTRA_PACKAGES)),
    ]
    for fixture, allowed in cases:
        manifest = fixture + ".manifest"
        assert os.path.exists(manifest), manifest
        total = 0
        with open(manifest) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                _, path, nbytes = line.rsplit("\t", 2)[-3:]
                total += int(nbytes)
                if "/site-packages/" in path:
                    pkg = path.split("/site-packages/")[1].split("/")[0]
                    pkg = pkg.split("-")[0]  # foo-1.2.dist-info -> foo
                    assert pkg in allowed, (
                        f"unpinned package in corpus provenance: {path}")
        assert total == os.path.getsize(fixture), fixture


def test_bpe_vocab_asset_matches_manifest_and_is_deterministic(tmp_path):
    """The committed vocab asset must (a) carry a manifest whose corpus
    sha256 matches the committed XL corpus — a drifted corpus fails here
    instead of silently re-deriving a different vocab — and (b) come from
    a deterministic trainer: double-training on a small corpus yields
    identical serialized vocabs."""
    import json
    import sys
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from make_bpe_vocab import _sha256, train_vocab

    asset_dir = os.path.join(REPO, "data", "fixtures", "bpe_english_prose")
    manifest = json.load(open(os.path.join(asset_dir, "MANIFEST.json")))
    xl = os.path.join(REPO, manifest["corpus"])
    assert _sha256(xl) == manifest["corpus_sha256"]
    assert _sha256(os.path.join(asset_dir, "tokenizer.json")) == \
        manifest["asset_sha256"]
    assert manifest["vocab_size"] == 50257

    # determinism on a small corpus / small vocab (full retrain is ~10 s;
    # this is the same trainer configuration at test scale)
    small = tmp_path / "c.txt"
    small.write_text(open(FIXTURE).read()[:300_000])
    m1 = train_vocab(str(small), str(tmp_path / "v1"), vocab_size=500)
    m2 = train_vocab(str(small), str(tmp_path / "v2"), vocab_size=500)
    assert m1["asset_sha256"] == m2["asset_sha256"]
