"""Telemetry spine tests: metric registry, span tracer, serving surface.

The contract under test (ISSUE 5):
  * registry label semantics — one family per name, kind/label mismatch
    raises, children per label combination, collectors run per scrape;
  * Prometheus text exposition that a stdlib-grammar parser accepts
    (the golden-format gate — what a k8s scrape consumes);
  * span ordering and rid correlation under the PIPELINED engine: a
    decode_step span closes at its lagged retire (after the next step's
    dispatch), request spans survive eviction + backfill with no
    orphans left open after a drain;
  * Chrome trace-event JSON schema (Perfetto-loadable) per request and
    per time window;
  * /metrics + /trace + /profile HTTP roundtrips on the real frontend;
  * telemetry adds NO host syncs (tracecheck ledger before == after,
    modulo the engine's own audited readbacks) and bounded overhead
    (the begin/end pair is microseconds — pinned, not vibes).
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.obs import (MetricRegistry, SpanTracer, global_registry,
                                 render_prometheus)
from nanosandbox_tpu.serve import Engine
from nanosandbox_tpu.utils import tracecheck


# ------------------------------------------------------------- registry

def test_registry_counter_gauge_basics():
    reg = MetricRegistry()
    c = reg.counter("requests_total", "Requests.")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)
    g = reg.gauge("depth", "Queue depth.")
    assert g.value is None          # unset gauge: no sample
    g.set(7)
    assert g.value == 7.0


def test_registry_label_semantics():
    reg = MetricRegistry()
    fam = reg.counter("hits_total", "Hits.", labelnames=("route",))
    fam.labels(route="/a").inc()
    fam.labels(route="/a").inc()
    fam.labels(route="/b").inc()
    assert fam.labels(route="/a").value == 2
    assert fam.labels(route="/b").value == 1
    # label-name mismatch raises rather than silently forking a series
    with pytest.raises(ValueError, match="takes labels"):
        fam.labels(path="/a")
    # a labeled family refuses label-less use
    with pytest.raises(ValueError, match="use .labels"):
        fam.inc()
    # same name, same shape -> the SAME family (process-wide semantics)
    assert reg.counter("hits_total", labelnames=("route",)) is fam
    # same name, different kind or labels -> programming error
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("hits_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("hits_total", labelnames=("other",))


def test_registry_name_validation():
    reg = MetricRegistry()
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad-name")
    with pytest.raises(ValueError, match="invalid label name"):
        reg.counter("ok_name", labelnames=("bad-label",))
    with pytest.raises(TypeError, match="not counter"):
        reg.gauge("g").inc()


def test_histogram_buckets_window_and_reset():
    reg = MetricRegistry()
    h = reg.histogram("lat_seconds", "Latency.", buckets=(0.1, 1.0),
                      window=4)
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    child = h.labels()
    assert child.count == 4 and child.sum == pytest.approx(6.05)
    # cumulative bucket counts: le=0.1 -> 1, le=1.0 -> 3, +Inf -> 4
    text = reg.prometheus_text()
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 3' in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    # the RingStat window view feeds percentiles (and /stats)
    assert h.percentiles((50,))["p50"] == 0.5
    h.observe(9.0)                  # evicts 0.05 from the 4-wide window
    assert h.mean() == pytest.approx((0.5 + 0.5 + 5.0 + 9.0) / 4)
    assert child.count == 5         # cumulative counts never window
    h.reset()
    assert child.count == 0 and h.mean() is None


def test_collectors_run_per_snapshot():
    reg = MetricRegistry()
    g = reg.gauge("mirrored", "Mirror of external state.")
    state = {"v": 1}
    reg.add_collector(lambda: g.set(state["v"]))
    assert reg.snapshot()["mirrored"]["series"][0]["value"] == 1
    state["v"] = 42
    assert reg.snapshot()["mirrored"]["series"][0]["value"] == 42


def test_snapshot_json_shape():
    reg = MetricRegistry()
    reg.counter("c_total", "C.", labelnames=("k",)).labels(k="x").inc(3)
    reg.histogram("h_s", "H.").observe(0.2)
    snap = reg.snapshot()
    json.dumps(snap)  # JSON-ready, no numpy/dataclass leakage
    assert snap["c_total"]["type"] == "counter"
    assert snap["c_total"]["series"] == [{"labels": {"k": "x"}, "value": 3.0}]
    h = snap["h_s"]["series"][0]
    assert h["count"] == 1 and h["percentiles"]["p50"] == pytest.approx(0.2)


# ------------------------------------------- Prometheus exposition format

# The subset of the text-format grammar we emit, as a scraper's parser
# accepts it: HELP/TYPE comments and `name{labels} value` samples.
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\]|\\.)*"(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
    r'(-?[0-9.eE+-]+|[+-]Inf|NaN)$')
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def parse_exposition(text: str) -> dict:
    """Stdlib-only parse; returns {family: type}. Raises on any line
    the grammar rejects and on duplicate TYPE declarations."""
    types: dict = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("#"):
            assert _COMMENT_RE.match(ln), f"bad comment line: {ln!r}"
            parts = ln.split(" ", 3)
            if parts[1] == "TYPE":
                assert parts[2] not in types, f"duplicate TYPE {parts[2]}"
                types[parts[2]] = parts[3]
        else:
            assert _SAMPLE_RE.match(ln), f"bad sample line: {ln!r}"
    return types


def test_prometheus_exposition_golden_format():
    reg = MetricRegistry()
    reg.counter("req_total", "Total requests.", labelnames=("route",)
                ).labels(route='/gen"x"\\y').inc(3)
    reg.gauge("depth", "Depth\nwith newline.").set(2)
    h = reg.histogram("ttft_seconds", "TTFT.", buckets=(0.01, 0.1))
    h.observe(0.05)
    h.observe(0.05)
    text = reg.prometheus_text()
    types = parse_exposition(text)
    assert types == {"req_total": "counter", "depth": "gauge",
                     "ttft_seconds": "histogram",
                     "ttft_seconds_window": "summary"}
    # label values escape quotes/backslashes; HELP escapes newlines
    assert r'route="/gen\"x\"\\y"' in text
    assert "# HELP depth Depth\\nwith newline." in text
    # integral floats render as ints; the summary carries the window
    # percentiles under `quantile` labels
    assert "req_total" in text and " 3\n" in text
    assert 'ttft_seconds_window{quantile="0.5"} 0.05' in text
    assert text.endswith("\n")


def test_render_prometheus_rejects_duplicate_families():
    a, b = MetricRegistry(), MetricRegistry()
    a.counter("dup_total").inc()
    b.counter("dup_total").inc()
    with pytest.raises(ValueError, match="two registries"):
        render_prometheus(a, b)


def test_unset_gauge_and_empty_family_emit_nothing():
    reg = MetricRegistry()
    reg.gauge("never_set", "Unset.")
    reg.counter("never_touched", "No children.", labelnames=("k",))
    assert reg.prometheus_text() == ""


# --------------------------------------------------------------- tracer

def test_tracer_begin_end_rid_filter_and_orphans():
    tr = SpanTracer(capacity=16)
    a = tr.begin("queued", cat="request", rid=7)
    b = tr.begin("decode_step", args={"step": 1})
    assert tr.open_count() == 2
    tr.end(a, {"wait_steps": 3})
    tr.end(b)
    tr.instant("marker", rid=7)
    assert tr.open_count() == 0
    mine = tr.spans(rid=7)
    assert [s.name for s in mine] == ["queued", "marker"]
    assert mine[0].args["wait_steps"] == 3
    # unknown/zero sids are teardown-safe no-ops
    tr.end(0)
    tr.end(999999)
    # clear drops completed spans but never in-flight ones
    c = tr.begin("inflight")
    tr.clear()
    assert tr.spans() == [] and tr.open_count() == 1
    tr.end(c)
    assert [s.name for s in tr.spans()] == ["inflight"]


def test_tracer_disabled_is_noop():
    tr = SpanTracer(enabled=False)
    sid = tr.begin("x", rid=1)
    assert sid == 0
    tr.end(sid)
    tr.instant("y")
    assert tr.spans() == [] and tr.open_count() == 0
    assert tr.export_chrome()["traceEvents"] == []


def test_tracer_ring_is_bounded():
    tr = SpanTracer(capacity=8)
    for i in range(50):
        tr.instant(f"s{i}")
    spans = tr.spans()
    assert len(spans) == 8
    assert spans[0].name == "s42" and spans[-1].name == "s49"


def test_export_chrome_schema_and_tracks():
    tr = SpanTracer()
    e = tr.begin("decode_step")              # engine track (no rid)
    r = tr.begin("generate", cat="request", rid=3)
    tr.end(r)
    tr.end(e)
    trace = tr.export_chrome()
    evs = trace["traceEvents"]
    meta = [ev for ev in evs if ev["ph"] == "M"]
    spans = [ev for ev in evs if ev["ph"] == "X"]
    # one thread_name metadata record per track: engine tid 0, rid 3
    # rides tid 4
    assert {(m["tid"], m["args"]["name"]) for m in meta} == {
        (0, "engine"), (4, "request 3")}
    for ev in spans:
        assert isinstance(ev["ts"], float) and ev["dur"] >= 0
        assert ev["pid"] == 0
    by_name = {ev["name"]: ev for ev in spans}
    assert by_name["generate"]["args"]["rid"] == 3
    assert by_name["decode_step"]["tid"] == 0
    json.dumps(trace)


def test_export_chrome_rid_includes_overlapping_engine_spans():
    tr = SpanTracer()
    before = tr.begin("decode_step")         # ends before rid 5 begins
    tr.end(before)
    time.sleep(0.002)                        # monotonic() must advance
    mine = tr.begin("generate", cat="request", rid=5)
    during = tr.begin("decode_step")         # overlaps rid 5's lifetime
    tr.end(during)
    other = tr.begin("generate", cat="request", rid=6)
    tr.end(other)
    tr.end(mine)
    names_tids = {(ev["name"], ev["tid"])
                  for ev in tr.export_chrome(rid=5)["traceEvents"]
                  if ev["ph"] == "X"}
    # rid 5's own span + the engine span overlapping it; NOT the
    # pre-dating engine span, NOT rid 6's track
    assert ("generate", 6) in names_tids
    assert ("decode_step", 0) in names_tids
    assert len([nt for nt in names_tids if nt[0] == "decode_step"]) == 1
    assert all(tid != 7 for _, tid in names_tids)
    # unknown rid -> empty export (the 404 the http route serves)
    assert tr.export_chrome(rid=12345)["traceEvents"] == []


def test_export_chrome_shows_inflight_request_open_spans():
    """A request still sitting in the queue exports its OPEN span with
    duration-so-far and args.incomplete — the admission-pressure
    diagnosis /trace exists for must not 404 until the request
    finishes."""
    tr = SpanTracer()
    tr.begin("queued", cat="request", rid=8, args={"prompt_len": 3})
    evs = [ev for ev in tr.export_chrome(rid=8)["traceEvents"]
           if ev["ph"] == "X"]
    assert len(evs) == 1
    assert evs[0]["name"] == "queued"
    assert evs[0]["args"]["incomplete"] is True
    assert evs[0]["dur"] >= 0
    # the span is still open in the tracer — the export took a copy
    assert tr.open_count() == 1


def test_tracer_overhead_pinned():
    """The begin/end pair must stay in the low-microsecond range — the
    engine records ~1 span per decode step + 2 per request, so at even
    50 us/pair telemetry could not move a tokens/sec benchmark by the
    3% acceptance bar. Generous CI-proof ceiling, median of 5."""
    tr = SpanTracer(capacity=1024)
    n = 2000
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        for i in range(n):
            tr.end(tr.begin("s", args={"i": i}))
        runs.append((time.perf_counter() - t0) / n)
    runs.sort()
    assert runs[2] < 50e-6, f"begin/end pair {runs[2] * 1e6:.1f}us"


# ------------------------------------------------- engine span semantics

@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def test_engine_spans_rid_correlation_and_no_orphans(served_model):
    """Every request's track is queued -> generate with matching rids;
    eviction + backfill (more requests than slots) leaves ZERO open
    spans after the drain — a leak means some finish path forgot its
    end, exactly the eviction/backfill bug class."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    rids = [eng.submit([1 + i, 2, 3], 4 + i) for i in range(5)]
    results = {r.rid: r for r in eng.drain()}
    assert set(results) == set(rids)
    assert eng.tracer.open_count() == 0
    for rid in rids:
        track = eng.tracer.spans(rid=rid)
        assert [s.name for s in track] == ["queued", "generate"], rid
        q, g = track
        assert q.args["prompt_len"] == 3
        assert g.args["finish_reason"] == "length"
        assert g.args["tokens"] == len(results[rid].tokens)
        # admission closes the queue span at the generate span's start
        assert q.t1 <= g.t0 + 1e-9


def test_engine_decode_spans_show_pipeline_lag(served_model):
    """Pipelined decode_step spans overlap: step k is dispatched while
    step k-1 is still unretired, so span k-1 must END after span k
    BEGINS. The synchronous engine's spans must NOT overlap — the
    timeline exports the loop's true shape either way."""
    _, model, params = served_model
    for pipeline, want_overlap in ((True, True), (False, False)):
        eng = Engine(model, params, num_slots=2, max_len=64,
                     pipeline=pipeline)
        eng.submit([1, 2, 3], 12)
        eng.drain()
        steps = sorted((s for s in eng.tracer.spans()
                        if s.name == "decode_step"),
                       key=lambda s: s.args["step"])
        assert len(steps) >= 4
        overlaps = [a.t1 > b.t0 for a, b in zip(steps, steps[1:])]
        if want_overlap:
            assert all(overlaps), "pipelined steps must overlap"
        else:
            assert not any(overlaps), "sync steps must not overlap"
        assert [s.args["step"] for s in steps] == \
            list(range(1, len(steps) + 1))


def test_engine_metrics_via_stats_and_exposition(served_model):
    """The registry IS the /stats backing store: counters mirror the
    engine ints at collection time, the exposition parses clean and
    carries the acceptance-criteria families."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    rids = [eng.submit([1, 2], 6) for _ in range(3)]
    eng.submit([1, 2], 0)   # zero-token fast path: completes too
    eng.drain()
    snap = eng.metrics.snapshot()
    assert snap["serve_requests_submitted_total"]["series"][0]["value"] == 4
    assert snap["serve_tokens_generated_total"]["series"][0]["value"] \
        == eng.tokens_generated
    # submitted - completed must not drift (the in-flight alert query)
    done = {s["labels"]["reason"]: s["value"]
            for s in snap["serve_requests_completed_total"]["series"]}
    assert done == {"length": 4}
    assert snap["serve_slots_active"]["series"][0]["value"] == 0
    types = parse_exposition(eng.metrics.prometheus_text())
    for fam in ("serve_ttft_seconds", "serve_tpot_seconds",
                "serve_queue_wait_steps", "serve_decode_tokens_per_sec",
                "serve_queue_depth", "serve_compile_traces_total",
                "serve_decode_steps_total"):
        assert fam in types, fam
    # legacy dict shape survives the migration (the /stats contract)
    st = eng.stats()
    assert st["completed"] == 3 and "p50" in st["ttft_s"]
    assert set(rids) == {0, 1, 2}


def test_engine_telemetry_adds_no_host_syncs(served_model):
    """The jaxlint contract, asserted at runtime: a traced+metered
    drain grows the tracecheck sync ledger by EXACTLY what the same
    workload does with telemetry off — the tracer and registry never
    touch a device value."""
    _, model, params = served_model

    def sync_delta(**engine_kw):
        before = tracecheck.sync_counts()
        eng = Engine(model, params, num_slots=2, max_len=64, **engine_kw)
        for i in range(4):
            eng.submit([1 + i, 2], 5, deadline_s=30.0)
        eng.drain()
        eng.metrics.snapshot()
        eng.tracer.export_chrome()
        eng.flight.to_jsonl()
        eng.debug_slots(), eng.debug_kvpool(), eng.debug_scheduler()
        after = tracecheck.sync_counts()
        return {k: after[k] - before.get(k, 0) for k in after
                if after[k] != before.get(k, 0)}

    from nanosandbox_tpu.obs import FlightRecorder

    with_obs = sync_delta()
    without = sync_delta(tracer=SpanTracer(enabled=False),
                         flight=FlightRecorder(enabled=False),
                         watchdogs=False)
    assert with_obs == without


def test_request_profile_is_freeze_safe(served_model):
    """POST /profile machinery: a profiler window over a live engine
    whose tracecheck registry is FROZEN must complete without raising —
    profiling wraps already-compiled programs, never new traces — and
    report its dir + in-window host-sync count."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    eng.submit([1, 2, 3], 4)
    eng.drain()                                   # full warmup
    with eng.tracecheck.frozen():
        res = eng.request_profile(3)
        eng.submit([1, 2, 3], 8)
        eng.step()                                # window STARTS here
        with pytest.raises(RuntimeError, match="already in progress"):
            eng.request_profile(2)                # started: not replaceable
        eng.drain()
    assert eng.last_profile is not None
    assert eng.last_profile["dir"] == res["dir"]
    assert eng.last_profile["steps"] == 3
    prof_spans = [s for s in eng.tracer.spans()
                  if s.name == "profile_window"]
    assert len(prof_spans) == 1
    assert "host_syncs" in prof_spans[0].args
    assert eng.stats()["profile"]["active"] is False
    with pytest.raises(ValueError, match=">= 1"):
        eng.request_profile(0)


def test_request_profile_bad_dir_rejected_at_arm_time(served_model):
    """A broken user-supplied dir must fail the ARMING call (a clean
    400 on the HTTP thread), never surface inside start_trace on the
    stepping thread — that would kill the whole serving loop."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    with pytest.raises(ValueError, match="unusable profile dir"):
        eng.request_profile(2, out_dir="/dev/null/nope")
    assert eng.stats()["profile"]["active"] is False  # nothing armed
    eng.submit([1, 2], 3)
    eng.drain()                                       # loop survives


def test_profile_window_closes_when_engine_runs_dry(served_model):
    """A window armed for more steps than the remaining traffic closes
    on the drain's last step instead of staying open (trace buffering,
    /profile 409s) until traffic returns."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    eng.submit([1, 2], 3)
    eng.drain()                                       # warmup
    eng.request_profile(500)
    eng.submit([1, 2], 4)
    eng.drain()
    assert eng.last_profile is not None
    assert 0 < eng.last_profile["steps_profiled"] < 500
    assert eng.stats()["profile"]["active"] is False
    eng.request_profile(2)                            # no 409: re-armable


def test_profile_rearm_and_cancel_while_idle(served_model):
    """A window armed during a traffic lull must not wedge /profile:
    re-arming replaces the un-started window instead of 409ing, and
    cancel_profile disarms it (a STARTED window still 409s — it
    belongs to the stepping thread)."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    first = eng.request_profile(100)       # idle engine: never starts
    assert eng.stats()["profile"]["active"] is True
    second = eng.request_profile(3)        # replaces, no 409
    assert second["dir"] != first["dir"]
    assert not os.path.exists(first["dir"])   # replaced dir reaped
    assert eng.cancel_profile() is True
    assert not os.path.exists(second["dir"])  # cancelled dir reaped
    assert eng.stats()["profile"]["active"] is False
    assert eng.cancel_profile() is False   # nothing armed


def test_spec_acceptance_gauge_clears_on_reset():
    """reset_latency_stats zeros the drafted/accepted ledger after
    warmup; the mirrored gauge must follow to 0.0 rather than freeze
    on the degenerate warmup acceptance rate."""
    from nanosandbox_tpu.serve.spec import SpecRunner

    class _Ledger:
        drafted, accepted, steps = 8, 6, 2

    reg = MetricRegistry()
    ledger = _Ledger()
    SpecRunner.register_metrics(ledger, reg)

    def rate():
        snap = reg.snapshot()
        series = snap["serve_spec_acceptance_rate"]["series"]
        return series[0]["value"] if series else None

    assert rate() == 0.75
    ledger.drafted = ledger.accepted = 0   # the post-warmup reset
    assert rate() == 0.0


def test_engine_refuses_shared_registry(served_model):
    """Two engines on one registry would hand both the same unlabeled
    families and let their collectors overwrite each other's mirrored
    counters silently — construction fails loudly instead."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    with pytest.raises(ValueError, match="own MetricRegistry"):
        Engine(model, params, num_slots=2, max_len=64,
               metrics=eng.metrics)


def test_global_registry_carries_tracecheck_ledgers(served_model):
    """host_sync() and accepted traces mirror into the process-global
    registry as labeled counter families — the scrape view of the
    ledgers tracecheck keeps."""
    mark = global_registry().snapshot()

    def total(snap, fam, key):
        return sum(s["value"] for s in snap.get(fam, {"series": []})
                   ["series"] if s["labels"]["name"] == key)

    tracecheck.host_sync("obs-test-sync", 1.5)
    tracecheck.host_sync("obs-test-sync")
    reg = tracecheck.TraceBudgetRegistry()
    guarded = reg.guard("obs-test-prog", 2)(lambda x: x)
    guarded("shape-a")
    guarded("shape-b")
    snap = global_registry().snapshot()
    assert total(snap, "host_syncs_total", "obs-test-sync") \
        == total(mark, "host_syncs_total", "obs-test-sync") + 2
    assert total(snap, "compile_traces_total", "obs-test-prog") == 2


# ----------------------------------------------------------------- http

def test_http_metrics_trace_profile_roundtrip(served_model):
    """GET /metrics parses as exposition and covers the acceptance
    families; GET /trace?rid=N is Perfetto-shaped JSON for a completed
    request (404 for unknown rids, 400 for junk); POST /profile arms a
    window the serve loop completes."""
    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64)
    loop = EngineLoop(eng)
    loop.start()
    encode = lambda s: [min(ord(c), cfg.vocab_size - 1) for c in s]  # noqa: E731
    decode = lambda ids: " ".join(str(i) for i in ids)  # noqa: E731
    srv = make_server("127.0.0.1", 0, loop, encode, decode)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=60) as r:
            return r.read(), r.headers.get("Content-Type")

    def post(path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    try:
        gen = post("/generate", {"prompt": "hi", "max_new_tokens": 6,
                                 "temperature": 0.0})
        rid = gen["id"]

        body, ctype = get("/metrics")
        assert ctype.startswith("text/plain")
        types = parse_exposition(body.decode())
        for fam in ("serve_decode_tokens_per_sec", "serve_ttft_seconds",
                    "serve_tpot_seconds", "serve_queue_depth",
                    "serve_compile_traces_total", "host_syncs_total",
                    "serve_loop_inbox_depth"):
            assert fam in types, (fam, sorted(types))

        body, _ = get(f"/trace?rid={rid}")
        trace = json.loads(body)
        names = {ev["name"] for ev in trace["traceEvents"]
                 if ev["ph"] == "X"}
        assert {"queued", "generate"} <= names
        assert all(ev["args"]["rid"] == rid for ev in trace["traceEvents"]
                   if ev["ph"] == "X" and ev["cat"] == "request")

        window = json.loads(get("/trace?last_s=600")[0])
        assert any(ev["name"] == "decode_step"
                   for ev in window["traceEvents"])

        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/trace?rid=99999")
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/trace?rid=junk")
        assert ei.value.code == 400

        # non-dict JSON body -> clean 400, not a dropped connection
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/profile", [1, 2])
        assert ei.value.code == 400

        prof = post("/profile", {"steps": 2})
        assert prof["ok"] and prof["steps"] == 2
        post("/generate", {"prompt": "go", "max_new_tokens": 4,
                           "temperature": 0.0})
        deadline = time.monotonic() + 30
        while (json.loads(get("/stats")[0])["profile"]["last"] is None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        last = json.loads(get("/stats")[0])["profile"]["last"]
        assert last is not None and last["steps"] == 2
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


# ------------------------------------------- label hygiene (ISSUE 10)

def test_exposition_label_hygiene_features_off(served_model):
    """A family registered for a feature that is OFF (or simply never
    exercised) must emit NOTHING — no empty/placeholder series. Pinned
    with the same stdlib parser a scrape implies: spec off => no
    serve_spec_* histograms; prefix cache off => no
    serve_prefix_ttft_seconds{prefix=}; no deadlines => no serve_slo_*
    series. Reading stats() must not mint the children either."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64,
                 prefix_cache=False)
    eng.submit([1, 2, 3], 4)
    eng.drain()
    eng.stats()                       # reads must not create series
    text = eng.metrics.prometheus_text()
    types = parse_exposition(text)
    for absent in ("serve_spec_accept_len", "serve_spec_req_accepted_tokens",
                   "serve_prefix_ttft_seconds", "serve_slo_requests_total",
                   "serve_goodput_tokens_total", "serve_slo_attainment",
                   "serve_deadline_margin_seconds",
                   "serve_requests_rejected_total", "watchdog_trips_total"):
        assert absent not in types, absent
        assert absent not in text, absent
    # the always-on families still render
    assert "serve_ttft_seconds" in types
    assert "serve_requests_shed_total" in types


def test_exposition_label_hygiene_features_on(served_model):
    """The same families DO render once the features record: a prefix
    cache observing TTFTs, a deadline-carrying request, a reject."""
    _, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    eng.submit([1, 2, 3], 4, deadline_s=30.0, slo_class="interactive")
    eng.drain()
    with pytest.raises(ValueError):
        eng.submit([], 4)
    types = parse_exposition(eng.metrics.prometheus_text())
    for present in ("serve_prefix_ttft_seconds", "serve_slo_requests_total",
                    "serve_goodput_tokens_total", "serve_slo_attainment",
                    "serve_deadline_margin_seconds",
                    "serve_requests_rejected_total"):
        assert present in types, present
    text = eng.metrics.prometheus_text()
    assert 'serve_prefix_ttft_seconds_bucket{prefix="miss"' in text
    assert 'serve_prefix_ttft_seconds_bucket{prefix="hit"' not in text
    assert 'serve_requests_rejected_total{reason="empty_prompt"} 1' in text


def test_family_reads_do_not_create_series():
    from nanosandbox_tpu.obs import MetricRegistry as _MR

    reg = _MR()
    h = reg.histogram("h_seconds", "H.", labelnames=("k",))
    g = reg.gauge("g_val", "G.")
    c = reg.counter("c_total", "C.")
    assert h.peek(k="x") is None
    assert g.value is None and c.value is None
    assert reg.prometheus_text() == ""
    h.labels(k="x").observe(0.1)
    assert h.peek(k="x").count == 1
    assert "h_seconds" in reg.prometheus_text()


# ------------------------------------------------------ process vitals

def test_process_vitals_families(served_model):
    from nanosandbox_tpu.obs import MetricRegistry as _MR
    from nanosandbox_tpu.obs import register_process_vitals

    reg = _MR()
    assert register_process_vitals(reg) is reg
    register_process_vitals(reg)      # idempotent: no duplicate collector
    snap = reg.snapshot()
    assert snap["process_resident_memory_bytes"]["series"][0]["value"] > 0
    assert snap["process_uptime_seconds"]["series"][0]["value"] >= 0
    assert snap["process_open_fds"]["series"][0]["value"] > 0
    # jax is imported in this process, so live-buffer gauges are real
    assert snap["jax_live_buffer_count"]["series"][0]["value"] > 0
    assert snap["jax_live_buffer_bytes"]["series"][0]["value"] > 0
    types = parse_exposition(reg.prometheus_text())
    assert types["process_resident_memory_bytes"] == "gauge"


# ------------------------------- /trace on the paged engine (ISSUE 10)

def test_trace_prefix_hit_shows_smaller_prefill_wave(served_model):
    """A prefix-hit request's admission wave prefills only the SUFFIX
    bucket: its prefill_wave span must carry a strictly smaller bucket
    than its cold twin's, and its queued->generate rid track stays
    intact — the /trace evidence that the hit skipped prefill work."""
    _, model, params = served_model
    import numpy as np

    base = np.random.default_rng(3).integers(0, 50, 40).tolist()
    eng = Engine(model, params, num_slots=2, max_len=64)
    cold = eng.submit(base, 4)
    eng.drain()
    hot = eng.submit(base[:35] + [7, 8, 9], 4)
    eng.drain()

    def wave_for(rid):
        waves = [s for s in eng.tracer.spans()
                 if s.name == "prefill_wave" and rid in s.args["rids"]]
        assert len(waves) == 1, (rid, waves)
        return waves[0]

    cold_wave, hot_wave = wave_for(cold), wave_for(hot)
    assert cold_wave.args["bucket"] == 64          # full-prompt bucket
    assert hot_wave.args["bucket"] < cold_wave.args["bucket"]
    # the flight ledger tells the same story
    pre = [e for e in eng.flight.events(rid=hot) if e["ev"] == "prefill"]
    assert pre[0]["prefix"] == "hit" and pre[0]["hit_tokens"] == 32
    # rid tracks intact in the chrome export
    for rid in (cold, hot):
        names = {ev["name"]
                 for ev in eng.tracer.export_chrome(rid=rid)["traceEvents"]
                 if ev["ph"] == "X" and ev["args"].get("rid") == rid}
        assert {"queued", "generate"} <= names


def test_pipelined_overlap_pin_holds_on_paged_engine(served_model):
    """The PR 2 pipelined-overlap span pin, explicitly on paged=True
    (and the sync engine's non-overlap twin): the block table rides the
    same decode program, so pipelining must survive paging."""
    _, model, params = served_model
    for pipeline, want_overlap in ((True, True), (False, False)):
        eng = Engine(model, params, num_slots=2, max_len=64,
                     pipeline=pipeline, paged=True)
        eng.submit([1, 2, 3], 12)
        eng.drain()
        steps = sorted((s for s in eng.tracer.spans()
                        if s.name == "decode_step"),
                       key=lambda s: s.args["step"])
        assert len(steps) >= 4
        overlaps = [a.t1 > b.t0 for a, b in zip(steps, steps[1:])]
        assert all(overlaps) if want_overlap else not any(overlaps)


# ----------------------------------------------- /debug HTTP endpoints

def test_http_debug_endpoints_roundtrip(served_model):
    """GET /debug/requests (JSON + JSONL + 404/400), /debug/slots,
    /debug/kvpool, /debug/scheduler on the real frontend."""
    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64)
    loop = EngineLoop(eng)
    loop.start()
    encode = lambda s: [min(ord(c), cfg.vocab_size - 1) for c in s]  # noqa: E731
    decode = lambda ids: " ".join(str(i) for i in ids)  # noqa: E731
    srv = make_server("127.0.0.1", 0, loop, encode, decode)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=60) as r:
            return r.read(), r.headers.get("Content-Type")

    def post(path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    try:
        gen = post("/generate", {"prompt": "hi", "max_new_tokens": 6,
                                 "temperature": 0.0, "deadline_s": 60.0,
                                 "slo_class": "interactive"})
        rid = gen["id"]
        assert gen["finish_reason"] == "length"

        body, _ = get(f"/debug/requests?rid={rid}")
        evs = json.loads(body)["events"]
        assert [e["ev"] for e in evs][:2] == ["submit", "queue"]
        assert evs[0]["slo_class"] == "interactive"
        # The HTTP layer appends the returned status after the terminal
        # (ISSUE 11 status hygiene): ... -> finish -> http{status=200}.
        assert [e["ev"] for e in evs][-2:] == ["finish", "http"]
        assert evs[-1]["status"] == 200

        body, ctype = get(f"/debug/requests?rid={rid}&format=jsonl")
        assert ctype == "application/x-ndjson"
        lines = [json.loads(ln) for ln in body.decode().splitlines()]
        assert all({"t", "ev", "rid", "wall"} <= set(e) for e in lines)

        body, _ = get("/debug/requests?last_s=600")
        assert json.loads(body)["events"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/debug/requests?rid=99999")
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/debug/requests?rid=junk")
        assert ei.value.code == 400

        slots = json.loads(get("/debug/slots")[0])
        assert slots["num_slots"] == 4
        assert {s["state"] for s in slots["slots"]} <= {"free", "active"}

        pool = json.loads(get("/debug/kvpool")[0])
        assert pool["paged"] is True and "fragmentation" in pool

        sched = json.loads(get("/debug/scheduler")[0])
        assert "queue" in sched and sched["free_slots"] == 4

        # the SLO series from the deadline-carrying request are on the
        # scrape, with real label values
        text = get("/metrics")[0].decode()
        assert 'serve_slo_requests_total{slo_class="interactive"' in text
        assert "serve_goodput_tokens_total" in text
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()
