"""Parallelism tests on 8 virtual CPU devices (SURVEY.md §4 Tier 1):
mesh construction, DP batch sharding, FSDP param sharding, TP rules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nanosandbox_tpu.parallel.distributed import derive_process_id_from_hostname
from nanosandbox_tpu.parallel.mesh import batch_sharding, make_mesh
from nanosandbox_tpu.parallel.sharding import spec_for_param
from nanosandbox_tpu.train import Trainer


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.devices.shape == (8, 1, 1, 1)
    m = make_mesh(mesh_fsdp=4)
    assert m.devices.shape == (2, 4, 1, 1)
    m = make_mesh(mesh_dp=2, mesh_fsdp=2, mesh_tp=2)
    assert m.devices.shape == (2, 2, 1, 2)
    m = make_mesh(mesh_sp=4)
    assert m.devices.shape == (2, 1, 4, 1)
    with pytest.raises(ValueError):
        make_mesh(mesh_dp=3)


def test_batch_is_sharded_over_data():
    mesh = make_mesh()
    sh = batch_sharding(mesh)
    x = jax.device_put(np.zeros((16, 4)), sh)
    # Each device holds 16/8 = 2 rows.
    assert x.addressable_shards[0].data.shape == (2, 4)


def test_spec_rules():
    sizes = {"data": 2, "fsdp": 2, "seq": 1, "model": 2}
    s = spec_for_param("h_0/attn/c_attn/kernel", (64, 192),
                       axis_sizes=sizes, shard_params=True, tp=True)
    assert s == P("fsdp", "model")
    s = spec_for_param("h_0/attn/c_proj/kernel", (64, 64),
                       axis_sizes=sizes, shard_params=True, tp=True)
    assert s == P("model", "fsdp")
    # Embedding tables only ever shard their ROW dim: a feature-sharded
    # table makes every lookup a C-sharded gather that SPMD can only
    # un-shard via involuntary full rematerialization (sharding.py).
    s = spec_for_param("wte/embedding", (65, 64),
                       axis_sizes=sizes, shard_params=True, tp=True)
    assert s == P()  # 65 not divisible by 2 -> replicate, NEVER P(None, 'fsdp')
    s = spec_for_param("wte/embedding", (64, 32),
                       axis_sizes=sizes, shard_params=True, tp=True)
    assert s == P("fsdp", None)  # divisible row dim -> row-sharded
    s = spec_for_param("wpe/embedding", (30, 64),
                       axis_sizes=sizes, shard_params=True, tp=True)
    assert s == P("fsdp", None)
    s = spec_for_param("ln_f/scale", (64,),
                       axis_sizes=sizes, shard_params=False, tp=True)
    assert s == P()


def test_spec_rejects_unregistered_mesh_axes():
    # The runtime twin of jaxlint's axis-mismatch rule: a mesh speaking
    # a different axis vocabulary must fail loudly, not silently
    # replicate what the caller thought was sharded.
    with pytest.raises(ValueError, match="registered"):
        spec_for_param("h_0/attn/c_attn/kernel", (64, 192),
                       axis_sizes={"data": 2, "sequence": 4},
                       shard_params=True, tp=True)


@pytest.mark.parametrize("mesh_args,shard_params,tp", [
    ((8, 1, 1, 1), False, False),   # dp: everything replicated
    ((1, 8, 1, 1), True, False),    # fsdp: ZeRO-3 sharding
    ((1, 1, 1, 8), False, False),   # sp: params replicated over seq
    ((1, 1, 8, 1), False, True),    # tp: Megatron kernel placement
])
def test_param_shardings_rule_table(mesh_args, shard_params, tp):
    """ISSUE 7 satellite: every param pytree leaf gets an explicit spec
    under each of the dp/fsdp/sp/tp meshes, and specs only name
    registered mesh axes — the invariant jaxlint's axis-mismatch rule
    enforces statically (pinned against parallel.mesh.AXES in
    test_shardcheck.py)."""
    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.parallel.mesh import REGISTERED_AXES
    from nanosandbox_tpu.parallel.sharding import param_shardings

    mesh = make_mesh(*mesh_args)
    cfg = GPTConfig(n_layer=2, n_head=4, n_embd=64, block_size=64,
                    vocab_size=256, dropout=0.0)
    model = GPT(cfg)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.int32)))["params"]
    shardings = param_shardings(mesh, abstract,
                                shard_params=shard_params, tp=tp)
    leaves = jax.tree_util.tree_leaves_with_path(shardings)
    assert len(leaves) == len(jax.tree.leaves(abstract)) > 10

    def axes_of(spec):
        return {a for entry in spec if entry
                for a in ((entry,) if isinstance(entry, str) else entry)}

    for path, sharding in leaves:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        assert isinstance(sharding, jax.sharding.NamedSharding), name
        used = axes_of(sharding.spec)
        # Only registered axis names, and only axes of THIS mesh with
        # size > 1 (a spec naming a trivial axis is a latent surprise).
        assert used <= REGISTERED_AXES, (name, sharding.spec)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        assert all(sizes[a] > 1 for a in used), (name, sharding.spec)

    flat = {"/".join(str(getattr(p, "key", p)) for p in path): s
            for path, s in leaves}
    if shard_params:       # fsdp=8: divisible kernels actually shard
        assert any("fsdp" in axes_of(s.spec) for s in flat.values())
        # embeddings shard their ROW dim only (vocab 256 % 8 == 0)
        wte = next(s for n, s in flat.items() if n.endswith("wte/embedding"))
        assert wte.spec == P("fsdp", None)
    elif tp:               # model=8: Megatron column/row placement
        cattn = next(s for n, s in flat.items()
                     if n.endswith("c_attn/kernel"))
        cproj = next(s for n, s in flat.items()
                     if "attn" in n and n.endswith("c_proj/kernel"))
        assert cattn.spec == P(None, "model")
        assert cproj.spec == P("model", None)
        wte = next(s for n, s in flat.items() if n.endswith("wte/embedding"))
        assert wte.spec == P()      # weight-tied head stays replicated
    else:                  # dp / sp: pure replication
        assert all(s.spec == P() for s in flat.values())


@pytest.mark.parametrize("mesh_kw", [
    dict(),                                   # pure DP over 8
    dict(mesh_dp=2, mesh_fsdp=4, shard_params=True),   # DP x FSDP
    dict(mesh_dp=2, mesh_fsdp=2, mesh_tp=2, shard_params=True),  # 3-axis
])
def test_train_step_parallel(tiny_cfg, mesh_kw):
    cfg = tiny_cfg.replace(batch_size=16, n_embd=64, **mesh_kw)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    losses = []
    rng = jax.random.key(0)
    for _ in range(8):
        xb, yb = next(loader)
        state, m = train_step(state, trainer.to_global(xb),
                              trainer.to_global(yb), rng)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_fsdp_actually_shards_params(tiny_cfg):
    cfg = tiny_cfg.replace(batch_size=16, mesh_dp=1, mesh_fsdp=8,
                           shard_params=True)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    kernel = state["params"]["h_0"]["attn"]["c_attn"]["kernel"]
    shard_shape = kernel.addressable_shards[0].data.shape
    assert shard_shape[0] == kernel.shape[0] // 8 or \
        shard_shape[1] == kernel.shape[1] // 8


def test_dp_matches_single_device_loss(tiny_cfg):
    """Same global batch -> same first-step loss, sharded or not."""
    cfg1 = tiny_cfg.replace(batch_size=16, compile=True)
    t1 = Trainer(cfg1)
    s1 = t1.init_state()
    step1, _ = t1.compiled_steps()
    xb, yb = t1.dataset.sample_batch("train", 0, 16, cfg1.block_size,
                                     seed=cfg1.seed)
    _, m1 = step1(s1, t1.to_global(xb), t1.to_global(yb), jax.random.key(0))

    mesh1 = make_mesh(mesh_dp=1, mesh_fsdp=1, mesh_tp=1,
                      devices=jax.devices()[:1])
    t2 = Trainer(cfg1)
    t2.mesh = mesh1
    # The model binds the mesh at construction (ring attention + the
    # activation-sharding anchors), so swapping the trainer's mesh must
    # rebuild the model too or the anchors would target retired devices.
    from nanosandbox_tpu.models.gpt import GPT
    t2.model = GPT(t2.model_cfg, mesh=mesh1)
    from nanosandbox_tpu.parallel.mesh import batch_sharding as bs
    t2.batch_sharding = bs(mesh1)
    # re-derive shardings for the single-device mesh
    from nanosandbox_tpu.parallel.sharding import param_shardings
    abstract = jax.eval_shape(t2._init_state, jax.random.key(cfg1.seed))
    t2.state_shardings = {
        "params": param_shardings(mesh1, abstract["params"]),
        "opt_state": param_shardings(mesh1, abstract["opt_state"]),
        "step": jax.sharding.NamedSharding(mesh1, P()),
    }
    s2 = t2.init_state()
    step2, _ = t2.compiled_steps()
    _, m2 = step2(s2, t2.to_global(xb), t2.to_global(yb), jax.random.key(0))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)


def _first_steps(cfg, n_steps=3):
    """Run n_steps on a FIXED batch sequence; return per-step
    (loss, grad_norm) floats. Deterministic across mesh layouts: the data
    comes from dataset.sample_batch with pinned seeds, not the loader."""
    trainer = Trainer(cfg)
    state = trainer.init_state()
    step, _ = trainer.compiled_steps()
    out = []
    rng = jax.random.key(0)
    for i in range(n_steps):
        xb, yb = trainer.dataset.sample_batch("train", i, cfg.batch_size,
                                              cfg.block_size, seed=cfg.seed)
        state, m = step(state, trainer.to_global(xb), trainer.to_global(yb),
                        rng)
        out.append((float(m["loss"]), float(m["grad_norm"])))
    return out


@pytest.mark.parametrize("mesh_kw", [
    dict(mesh_dp=2, mesh_fsdp=4, shard_params=True),          # DP x FSDP
    dict(mesh_dp=4, mesh_tp=2, shard_params=False),           # DP x TP
    dict(mesh_dp=2, mesh_fsdp=2, mesh_tp=2, shard_params=True),  # 3-axis
])
def test_sharded_matches_pure_dp_first_steps(tiny_cfg, mesh_kw):
    """TP/FSDP parity at the ring tests' standard (round-2 VERDICT weak
    #3): per-step loss AND grad-norm on identical data must match pure DP
    to rel 1e-4 over several optimizer steps.

    Scope note (measured, round 3): pure GSPMD sharding ANNOTATIONS are
    semantics-preserving — deliberately swapping the Megatron row/col
    placement moves collectives but changes the result only at reduction-
    order noise (~1e-7), so no numeric test can catch a 'wrong' annotation;
    that class of bug is a performance bug. What this parity DOES pin is
    every layer where sharding changes math: the batch row->process/device
    layout in to_global, shard_map bodies (ring attention has exact-parity
    tests), and the optimizer's sharded state update. The cross-process
    variant lives in test_distributed.py::test_two_process_nontrivial_mesh."""
    cfg_dp = tiny_cfg.replace(batch_size=16, n_embd=64)
    cfg_sh = cfg_dp.replace(**mesh_kw)
    ref = _first_steps(cfg_dp)
    got = _first_steps(cfg_sh)
    for (l0, g0), (l1, g1) in zip(ref, got):
        assert l1 == pytest.approx(l0, rel=1e-4), (ref, got)
        assert g1 == pytest.approx(g0, rel=1e-4), (ref, got)


def test_derive_process_id():
    assert derive_process_id_from_hostname("train-multipod-2") == 2
    assert derive_process_id_from_hostname("train-multipod-0") == 0
    assert derive_process_id_from_hostname("notastatefulset") is None


def test_chunked_loss_under_sequence_parallelism(tiny_cfg):
    """round-3: the chunked head+loss runs per-shard inside shard_map
    under sp>1 (full logits at long context would defeat ring attention's
    memory story). Same math as the full-logits path on the same batch."""
    full = Trainer(tiny_cfg.replace(batch_size=8, mesh_dp=2, mesh_sp=4,
                                    attention_impl="ring",
                                    loss_chunk_size=0))
    chunked = Trainer(tiny_cfg.replace(batch_size=8, mesh_dp=2, mesh_sp=4,
                                       attention_impl="ring",
                                       loss_chunk_size=4))
    s1, s2 = full.init_state(), chunked.init_state()
    step1, _ = full.compiled_steps()
    step2, _ = chunked.compiled_steps()
    xb, yb = full.dataset.sample_batch("train", 0, 8, tiny_cfg.block_size,
                                       seed=tiny_cfg.seed)
    _, m1 = step1(s1, full.to_global(xb), full.to_global(yb),
                  jax.random.key(0))
    _, m2 = step2(s2, chunked.to_global(xb), chunked.to_global(yb),
                  jax.random.key(0))
    assert float(m2["loss"]) == pytest.approx(float(m1["loss"]), rel=1e-5)
    assert float(m2["grad_norm"]) == pytest.approx(float(m1["grad_norm"]),
                                                   rel=1e-4)


# -- hybrid ICI x DCN mesh (round-5 VERDICT missing #4) --------------------


def test_hybrid_mesh_slice_major_layout():
    """2 'slices' x 4 devices, fsdp=2: dp axis spans slices slice-major —
    each slice contributes its own contiguous dp rows, and every fsdp
    block stays within one slice."""
    from nanosandbox_tpu.parallel.mesh import make_hybrid_mesh

    devs = jax.devices()
    m = make_hybrid_mesh(mesh_fsdp=2, num_slices=2)
    assert m.devices.shape == (4, 2, 1, 1)
    # Slice 0 = devices 0..3 -> dp rows 0-1; slice 1 = devices 4..7.
    flat = m.devices.reshape(4, 2)
    for dp_row in range(4):
        slice_of = 0 if dp_row < 2 else 1
        for d in flat[dp_row]:
            assert devs.index(d) // 4 == slice_of, (
                f"dp row {dp_row} leaked across the slice boundary")


def test_hybrid_mesh_rejects_ici_axes_crossing_slices():
    """fsdp=8 over 2 slices of 4 devices: the fsdp collectives would have
    to cross DCN — must be rejected at construction, with the placement
    rule in the message."""
    from nanosandbox_tpu.parallel.mesh import make_hybrid_mesh

    with pytest.raises(ValueError, match="ICI"):
        make_hybrid_mesh(mesh_fsdp=8, num_slices=2)
    with pytest.raises(ValueError, match="cannot split"):
        make_hybrid_mesh(num_slices=3)


def test_hybrid_mesh_trainer_end_to_end(tiny_cfg):
    """A Trainer on a 2-slice hybrid mesh (dp across slices, fsdp inside)
    runs a real step, and the loss matches the flat-mesh run on the same
    batch — the hybrid layout is a placement change, not a math change.

    Runs in a FRESH subprocess: two back-to-back collective-heavy
    Trainer steps in-process would raise the odds of XLA:CPU's 40s
    collective-rendezvous watchdog aborting a long pytest session (the
    recorded flake mode; see test_train_smoke.test_rng_impl_rbg_trains
    for the same pattern)."""
    import subprocess
    import sys

    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from nanosandbox_tpu.train import Trainer
from nanosandbox_tpu.config import TrainConfig
cfg = TrainConfig(**{tiny_cfg.replace(batch_size=8, mesh_fsdp=2,
                                      mesh_slices=2,
                                      shard_params=True).to_dict()!r})
trainer = Trainer(cfg)
assert dict(trainer.mesh.shape) == dict(data=4, fsdp=2, seq=1, model=1), \\
    trainer.mesh.shape
state = trainer.init_state()
step, _ = trainer.compiled_steps()
xg, yg = trainer.dataset.sample_batch(
    "train", 0, cfg.batch_size, cfg.block_size, seed=cfg.seed)
_, m = step(state, trainer.to_global(xg), trainer.to_global(yg),
            jax.random.key(0))
loss = float(m["loss"])

flat = Trainer(cfg.replace(mesh_slices=0))
fstate = flat.init_state()
fstep, _ = flat.compiled_steps()
_, fm = fstep(fstate, flat.to_global(xg), flat.to_global(yg),
              jax.random.key(0))
flat_loss = float(fm["loss"])
assert abs(loss - flat_loss) <= 1e-5 * abs(flat_loss), (loss, flat_loss)
print(f"HYBRID_OK {{loss:.8f}} {{flat_loss:.8f}}")
"""
    import os

    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          cwd=repo_root, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "HYBRID_OK" in proc.stdout
