"""Prefix-affinity fleet router tests (ISSUE 15).

The contract under test:
  * Digests: paged.prefix_digests chains per-block fingerprints of the
    FULL prompt blocks; the radix cache's digests() walk agrees with
    them, Result/flight/prefix_summary all report the same chain, and
    the router matches by contiguous membership.
  * Routing: shared-system-prompt requests land on the warm replica
    (measured hit-rate strictly above the seeded-random twin on the
    identical workload); a drained or quarantined replica leaves
    rotation within one health interval (= one fleet step in-process);
    greedy outputs are token-identical whichever replica serves,
    including across a mid-flight replica kill and failover restitch.
  * Identity: flight rids are replica-namespaced; the merged fleet
    JSONL has exactly ONE terminal per rid across a router failover
    (fuzzed over kill steps).
  * Backoff: fleet retry_after_s is the min over READY replicas of the
    per-replica queue-mass-weighted estimate; retry_info names the
    ready replica-set size (the 429 body contract).
  * Cost: the router adds zero compiled programs and zero audited host
    syncs — per-replica compile sets are byte-identical to a solo
    engine's.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.obs import TERMINAL_EVENTS, render_prometheus
from nanosandbox_tpu.serve import (Engine, FaultPlan, Fleet,
                                   NoReadyReplicaError,
                                   PrefixAffinityRouter, prefix_digests)
from nanosandbox_tpu.serve.paged import RadixPrefixCache, _block_digest
from nanosandbox_tpu.serve.router import _PrefixIndex
from nanosandbox_tpu.utils import tracecheck as _tracecheck


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _fleet(served_model, n=2, **kw):
    cfg, model, params = served_model
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 64)
    return Fleet(model, params, n_replicas=n, **kw)


def _grouped_requests(vocab, n_groups=2, per_group=5, prefix=35,
                      budget=3, seed=0):
    """Shared-system-prompt mix: n_groups prefixes, each with
    per_group short-suffix followers, interleaved round-robin."""
    rng = np.random.default_rng(seed)
    groups = [rng.integers(0, vocab, prefix).tolist()
              for _ in range(n_groups)]
    out = []
    for i in range(n_groups * per_group):
        g = groups[i % n_groups]
        sfx = rng.integers(0, vocab,
                           int(rng.integers(1, 6))).tolist()
        out.append((g + sfx, budget))
    return out


def _reference(served_model, requests):
    """Solo-engine oracle: greedy tokens per prompt (batch- and
    prefix-hit-independent, both pinned elsewhere)."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    want = {}
    for prompt, budget in requests:
        if tuple(prompt) in want:
            continue
        eng.submit(prompt, budget)
        want[tuple(prompt)] = eng.drain()[-1].tokens
    return want


# ------------------------------------------------------------- digests

def test_prefix_digests_chain_properties():
    toks = list(range(50))
    d = prefix_digests(toks, 16)
    assert len(d) == 3                       # only FULL blocks
    assert prefix_digests(toks, 16) == d     # deterministic
    assert prefix_digests(toks[:48], 16) == d  # trailing partial ignored
    assert prefix_digests(toks[:32], 16) == d[:2]  # chain is a prefix
    # changing an EARLY token changes every later digest (chained)
    d2 = prefix_digests([99] + toks[1:], 16)
    assert all(a != b for a, b in zip(d, d2))
    # hex strings, JSON-safe
    assert all(isinstance(x, str) and len(x) == 16 for x in d)
    assert prefix_digests(toks[:15], 16) == []


def test_cache_digests_agree_with_prompt_digests():
    cache = RadixPrefixCache(4)
    prompt = tuple(range(12))
    cache.insert_chain(prompt, [0, 1, 2], 0)
    assert sorted(cache.digests()) == sorted(prefix_digests(prompt, 4))
    # shared-prefix second chain adds only the divergent tail digest
    p2 = prompt[:8] + (90, 91, 92, 93)
    cache.insert_chain(p2, [0, 1, 3], 0)
    want = set(prefix_digests(prompt, 4)) | set(prefix_digests(p2, 4))
    assert set(cache.digests()) == want
    # _block_digest is the shared primitive (drift guard)
    assert prefix_digests(prompt, 4)[0] == _block_digest(
        b"", prompt[:4]).hex()


def test_engine_reports_prefix_digest(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    prompt = list(range(40))
    eng.submit(prompt, 3)
    res = eng.drain()[0]
    want = tuple(prefix_digests(prompt, eng.kv_page_size))
    assert res.prefix_digest == want
    summ = eng.prefix_summary()
    assert summ["enabled"] and summ["page"] == eng.kv_page_size
    assert set(want) <= set(summ["digests"])
    fin = [e for e in eng.flight.events() if e["ev"] == "finish"]
    assert fin[0]["prefix_digest"] == list(want)
    # dense / cache-less engines report nothing (no placeholder noise)
    dense = Engine(model, params, num_slots=2, max_len=64, paged=False)
    dense.submit(prompt, 2)
    assert dense.drain()[0].prefix_digest == ()
    assert dense.prefix_summary() == {"enabled": False, "page": 0,
                                      "blocks": 0, "digests": []}


# -------------------------------------------------------------- router

def test_router_index_membership_and_lru():
    ix = _PrefixIndex(cap=3)
    ix.add_chain(["a", "b", "c"])
    assert ix.match_blocks(["a", "b", "c"]) == 3
    assert ix.match_blocks(["a", "b", "x"]) == 2
    assert ix.match_blocks(["x", "b", "c"]) == 0   # contiguity
    ix.add_chain(["d"])                            # cap 3: evicts LRU
    assert len(ix) == 3
    ix.replace(["z"])                              # authoritative
    assert ix.match_blocks(["a"]) == 0 and ix.match_blocks(["z"]) == 1


def test_router_reasons_and_scoring():
    r = PrefixAffinityRouter(["r0", "r1"], page=16)
    r.update_replica("r0", ready=True)
    r.update_replica("r1", ready=True)
    chain = prefix_digests(list(range(32)), 16)
    dec = r.route(chain)
    assert dec.reason == "load" and dec.candidates == 2
    r.observe_digests("r0", chain)
    dec = r.route(chain)
    assert (dec.replica, dec.reason) == ("r0", "affinity")
    assert dec.est_hit_tokens == 32
    # load can outweigh a small hit
    r.update_replica("r0", ready=True, queued=100, active=2)
    assert r.route(chain).replica == "r1"
    # exclusion / failover tag
    r.update_replica("r0", ready=True)
    assert r.route(chain, exclude=("r0",)).reason == "fallback"
    assert r.route(chain, failover=True).reason == "fallback"
    # warm replica out of rotation -> redirected traffic is 'fallback'
    r.update_replica("r0", ready=False, reason="draining")
    dec = r.route(chain)
    assert (dec.replica, dec.reason) == ("r1", "fallback")
    r.update_replica("r1", ready=False, reason="draining")
    with pytest.raises(NoReadyReplicaError):
        r.route(chain)


def test_router_summary_refresh_evicts_stale():
    r = PrefixAffinityRouter(["r0"], page=16)
    r.update_replica("r0", ready=True)
    chain = prefix_digests(list(range(48)), 16)
    r.observe_digests("r0", chain)
    assert r.match_tokens("r0", chain) == 48
    # replica evicted the tail block since the last report
    r.refresh_summary("r0", chain[:1])
    assert r.match_tokens("r0", chain) == 16
    r.forget("r0")
    assert r.match_tokens("r0", chain) == 0


# --------------------------------------------------------------- fleet

def test_affinity_beats_random_hit_rate(served_model):
    cfg, _, _ = served_model
    # THREE groups over two replicas: coprime with the random twin's
    # rotation, so round-robin cannot accidentally reproduce affinity
    # (with 2 groups it aliases into it and both twins tie).
    reqs = _grouped_requests(cfg.vocab_size, n_groups=3, per_group=3)

    def hit_rate(affinity):
        fleet = _fleet(served_model, affinity=affinity)
        it = iter(reqs)
        pending = len(reqs)
        while pending or fleet.has_work():
            q = next(it, None)
            if q is not None:
                fleet.submit(q[0], q[1])
                pending -= 1
            fleet.step()
            fleet.step()
        st = fleet.stats()
        hits = sum(v["prefix_hit_tokens"]
                   for v in st["replicas"].values())
        miss = sum(v["prefix_miss_tokens"]
                   for v in st["replicas"].values())
        return hits / (hits + miss), st

    aff, aff_st = hit_rate(True)
    rand, _ = hit_rate(False)
    # Strictly above the random twin (the satellite-3 pin): affinity
    # keeps each group on one replica, random pays one cold prefill
    # per (group, replica) pair.
    assert aff > rand, (aff, rand)
    assert aff_st["router"]["decisions"]["affinity"] > 0


def test_fleet_greedy_parity_whichever_replica(served_model):
    cfg, _, _ = served_model
    # Random routing spreads the groups across BOTH replicas, so one
    # twin exercises "whichever replica serves"; the affinity twin's
    # parity rides in the failover test and the bench oracle.
    reqs = _grouped_requests(cfg.vocab_size, n_groups=3, per_group=3,
                             seed=5)
    want = _reference(served_model, reqs)
    fleet = _fleet(served_model, affinity=False)
    for prompt, budget in reqs:
        fleet.submit(prompt, budget)
    results = fleet.drain()
    assert len(results) == len(reqs)
    served = {r.rid.split(":")[0] for r in results}
    assert served == {"r0", "r1"}        # both replicas actually served
    for r in results:
        assert r.tokens == want[tuple(r.prompt)], r.rid
        assert r.finish_reason == "length"


def test_drain_and_quarantine_leave_rotation(served_model):
    fleet = _fleet(served_model)
    fleet.drain_replica("r0")
    assert fleet.router.ready_replicas() == ["r1"]
    rid = fleet.submit(list(range(20)), 2)
    assert rid.startswith("r1:")
    fleet.undrain_replica("r0")
    assert fleet.router.ready_replicas() == ["r0", "r1"]
    # quarantine leaves rotation within one health interval (= 1 step)
    fleet.replicas["r1"].quarantine("test")
    fleet.step()
    assert fleet.router.ready_replicas() == ["r0"]
    assert fleet.submit(list(range(20)), 2).startswith("r0:")
    fleet.drain()
    # all replicas out -> NoReadyReplicaError (503 upstream)
    fleet.drain_replica("r0")
    with pytest.raises(NoReadyReplicaError):
        fleet.submit([1, 2, 3], 2)


@pytest.mark.parametrize("kill_step", [
    2,
    pytest.param(5, marks=pytest.mark.slow),
    pytest.param(9, marks=pytest.mark.slow),
])
def test_replica_down_failover_exactly_once_and_parity(
        served_model, kill_step):
    """The satellite-1 fuzz pin, across kill timings: one replica
    hard-dies mid-traffic; every fleet request reaches exactly one
    fleet Result, the merged namespaced ledger carries exactly one
    terminal per rid, and greedy outputs are token-identical to an
    undisturbed run (failover restitch)."""
    cfg, _, _ = served_model
    reqs = _grouped_requests(cfg.vocab_size, per_group=4, budget=5,
                             seed=kill_step)
    want = _reference(served_model, reqs)
    fleet = _fleet(served_model,
                   faults=FaultPlan.parse(f"replica_down@{kill_step}"))
    rids = [fleet.submit(p, b) for p, b in reqs]
    results = fleet.drain()
    assert fleet.replica_downs == 1
    assert len(results) == len(reqs)
    assert sorted(r.rid for r in results) == sorted(rids)
    for r in results:
        assert r.finish_reason == "length", (r.rid, r.finish_reason)
        assert r.tokens == want[tuple(r.prompt)], r.rid
    terminals = {}
    for e in fleet.merged_flight_events():
        if e["ev"] in TERMINAL_EVENTS and e.get("rid") is not None:
            terminals[e["rid"]] = terminals.get(e["rid"], 0) + 1
    assert all(n == 1 for n in terminals.values()), terminals
    # victims really moved: at least one failover event with salvage
    if fleet.failovers:
        evs = [e for e in fleet.flight.events() if e["ev"] == "failover"]
        assert evs and all(e["dead"] != e["replica"] for e in evs)


def test_out_of_vocab_prompt_rejects_not_poisons(served_model):
    """The poison-pill vector closed at the boundary: an out-of-range
    token id would NaN-fill the embedding gather, trip the poison
    sentinel, and burn the recovery supervisor to PERMANENT failure —
    one malformed request killing the replica (and, pre-fence, the
    fleet via failover). It must be a plain reject (400 upstream)."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    with pytest.raises(ValueError, match="token_out_of_range|outside"):
        eng.submit([1, 2, cfg.vocab_size], 3)
    with pytest.raises(ValueError, match="outside"):
        eng.submit([-1], 3)
    assert eng.rejected.get("token_out_of_range") == 2
    assert eng.poisoned_steps == 0 and not eng.failed
    eng.submit([1, 2, 3], 2)              # engine still healthy
    assert eng.drain()[0].finish_reason == "length"


def test_failover_cap_fences_poison_pills(served_model):
    """max_failovers=0: a kill victim surfaces 'failed' even though a
    healthy replica remains — the fence that stops a replica-killing
    request from cascading through the whole fleet."""
    cfg, _, _ = served_model
    fleet = _fleet(served_model, max_failovers=0,
                   faults=FaultPlan.parse("replica_down@2"))
    for p, b in _grouped_requests(cfg.vocab_size, per_group=3, budget=5):
        fleet.submit(p, b)
    results = fleet.drain()
    assert fleet.failovers == 0
    assert any(r.finish_reason == "failed" for r in results)
    assert len(fleet.router.ready_replicas()) == 1   # fleet survives
    rid = fleet.submit([1, 2, 3], 2)                 # and still serves
    assert fleet.drain()[0].rid == rid


def test_failover_off_surfaces_failed(served_model):
    cfg, _, _ = served_model
    fleet = _fleet(served_model, failover=False,
                   faults=FaultPlan.parse("replica_down@2"))
    reqs = _grouped_requests(cfg.vocab_size, per_group=3, budget=5)
    for p, b in reqs:
        fleet.submit(p, b)
    results = fleet.drain()
    assert len(results) == len(reqs)
    assert any(r.finish_reason == "failed" for r in results)
    assert fleet.failovers == 0


def test_retry_after_aggregates_min_over_ready(served_model):
    fleet = _fleet(served_model)
    # load r1's queue so its estimate exceeds r0's
    eng1 = fleet.replicas["r1"]
    for _ in range(12):
        eng1.submit([1, 2, 3], 2)
    base0 = fleet.replicas["r0"].retry_after_s()
    base1 = eng1.retry_after_s()
    assert fleet.retry_after_s() == min(base0, base1)
    info = fleet.retry_info()
    assert info["replica_set"] == 2
    # the loaded replica alone would have quoted a bigger number
    fleet.drain_replica("r0")
    assert fleet.retry_info()["replica_set"] == 1
    assert fleet.retry_after_s() == eng1.retry_after_s()
    fleet.replicas["r1"].drain()


def test_router_metrics_families_and_stats(served_model):
    cfg, _, _ = served_model
    fleet = _fleet(served_model)
    for p, b in _grouped_requests(cfg.vocab_size, per_group=2):
        fleet.submit(p, b)
    fleet.drain()
    text = render_prometheus(fleet.metrics)
    assert "serve_router_decisions_total" in text
    assert 'serve_router_replica_ready{replica="r0"}' in text
    assert "serve_router_prefix_hit_est_tokens" in text
    st = fleet.stats()
    assert "router" in st and "decisions" in st["router"]
    assert set(st["router"]["replicas"]) == {"r0", "r1"}
    json.dumps(st)                       # /debug-able
    # label hygiene: only reasons that actually happened mint children
    reasons = {line.split('reason="')[1].split('"')[0]
               for line in text.splitlines()
               if line.startswith("serve_router_decisions_total{")}
    assert reasons <= {"affinity", "load", "fallback"}
    assert "fallback" not in reasons     # nothing failed over here


def test_flight_rid_namespacing_and_merge(served_model):
    fleet = _fleet(served_model)
    rid = fleet.submit(list(range(20)), 2)
    fleet.drain()
    assert rid.split(":")[0] in ("r0", "r1")
    replica = rid.split(":")[0]
    eng = fleet.replicas[replica]
    evs = eng.flight.events()
    assert all(isinstance(e["rid"], str) and e["rid"].startswith(replica)
               for e in evs if e.get("rid") is not None)
    # engine-internal int-rid lookups still work (the /debug contract)
    int_rid = int(rid.split(":")[1])
    assert eng.flight.events(rid=int_rid)
    assert eng.flight.terminals(int_rid) == ["finish"]
    # merged JSONL parses and carries the route event
    lines = fleet.merged_flight_jsonl().strip().splitlines()
    parsed = [json.loads(ln) for ln in lines]
    assert any(e["ev"] == "route" and e["rid"] == rid for e in parsed)
    # wall-clock ordering across recorders
    walls = [e["wall"] for e in parsed]
    assert walls == sorted(walls)


def test_fleet_adds_no_programs_and_no_syncs(served_model):
    """The acceptance pin: routing is host-side bookkeeping — each
    replica's compile set is byte-identical to a solo engine's and the
    audited host-sync ledger gains nothing."""
    cfg, model, params = served_model
    reqs = _grouped_requests(cfg.vocab_size, per_group=2)

    mark = _tracecheck.sync_counts()
    solo = Engine(model, params, num_slots=2, max_len=64)
    for p, b in reqs:
        solo.submit(p, b)
    solo.drain()
    solo_sync = _tracecheck.sync_delta(mark)

    mark = _tracecheck.sync_counts()
    fleet = _fleet(served_model)
    for p, b in reqs:
        fleet.submit(p, b)
    fleet.drain()
    fleet_sync = _tracecheck.sync_delta(mark)

    for eng in fleet.replicas.values():
        assert eng.max_programs() == solo.max_programs()
        for kind, count in eng.trace_counts.items():
            assert count <= eng.max_programs()[kind], kind
    assert set(fleet_sync) == set(solo_sync)


def test_priority_and_slo_passthrough(served_model):
    fleet = _fleet(served_model)
    rid = fleet.submit(list(range(30)), 2, slo_class="interactive",
                       priority=7, deadline_s=30.0, temperature=0.0,
                       seed=3)
    name, erid = rid.split(":")
    # parked in the chosen engine's queue with every field intact
    item = fleet.replicas[name].sched.queued_items()[0]
    assert (item.slo_class, item.priority, item.deadline_s) == \
        ("interactive", 7, 30.0)
    assert item.rid == int(erid)
    fleet.drain()


# ------------------------------------------------------ HTTP front tier

def _start_replica_server(model, params):
    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    eng = Engine(model, params, num_slots=2, max_len=64)
    loop = EngineLoop(eng)
    loop.start()
    srv = make_server("127.0.0.1", 0, loop,
                      lambda s: [ord(c) % 50 for c in s] or [0],
                      lambda ids: "".join(chr(65 + t % 26) for t in ids))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return eng, loop, srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _post(port, path, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def test_http_router_tier_end_to_end(served_model):
    """The (b) landing: asyncio front tier over two REAL replica
    servers — affinity keeps a shared prefix on one replica, the
    response body carries replica + prefix_digest, /metrics exposes
    the router families, and a drained replica leaves rotation within
    one health interval with traffic re-routed (fallback)."""
    from nanosandbox_tpu.serve.http import RouterFrontend

    cfg, model, params = served_model
    nodes = [_start_replica_server(model, params) for _ in range(2)]
    fe = RouterFrontend([n[3] for n in nodes], host="127.0.0.1",
                        port=0, health_interval_s=0.1).start()
    try:
        deadline = time.time() + 5
        while len(fe.router.ready_replicas()) < 2:
            assert time.time() < deadline, fe.router.stats()
            time.sleep(0.05)
        st, body, _ = _post(fe.port, "/generate",
                            {"prompt_tokens": list(range(40)),
                             "max_new_tokens": 3})
        assert st == 200 and body["finish_reason"] == "length"
        warm = body["replica"]
        assert body["prefix_digest"] == prefix_digests(
            list(range(40)), 16)
        st, body2, _ = _post(fe.port, "/generate",
                             {"prompt_tokens": list(range(32)) + [45],
                              "max_new_tokens": 2})
        assert st == 200 and body2["replica"] == warm
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "serve_router_decisions_total" in text
        # replica /debug/prefix_summary serves the digests
        warm_port = int(warm.rsplit(":", 1)[1])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{warm_port}/debug/prefix_summary",
                timeout=10) as r:
            summ = json.load(r)
        assert set(body["prefix_digest"]) <= set(summ["digests"])
        # drain the warm replica: rotation reacts within one interval
        _post(warm_port, "/drain", {})
        deadline = time.time() + 5
        while warm in fe.router.ready_replicas():
            assert time.time() < deadline, fe.router.stats()
            time.sleep(0.05)
        st, body3, _ = _post(fe.port, "/generate",
                             {"prompt_tokens": list(range(32)) + [44],
                              "max_new_tokens": 2})
        assert st == 200 and body3["replica"] != warm
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/debug/router",
                timeout=10) as r:
            dbg = json.load(r)
        assert dbg["router"]["decisions"]["fallback"] >= 1
    finally:
        fe.stop()
        for eng, loop, srv, _ in nodes:
            loop.stop()
            srv.shutdown()


def test_http_router_all_down_503(served_model):
    from nanosandbox_tpu.serve.http import RouterFrontend

    fe = RouterFrontend(["http://127.0.0.1:1"], host="127.0.0.1",
                        port=0, health_interval_s=0.1).start()
    try:
        time.sleep(0.3)
        st, body, headers = _post(fe.port, "/generate",
                                  {"prompt_tokens": [1, 2],
                                   "max_new_tokens": 1})
        assert st == 503
        assert body["replica_set"] == 0
        assert int(headers.get("Retry-After", "0")) >= 1
    finally:
        fe.stop()


# --------------------------------------------------------------- bench

@pytest.mark.slow
def test_bench_fleet_smoke():
    """bench.py --mode=fleet contract: the pinned fields exist and the
    structural invariants (parity, exactly-once, replica kill) hold on
    a minimal configuration."""
    import bench

    result = bench.bench_fleet(
        {"requests": "8", "groups": "2", "repeat": "1",
         "num_slots": "2", "max_len": "64", "kill_step": "3"},
        quick=True, on_tpu=False)
    x = result["extra"]
    for fld in ("affinity_vs_random_ttft", "affinity_vs_random_ttft_mean",
                "hit_rate_affinity", "hit_rate_random",
                "fleet_greedy_parity", "multi_terminal_rids", "kill"):
        assert fld in x, fld
    assert x["fleet_greedy_parity"] == 1.0
    assert x["multi_terminal_rids"] == 0
    assert x["kill"]["unreached_terminals"] == 0
    assert x["kill"]["replica_downs"] == 1
    assert x["kill"]["kill_parity_ok"]
    json.dumps(result)                   # the CI artifact serializes
