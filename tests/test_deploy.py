"""Deployment-shell tests: k8s manifests, entrypoint contract, scripts.

The reference's backlogged CI item (gh_sync.ps1:154-158) asked for
kubeval/yamllint + shellcheck; neither tool is in this image, so these tests
implement the same checks natively: YAML well-formedness + schema
invariants for every manifest, bash syntax checks for every script, and a
behavioural test of the entrypoint's rank-derivation contract
(README.md:21,102 — NODE_RANK from StatefulSet ordinal — reborn as
PROCESS_ID for jax.distributed.initialize).
"""

import os
import subprocess

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
K8S = os.path.join(REPO, "k8s")
ENTRYPOINT = os.path.join(REPO, "container", "entrypoint.sh")

MANIFESTS = [
    "00-namespace.yaml",
    "01-proxy-config.yaml",
    "storage/10-pv.yaml",
    "storage/11-pvc.yaml",
    "storage/12-filestore-rwx.yaml",
    "jobs/20-download-tiny-shakespeare.yaml",
    "jobs/21-download-openwebtext.yaml",
    "jobs/22-prepare-english-prose.yaml",
    "jobs/30-train-singlepod.yaml",
    "services/41-train-mp-headless.yaml",
    "statefulset/40-train-multipod.yaml",
]


def load(rel):
    with open(os.path.join(K8S, rel)) as f:
        return list(yaml.safe_load_all(f))


@pytest.mark.parametrize("rel", MANIFESTS)
def test_manifest_parses(rel):
    docs = load(rel)
    assert docs, f"{rel} is empty"
    for doc in docs:
        assert {"apiVersion", "kind", "metadata"} <= set(doc), rel
        # everything except cluster-scoped kinds is namespaced to disttrain
        if doc["kind"] not in ("Namespace", "PersistentVolume",
                               "StorageClass"):
            assert doc["metadata"]["namespace"] == "disttrain", rel


def test_filestore_pvc_swaps_in():
    """12-filestore-rwx binds the SAME claim name with RWX, so the multipod
    manifests work unchanged on multi-node GKE (hostPath is node-local)."""
    docs = load("storage/12-filestore-rwx.yaml")
    pvc = next(d for d in docs if d["kind"] == "PersistentVolumeClaim")
    hostpath_pvc = load("storage/11-pvc.yaml")[0]
    assert pvc["metadata"]["name"] == hostpath_pvc["metadata"]["name"]
    assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]


def _pod_spec(doc):
    return doc["spec"]["template"]["spec"]


def test_jobs_mount_pvc_and_proxy():
    for rel in ("jobs/20-download-tiny-shakespeare.yaml",
                "jobs/21-download-openwebtext.yaml",
                "jobs/22-prepare-english-prose.yaml",
                "jobs/30-train-singlepod.yaml"):
        doc = load(rel)[0]
        spec = _pod_spec(doc)
        vols = {v["name"]: v for v in spec["volumes"]}
        assert vols["data"]["persistentVolumeClaim"]["claimName"] == \
            "disttrain-pvc", rel
        c = spec["containers"][0]
        refs = [e["configMapRef"] for e in c["envFrom"]]
        assert any(r["name"] == "proxy-config" for r in refs), rel
        # The zero-egress english-prose Job must NOT hard-require the
        # proxy ConfigMap (air-gapped clusters skip 01-proxy-config.yaml);
        # the downloading jobs must (a silent missing proxy would just
        # hang the download).
        optional = any(r.get("optional") for r in refs)
        assert optional == ("english-prose" in rel), rel
        assert any(m["mountPath"] == "/data" for m in c["volumeMounts"]), rel


def test_singlepod_requests_tpu():
    """Workflow A requests google.com/tpu (was nvidia.com/gpu, README.md:118)."""
    c = _pod_spec(load("jobs/30-train-singlepod.yaml")[0])["containers"][0]
    assert "google.com/tpu" in c["resources"]["requests"]
    assert "google.com/tpu" in c["resources"]["limits"]


def test_statefulset_contract():
    """Workflow B invariants that make the rendezvous work."""
    sts = load("statefulset/40-train-multipod.yaml")[0]
    svc = load("services/41-train-mp-headless.yaml")[0]
    assert sts["spec"]["serviceName"] == svc["metadata"]["name"]
    # headless + selector matches pod labels -> stable per-pod DNS
    # (k8s spells headless as the literal string "None"; YAML null would
    # be rejected by the API server)
    assert svc["spec"]["clusterIP"] == "None"
    labels = sts["spec"]["template"]["metadata"]["labels"]
    assert svc["spec"]["selector"].items() <= labels.items()
    # entrypoint contract: the env the rank/coordinator derivation reads
    # (container/entrypoint.sh) must be internally consistent or
    # jax.distributed.initialize hangs on the cluster
    c = _pod_spec(sts)["containers"][0]
    env = {e["name"]: e.get("value") for e in c["env"]}
    assert int(env["NUM_PROCESSES"]) == sts["spec"]["replicas"]
    assert env["STATEFULSET_NAME"] == sts["metadata"]["name"]
    assert env["HEADLESS_SERVICE"] == svc["metadata"]["name"]
    port = int(env["COORDINATOR_PORT"])
    assert port in [p["port"] for p in svc["spec"]["ports"]]
    assert port in [p["containerPort"] for p in c["ports"]]
    assert "google.com/tpu" in c["resources"]["requests"]
    # all pods must start together or initialize() deadlocks
    assert sts["spec"]["podManagementPolicy"] == "Parallel"


def _run_entrypoint(extra_env, *args):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PROCESS_ID", "NUM_PROCESSES", "COORDINATOR_ADDRESS",
                        "HOSTNAME")}
    env.update({"DRY_RUN": "1", **extra_env})
    out = subprocess.run(["bash", ENTRYPOINT, *args], env=env,
                         capture_output=True, text=True, timeout=30)
    assert out.returncode == 0, out.stderr
    return dict(line.split("=", 1) for line in out.stdout.strip().splitlines())


def test_entrypoint_derives_ordinal():
    got = _run_entrypoint({"HOSTNAME": "train-multipod-2", "NUM_PROCESSES": "3"})
    assert got["PROCESS_ID"] == "2"
    assert got["NUM_PROCESSES"] == "3"
    assert got["COORDINATOR_ADDRESS"] == "train-multipod-0.train-mp-headless:12355"


def test_entrypoint_single_process_default():
    got = _run_entrypoint({"HOSTNAME": "train-singlepod-abc"})
    # random pod-suffix digits must not fake an ordinal into multi-host mode
    assert got["NUM_PROCESSES"] == "1"
    assert got["COORDINATOR_ADDRESS"] == ""


def test_entrypoint_no_ordinal_hostname():
    got = _run_entrypoint({"HOSTNAME": "somehost", "NUM_PROCESSES": "1"})
    assert got["PROCESS_ID"] == "0"


def test_entrypoint_explicit_overrides_win():
    got = _run_entrypoint({"HOSTNAME": "train-multipod-2", "NUM_PROCESSES": "4",
                           "PROCESS_ID": "7",
                           "COORDINATOR_ADDRESS": "elsewhere:1"})
    assert got["PROCESS_ID"] == "7"
    assert got["COORDINATOR_ADDRESS"] == "elsewhere:1"


def test_entrypoint_custom_service_names():
    got = _run_entrypoint({"HOSTNAME": "myjob-5", "NUM_PROCESSES": "8",
                           "STATEFULSET_NAME": "myjob",
                           "HEADLESS_SERVICE": "my-svc",
                           "COORDINATOR_PORT": "999"})
    assert got["PROCESS_ID"] == "5"
    assert got["COORDINATOR_ADDRESS"] == "myjob-0.my-svc:999"


@pytest.mark.parametrize("script", [
    "container/entrypoint.sh",
    "scripts/01_install_cluster.sh",
    "scripts/02_build_and_load_image.sh",
    "scripts/03_apply_basics.sh",
    "scripts/20_run_multipod.sh",
    "scripts/gh_sync.sh",
])
def test_shell_syntax(script):
    """bash -n: the shellcheck-lite the backlogged CI item asked for."""
    path = os.path.join(REPO, script)
    out = subprocess.run(["bash", "-n", path], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert os.access(path, os.X_OK), f"{script} not executable"


def test_entrypoint_matches_distributed_module():
    """The bash derivation and the python fallback must agree."""
    from nanosandbox_tpu.parallel.distributed import (
        derive_process_id_from_hostname)

    assert derive_process_id_from_hostname("train-multipod-2") == 2
    assert derive_process_id_from_hostname("somehost") is None


# -- cross-manifest topology (round-2 VERDICT missing #3 best-effort) -----
#
# No container runtime exists in this environment (docker/kind/kubectl all
# absent), so the reference's actually-run quick start cannot be replayed
# here. These tests implement the next-strongest offline check: a virtual
# `kubectl apply` that verifies every cross-file reference the real apply
# order depends on, so the manifests can only fail on a live cluster for
# environmental reasons, not internal inconsistency.

def _all_docs():
    return {rel: load(rel) for rel in MANIFESTS}


def _pod_specs(docs):
    """(rel, kind, pod_template_spec) for every workload manifest."""
    out = []
    for rel, dlist in docs.items():
        for d in dlist:
            if d["kind"] in ("Job", "StatefulSet"):
                out.append((rel, d, d["spec"]["template"]["spec"]))
    return out



def test_every_pvc_claim_and_configmap_reference_resolves():
    docs = _all_docs()
    pvcs = {d["metadata"]["name"] for dl in docs.values() for d in dl
            if d["kind"] == "PersistentVolumeClaim"}
    cms = {d["metadata"]["name"] for dl in docs.values() for d in dl
           if d["kind"] == "ConfigMap"}
    for rel, _, spec in _pod_specs(docs):
        for vol in spec.get("volumes", []):
            if "persistentVolumeClaim" in vol:
                claim = vol["persistentVolumeClaim"]["claimName"]
                assert claim in pvcs, f"{rel}: unknown PVC {claim}"
        for c in spec["containers"]:
            for ef in c.get("envFrom", []):
                if "configMapRef" in ef:
                    name = ef["configMapRef"]["name"]
                    assert name in cms, f"{rel}: unknown ConfigMap {name}"




def test_workloads_use_one_image_and_shared_data_mount():
    docs = _all_docs()
    images = set()
    for rel, _, spec in _pod_specs(docs):
        for c in spec["containers"]:
            images.add(c["image"])
            mounts = {m["mountPath"] for m in c.get("volumeMounts", [])}
            assert "/data" in mounts, (
                f"{rel}: container misses the /data artifact plane")
    assert len(images) == 1, f"inconsistent images: {images}"


def test_dataset_jobs_feed_the_train_jobs_data_dir():
    """The dataset Jobs must write where the train workloads read
    (--data_dir), or the quick-start order produces a FileNotFoundError
    on the cluster."""
    docs = _all_docs()
    train_dirs = set()
    for rel, _, spec in _pod_specs(docs):
        for c in spec["containers"]:
            for a in c.get("args", []) or []:
                if a.startswith("--data_dir="):
                    train_dirs.add(a.split("=", 1)[1])
    assert train_dirs == {"/data/datasets"}
    for rel in ("jobs/20-download-tiny-shakespeare.yaml",
                "jobs/21-download-openwebtext.yaml",
                "jobs/22-prepare-english-prose.yaml"):
        spec = docs[rel][0]["spec"]["template"]["spec"]
        text = str(spec)
        assert "/data/datasets" in text, (
            f"{rel}: does not write under /data/datasets")


def test_image_ships_the_offline_corpus_fixture():
    """jobs/22 runs english_prose_char prep with zero egress, which only
    works if the Dockerfile copies the committed fixture to the path
    prepare.py resolves (package root /app -> /app/data/fixtures)."""
    with open(os.path.join(REPO, "docker", "Dockerfile")) as f:
        dockerfile = f.read()
    assert "COPY data/fixtures/ /app/data/fixtures/" in dockerfile
