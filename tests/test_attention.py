"""Attention kernel tests: Pallas (interpret mode on CPU) vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.ops.attention import (causal_attention, flash_attention,
                                           xla_attention)


def rand_qkv(rng, B=2, H=2, T=128, D=64, dtype=jnp.float32):
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), dtype)
               for _ in range(3))
    return q, k, v


@pytest.mark.parametrize("T,D", [(128, 64), (128, 128), (256, 64), (96, 32)])
def test_flash_matches_xla(T, D):
    rng = np.random.default_rng(0)
    q, k, v = rand_qkv(rng, T=T, D=D)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gradients_match():
    rng = np.random.default_rng(1)
    q, k, v = rand_qkv(rng, T=64, D=32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, True).sum()

    def loss_ref(q, k, v):
        return xla_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_dispatch_auto_on_cpu_uses_xla():
    rng = np.random.default_rng(2)
    q, k, v = rand_qkv(rng, T=32, D=16)
    out = causal_attention(q, k, v, impl="auto")
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_causal_masking():
    rng = np.random.default_rng(3)
    q, k, v = rand_qkv(rng, B=1, H=1, T=64, D=32)
    out1 = flash_attention(q, k, v, True, None, True)
    k2 = k.at[:, :, 40:, :].set(0.0)
    v2 = v.at[:, :, 40:, :].set(0.0)
    out2 = flash_attention(q, k2, v2, True, None, True)
    # Positions < 40 never see keys >= 40, so they are identical.
    np.testing.assert_allclose(np.asarray(out1[:, :, :40]),
                               np.asarray(out2[:, :, :40]), atol=1e-5)


def test_bf16_inputs():
    rng = np.random.default_rng(4)
    q, k, v = rand_qkv(rng, T=128, D=64, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


@pytest.mark.parametrize("T,expect", [
    (64, (128, 128)),     # tiny T -> single 128 block
    (640, (128, 128)),    # 640 = 5*128: only 128 divides -> no pad waste
    (768, (384, 384)),    # largest divisor <= 512
    (1024, (512, 512)),
    (8192, (512, 512)),
])
def test_clamp_blocks_divides_padded_T(T, expect):
    from nanosandbox_tpu.ops.attention import _clamp_blocks, DEFAULT_BLOCK

    got = _clamp_blocks(T, DEFAULT_BLOCK, DEFAULT_BLOCK)
    assert got == expect
    Tp128 = -(-T // 128) * 128
    assert Tp128 % got[0] == 0 and Tp128 % got[1] == 0


@pytest.mark.parametrize("block", [200, 8, 1, 129, 511])
def test_clamp_blocks_off_grid_request_terminates(block):
    """Caller-supplied blocks off the 128-lane grid (e.g. 200, which
    passes _pad_qkv's %8 check) used to make the divisor search loop
    forever / go negative (ADVICE r2); they now round down to the grid."""
    from nanosandbox_tpu.ops.attention import _clamp_blocks

    bq, bk = _clamp_blocks(1024, block, block)
    assert bq % 128 == 0 and bk % 128 == 0
    assert bq >= 128 and bk >= 128
    assert 1024 % bq == 0 and 1024 % bk == 0


@pytest.mark.parametrize("T", [640, 320])
def test_flash_matches_xla_non_divisor_T(T):
    """T between block multiples must not pad past the 128 boundary
    (would waste FLOPs on pad query rows and change nothing numerically —
    this pins the parity either way)."""
    rng = np.random.default_rng(7)
    q, k, v = rand_qkv(rng, B=1, H=2, T=T, D=32)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# -- flash_attention_lse: the ring's block primitive ----------------------

def _lse_reference(q, k, v, causal=True):
    """(out, lse) via plain XLA ops."""
    sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale,
                   k.astype(jnp.float32))
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1),
                     v.astype(jnp.float32))
    return out, lse


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_matches_reference(causal):
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(11)
    mk = lambda: jnp.asarray(rng.normal(size=(1, 2, 256, 32)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    out, lse = flash_attention_lse(q, k, v, causal, None, True)  # interpret
    ref_out, ref_lse = _lse_reference(q, k, v, causal)
    assert lse.shape == (1, 2, 256)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-5, rtol=2e-5)


def test_flash_lse_gradients_including_dlse():
    """A loss that consumes BOTH outputs exercises the dlse fold-in
    (ds = p * (dp - (drow - dlse))) — exactly what the ring's
    logsumexp-weighted merge does in its backward."""
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(12)
    mk = lambda: jnp.asarray(rng.normal(size=(1, 2, 256, 32)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    w = jnp.asarray(rng.normal(size=(1, 2, 256)), jnp.float32)

    def loss_flash(q, k, v):
        out, lse = flash_attention_lse(q, k, v, True, None, True)
        return (out ** 2).sum() + (lse * w).sum()

    def loss_ref(q, k, v):
        out, lse = _lse_reference(q, k, v, True)
        return (out ** 2).sum() + (lse * w).sum()

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)
