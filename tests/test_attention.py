"""Attention kernel tests: Pallas (interpret mode on CPU) vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.ops.attention import (causal_attention, flash_attention,
                                           xla_attention)


def rand_qkv(rng, B=2, H=2, T=128, D=64, dtype=jnp.float32):
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), dtype)
               for _ in range(3))
    return q, k, v


@pytest.mark.parametrize("T,D", [(128, 64), (128, 128), (256, 64), (96, 32)])
def test_flash_matches_xla(T, D):
    rng = np.random.default_rng(0)
    q, k, v = rand_qkv(rng, T=T, D=D)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gradients_match():
    rng = np.random.default_rng(1)
    q, k, v = rand_qkv(rng, T=64, D=32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, True).sum()

    def loss_ref(q, k, v):
        return xla_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_dispatch_auto_on_cpu_uses_xla():
    rng = np.random.default_rng(2)
    q, k, v = rand_qkv(rng, T=32, D=16)
    out = causal_attention(q, k, v, impl="auto")
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_causal_masking():
    rng = np.random.default_rng(3)
    q, k, v = rand_qkv(rng, B=1, H=1, T=64, D=32)
    out1 = flash_attention(q, k, v, True, None, True)
    k2 = k.at[:, :, 40:, :].set(0.0)
    v2 = v.at[:, :, 40:, :].set(0.0)
    out2 = flash_attention(q, k2, v2, True, None, True)
    # Positions < 40 never see keys >= 40, so they are identical.
    np.testing.assert_allclose(np.asarray(out1[:, :, :40]),
                               np.asarray(out2[:, :, :40]), atol=1e-5)


def test_bf16_inputs():
    rng = np.random.default_rng(4)
    q, k, v = rand_qkv(rng, T=128, D=64, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


@pytest.mark.parametrize("T,expect", [
    (64, (128, 128)),     # tiny T -> single 128 block
    (640, (128, 128)),    # 640 = 5*128: only 128 divides -> no pad waste
    (768, (384, 384)),    # largest divisor <= 512
    (1024, (512, 512)),
    (8192, (512, 512)),
])
def test_clamp_blocks_divides_padded_T(T, expect):
    from nanosandbox_tpu.ops.attention import _clamp_blocks, DEFAULT_BLOCK

    got = _clamp_blocks(T, DEFAULT_BLOCK, DEFAULT_BLOCK)
    assert got == expect
    Tp128 = -(-T // 128) * 128
    assert Tp128 % got[0] == 0 and Tp128 % got[1] == 0


@pytest.mark.parametrize("block", [200, 8, 1, 129, 511])
def test_clamp_blocks_off_grid_request_terminates(block):
    """Caller-supplied blocks off the 128-lane grid (e.g. 200, which
    passes _pad_qkv's %8 check) used to make the divisor search loop
    forever / go negative (ADVICE r2); they now round down to the grid."""
    from nanosandbox_tpu.ops.attention import _clamp_blocks

    bq, bk = _clamp_blocks(1024, block, block)
    assert bq % 128 == 0 and bk % 128 == 0
    assert bq >= 128 and bk >= 128
    assert 1024 % bq == 0 and 1024 % bk == 0


@pytest.mark.parametrize("T", [640, 320])
def test_flash_matches_xla_non_divisor_T(T):
    """T between block multiples must not pad past the 128 boundary
    (would waste FLOPs on pad query rows and change nothing numerically —
    this pins the parity either way)."""
    rng = np.random.default_rng(7)
    q, k, v = rand_qkv(rng, B=1, H=2, T=T, D=32)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# -- flash_attention_lse: the ring's block primitive ----------------------

def _lse_reference(q, k, v, causal=True):
    """(out, lse) via plain XLA ops."""
    sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale,
                   k.astype(jnp.float32))
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1),
                     v.astype(jnp.float32))
    return out, lse


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_matches_reference(causal):
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(11)
    mk = lambda: jnp.asarray(rng.normal(size=(1, 2, 256, 32)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    out, lse = flash_attention_lse(q, k, v, causal, None, True)  # interpret
    ref_out, ref_lse = _lse_reference(q, k, v, causal)
    assert lse.shape == (1, 2, 256)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-5, rtol=2e-5)


# -- flash_attention_dropout: in-kernel attention-prob dropout ------------

def _reference_keep_mask(seed: int, bh: int, T: int, rate: float) -> np.ndarray:
    """Rebuild the kernel's counter-hash mask with the SAME shared helpers
    on full (T, T) indices — position-keyed, so block layout is irrelevant."""
    from nanosandbox_tpu.ops.attention import _GOLDEN, _fmix32

    mix = np.asarray(_fmix32(jnp.uint32(seed)
                             ^ (jnp.uint32(bh) * jnp.uint32(_GOLDEN))))
    idx = (np.arange(T, dtype=np.uint32)[:, None] * np.uint32(T)
           + np.arange(T, dtype=np.uint32)[None, :])
    h = np.asarray(_fmix32(jnp.asarray(idx ^ mix)))
    thr = np.uint32(min(int(round(rate * 2**32)), 2**32 - 1))
    return h >= thr


def _reference_dropout_attention(q, k, v, seed: int, rate: float):
    """dropout(softmax(s)) @ v with the kernel's exact mask, in plain jnp."""
    B, H, T, D = q.shape
    sm = D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm,
                   k.astype(jnp.float32))
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    keep = jnp.stack([
        jnp.stack([jnp.asarray(_reference_keep_mask(seed, b * H + h_, T, rate))
                   for h_ in range(H)]) for b in range(B)])
    p = jnp.where(keep, p / (1 - rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def test_flash_dropout_rate0_is_exact_flash():
    from nanosandbox_tpu.ops.attention import flash_attention_dropout

    rng = np.random.default_rng(20)
    q, k, v = rand_qkv(rng, T=128, D=64)
    seed = jnp.array([77], jnp.uint32)
    base = flash_attention(q, k, v, True, None, True)
    out = flash_attention_dropout(q, k, v, seed, True, None, 0.0, True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
    gb = jax.grad(lambda q, k, v: flash_attention(
        q, k, v, True, None, True).sum(), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: flash_attention_dropout(
        q, k, v, seed, True, None, 0.0, True).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gb, gd):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("rate", [0.1, 0.5])
def test_flash_dropout_matches_masked_reference(rate):
    """Forward AND all three grads against a plain-jnp reference using the
    identical positional mask — proves fwd and both bwd kernels agree on
    every mask bit (the whole correctness risk of recomputed-mask dropout)."""
    from nanosandbox_tpu.ops.attention import flash_attention_dropout

    rng = np.random.default_rng(21)
    q, k, v = rand_qkv(rng, B=2, H=2, T=256, D=64)
    seed_val = 12345
    seed = jnp.array([seed_val], jnp.uint32)
    w = jnp.asarray(rng.normal(size=(64,)), jnp.float32)

    ref = _reference_dropout_attention(q, k, v, seed_val, rate)
    out = flash_attention_dropout(q, k, v, seed, True, None, rate, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_got(q, k, v):
        return (flash_attention_dropout(q, k, v, seed, True, None, rate,
                                        True) * w).sum()

    def loss_ref(q, k, v):
        return (_reference_dropout_attention(q, k, v, seed_val, rate) * w).sum()

    g = jax.grad(loss_got, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_dropout_keep_rate_and_determinism():
    """Statistical contract: drop fraction ~ Binomial(rate), masks differ
    across seeds, identical across calls with the same seed."""
    from nanosandbox_tpu.ops.attention import flash_attention_dropout

    rate = 0.2
    B, H, T = 1, 2, 128
    # v = identity: each output row IS that query's dropped-prob row, so
    # the mask is directly observable from the forward output.
    q = jnp.zeros((B, H, T, T), jnp.float32)  # uniform scores
    k = jnp.zeros((B, H, T, T), jnp.float32)
    v = jnp.broadcast_to(jnp.eye(T, dtype=jnp.float32), (B, H, T, T))
    out1 = flash_attention_dropout(q, k, v, jnp.array([5], jnp.uint32),
                                   True, None, rate, True)
    out2 = flash_attention_dropout(q, k, v, jnp.array([5], jnp.uint32),
                                   True, None, rate, True)
    out3 = flash_attention_dropout(q, k, v, jnp.array([6], jnp.uint32),
                                   True, None, rate, True)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))
    tril = np.tril(np.ones((T, T), bool))
    dropped = (np.asarray(out1)[:, :, tril] == 0.0)
    frac = dropped.mean()
    sd = (rate * (1 - rate) / dropped.size) ** 0.5
    assert abs(frac - rate) < 6 * sd, (frac, rate, sd)
    # Kept cells carry the 1/(1-rate) inverted-dropout scale: row i holds
    # i+1 uniform probs 1/(i+1), so kept cells of the last row must all be
    # exactly 1/(T*(1-rate)).
    last_row = np.asarray(out1)[0, 0, T - 1]
    nz = last_row[last_row > 0]
    np.testing.assert_allclose(nz, 1.0 / (T * (1 - rate)), rtol=1e-5)


def test_causal_attention_dropout_dispatches_to_pallas_kernel():
    """impl='pallas_interpret' + dropout must run the in-kernel path (not
    silently fall back to XLA as rounds 1-3 did): kernel masks are a pure
    function of (seed, positions), so two calls with the SAME rng must
    agree — the XLA path consumes the rng differently."""
    from nanosandbox_tpu.ops.attention import flash_attention_dropout

    rng = np.random.default_rng(22)
    q, k, v = rand_qkv(rng, T=128, D=64)
    key = jax.random.PRNGKey(3)
    out = causal_attention(q, k, v, impl="pallas_interpret",
                           dropout_rate=0.25, dropout_rng=key)
    seed = jax.random.bits(key, (1,), jnp.uint32)
    direct = flash_attention_dropout(q, k, v, seed, True, None, 0.25, True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(direct))
    # and the mean over many cells is ~ the no-dropout output (unbiased)
    base = flash_attention(q, k, v, True, None, True)
    assert float(jnp.abs(out.mean() - base.mean())) < 0.05


def test_flash_lse_gradients_including_dlse():
    """A loss that consumes BOTH outputs exercises the dlse fold-in
    (ds = p * (dp - (drow - dlse))) — exactly what the ring's
    logsumexp-weighted merge does in its backward."""
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(12)
    mk = lambda: jnp.asarray(rng.normal(size=(1, 2, 256, 32)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    w = jnp.asarray(rng.normal(size=(1, 2, 256)), jnp.float32)

    def loss_flash(q, k, v):
        out, lse = flash_attention_lse(q, k, v, True, None, True)
        return (out ** 2).sum() + (lse * w).sum()

    def loss_ref(q, k, v):
        out, lse = _lse_reference(q, k, v, True)
        return (out ** 2).sum() + (lse * w).sum()

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


# ---------------------------------------------------------------------------
# Compact backward-stat layout (--attention_stat_layout=compact)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("T,D", [(128, 64), (256, 32), (640, 64)])
def test_compact_stat_layout_gradients_match_replicated(T, D):
    """'compact' must be a pure layout change: gradients bit-comparable to
    the replicated path at every shape class (single stat row, multiple
    rows, non-block-multiple T that exercises padding)."""
    rng = np.random.default_rng(21)
    q, k, v = rand_qkv(rng, T=T, D=D)

    def loss(layout):
        def f(q, k, v):
            return (flash_attention(q, k, v, True, None, True, layout)
                    ** 2).sum()
        return f

    gr = jax.grad(loss("replicated"), argnums=(0, 1, 2))(q, k, v)
    gc = jax.grad(loss("compact"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_compact_stat_layout_matches_xla_gradients():
    rng = np.random.default_rng(22)
    q, k, v = rand_qkv(rng, T=256, D=64)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, True, "compact").sum()

    def loss_ref(q, k, v):
        return xla_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_compact_stat_layout_dropout_gradients_match_replicated():
    """The same keep-mask is positional, so dropout gradients must also be
    layout-invariant."""
    from nanosandbox_tpu.ops.attention import flash_attention_dropout

    rng = np.random.default_rng(23)
    q, k, v = rand_qkv(rng, T=256, D=32)
    seed = jnp.asarray([1234], jnp.uint32)

    def loss(layout):
        def f(q, k, v):
            return (flash_attention_dropout(q, k, v, seed, True, None, 0.2,
                                            True, layout) ** 2).sum()
        return f

    gr = jax.grad(loss("replicated"), argnums=(0, 1, 2))(q, k, v)
    gc = jax.grad(loss("compact"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_compact_stat_layout_dlse_gradients_match_replicated():
    """flash_attention_lse's dlse cotangent rides in the stacked stats
    operand — the S=2 compact path."""
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(24)
    mk = lambda: jnp.asarray(rng.normal(size=(1, 2, 256, 32)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    w = jnp.asarray(rng.normal(size=(1, 2, 256)), jnp.float32)

    def loss(layout):
        def f(q, k, v):
            out, lse = flash_attention_lse(q, k, v, True, None, True, layout)
            return (out ** 2).sum() + (lse * w).sum()
        return f

    gr = jax.grad(loss("replicated"), argnums=(0, 1, 2))(q, k, v)
    gc = jax.grad(loss("compact"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_stat_layout_rejects_unknown():
    rng = np.random.default_rng(25)
    q, k, v = rand_qkv(rng, T=128, D=32)
    with pytest.raises(ValueError, match="stat_layout"):
        jax.grad(lambda q: flash_attention(q, k, v, True, None, True,
                                           "bogus").sum())(q)


def test_fused_and_split_backward_agree():
    """The two backward strategies (BWD_IMPL 'fused' default / 'split'
    reference) must produce the same gradients — this is what keeps the
    split path exercised and the fused path honest. dk/dv share the same
    kernel body (bit-identical); dq differs only by f32 accumulation
    order."""
    from nanosandbox_tpu.ops import attention as A

    rng = np.random.default_rng(99)
    B, H, T, D = 2, 3, 256, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))

    def grads():
        def loss(q, k, v):
            return (A.flash_attention(q, k, v, True, None, True)
                    .astype(jnp.float32) ** 2).sum()
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    orig = A.BWD_IMPL
    try:
        A.BWD_IMPL = "fused"
        gf = grads()
        A.BWD_IMPL = "split"
        gs = grads()
    finally:
        A.BWD_IMPL = orig
    for a, b, name in zip(gf, gs, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} diverged")


def test_fused_and_split_backward_agree_dropout_dlse():
    """Same parity through the heavier path: dropout active AND an lse
    cotangent (the ring-block surface) — every branch of the shared tile
    body plus the dq extension."""
    from nanosandbox_tpu.ops import attention as A

    rng = np.random.default_rng(100)
    B, H, T, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))
    seed = jnp.asarray([3], jnp.uint32)

    def grads():
        def loss(q, k, v):
            out, lse = A.flash_attention_lse_dropout(
                q, k, v, seed, True, None, 0.2, True)
            return ((out.astype(jnp.float32) ** 2).sum()
                    + (lse ** 2).sum())
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    orig = A.BWD_IMPL
    try:
        A.BWD_IMPL = "fused"
        gf = grads()
        A.BWD_IMPL = "split"
        gs = grads()
    finally:
        A.BWD_IMPL = orig
    for a, b, name in zip(gf, gs, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} diverged")
