"""Worker for the real 2-process jax.distributed test (not collected).

Run by tests/test_distributed.py in N subprocesses with the exact
environment container/entrypoint.sh exports in a StatefulSet pod:
COORDINATOR_ADDRESS + NUM_PROCESSES set, PROCESS_ID derived from the
HOSTNAME ordinal (train-multipod-<i>). Each process runs the SAME program
(SPMD), initializes the distributed runtime through the Trainer's normal
bootstrap path (parallel/distributed.py), executes one data-parallel
train step on its own batch shard, and prints the globally-reduced loss.
The parent asserts every process printed the identical value — the
allreduce that DDP/NCCL did per-step, done by the XLA partitioner.

usage: _dist_worker.py <data_dir> <out_dir>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The site hook on dev machines force-selects an out-of-process TPU
# platform regardless of JAX_PLATFORMS; the config API wins pre-init.
jax.config.update("jax_platforms", "cpu")


def main() -> None:
    data_dir, out_dir = sys.argv[1], sys.argv[2]

    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    cfg = TrainConfig(
        out_dir=out_dir, data_dir=data_dir, dataset="shakespeare_char",
        n_layer=2, n_head=2, n_embd=64, block_size=64,
        batch_size=4, max_iters=1, eval_interval=0, log_interval=1,
        warmup_iters=1, lr_decay_iters=1, dropout=0.0,
        compute_dtype="float32", tensorboard=False, device="cpu")

    trainer = Trainer(cfg)  # bootstraps jax.distributed from env
    assert trainer.multi_host, "expected multi-process initialization"
    assert trainer.process_count == 2, trainer.process_count
    print(f"WORKER process {trainer.process_index}/{trainer.process_count} "
          f"devices={jax.device_count()} local={jax.local_device_count()}")

    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    try:
        xb, yb = next(loader)
        state, metrics = train_step(state, trainer.to_global(xb),
                                    trainer.to_global(yb),
                                    jax.random.key(0))
        print(f"DIST_LOSS {float(metrics['loss']):.8f}")
        print(f"DIST_GRADNORM {float(metrics['grad_norm']):.8f}")
    finally:
        loader.close()


if __name__ == "__main__":
    main()
