"""Worker for the real multi-process jax.distributed tests (not collected).

Run by tests/test_distributed.py in N subprocesses with the exact
environment container/entrypoint.sh exports in a StatefulSet pod:
COORDINATOR_ADDRESS + NUM_PROCESSES set, PROCESS_ID derived from the
HOSTNAME ordinal (train-multipod-<i>). Each process runs the SAME program
(SPMD), initializes the distributed runtime through the Trainer's normal
bootstrap path (parallel/distributed.py), executes one train step, and
prints the globally-reduced loss. The parent asserts every process printed
the identical value — the allreduce that DDP/NCCL did per-step, done by
the XLA partitioner.

Modes (argv[3], default "dp"):
  dp        1 local device/process, pure data parallel (the round-2 test).
            Works for any NUM_PROCESSES (the round-5 4-process tier runs
            this with 4 workers — the shipped StatefulSet's replica count,
            k8s/statefulset/40-train-multipod.yaml:26).
  fsdp8     4 local devices/process, mesh fsdp=8 + shard_params: the fsdp
            axis SPANS the process boundary (params live half on each
            process, grads reduce-scatter across it) — the StatefulSet
            topology a v5e-16 FSDP run has (round-2 VERDICT weak #6).
  fsdp4sp2  4 local devices/process, mesh fsdp=4 x sp=2 with ring
            attention: sequence-parallel ppermute + FSDP collectives in
            one multi-process program.
  fsdp4x1   1 local device/process x 4 processes, mesh fsdp=4 +
            shard_params: every param shard lives on a DIFFERENT process
            (the fsdp axis spans all four) — round-4 VERDICT missing #3.

In the multi-device modes the batch is sampled with dataset.sample_batch
(global, topology-independent) and row-sliced per process, so the parent
can run the IDENTICAL global batch single-process and assert loss parity,
not just cross-process agreement.

usage: _dist_worker.py <data_dir> <out_dir> [mode]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The site hook on dev machines force-selects an out-of-process TPU
# platform regardless of JAX_PLATFORMS; the config API wins pre-init.
jax.config.update("jax_platforms", "cpu")


def worker_config(mode: str, data_dir: str, out_dir: str):
    from nanosandbox_tpu.config import TrainConfig

    base = dict(
        out_dir=out_dir, data_dir=data_dir, dataset="shakespeare_char",
        n_layer=2, n_head=2, n_embd=64, block_size=64,
        batch_size=4, max_iters=1, eval_interval=0, log_interval=1,
        warmup_iters=1, lr_decay_iters=1, dropout=0.0,
        compute_dtype="float32", tensorboard=False, device="cpu")
    if mode == "dp":
        pass
    elif mode == "fsdp8":
        base.update(batch_size=8, mesh_fsdp=8, shard_params=True)
    elif mode == "fsdp4sp2":
        base.update(batch_size=8, mesh_fsdp=4, mesh_sp=2,
                    shard_params=True, attention_impl="ring")
    elif mode == "fsdp4x1":
        base.update(batch_size=8, mesh_fsdp=4, shard_params=True)
    elif mode == "faulttol":
        # Full Trainer.run() against a SHARED out_dir (the k8s RWX-PV
        # contract, README.md:76): Orbax-coordinated checkpoints every 3
        # iters, init_from=auto so a restarted pod with the same ordinal
        # resumes instead of restarting from scratch (SURVEY.md §5
        # restart-with-stable-identity).
        base.update(max_iters=int(os.environ.get("FT_MAX_ITERS", "48")),
                    eval_interval=3, eval_iters=2, log_interval=1,
                    init_from="auto", always_save_checkpoint=True,
                    warmup_iters=2, lr_decay_iters=48)
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    return TrainConfig(**base)


def main() -> None:
    data_dir, out_dir = sys.argv[1], sys.argv[2]
    mode = sys.argv[3] if len(sys.argv) > 3 else "dp"

    from nanosandbox_tpu.train import Trainer

    cfg = worker_config(mode, data_dir, out_dir)
    trainer = Trainer(cfg)  # bootstraps jax.distributed from env
    assert trainer.multi_host, "expected multi-process initialization"
    want = int(os.environ["NUM_PROCESSES"])
    assert trainer.process_count == want, (trainer.process_count, want)
    print(f"WORKER process {trainer.process_index}/{trainer.process_count} "
          f"devices={jax.device_count()} local={jax.local_device_count()}")

    if mode == "faulttol":
        result = trainer.run()
        print(f"RUN_RESULT iter={result['iter_num']} "
              f"final_loss={result['final_loss']:.8f}")
        return

    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()

    if mode == "dp":
        loader = trainer.make_loader("train", prefetch=False)
        try:
            xb, yb = next(loader)
        finally:
            loader.close()
    else:
        # Topology-independent batch: sample the GLOBAL batch with a
        # pinned seed and keep this process's row slice (batch rows are
        # laid out process-major over the (data, fsdp) shards), so the
        # parent can replay the identical batch single-process.
        xg, yg = trainer.dataset.sample_batch(
            "train", 0, cfg.batch_size, cfg.block_size, seed=cfg.seed)
        rows = cfg.batch_size // trainer.process_count
        lo = trainer.process_index * rows
        xb, yb = xg[lo:lo + rows], yg[lo:lo + rows]

    if mode in ("fsdp8", "fsdp4sp2", "fsdp4x1"):
        # The param shards must actually SPAN the process boundary: each
        # process addresses only its local devices' shards of a
        # globally-sharded kernel.
        kernel = state["params"]["h_0"]["attn"]["c_attn"]["kernel"]
        n_local = len(kernel.addressable_shards)
        total = kernel.sharding.num_devices
        shard_shape = kernel.addressable_shards[0].data.shape
        assert total == jax.device_count(), (total, jax.device_count())
        assert n_local == jax.local_device_count(), n_local
        assert shard_shape != kernel.shape, "param not sharded"
        print(f"FSDP_SPAN local_shards={n_local} global_devices={total} "
              f"shard={shard_shape} full={tuple(kernel.shape)}")

    state, metrics = train_step(state, trainer.to_global(xb),
                                trainer.to_global(yb), jax.random.key(0))
    print(f"DIST_LOSS {float(metrics['loss']):.8f}")
    print(f"DIST_GRADNORM {float(metrics['grad_norm']):.8f}")


if __name__ == "__main__":
    main()
