"""Serve-engine tests: continuous batching, slot KV pool, fixed shapes.

The contract under test (ISSUE 1 acceptance bar, extended by ISSUE 2's
pipelined hot loop):
  * >= 8 concurrent mixed-length requests on CPU, each token-for-token
    identical to single-request sample.generate under greedy decoding —
    under the PIPELINED engine (one decode step in flight, finish
    decisions lagging one step);
  * a bounded compile set — prefill programs capped by the
    (admit-ladder x bucket) grid, ONE decode shape, admit programs
    capped by the ladder, ONE release shape — asserted via the engine's
    trace counters;
  * mid-flight backfill: more requests than slots all complete, and a
    just-finished row's ride-along token never leaks into results or a
    backfilled occupant;
  * batched-prefill admission preserves FIFO order;
  * per-request determinism independent of batch composition (per-row
    keyed sampling).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.models.gpt import GPT
from nanosandbox_tpu.sample import generate
from nanosandbox_tpu.serve import (Engine, SlotScheduler, admit_ladder,
                                   default_buckets)


def _assert_compile_budget(eng):
    """The closed-compile-set contract, enforced two ways: the runtime
    guard's own postcondition (utils.tracecheck — a retrace past budget
    would already have raised), and the published per-kind numbers."""
    eng.tracecheck.assert_within_budget()
    assert eng.tracecheck.budgets() == eng.max_programs()
    budget = eng.max_programs()
    for kind, count in eng.trace_counts.items():
        assert count <= budget[kind], (kind, count, budget)
    assert eng.trace_counts["decode"] <= 1
    assert eng.trace_counts["release"] <= 1


@pytest.fixture(scope="module")
def served_model():
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _ref_greedy(model, params, prompt, max_new, block_size):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32), max_new,
                   temperature=0.0, top_k=0, rng=jax.random.key(0),
                   block_size=block_size)
    return [int(t) for t in out[0, len(prompt):]]


# ---------------------------------------------------------------- scheduler

def test_default_buckets_ladder():
    assert default_buckets(64) == [16, 32, 64]
    assert default_buckets(100) == [16, 32, 64, 100]
    assert default_buckets(8) == [8]
    with pytest.raises(ValueError, match="max_len"):
        default_buckets(0)


def test_admit_ladder():
    assert admit_ladder(8) == [1, 2, 4, 8]
    assert admit_ladder(3) == [1, 2, 3]
    assert admit_ladder(1) == [1]
    with pytest.raises(ValueError, match="num_slots"):
        admit_ladder(0)


def test_scheduler_wave_fifo_prefix():
    """next_admission_wave pops the maximal FIFO *prefix* sharing the
    head's bucket — a different-bucket request ends the wave instead of
    being jumped over (FIFO preserved), and waves cap at free slots."""
    class Item:
        def __init__(self, n):
            self.prompt = [0] * n

    s = SlotScheduler(5, [8, 16])
    for n in (5, 3, 9, 4, 2):   # buckets: 8, 8, 16, 8, 8
        s.enqueue(Item(n))
    items, slots, bucket = s.next_admission_wave()
    # Only the two leading bucket-8 prompts: Item(9) fences the wave even
    # though Item(4)/Item(2) behind it would fit.
    assert bucket == 8 and [len(i.prompt) for i in items] == [5, 3]
    assert len(slots) == len(set(slots)) == 2
    items, slots, bucket = s.next_admission_wave()
    assert bucket == 16 and [len(i.prompt) for i in items] == [9]
    items, slots, bucket = s.next_admission_wave()
    assert bucket == 8 and [len(i.prompt) for i in items] == [4, 2]
    assert s.next_admission_wave() is None  # queue empty
    # Free-slot cap: 4 same-bucket requests, 1 free slot -> wave of 1.
    s2 = SlotScheduler(1, [8])
    for _ in range(4):
        s2.enqueue(Item(3))
    items, slots, _ = s2.next_admission_wave()
    assert len(items) == 1 and s2.queued == 3
    assert s2.next_admission_wave() is None  # no free slot left


def test_scheduler_admission_and_release():
    class Item:
        def __init__(self, n):
            self.prompt = [0] * n

    s = SlotScheduler(2, [8, 16])
    assert s.next_admission() is None  # nothing queued
    s.enqueue(Item(5))
    s.enqueue(Item(9))
    s.enqueue(Item(3))
    a = s.next_admission()
    b = s.next_admission()
    assert a[2] == 8 and b[2] == 16  # FIFO order, smallest fitting bucket
    assert a[1] != b[1]
    assert s.next_admission() is None  # both slots busy
    s.release(a[1])
    c = s.next_admission()
    assert c[1] == a[1] and c[2] == 8
    s.release(b[1])
    with pytest.raises(ValueError, match="twice"):
        s.release(b[1])


def test_scheduler_rejects_oversized_prompt():
    s = SlotScheduler(1, [8])
    with pytest.raises(ValueError, match="exceeds"):
        s.bucket_for(9)


# ------------------------------------------------------------------- engine

def test_single_request_greedy_matches_sample_generate(served_model):
    """The ISSUE's parity anchor: engine output for one request ==
    sample.generate token-for-token under greedy decoding."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    prompt = [1, 2, 3, 4, 5]
    rid = eng.submit(prompt, 15)
    res = {r.rid: r for r in eng.drain()}
    assert res[rid].tokens == _ref_greedy(model, params, prompt, 15,
                                          cfg.block_size)
    assert res[rid].finish_reason == "length"


def test_eight_concurrent_mixed_lengths_parity_and_compile_budget(
        served_model):
    """Acceptance: >= 8 concurrent mixed-length requests, per-request
    greedy parity with sample.generate, and a compile set bounded by
    #prefill-buckets + 1 decode shape."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=8, max_len=64)
    rng = np.random.default_rng(7)
    reqs = []
    for _ in range(8):
        L = int(rng.integers(1, 30))
        prompt = [int(x) for x in rng.integers(0, cfg.vocab_size, L)]
        mnt = int(rng.integers(1, 16))
        reqs.append((eng.submit(prompt, mnt), prompt, mnt))
    assert eng.stats()["queued"] == 8

    res = {r.rid: r for r in eng.drain()}
    assert len(res) == 8
    for rid, prompt, mnt in reqs:
        assert res[rid].tokens == _ref_greedy(model, params, prompt, mnt,
                                              cfg.block_size), rid

    assert eng.trace_counts["decode"] == 1
    _assert_compile_budget(eng)


def test_backfill_more_requests_than_slots(served_model):
    """Continuous batching proper: 10 requests through 3 slots, evicted
    rows backfilled mid-flight, every output still exact."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=3, max_len=64)
    rng = np.random.default_rng(11)
    reqs = []
    for _ in range(10):
        L = int(rng.integers(1, 20))
        prompt = [int(x) for x in rng.integers(0, cfg.vocab_size, L)]
        mnt = int(rng.integers(1, 10))
        reqs.append((eng.submit(prompt, mnt), prompt, mnt))
    res = {r.rid: r for r in eng.drain()}
    assert len(res) == 10
    assert eng.stats()["admitted"] == 10
    assert eng.stats()["free_slots"] == 3
    for rid, prompt, mnt in reqs:
        assert res[rid].tokens == _ref_greedy(model, params, prompt, mnt,
                                              cfg.block_size), rid


def test_eos_evicts_early(served_model):
    """A request whose eos_id is the first greedy token stops after one
    token with finish_reason='eos' and frees its slot."""
    cfg, model, params = served_model
    prompt = [3, 1, 4]
    first = _ref_greedy(model, params, prompt, 1, cfg.block_size)[0]
    eng = Engine(model, params, num_slots=1, max_len=64)
    rid = eng.submit(prompt, 20, eos_id=first)
    res = {r.rid: r for r in eng.drain()}
    assert res[rid].tokens == [first]
    assert res[rid].finish_reason == "eos"
    assert eng.stats()["free_slots"] == 1


def test_eos_mid_stream_one_step_lag_no_ride_along_leak(served_model):
    """The pipelined finish lag: an eos hit at step k is discovered after
    step k+1 was dispatched, so the engine decodes one ride-along token —
    which must NOT appear in the result, and the backfilled next occupant
    of the slot must not inherit it either."""
    cfg, model, params = served_model
    # Find a prompt whose greedy stream produces a NOVEL token somewhere
    # mid-generation (first occurrence at index >= 2) — that token is a
    # valid mid-stream eos for this randomly-initialized model.
    prompt = ref = idx = None
    for cand in ([5, 3], [6, 6, 2], [42, 13, 27, 33], [49, 48, 47]):
        r = _ref_greedy(model, params, cand, 12, cfg.block_size)
        novel = [i for i in range(2, len(r) - 1) if r[i] not in r[:i]]
        if novel:
            prompt, ref, idx = cand, r, novel[0]
            break
    assert prompt is not None, "no candidate prompt with a mid-stream " \
        "novel greedy token; extend the candidate list"
    eos = ref[idx]
    eng = Engine(model, params, num_slots=1, max_len=64)
    rid_a = eng.submit(prompt, 12, eos_id=eos)
    rid_b = eng.submit([9, 9], 6)   # backfills the SAME slot afterwards
    res = {r.rid: r for r in eng.drain()}
    assert res[rid_a].tokens == ref[:idx + 1]  # truncated AT the eos hit
    assert res[rid_a].finish_reason == "eos"
    assert res[rid_b].tokens == _ref_greedy(model, params, [9, 9], 6,
                                            cfg.block_size)
    assert eng.stats()["free_slots"] == 1


def test_pipelined_matches_synchronous_engine(served_model):
    """pipeline=True and pipeline=False produce identical results for an
    identical mixed workload — the overlap is a scheduling change, not a
    semantics change."""
    cfg, model, params = served_model
    rng = np.random.default_rng(3)
    work = []
    for i in range(7):
        L = int(rng.integers(1, 25))
        work.append(([int(x) for x in rng.integers(0, cfg.vocab_size, L)],
                     int(rng.integers(1, 12)), i))

    def run(pipeline):
        eng = Engine(model, params, num_slots=3, max_len=64,
                     pipeline=pipeline)
        rids = [eng.submit(p, mnt, temperature=0.8, top_k=7, seed=100 + s)
                for p, mnt, s in work]
        res = {r.rid: r for r in eng.drain()}
        return [(res[r].tokens, res[r].finish_reason) for r in rids]

    assert run(True) == run(False)


def test_batched_prefill_preserves_fifo_admission(served_model):
    """With 2 slots and a same-bucket pair queued BEHIND a bucket fence,
    the fenced request is admitted before later same-bucket ones (no
    reorder for wave-packing); every output still exact."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    prompts = [[1] * 4, [2] * 20, [3] * 5, [4] * 6]  # buckets 16,32,16,16
    rids = [eng.submit(p, 6) for p in prompts]
    eng.step()  # first admission wave only has room for... slots=2
    first_wave_rids = {st.req.rid for st in eng._active.values()}
    # FIFO: the wave is [prompt0] alone (bucket fence at prompt1), then
    # prompt1 takes the second slot in its own wave — prompts 2/3 (same
    # bucket as 0) must NOT jump it.
    assert first_wave_rids == {rids[0], rids[1]}
    res = {r.rid: r for r in eng.drain()}
    for rid, p in zip(rids, prompts):
        assert res[rid].tokens == _ref_greedy(model, params, p, 6,
                                              cfg.block_size)
    _assert_compile_budget(eng)


def test_stats_latency_fields(served_model):
    """The observability satellite: /stats-visible latency signal —
    tokens/sec, queue-wait, TTFT/TPOT percentiles from bounded rings."""
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    for i in range(5):
        eng.submit([1 + i, 2, 3], 8, seed=i)
    eng.drain()
    s = eng.stats()
    assert s["tokens_generated"] == 5 * 8
    assert s["decode_tokens_per_sec"] is None or s["decode_tokens_per_sec"] > 0
    assert s["queue_wait_steps_mean"] >= 0
    for key in ("ttft_s", "tpot_s"):
        pct = s[key]
        assert set(pct) == {"p50", "p90", "p99"}
        assert 0 <= pct["p50"] <= pct["p99"]
    assert s["pipeline"] is True
    assert s["admit_buckets"] == [1, 2]


def test_deliberate_extra_retrace_raises(served_model):
    """ISSUE 3 acceptance: the compile budget is ENFORCED, not just
    counted — feeding the compiled decode step operands of a new shape
    (the classic leak: a pool/state sized off a runtime value instead
    of num_slots) retraces past the budget of 1 and raises, instead of
    silently compiling a second program per distinct shape."""
    from nanosandbox_tpu.models.gpt import init_cache
    from nanosandbox_tpu.utils.tracecheck import CompileBudgetExceeded

    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=64)
    rid = eng.submit([1, 2, 3], 4)
    res = {r.rid: r for r in eng.drain()}
    assert len(res[rid].tokens) == 4
    assert eng.trace_counts["decode"] == 1

    shrunken_pool = init_cache(cfg, 1, eng.max_len)
    shrunken_state = {k: v[:1] for k, v in eng._state.items()}
    with pytest.raises(CompileBudgetExceeded, match="'decode'"):
        eng._decode(eng.params, shrunken_pool, shrunken_state)
    # The rejected trace compiled nothing and consumed no counter —
    # trace_counts keeps describing the REAL compile set.
    assert eng.trace_counts["decode"] == 1
    eng.tracecheck.assert_within_budget()
    # The healthy programs keep serving: the budget names the leaky
    # program instead of poisoning the engine.
    rid2 = eng.submit([4, 5], 3)
    res = {r.rid: r for r in eng.drain()}
    assert len(res[rid2].tokens) == 3


def test_frozen_registry_turns_lazy_compiles_into_errors(served_model):
    """The serve __main__ post-warmup contract: after --warmup=full the
    registry freezes, so a request shape that somehow escaped warmup
    fails loudly instead of eating a cold compile mid-traffic."""
    from nanosandbox_tpu.utils.tracecheck import CompileBudgetExceeded

    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=64)
    eng.submit([1, 2, 3], 2)
    eng.drain()                      # bucket-16 single-wave set compiled
    with eng.tracecheck.frozen():
        eng.submit([1, 2], 2)        # same (1, 16) programs: cached, fine
        eng.drain()
        eng.submit([9] * 20, 2)      # bucket 32: would need a NEW compile
        with pytest.raises(CompileBudgetExceeded, match="frozen"):
            eng.drain()


def test_sampled_output_independent_of_batch_composition(served_model):
    """Per-row keyed sampling: a request's tokens are a function of its
    own (prompt, settings, seed), not of its batch neighbours — the
    invariant that makes continuous batching deterministic per request."""
    cfg, model, params = served_model

    def run(prompts):
        eng = Engine(model, params, num_slots=4, max_len=64)
        rids = [eng.submit(p, 8, temperature=0.9, top_k=5, top_p=0.95,
                           seed=100 + i) for i, p in enumerate(prompts)]
        res = {r.rid: r.tokens for r in eng.drain()}
        return [res[r] for r in rids]

    solo = run([[1, 2, 3]])[0]
    crowded = run([[1, 2, 3], [9] * 12, [7, 8], [5, 4, 3, 2, 1]])[0]
    assert solo == crowded


def test_submit_validation(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=2, max_len=32)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([], 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1], -1)
    with pytest.raises(ValueError, match="prefill bucket"):
        eng.submit([1] * 33, 1)
    with pytest.raises(ValueError, match="per-slot KV length"):
        eng.submit([1] * 30, 10)


def test_max_new_tokens_zero_completes_without_slot(served_model):
    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=1, max_len=32)
    rid = eng.submit([1, 2], 0)
    res = {r.rid: r for r in eng.drain()}
    assert res[rid].tokens == [] and res[rid].finish_reason == "length"
    assert eng.stats()["admitted"] == 0  # never took a slot


def test_idle_slots_do_not_perturb_active_rows(served_model):
    """A decode step always runs all num_slots rows; idle/padding rows
    must not change an active row's tokens (masked frontiers)."""
    cfg, model, params = served_model
    prompt = [2, 7, 1, 8]
    ref = _ref_greedy(model, params, prompt, 12, cfg.block_size)
    for slots in (1, 4, 8):
        eng = Engine(model, params, num_slots=slots, max_len=64)
        rid = eng.submit(prompt, 12)
        res = {r.rid: r for r in eng.drain()}
        assert res[rid].tokens == ref, slots


# --------------------------------------------------------------------- http

def test_http_frontend_concurrent_roundtrip(served_model):
    """N concurrent HTTP clients multiplex into one engine batch and get
    their own results back; bad requests surface as 400s."""
    import json
    import urllib.error
    import urllib.request

    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    cfg, model, params = served_model
    eng = Engine(model, params, num_slots=4, max_len=64)
    loop = EngineLoop(eng)
    loop.start()
    encode = lambda s: [min(ord(c), cfg.vocab_size - 1) for c in s]  # noqa: E731
    decode = lambda ids: " ".join(str(i) for i in ids)  # noqa: E731
    srv = make_server("127.0.0.1", 0, loop, encode, decode)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(payload).encode())
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        out = {}

        def client(i):
            out[i] = post({"prompt": "ab" * (i + 1), "max_new_tokens": 4,
                           "temperature": 0.0})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert all(len(out[i]["tokens"]) == 4 for i in range(6))
        assert all(out[i]["finish_reason"] == "length" for i in range(6))

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read()) == {"ok": True}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            assert json.loads(r.read())["admitted"] >= 6

        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"prompt": "x" * 100, "max_new_tokens": 4})
        assert ei.value.code == 400
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


def test_engine_loop_failure_fails_waiters_fast():
    """If the engine dies mid-step, every waiter (queued AND in-flight)
    is failed immediately — not left to block until timeout — and later
    submissions fail fast with the death reason."""
    from nanosandbox_tpu.serve.http import EngineLoop

    class BoomEngine:
        def submit(self, **kw):
            return 0

        def has_work(self):
            return True

        def step(self):
            raise RuntimeError("boom")

    loop = EngineLoop(BoomEngine())
    loop.start()
    p = loop.submit(prompt=[1], max_new_tokens=1)
    assert p.done.wait(30)
    assert isinstance(p.error, RuntimeError) and "boom" in str(p.error)
    loop.join(30)
    assert loop.dead is not None
    p2 = loop.submit(prompt=[1], max_new_tokens=1)
    assert p2.done.is_set() and "boom" in str(p2.error)


# -------------------------------------------------------------------- bench

def test_bench_decode_mode_emits_json():
    import bench

    result = bench.bench_decode({"num_slots": "2", "max_new_tokens": "3",
                                 "requests": "3"}, quick=True, on_tpu=False)
    assert result["unit"] == "tokens/sec"
    assert result["value"] > 0
    extra = result["extra"]
    assert extra["tokens_generated"] == 9
    # Pipelined-vs-synchronous comparison fields (trend-tracking, no
    # threshold) + the latency signal.
    assert extra["pipelined_tokens_per_sec"] > 0
    assert extra["sync_tokens_per_sec"] > 0
    assert extra["pipeline_speedup"] == pytest.approx(
        extra["pipelined_tokens_per_sec"] / extra["sync_tokens_per_sec"])
    assert set(extra["ttft_s"]) == {"p50", "p90", "p99"}
    # Compile budget: the closed (admit-rung x bucket) grid.
    budget = (len(extra["prefill_buckets"]) * len(extra["admit_buckets"])
              + len(extra["admit_buckets"]) + 2)
    assert sum(extra["trace_counts"].values()) <= budget
    assert extra["trace_counts"]["decode"] == 1


def test_bench_decode_mixed_mode():
    import bench

    result = bench.bench_decode({"num_slots": "2", "max_new_tokens": "4",
                                 "requests": "4", "mixed": "1"},
                                quick=True, on_tpu=False)
    assert result["extra"]["mixed"] is True
    assert 0 < result["extra"]["tokens_generated"] <= 16
