"""bench.py / perf_sweep contract tests (round-2 VERDICT weak #4-#5,
ADVICE r2): batch-size semantics are per-chip everywhere, and the
measurement helper rejects configurations it would silently mis-time."""

import sys

import pytest

sys.path.insert(0, "/root/repo")

import bench  # noqa: E402
from nanosandbox_tpu.utils.benchmarking import measure_train_throughput  # noqa: E402


def test_bench_batch_size_is_per_chip(tmp_path):
    """--batch_size=N means N sequences PER CHIP: the global batch scales
    with the chip count instead of silently shrinking per-chip work."""
    for n_chips in (1, 8):
        cfg, _, _ = bench.build_config(
            {"batch_size": "16"}, on_tpu=True, n_chips=n_chips,
            tmp=str(tmp_path), data_dir=str(tmp_path), quick=True)
        assert cfg.batch_size == 16 * n_chips


def test_bench_default_batch_consistent(tmp_path):
    """No flag -> the documented default per-chip batch, scaled."""
    cfg, _, _ = bench.build_config(
        {}, on_tpu=True, n_chips=4, tmp=str(tmp_path),
        data_dir=str(tmp_path), quick=True)
    assert cfg.batch_size == 16 * 4
    cfg, _, _ = bench.build_config(
        {}, on_tpu=False, n_chips=1, tmp=str(tmp_path),
        data_dir=str(tmp_path), quick=True)
    assert cfg.batch_size == 8


def test_bench_iters_and_impl_flags(tmp_path):
    cfg, warmup, iters = bench.build_config(
        {"iters": "7", "impl": "xla"}, on_tpu=True, n_chips=1,
        tmp=str(tmp_path), data_dir=str(tmp_path), quick=False)
    assert iters == 7
    assert warmup >= 1
    assert cfg.attention_impl == "xla"


def test_measure_train_throughput_rejects_zero_warmup(tiny_cfg):
    """warmup=0 used to NameError on the sync line AND mis-time (no sync
    before t0); now it fails loudly at the API boundary."""
    with pytest.raises(ValueError, match="warmup"):
        measure_train_throughput(tiny_cfg, 0, 1)
