"""bench.py / perf_sweep contract tests (round-2 VERDICT weak #4-#5,
ADVICE r2): batch-size semantics are per-chip everywhere, and the
measurement helper rejects configurations it would silently mis-time."""

import sys

import pytest

sys.path.insert(0, "/root/repo")

import bench  # noqa: E402
from nanosandbox_tpu.utils.benchmarking import measure_train_throughput  # noqa: E402


def test_bench_batch_size_is_per_chip(tmp_path):
    """--batch_size=N means N sequences PER CHIP: the global batch scales
    with the chip count instead of silently shrinking per-chip work."""
    for n_chips in (1, 8):
        cfg, _, _ = bench.build_config(
            {"batch_size": "16"}, on_tpu=True, n_chips=n_chips,
            tmp=str(tmp_path), data_dir=str(tmp_path), quick=True)
        assert cfg.batch_size == 16 * n_chips


def test_bench_default_batch_consistent(tmp_path):
    """No flag -> the documented default per-chip batch, scaled."""
    cfg, _, _ = bench.build_config(
        {}, on_tpu=True, n_chips=4, tmp=str(tmp_path),
        data_dir=str(tmp_path), quick=True)
    assert cfg.batch_size == 16 * 4
    cfg, _, _ = bench.build_config(
        {}, on_tpu=False, n_chips=1, tmp=str(tmp_path),
        data_dir=str(tmp_path), quick=True)
    assert cfg.batch_size == 8


def test_bench_iters_and_impl_flags(tmp_path):
    cfg, warmup, iters = bench.build_config(
        {"iters": "7", "impl": "xla"}, on_tpu=True, n_chips=1,
        tmp=str(tmp_path), data_dir=str(tmp_path), quick=False)
    assert iters == 7
    assert warmup >= 1
    assert cfg.attention_impl == "xla"


def test_measure_train_throughput_rejects_zero_warmup(tiny_cfg):
    """warmup=0 used to NameError on the sync line AND mis-time (no sync
    before t0); now it fails loudly at the API boundary."""
    with pytest.raises(ValueError, match="warmup"):
        measure_train_throughput(tiny_cfg, 0, 1)


def test_bench_serve_mode_overload_sweep():
    """--mode=serve contract (ISSUE 10): every sweep point carries
    goodput_toks / slo_attainment / shed_rate, a 1x and a 2x arrival
    point exist, the burst point actually sheds, and every shed Result
    has exactly one terminal `shed` flight event (the ledger cross-check
    is computed inside bench_serve from the same engine)."""
    import jax  # noqa: F401  (engine import path needs a jax process)

    result = bench.bench_serve(
        {"num_slots": "4", "requests": "8", "burst": "6"},
        quick=True, on_tpu=False)
    extra = result["extra"]
    assert result["unit"] == "tokens/sec" and result["value"] >= 0
    assert extra["capacity_toks_per_sec"] > 0
    sweep = extra["sweep"]
    assert {"1x", "2x", "burst"} <= set(sweep)
    for point in sweep.values():
        for fld in ("goodput_toks", "goodput_toks_per_sec",
                    "slo_attainment", "shed_rate", "flight_shed_events"):
            assert fld in point, (point["scenario"], fld)
        assert 0.0 <= point["shed_rate"] <= 1.0
        assert point["slo_attainment"] is None or \
            0.0 <= point["slo_attainment"] <= 1.0
        # ledger agreement: shed Results == terminal shed flight events
        assert point["flight_shed_events"] == point["shed"]
    # the burst point is built to overload: sheds must actually happen,
    # or the queue-expiry path is dead code
    assert sweep["burst"]["shed"] > 0
    assert sweep["burst"]["slo_attainment"] < 1.0
    import json as _json
    _json.dumps(result)              # the CI artifact must serialize
