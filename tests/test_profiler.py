"""--profile_steps trace capture (SURVEY.md §5 tracing hook point).

The reference has no profiler at all; this repo's runbook advertises
jax.profiler traces next to the TB events, and round 1 shipped the doc
without the code (VERDICT.md weak: 'zero jax.profiler code hooks in the
trainer'). This test pins the hook: a smoke run with --profile_steps
leaves a non-empty trace directory under resolved_log_dir/profile.
"""

import os

import pytest


def test_profile_steps_writes_trace(tiny_cfg):
    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(max_iters=4, profile_steps="1:3",
                           eval_interval=0, log_interval=1)
    Trainer(cfg).run()
    prof = os.path.join(cfg.resolved_log_dir, "profile")
    assert os.path.isdir(prof), "profile dir missing"
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert found, "profiler produced no trace files"
    assert any(os.path.getsize(f) > 0 for f in found)


def test_profile_steps_validation(tiny_cfg):
    # Validated at config construction — before any loader threads or
    # writer file handles exist that a mid-run raise would leak.
    with pytest.raises(ValueError, match="profile_steps"):
        tiny_cfg.replace(profile_steps="3:3")
    with pytest.raises(ValueError, match="profile_steps"):
        tiny_cfg.replace(profile_steps="abc")
    with pytest.raises(ValueError, match="profile_steps"):
        tiny_cfg.replace(profile_steps="1:2:3")


def test_profile_stops_cleanly_when_run_ends_inside_window(tiny_cfg):
    """max_iters inside [a, b): the finally block must stop the trace so
    the process doesn't leak an active profiler session."""
    from nanosandbox_tpu.train import Trainer

    cfg = tiny_cfg.replace(max_iters=2, profile_steps="1:10",
                           eval_interval=0)
    trainer = Trainer(cfg)
    trainer.run()
    assert trainer._profiling is False
