#!/usr/bin/env bash
# Step 1 — provision a cluster that can schedule the training workloads.
#
# TPU-native successor of the reference's scripts/01_install_k3s_gpu_operator.sh
# (described at /root/reference/README.md:28-32: k3s + NVIDIA GPU Operator so
# pods can request nvidia.com/gpu). On GKE, TPU node pools ship their device
# plugin — there is nothing to install; this script therefore has two modes:
#
#   MODE=gke   print/run the gcloud commands creating a TPU node-pool cluster
#              (the google.com/tpu resource appears automatically)
#   MODE=kind  create a local kind cluster for CPU-only validation of the
#              manifests (the reference's scale-down testing philosophy,
#              SURVEY.md §4 — every distributed feature has a no-hardware repro)
#
# Usage: MODE=kind bash scripts/01_install_cluster.sh
set -euo pipefail

MODE="${MODE:-kind}"
CLUSTER_NAME="${CLUSTER_NAME:-disttrain}"

case "$MODE" in
  gke)
    : "${GCP_PROJECT:?set GCP_PROJECT}"
    : "${GCP_ZONE:?set GCP_ZONE (a TPU zone, e.g. us-central2-b)}"
    TPU_TYPE="${TPU_TYPE:-tpu-v4-podslice}"
    TPU_TOPOLOGY="${TPU_TOPOLOGY:-2x2x1}"
    cat <<EOF
# Run these (requires gcloud auth):
gcloud container clusters create ${CLUSTER_NAME} \\
  --project ${GCP_PROJECT} --zone ${GCP_ZONE} --num-nodes 1
gcloud container node-pools create tpu-pool \\
  --project ${GCP_PROJECT} --zone ${GCP_ZONE} --cluster ${CLUSTER_NAME} \\
  --machine-type ct4p-hightpu-4t \\
  --tpu-topology ${TPU_TOPOLOGY} --num-nodes 1
# Validate the device plugin exposes the TPU resource:
kubectl get nodes -o json | jq '.items[].status.allocatable["google.com/tpu"]'
EOF
    ;;
  kind)
    if ! command -v kind >/dev/null 2>&1; then
      echo "kind not installed — install from https://kind.sigs.k8s.io" >&2
      echo "(CPU-only manifest validation also works with any k8s cluster)" >&2
      exit 1
    fi
    kind create cluster --name "${CLUSTER_NAME}" --wait 120s
    kubectl cluster-info --context "kind-${CLUSTER_NAME}"
    ;;
  *)
    echo "unknown MODE=${MODE} (expected gke|kind)" >&2
    exit 2
    ;;
esac
