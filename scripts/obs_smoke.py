#!/usr/bin/env python
"""CI smoke for the telemetry spine: start the serve HTTP frontend,
scrape /metrics, validate the Prometheus exposition with a stdlib
parser, fetch a /trace export and check its Chrome trace-event schema,
hit the /debug introspection surface (requests / slots / kvpool /
scheduler) and schema-validate a flight-recorder JSONL dump.

Runs the REAL frontend (EngineLoop + make_server) over a tiny randomly
initialized model — the wiring under test is the observability surface,
not the weights — so the scrape exercises exactly the handler, renderer
and registry path a k8s Prometheus hits in deployment.

    JAX_PLATFORMS=cpu python scripts/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Exposition grammar (the subset we emit): HELP/TYPE comments and
# `name{labels} value` samples — what a scraper's parser accepts.
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\]|\\.)*"(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
    r'(-?[0-9.eE+-]+|[+-]Inf|NaN)$')
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def validate_exposition(text: str) -> dict[str, str]:
    """Parse the text format with stdlib only; returns {metric: type}.
    Raises AssertionError on any line the grammar rejects."""
    types: dict[str, str] = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("#"):
            assert _COMMENT_RE.match(ln), f"bad comment line: {ln!r}"
            parts = ln.split(" ", 3)
            if parts[1] == "TYPE":
                assert parts[2] not in types, f"duplicate TYPE {parts[2]}"
                types[parts[2]] = parts[3]
        else:
            assert _SAMPLE_RE.match(ln), f"bad sample line: {ln!r}"
    assert types, "no TYPE lines in exposition"
    return types


def validate_flight_jsonl(text: str) -> list[dict]:
    """Schema-validate a flight-recorder JSONL dump: every line is one
    JSON object carrying the event keys the playbook documents."""
    events = []
    for ln in text.splitlines():
        e = json.loads(ln)
        assert isinstance(e, dict), e
        assert {"t", "ev", "rid", "wall"} <= set(e), e
        assert isinstance(e["ev"], str) and e["ev"]
        assert e["rid"] is None or isinstance(e["rid"], int)
        assert isinstance(e["t"], (int, float)) and e["t"] >= 0
        assert isinstance(e["wall"], (int, float))
        events.append(e)
    assert events, "empty flight dump"
    return events


def validate_chrome_trace(trace: dict) -> None:
    assert set(trace) >= {"traceEvents"}, trace.keys()
    events = trace["traceEvents"]
    assert events, "empty traceEvents"
    for ev in events:
        assert ev["ph"] in ("X", "M"), ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float))
            assert isinstance(ev["dur"], (int, float))
            assert ev["dur"] >= 0
    assert any(ev["ph"] == "X" for ev in events), "no complete events"


def main() -> int:
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.serve import Engine
    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=64, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = Engine(model, params, num_slots=4, max_len=64)
    # The startup step `python -m nanosandbox_tpu.serve` performs: the
    # pinned shardcheck comms budget rides /metrics as
    # shardcheck_collectives_total{program=,kind=} gauges.
    from nanosandbox_tpu.analysis.shardcheck import (export_manifest_metrics,
                                                     load_budget)
    from nanosandbox_tpu.obs import global_registry

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    export_manifest_metrics(
        load_budget(os.path.join(repo_root, "budgets", "serve_cpu8.json")),
        global_registry())
    # The concurrency-analysis twin (ISSUE 18): the lockcheck report
    # over the package tree and a seeded schedule-fuzz run both ride
    # /metrics, so a scrape shows the host-concurrency posture next to
    # the comms budget.
    from nanosandbox_tpu.analysis.lockcheck import (analyze_paths,
                                                    export_report_metrics,
                                                    load_lock_order)
    from nanosandbox_tpu.utils import schedcheck

    order_file = os.path.join(repo_root, "budgets", "lock_order.json")
    export_report_metrics(
        analyze_paths([os.path.join(repo_root, "nanosandbox_tpu")],
                      lock_order=load_lock_order(order_file)),
        global_registry())
    fuzz = schedcheck.fuzz_router(0, order=schedcheck.load_order(order_file))
    fuzz.assert_clean()
    fuzz.export_metrics(global_registry())
    # Host-health gauges the deployment registers at startup.
    from nanosandbox_tpu.obs import register_process_vitals

    register_process_vitals()
    loop = EngineLoop(engine)
    loop.start()
    encode = lambda s: [min(ord(c), cfg.vocab_size - 1) for c in s]  # noqa: E731
    decode = lambda ids: " ".join(str(i) for i in ids)  # noqa: E731
    srv = make_server("127.0.0.1", 0, loop, encode, decode)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def get(path: str):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=60) as r:
            return r.read()

    try:
        # Traffic first, so the scrape carries real latency samples.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "hello", "max_new_tokens": 8,
                             "temperature": 0.0, "deadline_s": 60.0,
                             "slo_class": "interactive"}).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            gen = json.loads(r.read())
        assert len(gen["tokens"]) == 8, gen
        rid = gen["id"]

        text = get("/metrics").decode()
        types = validate_exposition(text)
        for required in ("serve_ttft_seconds", "serve_tpot_seconds",
                         "serve_decode_tokens_per_sec",
                         "serve_queue_depth", "serve_tokens_generated_total",
                         "serve_compile_traces_total",
                         # host vitals (ISSUE 10 satellite)
                         "process_resident_memory_bytes",
                         "process_open_fds", "process_uptime_seconds",
                         "jax_live_buffer_bytes",
                         # SLO ledger: the deadline-carrying request above
                         "serve_slo_requests_total",
                         "serve_goodput_tokens_total",
                         "serve_slo_attainment"):
            assert required in types, (required, sorted(types))
        assert 'serve_slo_requests_total{slo_class="interactive",' \
            'outcome="met"} 1' in text, "SLO outcome missing from scrape"
        assert types["serve_ttft_seconds"] == "histogram"
        assert "serve_ttft_seconds_window" in types  # percentile summary
        # The pinned comms contract is on the scrape: every serve
        # program's collective count (zero today — single-chip).
        assert "shardcheck_collectives_total" in types, sorted(types)
        assert 'shardcheck_collectives_total{program="decode",' \
            in text, "decode gauge missing from exposition"
        # The concurrency posture is on the scrape too: a clean
        # lockcheck tree and a violation-free schedule-fuzz run.
        assert "lockcheck_findings_total" in types, sorted(types)
        assert 'lockcheck_findings_total{rule="none"} 0' in text, \
            "lockcheck tree not clean (or export missing)"
        assert "schedcheck_violations_total" in types, sorted(types)
        assert "schedcheck_violations_total 0" in text, \
            "schedule fuzz recorded violations"
        assert "schedcheck_acquires_total" in types, sorted(types)

        trace = json.loads(get(f"/trace?rid={rid}"))
        validate_chrome_trace(trace)
        names = {ev["name"] for ev in trace["traceEvents"]}
        assert {"queued", "generate"} <= names, names

        window = json.loads(get("/trace?last_s=600"))
        validate_chrome_trace(window)

        # Flight-recorder surface (ISSUE 10): the rid's lifecycle track
        # as JSON, the JSONL dump schema-validated, and a terminal
        # `finish` exactly once. The HTTP layer appends the returned
        # status AFTER the terminal (ISSUE 11 status hygiene) — the
        # client's 200 next to the engine's finish.
        track = json.loads(get(f"/debug/requests?rid={rid}"))["events"]
        evs = [e["ev"] for e in track]
        assert evs[0] == "submit" and evs[-1] == "http", evs
        assert track[-1]["status"] == 200, track[-1]
        assert "admit" in evs and "prefill" in evs, evs
        assert evs.count("finish") == 1, evs
        assert evs.index("finish") == len(evs) - 2, evs
        flight = validate_flight_jsonl(
            get("/debug/requests?format=jsonl").decode())
        assert any(e["ev"] == "finish" and e["rid"] == rid
                   for e in flight), "rid's finish missing from dump"

        # Live introspection endpoints.
        slots = json.loads(get("/debug/slots"))
        assert slots["num_slots"] == 4, slots
        assert len(slots["slots"]) == 4
        pool = json.loads(get("/debug/kvpool"))
        assert pool["paged"] is True, pool
        assert {"free", "live", "cached", "fragmentation",
                "trie"} <= set(pool), sorted(pool)
        assert pool["free"] + pool["live"] + pool["cached"] \
            == pool["num_blocks"], pool
        sched = json.loads(get("/debug/scheduler"))
        assert {"queue", "free_slots", "prefill_buckets",
                "shed"} <= set(sched), sorted(sched)
        # the fleet router's authoritative index-refresh surface
        # (ISSUE 15): enabled on this paged engine, digests are the
        # 16-hex chained block fingerprints
        summary = json.loads(get("/debug/prefix_summary"))
        assert summary["enabled"] is True, summary
        assert summary["page"] == 16, summary
        assert summary["blocks"] == len(summary["digests"])
        assert all(isinstance(d, str) and len(d) == 16
                   for d in summary["digests"]), summary

        health = json.loads(get("/healthz"))
        assert health == {"ok": True}, health
        print(f"obs smoke OK: {len(types)} metric families, "
              f"{len(trace['traceEvents'])} trace events and "
              f"{len(track)} flight events for rid {rid}, "
              f"{len(flight)} flight events dumped")
        return 0
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


if __name__ == "__main__":
    sys.exit(main())
