#!/usr/bin/env python
"""CI smoke for the telemetry spine: start the serve HTTP frontend,
scrape /metrics, validate the Prometheus exposition with a stdlib
parser, fetch a /trace export and check its Chrome trace-event schema.

Runs the REAL frontend (EngineLoop + make_server) over a tiny randomly
initialized model — the wiring under test is the observability surface,
not the weights — so the scrape exercises exactly the handler, renderer
and registry path a k8s Prometheus hits in deployment.

    JAX_PLATFORMS=cpu python scripts/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Exposition grammar (the subset we emit): HELP/TYPE comments and
# `name{labels} value` samples — what a scraper's parser accepts.
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\]|\\.)*"(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
    r'(-?[0-9.eE+-]+|[+-]Inf|NaN)$')
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def validate_exposition(text: str) -> dict[str, str]:
    """Parse the text format with stdlib only; returns {metric: type}.
    Raises AssertionError on any line the grammar rejects."""
    types: dict[str, str] = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("#"):
            assert _COMMENT_RE.match(ln), f"bad comment line: {ln!r}"
            parts = ln.split(" ", 3)
            if parts[1] == "TYPE":
                assert parts[2] not in types, f"duplicate TYPE {parts[2]}"
                types[parts[2]] = parts[3]
        else:
            assert _SAMPLE_RE.match(ln), f"bad sample line: {ln!r}"
    assert types, "no TYPE lines in exposition"
    return types


def validate_chrome_trace(trace: dict) -> None:
    assert set(trace) >= {"traceEvents"}, trace.keys()
    events = trace["traceEvents"]
    assert events, "empty traceEvents"
    for ev in events:
        assert ev["ph"] in ("X", "M"), ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float))
            assert isinstance(ev["dur"], (int, float))
            assert ev["dur"] >= 0
    assert any(ev["ph"] == "X" for ev in events), "no complete events"


def main() -> int:
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.serve import Engine
    from nanosandbox_tpu.serve.http import EngineLoop, make_server

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=64, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = Engine(model, params, num_slots=4, max_len=64)
    # The startup step `python -m nanosandbox_tpu.serve` performs: the
    # pinned shardcheck comms budget rides /metrics as
    # shardcheck_collectives_total{program=,kind=} gauges.
    from nanosandbox_tpu.analysis.shardcheck import (export_manifest_metrics,
                                                     load_budget)
    from nanosandbox_tpu.obs import global_registry

    export_manifest_metrics(
        load_budget(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "budgets", "serve_cpu8.json")),
        global_registry())
    loop = EngineLoop(engine)
    loop.start()
    encode = lambda s: [min(ord(c), cfg.vocab_size - 1) for c in s]  # noqa: E731
    decode = lambda ids: " ".join(str(i) for i in ids)  # noqa: E731
    srv = make_server("127.0.0.1", 0, loop, encode, decode)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def get(path: str):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=60) as r:
            return r.read()

    try:
        # Traffic first, so the scrape carries real latency samples.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "hello", "max_new_tokens": 8,
                             "temperature": 0.0}).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            gen = json.loads(r.read())
        assert len(gen["tokens"]) == 8, gen
        rid = gen["id"]

        text = get("/metrics").decode()
        types = validate_exposition(text)
        for required in ("serve_ttft_seconds", "serve_tpot_seconds",
                         "serve_decode_tokens_per_sec",
                         "serve_queue_depth", "serve_tokens_generated_total",
                         "serve_compile_traces_total"):
            assert required in types, (required, sorted(types))
        assert types["serve_ttft_seconds"] == "histogram"
        assert "serve_ttft_seconds_window" in types  # percentile summary
        # The pinned comms contract is on the scrape: every serve
        # program's collective count (zero today — single-chip).
        assert "shardcheck_collectives_total" in types, sorted(types)
        assert 'shardcheck_collectives_total{program="decode",' \
            in text, "decode gauge missing from exposition"

        trace = json.loads(get(f"/trace?rid={rid}"))
        validate_chrome_trace(trace)
        names = {ev["name"] for ev in trace["traceEvents"]}
        assert {"queued", "generate"} <= names, names

        window = json.loads(get("/trace?last_s=600"))
        validate_chrome_trace(window)

        health = json.loads(get("/healthz"))
        assert health == {"ok": True}, health
        print(f"obs smoke OK: {len(types)} metric families, "
              f"{len(trace['traceEvents'])} trace events for rid {rid}")
        return 0
    finally:
        srv.shutdown()
        srv.server_close()
        loop.stop()


if __name__ == "__main__":
    sys.exit(main())
