#!/usr/bin/env bash
# Idempotent GitHub project sync: label taxonomy + issue backlog.
#
# Bash port of the reference's scripts/gh_sync.ps1 (structure:
# Get-RepoSlug :5-15, Ensure-Label GET->PATCH/POST :17-35, Ensure-Issue
# search-by-title->edit/create :37-49, auth preflight :51-57, 24-label
# table :63-97, 11-issue table :103-159), retargeted to this TPU stack:
# area:gpu becomes area:tpu, the training labels name JAX/pjit instead of
# PyTorch/DDP, and the backlog tracks the TPU build's components.
#
# DRY_RUN=1 prints every action instead of calling gh — used by
# tests/test_ops.py and safe to run anywhere.
set -euo pipefail

DRY_RUN="${DRY_RUN:-0}"

run_gh() {
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: gh $*"
  else
    gh "$@" >/dev/null
  fi
}

# --- preflight (gh present + authenticated; ps1:51-57) ----------------------
if [[ "$DRY_RUN" != "1" ]]; then
  command -v gh >/dev/null || { echo "gh CLI not installed" >&2; exit 1; }
  gh auth status >/dev/null || { echo "gh not authenticated" >&2; exit 1; }
fi

# --- repo slug from the origin remote (ps1:5-15) ----------------------------
repo_slug() {
  local url
  url="$(git remote get-url origin 2>/dev/null || true)"
  url="${url%.git}"
  if [[ "$url" =~ github\.com[:/]([^/]+/[^/]+)$ ]]; then
    echo "${BASH_REMATCH[1]}"
  else
    echo ""
  fi
}
REPO="${REPO:-$(repo_slug)}"
if [[ -z "$REPO" ]]; then
  echo "cannot derive repo slug from origin remote; set REPO=owner/name" >&2
  if [[ "$DRY_RUN" == "1" ]]; then
    REPO="example/tpu-disttrain"
  else
    exit 1
  fi
fi
echo "Using repo: $REPO"

# --- label taxonomy (24 labels; ps1:63-97 adapted to the TPU stack) ---------
# format: name|color|description
LABELS=(
  "type:bug|d73a4a|Something isn't working"
  "type:enhancement|a2eeef|New feature or improvement"
  "type:documentation|0075ca|Docs, README, or playbook work"
  "type:task|cfd3d7|Actionable task"
  "type:chore|d4c5f9|Build, tooling, maintenance"
  "area:k8s|0e8a16|Kubernetes manifests & cluster"
  "area:tpu|1f883d|TPU runtime, libtpu, device plugin, ICI"
  "area:docker|0366d6|Dockerfiles and images"
  "area:data|fbca04|Datasets and storage"
  "area:training|5319e7|JAX training core, pjit sharding, model config"
  "area:monitoring|a2eeef|Logs, metrics, TensorBoard, profiler"
  "area:ci|d876e3|CI/CD scripts and workflows"
  "priority:P0|b60205|Critical"
  "priority:P1|d93f0b|High"
  "priority:P2|fbca04|Medium"
  "priority:P3|e4e669|Low"
  "status:blocked|e11d21|Blocked on external dependency"
  "status:needs-info|c5def5|Needs clarification or data"
  "status:ready|0e8a16|Ready to pick up"
  "good first issue|7057ff|Good for newcomers"
  "help wanted|008672|Contributions welcome"
  "size:XS|ededed|< 30 min"
  "size:S|c5def5|~1-2 hours"
  "size:M|bfdadc|~1 day"
  "size:L|c2e0c6|> 1 day"
  "security|ee0701|Security implications"
  "question|d876e3|Further information requested"
)

ensure_label() {
  local name="$1" color="$2" desc="$3"
  if [[ "$DRY_RUN" != "1" ]] && gh api \
      "repos/${REPO}/labels/$(printf %s "$name" | sed 's/ /%20/g')" \
      >/dev/null 2>&1; then
    run_gh api -X PATCH "repos/${REPO}/labels/${name}" \
      -f new_name="$name" -f color="$color" -f description="$desc"
  else
    # Tolerate ONLY the already-exists race (two syncs colliding); any
    # other failure (auth scope, rate limit) must stop the script.
    if ! out="$(run_gh api -X POST "repos/${REPO}/labels" \
          -f name="$name" -f color="$color" -f description="$desc" 2>&1)"; then
      if [[ "$out" != *"already_exists"* ]]; then
        echo "$out" >&2
        exit 1
      fi
    elif [[ "$DRY_RUN" == "1" ]]; then
      echo "$out"
    fi
  fi
}

echo "Syncing labels..."
for row in "${LABELS[@]}"; do
  IFS='|' read -r name color desc <<<"$row"
  ensure_label "$name" "$color" "$desc"
done

# --- issue backlog (ps1:103-159 adapted; doubles as the component list) -----
ensure_issue() {
  local title="$1" body="$2" labels="$3"
  local existing=""
  if [[ "$DRY_RUN" != "1" ]]; then
    existing="$(gh issue list --repo "$REPO" --state all \
      --search "in:title \"$title\"" --json number,title \
      --jq ".[] | select(.title == \"$title\") | .number" | head -1)"
  fi
  if [[ -n "$existing" ]]; then
    run_gh issue edit "$existing" --repo "$REPO" --add-label "$labels"
  else
    run_gh issue create --repo "$REPO" --title "$title" --body "$body" \
      --label "$labels"
  fi
}

echo "Creating issues..."
ensure_issue "Configure corporate proxy for Pods and builds" \
  "Set HTTP_PROXY/HTTPS_PROXY/NO_PROXY in k8s/01-proxy-config.yaml and verify egress for dataset prep; keep the JAX coordinator rendezvous on NO_PROXY." \
  "type:task,area:k8s,priority:P0,status:ready,size:S"
ensure_issue "Provision TPU cluster (GKE node pool or kind for CI)" \
  "MODE=gke scripts/01_install_cluster.sh creates the TPU node pool; validate google.com/tpu is allocatable. MODE=kind for CPU-only manifest validation." \
  "type:task,area:k8s,area:tpu,priority:P0,status:ready,size:S"
ensure_issue "Build and load jax[tpu] training image" \
  "Use scripts/02_build_and_load_image.sh (TARGET=kind|k3s|push) to build docker/Dockerfile and make it pullable by the cluster." \
  "type:task,area:docker,priority:P1,status:ready,size:S"
ensure_issue "Create storage (hostPath single-node or Filestore RWX) and verify write perms" \
  "STORAGE=hostpath|filestore scripts/03_apply_basics.sh; ensure Pods can write /data." \
  "type:task,area:k8s,priority:P1,status:ready,size:S"
ensure_issue "Dataset job: tiny Shakespeare char-level" \
  "Run k8s/jobs/20-download-tiny-shakespeare.yaml to generate train/val bins at /data/datasets/shakespeare_char." \
  "type:task,area:data,priority:P1,status:ready,size:S"
ensure_issue "Single-Pod multi-chip training (v4-8 host)" \
  "Run k8s/jobs/30-train-singlepod.yaml requesting google.com/tpu: 4; pjit data-parallels over the local chips in one SPMD process." \
  "type:enhancement,area:training,area:tpu,priority:P1,status:ready,size:M"
ensure_issue "Validate multi-Pod multi-host StatefulSet" \
  "Headless Service + StatefulSet(4 replicas): jax.distributed.initialize rendezvous via pod-0 DNS, ordinal-derived process_id, end-to-end training." \
  "type:task,area:k8s,area:training,priority:P1,status:ready,size:M"
ensure_issue "TensorBoard: document workflow and logdir conventions" \
  "Document reading TensorBoard + jax.profiler logs from /data/runs and safe copying off-cluster without exposing a service." \
  "type:documentation,area:monitoring,priority:P2,status:ready,size:S"
ensure_issue "Add medium dataset Job (OpenWebText subset)" \
  "k8s/jobs/21-download-openwebtext.yaml streams an OWT subset, size via DATASET_NUM_CHARS env." \
  "type:enhancement,area:data,priority:P2,status:ready,size:M,good first issue"
ensure_issue "Document ICI/DCN collective mapping (replaces NCCL presets)" \
  "docs/collectives.md: how XLA places all-reduce on ICI within a slice and DCN across slices; what replaced NCCL_IB_DISABLE/SOCKET_IFNAME." \
  "type:documentation,area:training,area:tpu,priority:P2,status:ready,size:S"
ensure_issue "Add CI: lint YAML and shell scripts, run pytest tiers" \
  "GitHub Actions workflow: manifest/shell lint (tests/test_deploy.py) plus the JAX-CPU test tiers." \
  "type:chore,area:ci,priority:P3,status:ready,size:S,help wanted"

echo "Done."
