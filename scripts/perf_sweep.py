"""Throughput sweep on the current backend: impl x batch x remat x chunk.

Promotes round-1's perf_probe.py scratch script into a proper JSON-emitting
tool (VERDICT.md next-step #4). Each point trains GPT-2 124M (or a tiny
model on CPU) for a few timed steps and records tokens/sec/chip + MFU;
results stream to stdout as JSON lines and are summarized at the end.

Usage:
    python scripts/perf_sweep.py [--out=sweep.json] [--iters=10]
        [--impls=pallas,xla] [--batch_sizes=8,16,32,64] [--full]
        [--mode=remat|longcontext|scale]

Default sweeps impl x batch at remat=False/chunk=128, then re-measures the
winner with remat on/off and chunked vs full loss. --full crosses
everything (slow). --mode presets replace the grid (and take precedence
over --full): 'remat' compares no-remat vs remat_policy
save_attention/full per batch size; 'longcontext' measures block 8192
with chunked loss; 'scale' measures 350M/760M single-chip points;
'decode' measures KV-cached vs windowed generation tok/s; 'autoconfig'
measures the UNPINNED flag surface of a real config file
(--config=configs/train_gpt2_124m_....py) so the headline number is
proven for the command a user actually types, not just bench.py's
hand-pinned flags.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from nanosandbox_tpu.utils.benchmarking import measure_train_throughput


def main(argv: list[str]) -> list[dict]:
    kv = dict(a.lstrip("-").split("=", 1) for a in argv if "=" in a)
    full = "--full" in argv
    import jax

    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.data.prepare import prepare_char_dataset

    on_tpu = jax.default_backend() == "tpu"
    n_chips = len(jax.devices())
    tmp = tempfile.mkdtemp(prefix="sweep_")
    data_dir = os.path.join(tmp, "data")
    prepare_char_dataset(os.path.join(data_dir, "shakespeare_char"),
                         allow_synthetic=True,
                         url="http://invalid.localhost/offline")

    if on_tpu:
        base = TrainConfig(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char", vocab_size=50304,
            n_layer=12, n_head=12, n_embd=768, block_size=1024,
            max_iters=0, eval_interval=0, dropout=0.0,
            compute_dtype="bfloat16", tensorboard=False)
        impls = kv.get("impls", "pallas,xla,pallas_jax").split(",")
        batches = [int(b) for b in kv.get("batch_sizes", "8,16,32,64").split(",")]
        warmup, iters = 2, int(kv.get("iters", 10))
    else:
        base = TrainConfig(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char",
            n_layer=2, n_head=2, n_embd=64, block_size=128,
            max_iters=0, eval_interval=0, dropout=0.0,
            compute_dtype="float32", tensorboard=False)
        impls = kv.get("impls", "xla").split(",")
        batches = [int(b) for b in kv.get("batch_sizes", "8").split(",")]
        warmup, iters = 1, int(kv.get("iters", 3))

    results = []

    def record(point, cfg):
        """Measure cfg, merge into the point dict, stream + collect it —
        errors become recorded rows, never crashes (the tunnel's
        remote-compile 500s land here)."""
        try:
            point.update(measure_train_throughput(cfg, warmup, iters))
        except Exception as e:
            point["error"] = f"{type(e).__name__}: {str(e)[:200]}"
        print(json.dumps(point), flush=True)
        results.append(point)
        return point

    def run_point(**overrides):
        # batch_size values are PER-CHIP (same semantics as bench.py, so
        # sweep points stay comparable to bench output on any host size);
        # the global batch scales with the chip count. The recorded point
        # keeps the per-chip value so re-feeding a winner doesn't rescale.
        point = {k: overrides[k] for k in sorted(overrides)}
        if "batch_size" in overrides:
            overrides = dict(overrides,
                             batch_size=overrides["batch_size"] * n_chips)
        cfg = base.replace(**overrides)
        point["global_batch_size"] = cfg.batch_size
        return record(point, cfg)

    mode = kv.get("mode", "")
    if mode and full:
        print(json.dumps({"warning": "--full is ignored when --mode is "
                                     "given"}), flush=True)
    if mode and mode not in ("remat", "longcontext", "scale", "decode",
                             "autoconfig", "statlayout"):
        raise SystemExit(f"unknown --mode={mode} (expected 'remat', "
                         "'longcontext', 'scale', 'decode', 'autoconfig', "
                         "or 'statlayout')")
    if mode == "decode":
        results.extend(_decode_mode(kv, on_tpu))
    elif mode == "autoconfig":
        # VERDICT r3 next #8: bench.py hand-pins the fast flags; this
        # measures the config FILE's own flag surface (attention_impl
        # auto, loss_chunk_size auto, remat as written) so the recorded
        # headline holds for `python -m nanosandbox_tpu.train <config>`.
        cfg_path = kv.get("config")
        if not cfg_path:
            raise SystemExit("--mode=autoconfig requires --config=<file.py>")
        from nanosandbox_tpu.config import load_config

        user = load_config([cfg_path])
        # resolved_loss_chunk_size is reported by measure_train_throughput
        # from the Trainer that actually runs — never recomputed here,
        # which would silently desync from train.py's resolution.
        point = {"mode": "autoconfig", "config": os.path.basename(cfg_path),
                 "attention_impl": user.attention_impl,
                 "loss_chunk_size": user.loss_chunk_size,
                 "remat": user.remat, "batch_size": user.batch_size}
        cfg = user.replace(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char", vocab_size=user.vocab_size or 50304,
            max_iters=0, eval_interval=0, tensorboard=False,
            profile_steps="", init_from="scratch")
        record(point, cfg)
    elif mode == "statlayout":
        # A/B the flash-backward stat-operand layout (r3 VERDICT next #6):
        # 'compact' cuts ~128x of lane-replicated stat HBM traffic at the
        # cost of an in-kernel expansion matmul; gradients are bitwise
        # identical (tests/test_attention.py + on-chip parity check).
        # run_point's try/except keeps a Mosaic regression or the
        # tunnel's remote-compile 500 as a recorded error row, not a
        # crash. Also A/B'd at 8k context where stat bytes scale with T.
        for bs in batches:
            for layout in ("replicated", "compact"):
                run_point(attention_impl="pallas", batch_size=bs,
                          loss_chunk_size=0, attention_stat_layout=layout)
        if on_tpu:
            for layout in ("replicated", "compact"):
                run_point(attention_impl="pallas", batch_size=1,
                          block_size=8192, loss_chunk_size=512,
                          attention_stat_layout=layout)
    elif mode == "remat":
        # Round-2 VERDICT weak #2: remat was 35.5% MFU vs 43% without.
        # Compare the selective policy (saves flash residuals, backward
        # never re-runs the forward kernel) against classic full remat
        # and the no-remat ceiling, at the remat configs' batch size.
        # loss_chunk_size pinned to 0 (full logits): the TrainConfig
        # default of 128 would silently put these points on the chunked
        # path, ~10% off the full-logits numbers bench.py reports.
        for bs in batches:
            run_point(attention_impl="pallas", batch_size=bs, remat=False,
                      loss_chunk_size=0)
            for policy in ("save_attention", "full"):
                run_point(attention_impl="pallas", batch_size=bs,
                          remat=True, remat_policy=policy,
                          loss_chunk_size=0)
    elif mode == "scale":
        # Model-size scaling on ONE chip: bigger matmuls feed the MXU
        # better (124M ~39-43% MFU by chip conditions; 350M ~47%; 760M
        # fits in 16 GB HBM only with remat). batch_size here is pinned
        # per point — the known-good HBM fit, not the CLI list.
        # 350M batch 8: full logits for the MFU-ceiling number; the
        # batch-16 remat point pins the chunked loss at 512 (full logits
        # there are 3.3 GB and the lingering allocation makes the NEXT
        # point spill — memory economy is the whole reason to remat).
        run_point(n_layer=24, n_head=16, n_embd=1024, batch_size=8,
                  attention_impl="pallas", remat=False,
                  loss_chunk_size=0)                             # 350M
        run_point(n_layer=24, n_head=16, n_embd=1024, batch_size=16,
                  attention_impl="pallas", remat=True,
                  loss_chunk_size=512)
        run_point(n_layer=36, n_head=20, n_embd=1280, batch_size=8,
                  attention_impl="pallas", remat=True,
                  loss_chunk_size=512)                           # 760M
    elif mode == "longcontext":
        # Round-2 VERDICT weak #1 follow-through: a measured long-context
        # number on this hardware (single chip -> plain flash at T=8192;
        # the ring carries the same kernel across chips). The block-1024
        # default batch list would mostly OOM at 8192 tokens/sequence, so
        # this mode has its own default; --batch_sizes still overrides.
        if "batch_sizes" not in kv:
            batches = [1, 2]
        for bs in batches:
            for remat, policy in [(False, "save_attention"),
                                  (True, "save_attention"), (True, "full")]:
                run_point(attention_impl="pallas", batch_size=bs,
                          block_size=8192, remat=remat, remat_policy=policy,
                          loss_chunk_size=512)
    elif full:
        grid = itertools.product(impls, batches, [False, True], [0, 128])
        for impl, bs, remat, chunk in grid:
            run_point(attention_impl=impl, batch_size=bs, remat=remat,
                      loss_chunk_size=chunk)
    else:
        for impl, bs in itertools.product(impls, batches):
            run_point(attention_impl=impl, batch_size=bs)
        good = [r for r in results if "error" not in r]
        if good:
            best = max(good, key=lambda r: r["tokens_per_sec_per_chip"])
            for remat, chunk in [(True, 128), (False, 0), (True, 0)]:
                run_point(attention_impl=best["attention_impl"],
                          batch_size=best["batch_size"], remat=remat,
                          loss_chunk_size=chunk)

    good = [r for r in results
            if "error" not in r and "tokens_per_sec_per_chip" in r]
    if good:
        best = max(good, key=lambda r: r["tokens_per_sec_per_chip"])
        print(json.dumps({"best": best}), flush=True)
    if "out" in kv:
        with open(kv["out"], "w") as f:
            json.dump(results, f, indent=1)
    return results


def _decode_mode(kv, on_tpu) -> list[dict]:
    """KV-cached vs sliding-window decode throughput (VERDICT r3 next #3).

    Both paths run as ONE jit-compiled program (prefill + lax.scan), so the
    comparison isolates the algorithmic difference — cached O(1) model work
    per token vs the windowed path's full block_size re-forward — from
    dispatch overhead. Sync is a token readback, not block_until_ready:
    the tunneled PJRT transport makes the latter a no-op.
    """
    import time
    from functools import partial

    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.sample import (_generate_windowed,
                                        cast_params_for_serving, generate)

    if on_tpu:
        gcfg = GPTConfig(n_layer=12, n_head=12, n_embd=768, block_size=1024,
                         vocab_size=50304, compute_dtype="bfloat16",
                         attention_impl="auto")
        prompt_len = int(kv.get("prompt_len", 64))
        new_tokens = int(kv.get("new_tokens", 448))
        batches = [int(b) for b in kv.get("batch_sizes", "1,8").split(",")]
        reps = int(kv.get("reps", 3))
    else:
        gcfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=128,
                         vocab_size=256, compute_dtype="float32",
                         attention_impl="xla")
        prompt_len, new_tokens, batches, reps = 8, 24, [1], 1

    model = GPT(gcfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # Serve in compute_dtype exactly as sample.py main does: batch-1 decode
    # is weight-read-bound, so f32 params would halve BOTH paths' rates.
    params = cast_params_for_serving(params, gcfg.compute_dtype)
    results = []
    for bs in batches:
        idx = jax.random.randint(jax.random.key(1), (bs, prompt_len), 0,
                                 gcfg.vocab_size, jnp.int32)
        for path, fn in (("cached", generate),
                         ("windowed", _generate_windowed)):
            point = {"mode": "decode", "path": path, "batch_size": bs,
                     "prompt_len": prompt_len, "new_tokens": new_tokens}
            try:
                g = jax.jit(partial(fn, model, max_new_tokens=new_tokens,
                                    temperature=0.8, top_k=40,
                                    block_size=gcfg.block_size))
                out = g(params, idx, rng=jax.random.key(2))
                int(out[0, -1])  # hard sync past compile + warmup
                t0 = time.perf_counter()
                for r in range(reps):
                    out = g(params, idx, rng=jax.random.key(3 + r))
                int(out[0, -1])
                dt = (time.perf_counter() - t0) / reps
                point.update(gen_s=round(dt, 4),
                             decode_tok_per_sec=round(
                                 bs * new_tokens / dt, 1))
            except Exception as e:
                point["error"] = f"{type(e).__name__}: {str(e)[:200]}"
            print(json.dumps(point), flush=True)
            results.append(point)
    return results


if __name__ == "__main__":
    main(sys.argv[1:])
