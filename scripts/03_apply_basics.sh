#!/usr/bin/env bash
# Step 3 — apply namespace + proxy ConfigMap + storage, in order.
#
# Successor of the reference's scripts/03_apply_basics.sh (named at
# /root/reference/.github/ISSUE_TEMPLATE/bug_report.yml:23; bundles the
# README.md:43-45 steps).
# STORAGE=hostpath (default, single-node k3s/kind parity with the reference)
# or STORAGE=filestore (GKE multi-node RWX — required for Workflow B when
# pods land on different TPU hosts).
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
K8S="${REPO_ROOT}/k8s"
STORAGE="${STORAGE:-hostpath}"

kubectl apply -f "${K8S}/00-namespace.yaml"
kubectl apply -f "${K8S}/01-proxy-config.yaml"
case "$STORAGE" in
  hostpath)
    kubectl apply -f "${K8S}/storage/10-pv.yaml"
    kubectl apply -f "${K8S}/storage/11-pvc.yaml"
    ;;
  filestore)
    kubectl apply -f "${K8S}/storage/12-filestore-rwx.yaml"
    ;;
  *) echo "unknown STORAGE=${STORAGE} (expected hostpath|filestore)" >&2; exit 2 ;;
esac

kubectl -n disttrain get pvc disttrain-pvc
echo "basics applied: namespace, proxy-config, PV/PVC"
