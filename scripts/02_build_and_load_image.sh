#!/usr/bin/env bash
# Step 2 — build the training image and make it pullable by the cluster.
#
# Successor of the reference's scripts/02_build_and_load_image.sh
# (/root/reference/README.md:34-38,103: docker build + `k3s ctr` import into
# containerd so imagePullPolicy: IfNotPresent finds it). Three targets:
#
#   TARGET=kind  load into a local kind cluster (CI / manifest validation)
#   TARGET=k3s   import into k3s containerd (the reference's mechanism)
#   TARGET=push  push to a registry (GKE; set IMAGE to the registry path)
#
# Usage: TARGET=kind bash scripts/02_build_and_load_image.sh
set -euo pipefail

IMAGE="${IMAGE:-tpu-disttrain:latest}"
TARGET="${TARGET:-kind}"
CLUSTER_NAME="${CLUSTER_NAME:-disttrain}"
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

docker build -f "${REPO_ROOT}/docker/Dockerfile" -t "${IMAGE}" "${REPO_ROOT}"

case "$TARGET" in
  kind) kind load docker-image "${IMAGE}" --name "${CLUSTER_NAME}" ;;
  k3s)  docker save "${IMAGE}" | sudo k3s ctr images import - ;;
  push) docker push "${IMAGE}" ;;
  *) echo "unknown TARGET=${TARGET} (expected kind|k3s|push)" >&2; exit 2 ;;
esac

echo "image ${IMAGE} ready for target ${TARGET}"
