#!/usr/bin/env bash
# Regenerate the committed shardcheck comms budgets (budgets/*.json).
#
# This is the EXPLICIT ratchet step: budgets only change when a human
# runs this and commits the diff — which is the whole point. A PR that
# legitimately adds communication (e.g. ROADMAP item 1's tensor-parallel
# serving) regenerates here and the budget diff becomes part of its
# review; a PR that fails the CI shardcheck gate without having meant to
# touch comms has found a real accidental collective instead.
#
# Budgets are per-mesh, per-runtime contracts: the provenance block
# records the jax/jaxlib that produced them, and the checker notes a
# drift (regenerate after a pinned-version bump if the check fails).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m nanosandbox_tpu.analysis shardcheck --fleet=train \
    --write-budget=budgets/train_cpu8.json
python -m nanosandbox_tpu.analysis shardcheck --fleet=serve \
    --write-budget=budgets/serve_cpu8.json
# The tensor-parallel serve contract states itself on a PURE model-axis
# mesh (a spectator data axis would leak partitioner layout noise into
# the pinned counts) while keeping the standard 8-device CI bootstrap.
python -m nanosandbox_tpu.analysis shardcheck --fleet=serve_tp \
    --mesh=1,1,1,2 --devices=8 \
    --write-budget=budgets/serve_tp_cpu8.json

echo "regenerated budgets/{train,serve,serve_tp}_cpu8.json —"
echo "review the diff and commit it WITH the change that moved the needle"
