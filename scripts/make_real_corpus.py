#!/usr/bin/env python3
"""Assemble a REAL English-text training corpus from redistributable prose
already present in the image — the zero-egress stand-in for tiny-shakespeare.

Why this exists: the parity metric is "tokens/sec/chip + final val loss"
(BASELINE.json), and the val-loss half is only meaningful on real natural
language. The reference obtains tiny-shakespeare over the network
(its notebook downloads the corpus before training); this environment has
no egress, so instead we harvest human-written English that ships inside
the image and is licensed for verbatim redistribution:

  * documentation files (*.rst, *.md, *.txt) bundled in site-packages
    (numpy/scipy/jax/... docs — BSD/Apache/PSF licensed),
  * the FSF license texts in /usr/share/common-licenses (verbatim
    redistribution explicitly permitted),
  * module/class/function docstrings extracted (via ast, no imports) from
    the .py sources of mainstream scientific-Python packages — genuine
    human-authored English prose under the same permissive licenses.

Everything goes through a prose filter (drops code blocks, tables, markup
lines), is deduplicated at paragraph granularity, normalized to printable
ASCII (keeps the char-level vocab ~90 symbols, matching the
shakespeare-char regime), and emitted in a deterministic order with a
provenance manifest. The result is real English with natural statistics —
word frequencies, syntax, punctuation — on which a char-level LM's val
loss is a meaningful number.

Usage:  python scripts/make_real_corpus.py [--out data/fixtures/english_prose.txt]
                                           [--max_mb 4.0]
"""

from __future__ import annotations

import argparse
import ast
import glob
import hashlib
import os
import re
import sys
import sysconfig

# Packages whose .py docstrings we harvest. Pinned (not "everything in
# site-packages") so the corpus is reproducible and its licensing is
# auditable: all are BSD-3/Apache-2.0/PSF projects.
DOCSTRING_PACKAGES = [
    "numpy", "scipy", "jax", "flax", "optax", "chex", "pandas",
    "sklearn", "matplotlib", "einops", "orbax",
]

# The 'xl' profile extends the harvest for the BPE-regime corpus
# (english_prose_xl.txt): same mechanism, more pinned permissive packages
# (BSD-2/BSD-3/Apache-2.0 all). Kept SEPARATE from the base list because
# the committed 4 MB english_prose.txt is built source-order-dependently
# and truncated — changing the base list would silently change that
# fixture on regeneration and invalidate every recorded char-level loss.
XL_EXTRA_PACKAGES = [
    "torch", "transformers", "tensorflow", "sympy", "networkx",
    "nltk", "keras", "tf_keras", "pygments",
]

DOC_GLOBS = ["**/*.rst", "**/*.md", "**/LICENSE*", "**/*.txt"]

_PRINTABLE = set(chr(c) for c in range(32, 127)) | {"\n"}

# Lines that are markup/code rather than prose.
_NONPROSE_LINE = re.compile(
    r"^\s*(>>>|\.\.\s|:[a-z]+:|[-=~^`#*+_|]{4,}\s*$|\||\+[-+]|@|def |class "
    r"|import |from |return |assert |\$ |#include|//|/\*)")


def _ascii_clean(text: str) -> str:
    out = []
    for ch in text:
        if ch in _PRINTABLE:
            out.append(ch)
        elif ch in "‘’":
            out.append("'")
        elif ch in "“”":
            out.append('"')
        elif ch in "–—":
            out.append("-")
        elif ch == "\t":
            out.append("  ")
        # other non-ASCII dropped (corpus stays char-vocab friendly)
    return "".join(out)


def _is_prose_paragraph(par: str) -> bool:
    """Keep paragraphs that read like English sentences."""
    if len(par) < 120:
        return False
    lines = par.split("\n")
    bad = sum(1 for ln in lines if _NONPROSE_LINE.match(ln))
    if bad * 3 > len(lines):
        return False
    letters = sum(c.isalpha() for c in par)
    if letters / len(par) < 0.62:
        return False
    words = par.split()
    if not words:
        return False
    avg = sum(len(w) for w in words) / len(words)
    if not (2.5 <= avg <= 9.5):
        return False
    # Real sentences contain common function words.
    lower = par.lower()
    hits = sum(1 for w in (" the ", " a ", " of ", " is ", " to ", " and ",
                           " in ", " that ", " for ") if w in lower)
    return hits >= 3


def _paragraphs(text: str):
    text = _ascii_clean(text)
    for par in re.split(r"\n\s*\n", text):
        par = "\n".join(ln.rstrip() for ln in par.strip("\n").split("\n"))
        if par:
            yield par


def harvest_doc_files(roots: list[str], any_name: bool = False):
    files = []
    for root in roots:
        for pat in (["*"] if any_name else DOC_GLOBS):
            files.extend(glob.glob(os.path.join(root, pat), recursive=True))
    files = [f for f in files if os.path.isfile(f)]
    for path in sorted(set(files)):
        try:
            if os.path.getsize(path) > 2_000_000:
                continue
            with open(path, "r", encoding="utf-8", errors="ignore") as f:
                yield path, f.read()
        except OSError:
            continue


def harvest_docstrings(site: str, packages: list[str] | None = None):
    for pkg in (packages or DOCSTRING_PACKAGES):
        pkg_dir = os.path.join(site, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for path in sorted(glob.glob(os.path.join(pkg_dir, "**/*.py"),
                                     recursive=True)):
            try:
                with open(path, "r", encoding="utf-8", errors="ignore") as f:
                    src = f.read()
                tree = ast.parse(src)
            except (OSError, SyntaxError):
                continue
            parts = []
            for node in ast.walk(tree):
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef, ast.AsyncFunctionDef)):
                    doc = ast.get_docstring(node)
                    if doc and len(doc) >= 200:
                        parts.append(doc)
            if parts:
                yield path, "\n\n".join(parts)


# Import name -> distribution name where they differ (dist-info dirs are
# named after the distribution).
_DIST_NAMES = {"sklearn": "scikit_learn", "orbax": "orbax_checkpoint"}


def _allowed_doc_roots(site: str,
                       packages: list[str] | None = None) -> list[str]:
    """Doc-file harvesting is restricted to the SAME pinned package list
    as docstrings (plus those packages' dist-info license files) so the
    redistribution claim in data/fixtures/PROVENANCE.md is enforced by
    code, not assumed — an unvetted transitive dependency in the image
    can never leak into the corpus."""
    roots = []
    for pkg in (packages or DOCSTRING_PACKAGES):
        roots.append(os.path.join(site, pkg))
        dist = _DIST_NAMES.get(pkg, pkg)
        roots.extend(glob.glob(os.path.join(site, dist + "-*.dist-info")))
    return [r for r in roots if os.path.isdir(r)]


def build(out_path: str, max_bytes: int, profile: str = "base") -> dict:
    site = sysconfig.get_paths()["purelib"]
    packages = DOCSTRING_PACKAGES
    if profile == "xl":
        packages = DOCSTRING_PACKAGES + XL_EXTRA_PACKAGES
    elif profile != "base":
        raise ValueError(f"unknown corpus profile: {profile!r}")
    sources = [
        ("licenses", harvest_doc_files(["/usr/share/common-licenses"],
                                       any_name=True)),
        ("package-docs", harvest_doc_files(_allowed_doc_roots(site, packages))),
        ("docstrings", harvest_docstrings(site, packages)),
    ]
    seen: set[bytes] = set()
    chunks: list[str] = []
    stats = {name: {"files": 0, "bytes": 0} for name, _ in sources}
    manifest: list[str] = []
    total = 0
    for name, it in sources:
        for path, text in it:
            kept = []
            for par in _paragraphs(text):
                h = hashlib.sha1(par.encode()).digest()
                if h in seen or not _is_prose_paragraph(par):
                    continue
                seen.add(h)
                kept.append(par)
            if not kept:
                continue
            doc = "\n\n".join(kept) + "\n\n"
            chunks.append(doc)
            # Record what actually lands in the emitted file: the final
            # document may be cut by the [:max_bytes] truncation below,
            # and the manifest's bytes_contributed column must sum to the
            # corpus size.
            contrib = min(len(doc), max_bytes - total)
            stats[name]["files"] += 1
            stats[name]["bytes"] += contrib
            manifest.append(f"{name}\t{path}\t{contrib}")
            total += len(doc)
            if total >= max_bytes:
                break
        if total >= max_bytes:
            break

    corpus = "".join(chunks)[:max_bytes]
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(corpus)
    with open(out_path + ".manifest", "w", encoding="utf-8") as f:
        f.write("# source\tpath\tbytes_contributed\n")
        f.write("\n".join(manifest) + "\n")
    vocab = sorted(set(corpus))
    return {"bytes": len(corpus), "vocab_size": len(vocab),
            "stats": stats, "out": out_path}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/fixtures/english_prose.txt")
    ap.add_argument("--max_mb", type=float, default=4.0)
    ap.add_argument("--profile", choices=["base", "xl"], default="base",
                    help="base: the 4 MB char-regime fixture's pinned "
                         "sources (do not change); xl: extended pinned "
                         "package list for the BPE-regime corpus")
    args = ap.parse_args(argv)
    info = build(args.out, int(args.max_mb * 1e6), profile=args.profile)
    print(f"wrote {info['out']}: {info['bytes']:,} bytes, "
          f"char vocab {info['vocab_size']}")
    for name, s in info["stats"].items():
        print(f"  {name}: {s['files']} files, {s['bytes']:,} bytes")
    return info


if __name__ == "__main__":
    main()
