"""Per-component time breakdown of the 124M train step on one chip.

Round-4 VERDICT weak #1: the single-chip 124M headline sat at 99-100k
tok/s / 43% MFU for three rounds while 350M reached 48.4% on the same
chip, and no committed artifact showed WHERE the ~164 ms step goes. This
script answers that by timing the pieces separately, plus candidate
replacements for the suspected bottleneck (the weight-tied LM head +
cross entropy, whose full-logits f32 tensor is B*T*V*4 = 3.3 GB of HBM
traffic per pass at the bench shape):

  full_step        the real jitted train step (anchor; = bench.py timing)
  body_fwd_bwd     transformer body only (return_hidden, loss=mean(hidden))
  head_*           LM head + CE fwd+bwd on a FIXED hidden buffer:
                     full_f32    current default (f32 attend + CE)
                     full_bf16   bf16-materialized logits, f32 softmax math
                     lse_f32     logsumexp-form CE (fusion-friendly)
                     chunk_N     existing chunked path at several sizes
  optimizer        tx.update + apply_updates on fixed grads
  attention_12x    12 layers of just the flash kernel fwd+bwd

Timing matches utils/benchmarking.py: enqueue all iters, one scalar
readback (the tunnel's ~110 ms RTT amortizes over the loop; per-iter
syncs would swamp ms-scale components).

Usage: python scripts/roofline_124m.py [--iters=20] [--batch_size=16]
       [--out=benchmarks/r5/roofline_124m.json]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_RTT_S = None


def _measure_rtt(readback, out) -> float:
    """Scalar readback of a trivial (pre-compiled) computation = dispatch
    + transport round trip. On the tunneled PJRT transport this is
    ~110 ms — charged once per timed loop, so for ms-scale components at
    20 iters it would inflate every number by ~5.5 ms if not subtracted
    (the r4 bench's 164 ms steps hid it at the 3% level; component timing
    cannot). A FRESH computation each probe: re-reading an already-fetched
    array returns jax's host-cached value in ~0 time."""
    global _RTT_S
    if _RTT_S is None:
        import jax
        import jax.numpy as jnp

        tiny = jax.jit(lambda i: jnp.float32(i) * 2)
        float(tiny(0))  # compile
        samples = []
        for i in range(1, 4):
            t0 = time.perf_counter()
            float(tiny(i))
            samples.append(time.perf_counter() - t0)
        _RTT_S = min(samples)
    return _RTT_S


def time_fn(fn, args, iters: int, readback) -> float:
    """Enqueue `iters` calls of jitted `fn`, sync once; RTT-corrected ms
    per call."""
    out = fn(*args)
    float(readback(out))  # warmup + hard sync (compile outside the clock)
    rtt = _measure_rtt(readback, out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(readback(out))
    return max(time.perf_counter() - t0 - rtt, 0.0) / iters * 1000


def main(argv: list[str]) -> dict:
    kv = dict(a.lstrip("-").split("=", 1) for a in argv if "=" in a)
    iters = int(kv.get("iters", 20))
    B = int(kv.get("batch_size", 16))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.data.prepare import prepare_char_dataset
    from nanosandbox_tpu.models.gpt import (chunked_cross_entropy_loss,
                                            cross_entropy_loss)
    from nanosandbox_tpu.train import Trainer

    tmp = tempfile.mkdtemp(prefix="roofline_")
    data_dir = os.path.join(tmp, "data")
    prepare_char_dataset(os.path.join(data_dir, "shakespeare_char"),
                         allow_synthetic=True,
                         url="http://invalid.localhost/offline")
    cfg = TrainConfig(
        out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
        dataset="shakespeare_char", vocab_size=50304,
        n_layer=12, n_head=12, n_embd=768, block_size=1024,
        batch_size=B, max_iters=0, eval_interval=0, log_interval=1,
        dropout=0.0, compute_dtype="bfloat16", loss_chunk_size=0,
        attention_impl="auto", tensorboard=False)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    xb, yb = next(loader)
    loader.close()
    x, y = trainer.to_global(xb), trainer.to_global(yb)
    rng = trainer.train_rng(0)

    T, C, V = cfg.block_size, cfg.n_embd, 50304
    results: dict[str, float] = {}

    # -- anchor: the real train step (no donation here; state reused) -----
    step_nodonate = jax.jit(trainer._train_step_fn)
    results["full_step"] = time_fn(
        step_nodonate, (state, x, y, rng), iters, lambda o: o[1]["loss"])

    # -- body only: fwd+bwd through the 12 blocks, no head ----------------
    def body_loss(params, x):
        h = trainer.model.apply({"params": params}, x, deterministic=True,
                                return_hidden=True)
        return h.astype(jnp.float32).mean()

    body_g = jax.jit(jax.value_and_grad(body_loss))
    results["body_fwd_bwd"] = time_fn(
        body_g, (state["params"], x), iters, lambda o: o[0])

    # -- head variants on a fixed hidden buffer ---------------------------
    hidden = trainer.model.apply({"params": state["params"]}, x,
                                 deterministic=True, return_hidden=True)
    hidden = jax.block_until_ready(hidden)
    emb = state["params"]["wte"]["embedding"]  # (V, C) f32

    def head_full_f32(h, w, y):  # current default: f32 attend + CE
        logits = lax.dot_general(h.astype(jnp.float32), w,
                                 (((2,), (1,)), ((), ())))
        return cross_entropy_loss(logits, y)

    def head_full_bf16(h, w, y):  # bf16-materialized logits
        logits = lax.dot_general(h.astype(jnp.bfloat16),
                                 w.astype(jnp.bfloat16),
                                 (((2,), (1,)), ((), ())),
                                 preferred_element_type=jnp.bfloat16)
        return cross_entropy_loss(logits, y)

    def head_lse_f32(h, w, y):  # logsumexp-form CE (no logp tensor)
        logits = lax.dot_general(h.astype(jnp.float32), w,
                                 (((2,), (1,)), ((), ())))
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, y[..., None], axis=-1)[..., 0]
        return (lse - tgt).mean()

    def head_lse_bf16(h, w, y):
        logits = lax.dot_general(h.astype(jnp.bfloat16),
                                 w.astype(jnp.bfloat16),
                                 (((2,), (1,)), ((), ())),
                                 preferred_element_type=jnp.bfloat16)
        logits32 = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits32, axis=-1)
        tgt = jnp.take_along_axis(logits32, y[..., None], axis=-1)[..., 0]
        return (lse - tgt).mean()

    for name, fn in [("head_full_f32", head_full_f32),
                     ("head_full_bf16", head_full_bf16),
                     ("head_lse_f32", head_lse_f32),
                     ("head_lse_bf16", head_lse_bf16)]:
        g = jax.jit(jax.value_and_grad(fn, argnums=(0, 1)))
        results[name] = time_fn(g, (hidden, emb, y), iters, lambda o: o[0])

    for cs in (256, 512, 1024):
        def head_chunk(h, w, y, cs=cs):
            return chunked_cross_entropy_loss(h, w, y, chunk_size=cs,
                                              compute_dtype="bfloat16")
        g = jax.jit(jax.value_and_grad(head_chunk, argnums=(0, 1)))
        results[f"head_chunk_{cs}"] = time_fn(
            g, (hidden, emb, y), iters, lambda o: o[0])

    # -- optimizer ---------------------------------------------------------
    grads = jax.tree.map(jnp.zeros_like, state["params"])

    def opt_only(grads, opt_state, params):
        import optax
        updates, opt_state = trainer.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params["wte"]["embedding"][0, 0], opt_state

    opt_j = jax.jit(opt_only)
    results["optimizer"] = time_fn(
        opt_j, (grads, state["opt_state"], state["params"]), iters,
        lambda o: o[0])

    # -- attention kernel, 12 layers worth --------------------------------
    from nanosandbox_tpu.ops.attention import causal_attention
    q = jax.random.normal(jax.random.key(0),
                          (B, cfg.n_head, T, C // cfg.n_head), jnp.bfloat16)

    def attn12(q):
        def body(x, _):
            # stat_layout matches the production (TrainConfig) default so
            # the component number decomposes the same step full_step runs.
            o = causal_attention(x, x, x, impl="auto",
                                 stat_layout=cfg.attention_stat_layout)
            return o, None
        o, _ = lax.scan(body, q, None, length=cfg.n_layer)
        return o.astype(jnp.float32).mean()

    attn_g = jax.jit(jax.value_and_grad(attn12))
    results["attention_12x"] = time_fn(attn_g, (q,), iters, lambda o: o[0])

    report = {
        "shape": {"B": B, "T": T, "C": C, "V": V, "n_layer": cfg.n_layer},
        "iters": iters,
        "ms": {k: round(v, 2) for k, v in results.items()},
        "derived": {
            "head_current_ms": round(results["head_full_f32"], 2),
            "body_plus_head_plus_opt_ms": round(
                results["body_fwd_bwd"] + results["head_full_f32"]
                + results["optimizer"], 2),
            "full_step_ms": round(results["full_step"], 2),
        },
    }
    print(json.dumps(report, indent=1))
    out = kv.get("out")
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=1)
    return report


if __name__ == "__main__":
    main(sys.argv[1:])
