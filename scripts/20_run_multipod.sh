#!/usr/bin/env bash
# Launch Workflow B — multi-host training via headless Service + StatefulSet.
#
# Successor of the reference's scripts/20_run_multipod.sh (named at
# /root/reference/.github/ISSUE_TEMPLATE/bug_report.yml:24; steps from
# README.md:62-72: apply service, apply statefulset, wait for rollout,
# follow pod-0 logs).
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
K8S="${REPO_ROOT}/k8s"
NS=disttrain

kubectl apply -f "${K8S}/services/41-train-mp-headless.yaml"
kubectl apply -f "${K8S}/statefulset/40-train-multipod.yaml"

# All pods must come up for jax.distributed.initialize to complete —
# rollout status is the liveness gate (reference README.md:67).
kubectl -n "$NS" rollout status statefulset/train-multipod --timeout=10m

echo "following logs of pod 0 (Ctrl-C detaches, training continues):"
kubectl -n "$NS" logs -f train-multipod-0
