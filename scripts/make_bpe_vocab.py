#!/usr/bin/env python3
"""Train the committed byte-level BPE vocabulary for the GPT-2 regime.

The reference's GPT-2-scale data contract tokenizes with tiktoken's gpt2
encoding (/root/reference/notebooks/colab_nanoGPT_companion.ipynb:37),
which fetches its merge table over the network — impossible in this
zero-egress environment. The offline equivalent is a byte-level BPE of the
SAME shape (50,257 entries: 256 byte symbols + merges, GPT-2's exact
budget) trained deterministically on the committed real-English XL corpus
and checked into data/fixtures/, so every host — k8s dataset Jobs, CI,
laptops — tokenizes identically without any download.

Determinism: HF `tokenizers` BPE training is deterministic for a fixed
corpus + settings (verified by double-train comparison in
tests/test_data.py); the manifest records the corpus sha256 so a drifted
corpus fails loudly rather than silently re-deriving a different vocab.

Usage:
  python scripts/make_real_corpus.py --out data/fixtures/english_prose_xl.txt \
      --max_mb 100 --profile xl        # (once) build the training corpus
  python scripts/make_bpe_vocab.py    # train + write the vocab asset
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_CORPUS = os.path.join(REPO_ROOT, "data", "fixtures",
                              "english_prose_xl.txt")
DEFAULT_OUT = os.path.join(REPO_ROOT, "data", "fixtures", "bpe_english_prose")
GPT2_VOCAB_SIZE = 50257  # GPT-2's exact entry count (tiktoken n_vocab)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def train_vocab(corpus: str, out_dir: str,
                vocab_size: int = GPT2_VOCAB_SIZE) -> dict:
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    if not os.path.exists(corpus):
        raise FileNotFoundError(
            f"{corpus} not found — build it first: python "
            "scripts/make_real_corpus.py --out data/fixtures/"
            "english_prose_xl.txt --max_mb 100 --profile xl")
    tok = Tokenizer(models.BPE())
    # ByteLevel pre-tokenization = GPT-2's scheme: every byte is encodable,
    # no <unk>, word boundaries marked with the U+0120 space marker.
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size, show_progress=False,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train([corpus], trainer)
    got = tok.get_vocab_size()
    if got != vocab_size:
        raise RuntimeError(
            f"corpus supports only {got} of the requested {vocab_size} "
            "BPE entries — grow the corpus (make_real_corpus.py --profile "
            "xl) before committing a smaller-than-GPT-2 vocab")

    os.makedirs(out_dir, exist_ok=True)
    asset = os.path.join(out_dir, "tokenizer.json")
    tok.save(asset)
    manifest = {
        "corpus": os.path.relpath(corpus, REPO_ROOT),
        "corpus_sha256": _sha256(corpus),
        "vocab_size": got,
        "scheme": "byte-level BPE (GPT-2 shape), HF tokenizers",
        "asset_sha256": _sha256(asset),
    }
    with open(os.path.join(out_dir, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=2)
        f.write("\n")
    return manifest


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", default=DEFAULT_CORPUS)
    ap.add_argument("--out_dir", default=DEFAULT_OUT)
    ap.add_argument("--vocab_size", type=int, default=GPT2_VOCAB_SIZE)
    args = ap.parse_args(argv)
    info = train_vocab(args.corpus, args.out_dir, args.vocab_size)
    print(f"wrote {args.out_dir}: vocab {info['vocab_size']}, "
          f"corpus sha {info['corpus_sha256'][:12]}")
    return info


if __name__ == "__main__":
    main()
