# BASELINE config 3: GPT-2 124M on OpenWebText, single-pod 8-device data
# parallel (v4-8) — the TPU analogue of workflow A's
# `torchrun --standalone --nproc_per_node=N` (README.md:7).
out_dir = "out/gpt2_124m_owt"
dataset = "openwebtext"
vocab_size = 50304  # GPT-2 50257 padded to 64 for the MXU
n_layer = 12
n_head = 12
n_embd = 768
block_size = 1024
batch_size = 64  # global; 8 per chip on a v4-8
gradient_accumulation_steps = 1
dropout = 0.0
max_iters = 600000
lr_decay_iters = 600000
eval_interval = 1000
eval_iters = 100
log_interval = 10
learning_rate = 6e-4
min_lr = 6e-5
mesh_dp = -1  # all chips on the data axis
