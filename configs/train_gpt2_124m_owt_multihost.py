# BASELINE config 4: GPT-2 124M OpenWebText, multi-host (StatefulSet
# nnodes=4, v5e-16) — the TPU analogue of workflow B (README.md:8, 62-72).
# The entrypoint exports COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID;
# this config only has to size the global batch for 16 chips.
out_dir = "out/gpt2_124m_owt_mh"
dataset = "openwebtext"
vocab_size = 50304
n_layer = 12
n_head = 12
n_embd = 768
block_size = 1024
batch_size = 128  # global across 16 chips
gradient_accumulation_steps = 1
dropout = 0.0
max_iters = 600000
lr_decay_iters = 600000
eval_interval = 1000
eval_iters = 100
log_interval = 10
learning_rate = 6e-4
min_lr = 6e-5
mesh_dp = -1
