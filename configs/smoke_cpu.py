# Tier-0 CPU smoke config — mirrors the reference's Colab CPU smoke test
# (colab_nanoGPT_companion.ipynb:69-80): 2L/2H/64d, block 128, batch 16,
# 50 iters, no compile-cache pressure. Proves the loop end-to-end fast.
out_dir = "out/smoke_cpu"
dataset = "shakespeare_char"
device = "cpu"
n_layer = 2
n_head = 2
n_embd = 64
block_size = 128
batch_size = 16
max_iters = 50
lr_decay_iters = 50
eval_interval = 25
eval_iters = 8
log_interval = 10
warmup_iters = 5
learning_rate = 1e-3
min_lr = 1e-4
dropout = 0.0
compute_dtype = "float32"  # CPU has no MXU; keep numerics simple
