# Sustained production-shape run on REAL data for the round-3 evidence
# chain (VERDICT r2 "Next round" #1): GPT-2 124M (12L/12H/768d, block
# 1024) char-level on the committed english_prose corpus, driven through
# the FULL Trainer.run() loop on the TPU — on-chip eval, Orbax
# checkpointing, TB/JSONL metrics, one jax.profiler window — with tok/s
# read from the trainer's own iteration log, not a bare bench loop.
#
# Scale note: 3.6M train tokens under a 124M model is ~14 epochs over
# this run; the point is proving the loop + throughput on hardware, and
# the recorded val-loss curve shows exactly where memorization sets in.
out_dir = "runs_r3/gpt2_124m_englishprose"
dataset = "english_prose_char"
n_layer = 12
n_head = 12
n_embd = 768
block_size = 1024
batch_size = 16
gradient_accumulation_steps = 1
dropout = 0.0
max_iters = 3000
lr_decay_iters = 3000
warmup_iters = 100
eval_interval = 500
eval_iters = 20
log_interval = 50
learning_rate = 6e-4
min_lr = 6e-5
compute_dtype = "bfloat16"
attention_impl = "auto"
loss_chunk_size = 0
profile_steps = "1000:1003"
