# GPT-2-REGIME convergence evidence on real tokens (round-3 VERDICT
# "Next round" #1): GPT-2 124M (12L/12H/768d, block 1024, vocab 50304)
# trained on the committed XL real-English corpus tokenized with the
# committed 50,257-entry byte-BPE vocab (scripts/make_bpe_vocab.py) —
# the first run in the evidence chain where the LM head, chunked loss,
# and embedding paths see real tokens at the vocabulary scale they were
# sized for (the reference's tiktoken/OpenWebText contract, ipynb:37).
#
# Scale note: 5.46M train tokens under 16x1024 batches is ~333
# iters/epoch; 3000 iters is ~9 epochs, so the recorded val curve shows
# real-language learning first and the memorization knee after — both
# are the point of the artifact.
out_dir = "runs_r4/gpt2_124m_englishprose_bpe"
dataset = "english_prose_bpe"
vocab_size = 50304  # dataset meta says 50257; padded to 64 for the MXU
n_layer = 12
n_head = 12
n_embd = 768
block_size = 1024
batch_size = 16
gradient_accumulation_steps = 1
dropout = 0.0
max_iters = 3000
lr_decay_iters = 3000
warmup_iters = 100
eval_interval = 250
eval_iters = 20
log_interval = 50
learning_rate = 6e-4
min_lr = 6e-5
compute_dtype = "bfloat16"
attention_impl = "auto"
# loss_chunk_size stays on the -1 auto default: at 16x1024x50304 the f32
# logits fit the 4 GB budget, so it resolves to 0 (full logits) — the
# measured-faster path. perf_sweep --mode=autoconfig pins this config's
# unpinned surface at the bench headline (benchmarks/r4/sweep_autoconfig.json).
profile_steps = "1000:1003"
