# BASELINE row-1 shape (6L/6H/384d char-level GPT, the reference's
# shakespeare-char config) trained on the committed REAL English corpus
# (data/fixtures/english_prose.txt; see data/fixtures/PROVENANCE.md) —
# the zero-egress stand-in for tiny-shakespeare that makes the val-loss
# half of the parity metric measurable on real natural language.
out_dir = "out/englishprose_char"
dataset = "english_prose_char"
n_layer = 6
n_head = 6
n_embd = 384
block_size = 256
batch_size = 64
dropout = 0.2
# Hardware-RNG dropout masks: threefry mask generation costs ~17% at
# this shape (BASELINE.md rng A/B: 733.7k vs 629.0k tok/s).
rng_impl = "rbg"
max_iters = 5000
lr_decay_iters = 5000
eval_interval = 250
eval_iters = 200
log_interval = 10
warmup_iters = 100
learning_rate = 1e-3
min_lr = 1e-4
beta2 = 0.99
