# BASELINE config 1: tiny-shakespeare char-level GPT (6L/6H/384d) — the
# nanoGPT config/train_shakespeare_char.py equivalent the reference's k8s
# jobs run (README.md:58, gh_sync.ps1:131).
out_dir = "out/shakespeare_char"
dataset = "shakespeare_char"
n_layer = 6
n_head = 6
n_embd = 384
block_size = 256
batch_size = 64
dropout = 0.2
max_iters = 5000
lr_decay_iters = 5000
eval_interval = 250
eval_iters = 200
log_interval = 10
warmup_iters = 100
learning_rate = 1e-3
min_lr = 1e-4
beta2 = 0.99
