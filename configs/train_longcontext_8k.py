# Long-context demonstration: GPT-2 124M at 8192-token context with ring
# attention (sequence parallelism over the mesh's seq axis). Beyond the
# reference's envelope (it caps at block_size=1024, SURVEY.md §5) — this is
# the config that exercises ops/ring_attention.py at scale.
#
# Sized for a 4-chip host (mesh 1x1x4x1); no-hardware sanity run on 8
# virtual devices needs --mesh_dp=2 plus scale-down flags (the full 12L
# model at 50304 vocab takes tens of CPU-minutes per step):
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 \
#     python -m nanosandbox_tpu.train configs/train_longcontext_8k.py \
#       --device=cpu --mesh_dp=2 --max_iters=2 --block_size=2048 \
#       --batch_size=4 --gradient_accumulation_steps=1 \
#       --n_layer=2 --n_embd=128 --n_head=2 --remat=False
out_dir = "out/longcontext_8k"
dataset = "openwebtext"
vocab_size = 50304

n_layer = 12
n_head = 12
n_embd = 768
block_size = 8192
dropout = 0.0

mesh_dp = 1
mesh_sp = 4          # sequence sharded 4-way; K/V rings over ICI
attention_impl = "ring"
remat = True         # 8k activations are HBM-hungry; trade FLOPs for memory
# Chunked head+loss runs per-shard inside shard_map under sp (full
# logits at 8k x 50304 would be 1.6 GB f32 per sequence).
loss_chunk_size = 512

batch_size = 4
gradient_accumulation_steps = 8
learning_rate = 6e-4
max_iters = 600000
lr_decay_iters = 600000
warmup_iters = 2000
eval_interval = 1000
eval_iters = 100
log_interval = 10
compute_dtype = "bfloat16"
