# Fine-tune pretrained GPT-2 124M on an OpenWebText subset (nanoGPT's
# finetune config counterpart). Requires the HF weights: pass
# --init_from=gpt2 on a networked machine, or point
# --init_from=hf:/data/models/gpt2 at a local save_pretrained directory
# (e.g. pre-staged on the PVC for air-gapped clusters).
#
# The dataset must be GPT-2-BPE tokenized (python -m
# nanosandbox_tpu.data.prepare openwebtext — or prepare_bpe_dataset on
# any text, including the committed english_prose fixture, when tiktoken
# can fetch its vocab; char-level ids are NOT BPE-compatible).
out_dir = "out/finetune_gpt2"
dataset = "openwebtext"
init_from = "gpt2"  # adopts 12L/12H/768d, vocab 50257, bias=True

# fine-tune schedule: short, low LR, no warmup restart (nanoGPT's
# finetune_shakespeare recipe shape)
max_iters = 2000
lr_decay_iters = 2000
warmup_iters = 0
learning_rate = 3e-5
min_lr = 3e-6
decay_lr = False

block_size = 1024
batch_size = 8
gradient_accumulation_steps = 4
dropout = 0.1          # regularize when fine-tuning on small corpora
eval_interval = 200
eval_iters = 40
log_interval = 10
compute_dtype = "bfloat16"
