# The REGULARIZED GPT-2-regime run: identical to
# train_gpt2_124m_englishprose_bpe.py except dropout 0.1, which runs
# INSIDE the Pallas flash kernels (r4: flash_attention_dropout — the r3
# convergence runs fell to the ~10%-MFU XLA fallback whenever dropout was
# on). Two things this artifact demonstrates at once:
#   1. the in-kernel dropout path sustaining a real 124M training run on
#      real BPE tokens at flash-kernel speed (BASELINE.md A/B: 82.4k
#      tok/s vs 42.7k on the XLA fallback at this exact shape);
#   2. regularization vs the dropout-0 twin on the same 5.46M-token
#      corpus, where the unregularized run's val curve knees into
#      memorization at ~9 epochs (best val 3.052 @ 2500).
out_dir = "runs_r4/gpt2_124m_englishprose_bpe_dropout"
# Hardware RNG for the dropout mask stream: threefry mask generation is
# ~half the e2e cost of dropout>0 configs on TPU (A/B in BASELINE.md —
# 93.5k vs 85.7k tok/s at this exact shape); same statistics, different
# bits, so only the mask realization changes.
rng_impl = "rbg"
dataset = "english_prose_bpe"
vocab_size = 50304  # dataset meta says 50257; padded to 64 for the MXU
n_layer = 12
n_head = 12
n_embd = 768
block_size = 1024
batch_size = 16
gradient_accumulation_steps = 1
dropout = 0.1
max_iters = 3000
lr_decay_iters = 3000
warmup_iters = 100
eval_interval = 250
eval_iters = 20
log_interval = 50
learning_rate = 6e-4
min_lr = 6e-5
compute_dtype = "bfloat16"
attention_impl = "auto"
