# BASELINE config 2: GPT-2 124M on tiny-shakespeare, single chip
# (Colab TPU / 1xA10 parity).
out_dir = "out/gpt2_124m_shakespeare"
dataset = "shakespeare_char"
n_layer = 12
n_head = 12
n_embd = 768
block_size = 1024
batch_size = 12
gradient_accumulation_steps = 1
dropout = 0.0
max_iters = 2000
lr_decay_iters = 2000
eval_interval = 500
eval_iters = 50
log_interval = 10
learning_rate = 6e-4
min_lr = 6e-5
