# BASELINE config 5 (stretch): GPT-2 1.5B OpenWebText, FSDP across v5e-16 —
# params/optimizer sharded over the fsdp axis (ZeRO-3 under jit), the one
# place this build intentionally exceeds the reference's DDP-only scope
# (SURVEY.md §2.5).
out_dir = "out/gpt2_1p5b_fsdp"
dataset = "openwebtext"
vocab_size = 50304
n_layer = 48
n_head = 25
n_embd = 1600
block_size = 1024
batch_size = 32
gradient_accumulation_steps = 4
dropout = 0.0
max_iters = 100000
lr_decay_iters = 100000
eval_interval = 1000
eval_iters = 50
log_interval = 10
learning_rate = 2e-4
min_lr = 2e-5
mesh_dp = 1
mesh_fsdp = 16  # all 16 chips on the fsdp axis
shard_params = True
remat = True  # rematerialize blocks: 1.5B activations exceed HBM otherwise
