# The REGULARIZED long-context config (round-5 VERDICT next #5): identical
# to train_longcontext_8k.py but with attention+residual dropout 0.1 —
# possible since the ring path supports in-kernel dropout via the
# global-position hash mask (ops/ring_attention.py): every ring step and
# both flash backward kernels reconstruct the same keep-mask for the same
# global score element, so sequence parallelism no longer forces
# unregularized training. rng_impl=rbg keeps mask generation off the
# critical path (hardware RNG; see BASELINE.md r4 A/B).
out_dir = "out/longcontext_8k_dropout"
dataset = "openwebtext"
vocab_size = 50304

n_layer = 12
n_head = 12
n_embd = 768
block_size = 8192
dropout = 0.1
rng_impl = "rbg"

mesh_dp = 1
mesh_sp = 4          # sequence sharded 4-way; K/V rings over ICI
attention_impl = "ring"
remat = True         # 8k activations are HBM-hungry; trade FLOPs for memory
# Chunked head+loss runs per-shard inside shard_map under sp (full
# logits at 8k x 50304 would be 1.6 GB f32 per sequence).
loss_chunk_size = 512

batch_size = 4
gradient_accumulation_steps = 8
learning_rate = 6e-4
max_iters = 600000
lr_decay_iters = 600000
warmup_iters = 2000
eval_interval = 1000
eval_iters = 100
log_interval = 10
compute_dtype = "bfloat16"
