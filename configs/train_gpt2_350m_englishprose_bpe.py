# GPT-2 350M (medium: 24L/16H/1024d) sustained convergence on the real
# byte-BPE corpus — the round-5 scale-up evidence (r4 VERDICT next #7:
# "the scale rows are 20-iter probes; nothing above 124M has ever trained
# for real"). Unlike the probes this exercises the full Trainer.run()
# surface at 350M: on-chip eval, Orbax checkpointing, TB/JSONL metrics,
# and the auto-resolved loss path at the bigger width.
#
# Batch 8 / no remat is the measured-best single-chip 350M point
# (benchmarks/r4/sweep_scale.json: 39.4k tok/s, 48.4% MFU vs 33.5k with
# remat+chunk at batch 16). Dropout 0.1 because the corpus is 5.46M
# tokens: the 124M dropout-0 twin memorized at ~9 epochs (val knee at
# step 2500), and this run passes ~6 epochs.
out_dir = "runs_r5/gpt2_350m_englishprose_bpe"
rng_impl = "rbg"
dataset = "english_prose_bpe"
vocab_size = 50304  # dataset meta says 50257; padded to 64 for the MXU
n_layer = 24
n_head = 16
n_embd = 1024
block_size = 1024
batch_size = 8
gradient_accumulation_steps = 1
dropout = 0.1
max_iters = 4000
lr_decay_iters = 4000
warmup_iters = 200
eval_interval = 250
eval_iters = 20
log_interval = 50
learning_rate = 3e-4  # nanoGPT's gpt2-medium-scale LR tier
min_lr = 3e-5
compute_dtype = "bfloat16"
attention_impl = "auto"
