"""Benchmark: GPT-2 124M training throughput, tokens/sec/chip — and,
with --mode=decode, continuous-batching inference throughput through
the serve engine (nanosandbox_tpu/serve/).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no numbers (SURVEY.md §6; BASELINE.json
"published": {}), so the parity target is nanoGPT GPT-2 124M tokens/sec on
one NVIDIA A10 — the reference's per-device hardware (README.md:5,13).
Public nanoGPT runs with torch.compile + flash attention put that at
~22k tokens/sec/A10 for the 124M/1024-ctx config; vs_baseline is measured
tokens/sec/chip divided by that estimate (>1.0 beats the reference's
per-device hardware).

Usage: python bench.py [--quick] [--batch_size=N] [--iters=N] [--impl=NAME]
       python bench.py --mode=decode [--quick] [--num_slots=N] \
           [--max_new_tokens=N] [--requests=N] [--mixed=1] \
           [--paged={on,off}] [--prefix_share=F] [--kv_page_size=N] \
           [--scan_k=N] [--kv_dtype={fp32,bf16,int8,int4}] \
           [--baseline_kv_dtype=MODE] [--decode_impl=IMPL] [--tp=N] \
           [--spec={off,ngram}] [--spec_k=N] [--repetitive] [--repeat=N] \
           [--emit_obs]
       python bench.py --mode=serve [--quick] [--num_slots=N] \
           [--requests=N] [--load=1,2] [--burst=6] \
           [--interactive_share=F] [--emit_obs] \
           [--faults=chaos-smoke] [--flight_out=PATH] \
           [--sched] [--disagg] [--prefill_chunk=N]

--mode=serve is the closed-loop load generator (Poisson arrivals at
multiples of measured capacity, per-class deadlines, an all-at-once
burst point): every sweep point emits goodput_toks, slo_attainment and
shed_rate, turning goodput-under-overload into a regression-pinned
number like tokens/sec.

--faults=<plan> adds a CHAOS point to the serve sweep: the same 1x
Poisson arrivals with a deterministic fault plan armed (serve/faults.py
syntax, or a canned name like 'chaos-smoke') and the crash-safe
supervisor driving recovery. The JSON gains extra.fault —
goodput_under_fault_ratio (fault-point goodput / clean 1x), recovery
counts/latency, time-to-first-retired-token — the numbers the CI chaos
smoke pins. --flight_out dumps the fault run's flight-recorder JSONL
for artifact upload.

--sched adds the ISSUE-13 scheduling probes to the serve sweep
(extra.scheduling): a PREFILL-STORM twin — a burst of max-length
prompts against active decoders, chunked (--prefill_chunk, default the
smallest bucket) vs unchunked in the same interleaved rounds, emitting
tpot_p99_under_storm for both and their ratio (CI pins <= 0.5x); a
PRIORITY twin at 2x capacity — class-priority scheduling + preemption
vs a FIFO/no-preemption engine on identical arrivals, emitting
per-class attainment (CI pins interactive strictly above the FIFO
twin); and a PREEMPT-RESUME PARITY probe — a preempt_storm fault plan
repeatedly evicting victims, outputs compared token-for-token against
a clean twin (CI pins parity == 1.0).

--disagg adds the ISSUE-16 disaggregation probe (extra.disagg): a
DisaggPair (prefill tier + decode tier, paged block chains as the
migration wire format) vs the chunked-colocated engine under the SAME
prefill storm, in the same interleaved rotated rounds. Emits decode-
tier tpot_p99_under_storm vs the chunked twin and their ratio (CI pins
<= 1.0 — the decode tier never sees a prefill dispatch, so chunking's
residual interleave tax disappears), migration latency p50/p99, the
decode-tier dispatch ledger (CI pins prefill dispatches == 0), and a
greedy token-parity count vs colocated (CI pins parity == 1.0).

--emit_obs attaches the obs metric-registry snapshot (the same series a
live /metrics scrape exposes) to the JSON under "obs".

Decode mode reports pipelined AND synchronous tokens/sec (plus TTFT
percentiles) so the pipelining win is trend-tracked in CI, no threshold.
Engine comparisons run --repeat interleaved rounds (3 by default off
--quick) and report per-engine MEDIANS, so a contended host can't turn
a single slow drain into a bogus ratio.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

A10_BASELINE_TOKS_PER_SEC = 22_000.0


def _flag(kv: dict, name: str) -> bool:
    """One boolean-flag parse for every `--name[=0|false|no]` switch —
    the hand-rolled variants had already drifted across call sites."""
    return name in kv and kv[name] not in ("0", "false", "no")


def preflight_impls() -> dict[str, str]:
    """AOT-compile each attention impl once on tiny shapes and report
    per-impl status — a kernel regression shows up here as a note in the
    bench output instead of a crashed bench (VERDICT.md round-1 weak #3:
    'auto' hard-selecting a broken kernel took down every TPU run)."""
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.ops.attention import causal_attention

    status = {}
    impls = (["pallas", "pallas_jax", "xla"]
             if jax.default_backend() == "tpu" else
             ["pallas_interpret", "xla"])
    x = jax.ShapeDtypeStruct((1, 2, 128, 64), jnp.bfloat16)
    for impl in impls:
        def loss(q, k, v, impl=impl):
            return causal_attention(q, k, v, impl=impl).astype(
                jnp.float32).sum()
        try:
            jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(x, x, x).compile()
            status[impl] = "ok"
        except Exception as e:
            status[impl] = f"FAIL: {type(e).__name__}: {str(e)[:200]}"
    return status


def build_config(kv: dict, *, on_tpu: bool, n_chips: int, tmp: str,
                 data_dir: str, quick: bool):
    """Bench config from CLI key=value flags.

    --batch_size is PER-CHIP (matching the reported metric, tokens/sec/
    chip); the global batch is batch_size * n_chips. Round-2 VERDICT weak
    #4: the old code set the global batch from the flag twice with
    conflicting semantics, so on a multi-chip host --batch_size=16
    silently meant 2/chip.
    """
    from nanosandbox_tpu.config import TrainConfig

    per_chip = int(kv.get("batch_size", 16 if on_tpu else 8))
    if on_tpu:
        # Best measured single-chip config (scripts/perf_sweep.py, v5e):
        # batch 16/chip, pallas flash via 'auto', full-logits loss (the
        # fused chunked head trades ~8% step time for memory it doesn't
        # need at this batch), no remat. 99.2k tok/s/chip, 43% MFU.
        cfg = TrainConfig(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char", vocab_size=50304,
            n_layer=12, n_head=12, n_embd=768, block_size=1024,
            batch_size=per_chip * n_chips,
            max_iters=0, eval_interval=0, log_interval=1,
            dropout=0.0, compute_dtype="bfloat16", loss_chunk_size=0,
            attention_impl="auto", tensorboard=False)
        warmup, iters = (2, 5) if quick else (3, 20)
    else:  # CPU fallback keeps the bench runnable anywhere
        cfg = TrainConfig(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char",
            n_layer=2, n_head=2, n_embd=64, block_size=128,
            batch_size=per_chip * n_chips, max_iters=0, eval_interval=0,
            dropout=0.0, compute_dtype="float32", tensorboard=False)
        warmup, iters = (1, 3)

    if "impl" in kv:
        cfg = cfg.replace(attention_impl=kv["impl"])
    iters = int(kv.get("iters", iters))
    return cfg, warmup, iters


def preflight_decode_impls() -> dict[str, str]:
    """Per-impl compile status for the flash-decode ladder, the decode
    twin of preflight_impls(). Runs the SAME probe harness the 'auto'
    gate uses (flash_decode.compile_probe_check — fp AND
    int8-with-scales), so the reported verdicts can't drift from what
    resolve_decode_impl actually checks."""
    import jax

    from nanosandbox_tpu.ops.flash_decode import compile_probe_check

    status = {"xla": "ok"}  # plain jnp; nothing to probe
    impls = (["pallas"] if jax.default_backend() == "tpu"
             else ["pallas_interpret"])
    for impl in impls:
        try:
            compile_probe_check(interpret=impl == "pallas_interpret")
            status[impl] = "ok"
        except Exception as e:
            status[impl] = f"FAIL: {type(e).__name__}: {str(e)[:200]}"
    return status


def estimate_decode_hbm_bytes_per_token(cfg, *, num_slots: int,
                                        mean_frontier: float,
                                        kv_dtype: str,
                                        param_count: int) -> int:
    """Analytic HBM bytes moved per generated token at full occupancy —
    the roofline the kv_dtype knob moves. Per token of one slot row:
    the whole parameter set streams once per STEP and amortizes over
    num_slots rows; that row's K/V history (mean_frontier positions x
    n_layer x 2 tensors) streams once for the attention read, plus one
    position's write. int8 adds 4 scale bytes per (head, position) next
    to 1-byte values. An estimate, not a measurement: it ignores
    activations (tiny at T=1) and assumes every slot is occupied."""
    head_dim = cfg.n_embd // cfg.n_head
    if kv_dtype == "int4":
        val_bytes, scale_bytes = 0.5, 4      # two nibbles per byte
    elif kv_dtype == "int8":
        val_bytes, scale_bytes = 1, 4
    elif kv_dtype in ("bf16", "bfloat16"):
        val_bytes, scale_bytes = 2, 0
    else:
        val_bytes, scale_bytes = 4, 0
    pos_bytes = cfg.n_head * (head_dim * val_bytes + scale_bytes)
    kv_bytes = cfg.n_layer * 2 * pos_bytes * (mean_frontier + 1)
    import jax.numpy as jnp
    param_bytes = param_count * jnp.dtype(cfg.compute_dtype).itemsize
    return int(param_bytes / num_slots + kv_bytes)


def _tp_collective_bytes_per_token(engine):
    """Model-axis collective bytes one decode dispatch moves per
    generated token: the engine's own rung-1 decode program is
    AOT-lowered under its live mesh and parsed by the shardcheck
    manifest machinery — the exact number budgets/serve_tp_cpu8.json
    pins, surfaced in the bench JSON next to the throughput it buys.
    Rung 1 emits one token per dispatch, so program bytes == bytes per
    token. None when the analysis backend can't lower (never fails the
    bench)."""
    try:
        from nanosandbox_tpu.analysis.shardcheck.manifest import (
            analyze_program)

        spec = next(s for s in engine.shardcheck_programs(engine.mesh)
                    if not s.name.startswith("decode_scan")
                    and s.name.startswith("decode"))
        return analyze_program(spec, engine.mesh)["totals"]["bytes_moved"]
    except Exception:
        return None


def bench_decode(kv: dict, *, quick: bool, on_tpu: bool) -> dict:
    """Batched-decode tokens/sec through the serve engine, pipelined vs
    synchronous.

    Measures the serving metric that matters — aggregate generated
    tokens/sec across a full continuous batch with mixed prompt lengths
    and mid-flight backfill — not batch-1 latency. The SAME workload
    runs twice, once with the synchronous PR-1-style loop (pipeline=
    False: one host readback per token) and once pipelined (one decode
    step in flight ahead of the host), so the JSON carries the overlap
    win as a trend-tracked ratio, no threshold. Params are randomly
    initialized (throughput does not depend on the weights) and cast to
    the serving dtype, exactly as `python -m nanosandbox_tpu.serve`
    casts a restored checkpoint. A warmup drain first touches every
    compiled program so compilation never lands inside a timed window.

    Knobs: --num_slots (alias --slots), --max_new_tokens, --requests,
    --mixed (vary max_new_tokens per request so finishes stagger and
    mid-run backfill/eviction dominate — the continuous-batching regime,
    and the acceptance workload for the pipelining PR), --spec={off,
    ngram} (+ --spec_k=N) to ALSO run the same workload through the
    speculative-decoding engine (serve/spec.py) and report acceptance
    rate, mean accepted draft length and the spec-vs-baseline tokens/sec
    ratio, --repetitive (prompts built from a short repeated motif — the
    prompt-lookup drafter's favorable regime, and the workload the
    speculative acceptance bar is measured on).
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.sample import cast_params_for_serving
    from nanosandbox_tpu.serve import Engine, NGramDrafter

    if on_tpu:  # GPT-2 124M, the train bench's model, in serving dtype
        cfg = GPTConfig(n_layer=12, n_head=12, n_embd=768, block_size=1024,
                        vocab_size=50304, dropout=0.0,
                        compute_dtype="bfloat16", attention_impl="auto")
        max_len, max_new = 512, (64 if quick else 128)
    else:  # CPU fallback keeps the bench runnable anywhere
        cfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=128,
                        vocab_size=256, dropout=0.0,
                        compute_dtype="float32", attention_impl="xla")
        # Quick keeps the CI-smoke shape small; the full CPU bench runs
        # 128-position slots (8 KV pages each) so the paged pool's
        # elasticity — requests reserving their ACTUAL need instead of
        # a max_len row — is measured at a non-degenerate page count.
        max_len, max_new = (64, 8) if quick else (128, 16)

    num_slots = int(kv.get("num_slots", kv.get("slots", 8)))
    max_len = int(kv.get("max_len", max_len))
    max_new = int(kv.get("max_new_tokens", max_new))
    n_requests = int(kv.get("requests", 2 * num_slots))
    mixed = _flag(kv, "mixed")
    from nanosandbox_tpu.models.gpt import normalize_kv_dtype

    # --kv_dtype benches the requested KV-pool mode as the PRIMARY
    # engines; when it differs from the baseline mode (--baseline_kv_dtype,
    # default the serving compute dtype), a baseline-mode pipelined twin
    # (and, under --spec, a spec twin) runs in the same interleaved
    # rounds so the JSON records the kv-vs-baseline ratio, greedy token
    # parity, and spec-acceptance delta — the ISSUE-8 acceptance
    # numbers (--kv_dtype=int8 --baseline_kv_dtype=fp32 measures the
    # literal int8-vs-fp32 bar even on a bf16-compute TPU).
    # --decode_impl pins the flash-decode ladder for EVERY engine (so
    # the dtype comparison isolates bytes, not impls).
    kv_dtype = normalize_kv_dtype(kv.get("kv_dtype"))
    decode_impl = kv.get("decode_impl")
    default_mode = "bf16" if cfg.compute_dtype == "bfloat16" else "fp32"
    baseline_kv = normalize_kv_dtype(kv.get("baseline_kv_dtype"))
    baseline_mode = baseline_kv or default_mode
    compare_kv = kv_dtype is not None and kv_dtype != baseline_mode
    # --paged={on,off}: the block-paged pool + radix prefix cache is the
    # default engine; 'on' ALSO runs a dense-pool pipelined twin in the
    # same interleaved rounds so the JSON pins paged_vs_dense_toks (the
    # <=5% ISSUE-9 throughput bar) and the capacity story at equal pool
    # bytes. --prefix_share=<frac> makes that fraction of the workload
    # share one system-prompt prefix (the dominant production shape):
    # the JSON then carries prefix_hit_rate and an isolated
    # ttft_hit_vs_miss probe (single-request, no queueing confound).
    paged = kv.get("paged", "on") != "off"
    prefix_share = float(kv.get("prefix_share", 0.0))
    if not 0.0 <= prefix_share <= 1.0:
        raise SystemExit(f"--prefix_share={prefix_share}: need [0, 1]")
    kv_page = int(kv.get("kv_page_size", 16))
    spec = kv.get("spec", "off")
    if spec not in ("off", "ngram"):
        # ModelDrafter needs a restored checkpoint; the bench initializes
        # random weights, so only the weight-free drafter is benchable.
        raise SystemExit(f"--spec={spec!r}: decode bench supports off|ngram")
    spec_k = int(kv.get("spec_k", 4))
    repetitive = _flag(kv, "repetitive")
    # --scan_k=N: the primary engines dispatch multi-token scan chunks
    # (serve/engine.py megaprogram ladder); a scan_k=1 pipelined twin
    # rides the SAME interleaved rotated rounds so scan_vs_single_toks
    # is attributable to the dispatch amortization alone, with greedy
    # parity pinned at 1.0 and dispatches_per_token measured (the
    # ISSUE-12 <= 0.15 bar).
    scan_k = int(kv.get("scan_k", 1))
    # --tp=N: the primary engines shard over N chips (ISSUE 14 — the
    # Megatron weights + heads-sharded KV pool engine); a tp=1 twin
    # rides the SAME interleaved rotated rounds so tp_vs_single_toks is
    # attributable to the sharding alone, tp_greedy_parity is pinned at
    # 1.0 (same keys, same per-row math, deterministic collectives),
    # and collective_bytes_per_token comes from AOT-lowering the
    # engine's own decode program (the number the TP budget pins).
    tp = int(kv.get("tp", 1))
    # int4-vs-int8 capacity twin: at equal VALUE bytes an int4 pool
    # holds 2x the blocks of an int8 one, so when the baseline mode is
    # int8 the primary int4 engines get a 2x-block pool — the
    # effective_slot_capacity comparison then holds pool value-HBM
    # constant, exactly like the paged-vs-dense capacity story.
    slot_blocks = -(-max_len // kv_page)
    pool_blocks_primary = None
    if paged and kv_dtype == "int4" and baseline_mode == "int8":
        pool_blocks_primary = 2 * num_slots * slot_blocks

    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    params = cast_params_for_serving(params, cfg.compute_dtype)

    # One shared "system prompt" for the --prefix_share fraction: about
    # two thirds of the admissible prompt range (production system
    # prompts dominate the context — that ratio is what makes prefix
    # reuse the big lever it is), rounded DOWN to whole KV pages so the
    # radix cache can actually share it (only full blocks are
    # shareable). Fixed across rounds — round 0's first occupants miss
    # and donate, everything after hits, which is exactly the
    # production shape the prefix cache targets.
    max_prompt = max(2, max_len - max_new)
    shared_len = max(kv_page, (2 * max_prompt // 3) // kv_page * kv_page)
    shared_prefix = np.random.default_rng(12345).integers(
        0, cfg.vocab_size, shared_len).tolist()

    def workload(engine, n, seed):
        """Mixed prompt lengths (drawn per request, same stream for both
        engines); --mixed also staggers the token budgets; --repetitive
        tiles a short per-request motif instead of sampling tokens
        independently (the regime where prompt-lookup drafting hits);
        --prefix_share starts that fraction of prompts with the shared
        system prefix (same stream for every engine, so the dense twin
        pays full prefill on the identical token sequences)."""
        rng = np.random.default_rng(seed)
        for _ in range(n):
            L = int(rng.integers(1, max_prompt))
            mnt = (int(rng.integers(max(1, max_new // 4), max_new + 1))
                   if mixed else max_new)
            if repetitive:
                motif = rng.integers(0, cfg.vocab_size,
                                     int(rng.integers(2, 5)))
                prompt = np.tile(motif, max(L, 1) // len(motif) + 1)[
                    :max(L, 1)].tolist()
            else:
                prompt = rng.integers(0, cfg.vocab_size, max(L, 1)).tolist()
            if prefix_share and rng.random() < prefix_share:
                tail = max(1, min(len(prompt), max_prompt - shared_len))
                prompt = shared_prefix + prompt[:tail]
            engine.submit(prompt, mnt)

    def build(pipeline: bool, drafter=None, kvd=kv_dtype, pg=paged,
              sk=scan_k, impl=decode_impl, pool_blocks=None, tpn=tp):
        engine = Engine(model, params, num_slots=num_slots, max_len=max_len,
                        pipeline=pipeline, spec=drafter, kv_dtype=kvd,
                        decode_impl=impl, paged=pg, scan_k=sk,
                        kv_page_size=kv_page, kv_pool_blocks=pool_blocks,
                        tp=tpn)
        # Warmup: every (wave rung, bucket) prefill + admit + decode +
        # release program, so no timed window eats an XLA compile. The
        # prompt length must MAP to the bucket being warmed (in
        # (previous rung, bucket]); a bucket with no decodable length is
        # unreachable by the workload too, so skipping it is sound.
        lo = 1
        for bucket in engine.sched.buckets:
            length = min(bucket, max_len - 2)
            lo, prev_lo = bucket + 1, lo
            if length < prev_lo:
                continue
            for k in engine.admit_buckets:
                for _ in range(k):
                    engine.submit([0] * length, 2)
                engine.drain()
                # A warmup prompt's donated blocks must never shrink the
                # NEXT wave's suffix bucket (the program it exists to
                # compile) — same hygiene as serve __main__'s warmup.
                engine.reset_prefix_cache()
        # The scan-chunk rung ladder (scan_k > 1): compile every
        # megaprogram up front — no timed round may eat a rung compile.
        engine.warm_scan_rungs()
        # Warmup TTFT/TPOT samples would swamp the workload's in the
        # rings (45 warmup requests vs 16 timed at the defaults): the
        # reported percentiles must describe the measured traffic.
        engine.reset_latency_stats()
        return engine

    def timed(engine, seed: int):
        workload(engine, n_requests, seed=seed)
        t0 = time.perf_counter()
        results = engine.drain()
        dt = time.perf_counter() - t0
        # Submission order == rid order within the round, so sorted
        # token lists align across engines fed the same workload seed
        # (the greedy-parity comparison below).
        toks = [r.tokens for r in sorted(results, key=lambda r: r.rid)]
        return sum(len(t) for t in toks), dt, toks

    # INTERLEAVED repeats, median rate per engine (--repeat=N; 3 by
    # default off --quick): a shared/contended host can swing a single
    # 50ms drain several-fold, so engine comparisons alternate rounds
    # (same per-round workload seed for every engine) and report the
    # median — the PR 2 measurement discipline, now built in.
    def greedy_parity(rounds_a, rounds_b):
        """Matched-token fraction between two engines' per-round token
        lists (same workload seeds): the ONE definition every parity
        field in this bench reports."""
        total = matched = 0
        for ra, rb in zip(rounds_a, rounds_b):
            for ta, tb in zip(ra, rb):
                total += max(len(ta), len(tb))
                matched += sum(x == y for x, y in zip(ta, tb))
        return matched / max(total, 1)

    repeat = int(kv.get("repeat", 1 if quick else 3))
    engines = {"sync": build(pipeline=False,
                             pool_blocks=pool_blocks_primary),
               "pipe": build(pipeline=True,
                             pool_blocks=pool_blocks_primary)}
    if scan_k > 1:
        # The scan_k=1 pipelined twin: same pool layout/bytes, same
        # workload seeds, same rotated rounds — the ratio isolates the
        # dispatch amortization.
        engines["scan1"] = build(pipeline=True, sk=1,
                                 pool_blocks=pool_blocks_primary)
    if tp > 1:
        # The tp=1 twin: same pool layout/bytes, same workload seeds,
        # same rotated rounds — the ratio isolates the sharding.
        engines["tp1"] = build(pipeline=True, tpn=1,
                               pool_blocks=pool_blocks_primary)
    if paged:
        # The dense-pool twin rides the SAME interleaved rounds and
        # workload seeds: paged_vs_dense_toks is then attributable to
        # the pool layout alone (the ISSUE-9 <=5% decode bar), and the
        # greedy token lists must match outright.
        engines["dense"] = build(pipeline=True, pg=False)
    if compare_kv:
        engines["kv_base"] = build(pipeline=True, kvd=baseline_kv)
    if spec != "off":
        engines["spec"] = build(pipeline=True,
                                drafter=NGramDrafter(k=spec_k))
        if compare_kv:
            engines["spec_base"] = build(pipeline=True,
                                         drafter=NGramDrafter(k=spec_k),
                                         kvd=baseline_kv)
    rates = {name: [] for name in engines}
    gen_total = {name: 0 for name in engines}
    dt_total = {name: 0.0 for name in engines}
    tokens_by_engine = {name: [] for name in engines}
    # Dispatch-ledger marks at the end of warmup: the reported
    # dispatches/token must describe the TIMED workload (warmup traffic
    # is all tiny-budget rung-1 chunks, which would skew the ratio the
    # ISSUE-12 <= 0.15 bar is judged on).
    dispatch_marks = {
        name: (e.host_dispatches["decode"] + e.host_dispatches["verify"],
               e.tokens_generated)
        for name, e in engines.items()}

    def timed_dispatch_ratio(name):
        e = engines[name]
        d0, t0 = dispatch_marks[name]
        d = e.host_dispatches["decode"] + e.host_dispatches["verify"] - d0
        t = e.tokens_generated - t0
        return (d / t if t else None), (t / d if d else None)
    names = list(engines)
    steady_mark = None
    for r in range(repeat):
        if paged and r == repeat - 1:
            # Mark the paged engine's allocation ledger before the FINAL
            # round: capacity is a steady-state number, and the cold
            # cache's round-0 misses (every shared prefix paid in full
            # once) would understate it for short benches.
            bp = engines["pipe"].block_pool
            steady_mark = (bp.requests, bp.private_blocks_allocated)
        # Rotate the within-round order: on a contended host the engine
        # that runs SECOND on a given workload measurably benefits from
        # the first's warm allocator/caches (observed ~15% on CPU), so
        # a fixed order biases every pairwise ratio. Rotation gives
        # each engine each position, and the median washes the rest.
        for name in names[r % len(names):] + names[:r % len(names)]:
            g, d, toks = timed(engines[name], seed=r)
            rates[name].append(g / d)
            gen_total[name] += g
            dt_total[name] += d
            tokens_by_engine[name].append(toks)

    from statistics import median

    engine = engines["pipe"]
    stats = engine.stats()
    # Capture the timed-workload dispatch ratios NOW — the TTFT probes
    # below submit extra requests that would re-contaminate the ledger.
    pipe_dpt, pipe_tpd = timed_dispatch_ratio("pipe")
    scan1_dpt = (timed_dispatch_ratio("scan1")[0]
                 if "scan1" in engines else None)
    rate = median(rates["pipe"])
    generated, dt = gen_total["pipe"], dt_total["pipe"]

    # Decode-attention + KV-mode signal (ISSUE 8 satellite): the
    # RESOLVED impl per engine, the flash-decode preflight ladder, and
    # the analytic HBM bytes/token the kv_dtype knob moves. The mean
    # attended frontier under this workload: prompts draw uniform from
    # [1, max_len - max_new) and a request's decode walk averages half
    # its budget — which under --mixed is itself uniform in
    # [max_new/4, max_new] (mean 0.625 * max_new), not max_new.
    from nanosandbox_tpu.models.gpt import count_params

    mean_budget = (max(1, max_new // 4) + max_new) / 2 if mixed else max_new
    mean_frontier = (1 + max(2, max_len - max_new)) / 2 + mean_budget / 2
    n_params = count_params({"params": params})
    kv_extra = {
        "kv_dtype": engines["pipe"].kv_dtype,
        "decode_attention_impl": engines["pipe"].decode_impl,
        "decode_impl_status": preflight_decode_impls(),
        "estimated_hbm_bytes_per_token": estimate_decode_hbm_bytes_per_token(
            cfg, num_slots=num_slots, mean_frontier=mean_frontier,
            kv_dtype=engines["pipe"].kv_dtype, param_count=n_params),
    }

    # Paged-pool signal (ISSUE 9): throughput vs the dense twin + greedy
    # parity over the same seeds, the prefix-cache hit rate over the
    # timed rounds, effective concurrent-session capacity at FIXED pool
    # bytes (pool blocks / mean private blocks actually reserved per
    # request — the dense layout pins exactly num_slots sessions into
    # the same bytes), and an isolated single-request TTFT hit-vs-miss
    # probe (throughput-round TTFTs include queueing, which would bury
    # the prefill cut this cache exists to deliver).
    paged_extra = {"paged": paged, "prefix_share": prefix_share}
    if paged:
        pool_stats = engine.block_pool.stats()
        dense_rate = median(rates["dense"])
        mean_priv = pool_stats["mean_private_blocks_per_request"]
        # Steady-state footprint: the final (cache-warm) round only —
        # what a long-running deployment's admission actually reserves.
        steady_priv = mean_priv
        if steady_mark is not None:
            bp = engine.block_pool
            dreq = bp.requests - steady_mark[0]
            if dreq > 0:
                steady_priv = ((bp.private_blocks_allocated
                                - steady_mark[1]) / dreq)
        eff_capacity = (engine.kv_pool_blocks / steady_priv
                        if steady_priv else None)
        paged_extra.update({
            "kv_page_size": engine.kv_page_size,
            "kv_pool_blocks": engine.kv_pool_blocks,
            "dense_tokens_per_sec": dense_rate,
            "paged_vs_dense_toks": rate / dense_rate,
            "paged_greedy_parity": greedy_parity(
                tokens_by_engine["pipe"], tokens_by_engine["dense"]),
            "prefix_hit_rate": pool_stats["prefix_hit_rate"],
            "prefix_hit_tokens": pool_stats["prefix_hit_tokens"],
            "prefix_miss_tokens": pool_stats["prefix_miss_tokens"],
            "block_stall_steps": pool_stats["block_stall_steps"],
            "mean_private_blocks_per_request": mean_priv,
            "steady_private_blocks_per_request": steady_priv,
            "effective_slot_capacity": eff_capacity,
            "capacity_vs_dense": (eff_capacity / num_slots
                                  if eff_capacity else None),
        })
        if prefix_share > 0:
            # TTFT probe: alternate cold-prefix / shared-prefix
            # single-request drains on the quiesced primary engine, so
            # hit and miss TTFTs compare prefill work, not queue luck.
            engine.reset_latency_stats()
            probe_rng = np.random.default_rng(999)
            tail = [int(t) for t in probe_rng.integers(0, cfg.vocab_size,
                                                       8)]
            for i in range(3 if quick else 7):
                miss_prompt = probe_rng.integers(
                    0, cfg.vocab_size, shared_len + len(tail)).tolist()
                engine.submit(miss_prompt, 2)
                engine.drain()
                engine.submit(shared_prefix + tail, 2)
                engine.drain()
                tail[0] = (tail[0] + 1) % cfg.vocab_size
            ps = engine.stats()["kv_pool"]
            hit_p50 = (ps["ttft_hit_s"] or {}).get("p50")
            miss_p50 = (ps["ttft_miss_s"] or {}).get("p50")
            paged_extra["ttft_hit_vs_miss"] = {
                "hit_p50_s": hit_p50,
                "miss_p50_s": miss_p50,
                "hit_over_miss": (hit_p50 / miss_p50
                                  if hit_p50 and miss_p50 else None),
            }
    # Multi-token scan signal (ISSUE 12): tokens/sec vs the scan_k=1
    # twin, greedy parity (must be 1.0 — chunks are dispatch
    # boundaries, not sampling state), and the dispatch-floor numbers
    # (timed-workload deltas only — warmup traffic excluded).
    scan_extra = {
        "scan_k": scan_k,
        "scan_rungs": list(engine.scan_rungs),
        "dispatches_per_token": pipe_dpt,
        "tokens_per_dispatch": pipe_tpd,
    }
    if scan_k > 1:
        single_rate = median(rates["scan1"])
        scan_extra.update({
            "single_step_tokens_per_sec": single_rate,
            "scan_vs_single_toks": rate / single_rate,
            "scan_greedy_parity": greedy_parity(tokens_by_engine["pipe"],
                                                tokens_by_engine["scan1"]),
            "single_step_dispatches_per_token": scan1_dpt,
        })

    # Tensor-parallel signal (ISSUE 14): tokens/sec vs the tp=1 twin,
    # greedy parity (pinned 1.0 — the sharding is a layout choice, not
    # sampling state), and the model-axis collective bytes one decode
    # dispatch moves per generated token, from AOT-lowering the
    # engine's own rung-1 decode program under its live mesh — the
    # same machinery (and the same number) the committed TP budget
    # pins in CI.
    tp_extra = {"tp": tp}
    if tp > 1:
        tp1_rate = median(rates["tp1"])
        tp_extra.update({
            "tp1_tokens_per_sec": tp1_rate,
            "tp_vs_single_toks": rate / tp1_rate,
            "tp_greedy_parity": greedy_parity(tokens_by_engine["pipe"],
                                              tokens_by_engine["tp1"]),
            "collective_bytes_per_token":
                _tp_collective_bytes_per_token(engines["pipe"]),
        })

    # Paged-prefill kernel vs the gathered XLA fallback, as an isolated
    # single-request TTFT probe (throughput rounds bury prefill inside
    # queueing): only meaningful when the primary engines actually run
    # a kernel impl — on CPU that is interpret mode, a correctness
    # surface whose ratio documents the interpreter tax, while on TPU
    # the same field carries the real kernel-vs-gather TTFT cut.
    if paged and engine.decode_impl != "xla":
        xla_twin = build(pipeline=True, impl="xla",
                         pool_blocks=pool_blocks_primary)
        probe_len = max(2, max_prompt - 1)

        def ttft_p50(e):
            e.reset_latency_stats()
            prng = np.random.default_rng(77)
            for _ in range(3 if quick else 7):
                e.submit(prng.integers(0, cfg.vocab_size,
                                       probe_len).tolist(), 2)
                e.drain()
            p = e.stats()["ttft_s"]
            return (p or {}).get("p50")

        k_p50, x_p50 = ttft_p50(engine), ttft_p50(xla_twin)
        scan_extra["paged_prefill_kernel_vs_xla_ttft"] = {
            "kernel_impl": engine.decode_impl,
            "kernel_p50_s": k_p50, "xla_p50_s": x_p50,
            "kernel_over_xla": (k_p50 / x_p50
                                if k_p50 and x_p50 else None),
        }

    if compare_kv:
        base_rate = median(rates["kv_base"])
        # Greedy token parity vs the default-mode pipelined twin: same
        # workload seeds, deterministic engines, so the match fraction
        # is a pure function of the quantization drift.
        kv_extra.update({
            "baseline_kv_dtype": engines["kv_base"].kv_dtype,
            "baseline_tokens_per_sec": base_rate,
            "kv_vs_baseline": median(rates["pipe"]) / base_rate,
            "kv_greedy_parity": greedy_parity(tokens_by_engine["pipe"],
                                              tokens_by_engine["kv_base"]),
            "estimated_hbm_bytes_per_token_baseline":
                estimate_decode_hbm_bytes_per_token(
                    cfg, num_slots=num_slots, mean_frontier=mean_frontier,
                    kv_dtype=engines["kv_base"].kv_dtype,
                    param_count=n_params),
        })
        if kv_dtype == "int8" and baseline_mode == "fp32":
            # The alias only when it is TRUE under its own name — on a
            # bf16-compute host pass --baseline_kv_dtype=fp32 to get it;
            # otherwise the honest keys are kv_vs_baseline +
            # baseline_kv_dtype.
            kv_extra["int8_vs_fp32"] = kv_extra["kv_vs_baseline"]
        if kv_dtype == "int4" and baseline_mode == "int8":
            kv_extra["int4_vs_int8_toks"] = kv_extra["kv_vs_baseline"]
            if paged:
                # Capacity at equal pool VALUE bytes: the primary int4
                # engines run a 2x-block pool (pool_blocks_primary
                # above), the int8 twin the default — block need per
                # request is dtype-independent, so the measured
                # effective-capacity ratio is the slot-capacity
                # doubling int4 buys at constant value HBM.
                # Lifetime means on BOTH sides (mean_priv is the
                # primary's lifetime figure): mixing the primary's
                # cache-warm steady window with the baseline's
                # all-rounds mean would flatter the ratio.
                bstats = engines["kv_base"].block_pool.stats()
                bpriv = bstats["mean_private_blocks_per_request"]
                cap4 = (engine.kv_pool_blocks / mean_priv
                        if mean_priv else None)
                cap_base = (engines["kv_base"].kv_pool_blocks / bpriv
                            if bpriv else None)
                kv_extra["int4_capacity_vs_int8_equal_value_bytes"] = (
                    cap4 / cap_base if cap4 and cap_base else None)

    spec_extra = {"spec": spec}
    if spec != "off":
        # SAME per-round workload seeds through the speculative engine;
        # greedy parity with the baseline engines is pinned by
        # tests/test_spec.py, so the bench only times it. The comparison
        # baseline is the pipelined engine (the PR 3 configuration).
        sstats = engines["spec"].stats()
        spec_rate = median(rates["spec"])
        spec_extra.update({
            "spec_k": spec_k,
            "spec_tokens_per_sec": spec_rate,
            "spec_vs_baseline": spec_rate / rate,
            "acceptance_rate": sstats["spec_acceptance_rate"],
            "mean_accepted_len": sstats["spec_accepted_len_mean"],
            "spec_verify_steps": sstats["spec"]["verify_steps"],
            "spec_tokens_generated": gen_total["spec"],
        })
        if compare_kv:
            # Acceptance non-regression under the quantized pool: the
            # default-mode spec twin ran the same interleaved rounds, so
            # the delta is attributable to kv_dtype alone (ISSUE-8
            # acceptance: within 1% of fp32).
            acc = sstats["spec_acceptance_rate"]
            acc_base = engines["spec_base"].stats()["spec_acceptance_rate"]
            spec_extra.update({
                "spec_acceptance_rate_baseline": acc_base,
                "spec_acceptance_delta": (
                    None if acc is None or acc_base is None
                    else acc - acc_base),
            })

    from nanosandbox_tpu.analysis.shardcheck import provenance

    sync_rate = median(rates["sync"])
    obs_extra = {"provenance": provenance()}
    if _flag(kv, "emit_obs"):
        # --emit_obs: attach the full metric-registry snapshots (plus
        # the process-global ledgers) so a bench artifact carries the
        # SAME series a live /metrics scrape would — compile counts,
        # latency histograms — not just the headline rate. The spec
        # acceptance families live on the SPEC engine's registry, so it
        # gets its own snapshot when --spec is on.
        from nanosandbox_tpu.obs import global_registry
        obs_extra["obs"] = {"engine": engine.metrics.snapshot(),
                            "process": global_registry().snapshot()}
        if spec != "off":
            obs_extra["obs"]["spec_engine"] = \
                engines["spec"].metrics.snapshot()
    return {
        "metric": "gpt2_124m_batched_decode_tokens_per_sec" if on_tpu
        else "tiny_batched_decode_tokens_per_sec_cpu",
        "value": rate,
        "unit": "tokens/sec",
        "vs_baseline": None,  # no published serving baseline (BASELINE.json)
        "extra": {
            "backend": jax.default_backend(),
            "num_slots": num_slots,
            "max_len": max_len,
            "max_new_tokens": max_new,
            "requests": n_requests,
            "mixed": mixed,
            "repeat": repeat,
            "tokens_generated": generated,
            "decode_steps": engine.steps,
            "prefill_buckets": list(engine.sched.buckets),
            "admit_buckets": list(engine.admit_buckets),
            "trace_counts": dict(engine.trace_counts),
            "elapsed_s": dt,
            "pipelined_tokens_per_sec": rate,
            "sync_tokens_per_sec": sync_rate,
            "pipeline_speedup": rate / sync_rate,
            "rates_per_round": {name: [round(r, 1) for r in rs]
                                for name, rs in rates.items()},
            "ttft_s": stats["ttft_s"],
            "tpot_s": stats["tpot_s"],
            "queue_wait_steps_mean": stats["queue_wait_steps_mean"],
            "repetitive": repetitive,
            **scan_extra,
            **tp_extra,
            **kv_extra,
            **paged_extra,
            **spec_extra,
        },
        **obs_extra,
    }


def _serve_warmup(engine, max_len: int) -> None:
    """Compile a serve engine's reachable admission set by driving the
    real submit/drain path (one wave per (rung, bucket) pair; chunked
    engines compile their chunk shapes the same way), then clear the
    measurement windows — shared by bench_serve's main engine and the
    priority-overload twins (the storm twins instead warm with an
    untimed round of their own storm shape, and the parity probe is
    untimed)."""
    lo = 1
    for bucket in engine.sched.buckets:
        length = min(bucket, max_len - 2)
        lo, prev_lo = bucket + 1, lo
        if length < prev_lo:
            continue
        for k in engine.admit_buckets:
            for _ in range(k):
                engine.submit([0] * length, 2)
            engine.drain()
            engine.reset_prefix_cache()
    engine.reset_latency_stats()


def _bench_serve_scheduling(build_engine, *, cfg, num_slots, max_len,
                            chunk, quick, req_rate_1x, deadline_i,
                            deadline_b, max_prompt, max_new) -> dict:
    """The ISSUE-13 scheduling probes (--sched): prefill-storm twin,
    priority-vs-FIFO twin at overload, and preemption-resume parity.
    Each probe builds fresh engine twins off ``build_engine`` and runs
    them in the interleaved/identical-input style the decode bench
    twins use, so host noise cannot manufacture a ratio."""
    import time

    import numpy as np

    from nanosandbox_tpu.obs import TERMINAL_EVENTS
    from nanosandbox_tpu.serve import EngineSupervisor, FaultPlan

    rng = np.random.default_rng(777)

    # ---- 1. prefill storm: chunked vs unchunked twin -----------------
    # A burst of max-length prompts lands while half the slots decode.
    # The decoders' inter-token gaps come from their flight-recorder
    # retire timestamps; the p99 of those gaps IS TPOT-under-storm.
    rounds = 3 if quick else 5
    engines = {"chunked": build_engine(prefill_chunk=chunk),
               "unchunked": build_engine()}
    n_dec = max(2, num_slots // 2)
    dec_budget = max(8, max_len - 12)
    storm_len = max_len - 2
    n_storm = num_slots
    missing = 0

    def storm_round(eng, seed):
        r = np.random.default_rng(seed)
        eng.reset_latency_stats()
        if eng.paged:
            eng.reset_prefix_cache()
        dec = [eng.submit(r.integers(0, cfg.vocab_size, 4).tolist(),
                          dec_budget, slo_class="interactive")
               for _ in range(n_dec)]
        for _ in range(6):
            eng.step()
        storm = [eng.submit(
            r.integers(0, cfg.vocab_size, storm_len).tolist(), 2,
            slo_class="batch") for _ in range(n_storm)]
        eng.drain()
        events = eng.flight.events()
        gaps = []
        for rid in dec:
            ts = [e["t"] for e in events
                  if e.get("rid") == rid and e["ev"] == "retire"]
            gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        miss = sum(1 for rid in dec + storm
                   if len([e for e in events if e.get("rid") == rid
                           and e["ev"] in TERMINAL_EVENTS]) != 1)
        return (float(np.percentile(gaps, 99)) if gaps else 0.0), miss

    for eng in engines.values():
        storm_round(eng, seed=123)       # untimed compile round
    p99s = {name: [] for name in engines}
    for i in range(rounds):
        order = list(engines)
        if i % 2:
            order.reverse()              # rotation: no fixed adjacency
        for name in order:
            p99, miss = storm_round(engines[name], seed=1000 + i)
            p99s[name].append(p99)
            missing += miss
    med = {n: float(np.median(v)) for n, v in p99s.items()}
    storm = {"tpot_p99_under_storm": med["chunked"],
             "tpot_p99_under_storm_unchunked": med["unchunked"],
             "tpot_p99_ratio": (med["chunked"] / med["unchunked"]
                                if med["unchunked"] else None),
             "rounds": rounds, "per_round_p99_s": p99s,
             "prefill_chunk": chunk, "storm_size": n_storm,
             "active_decoders": n_dec,
             "unreached_terminals": missing}

    # ---- 2. priority + preemption vs FIFO at 2x capacity -------------
    # Identical arrival schedule and request stream against two twins:
    # class priorities + preemption on, vs every submission at one
    # priority with preemption off (the pre-ISSUE-13 FIFO engine).
    # Interactive is the MINORITY class (~35% of requests, small
    # budgets): its own offered load fits inside capacity, so priority
    # scheduling can actually save it — the overload is the long batch
    # work FIFO head-of-line-blocks it behind. (A majority class past
    # capacity on its own is unsavable by ANY ordering.)
    # Long enough that 2x-capacity arrivals build a REAL backlog: work
    # arrives at ~2x the service rate, so unfinished work at the last
    # arrival grows to ~half the total — n_req = 24 * num_slots makes
    # that terminal backlog ~12 batch-turnovers (base_lat units), 4x
    # the interactive deadline below, so the FIFO twin's misses are a
    # structural fraction of the class, not a tail-of-window accident.
    # (With every shape precompiled by _serve_warmup there are no
    # compile stalls left to manufacture queueing, so the run length
    # must produce it honestly; the timed window stays sub-second on
    # the quick CPU config — requests are a few tokens each.)
    n_req = 24 * num_slots
    arrivals = np.cumsum(
        rng.exponential(1.0 / (req_rate_1x * 2.0), n_req)).tolist()
    reqs = []
    for _ in range(n_req):
        L = int(rng.integers(1, max_prompt))
        prompt = rng.integers(0, cfg.vocab_size, L).tolist()
        if rng.random() < 0.35:
            # The sweep's own interactive deadline (3x base_lat):
            # meetable WHEN the class is prioritized (its own load fits
            # inside capacity, so it only ever waits behind in-service
            # batch rows — and a deadline-pressed head preempts those),
            # hopeless for the later arrivals when FIFO parks them
            # behind a batch backlog that passes 3 base_lat mid-run —
            # which is exactly the separation the CI pin asserts.
            mnt = int(rng.integers(max(1, max_new // 4),
                                   max(2, max_new // 2)))
            reqs.append((prompt, mnt, "interactive", deadline_i))
        else:
            mnt = int(rng.integers(max(2, max_new // 2), max_new + 1))
            reqs.append((prompt, mnt, "batch", deadline_b))

    def overload_point(eng, submit_priority=None):
        # Untimed FULL-GRID warmup — every (rung, bucket) admission
        # shape, not just the shapes the first few requests happen to
        # hit: a mid-window arrival landing on an uncompiled shape
        # would stall queued deadlines on an XLA compile and charge
        # the attainment pin to compile placement instead of
        # scheduling policy.
        _serve_warmup(eng, max_len)
        if eng.paged:
            eng.reset_prefix_cache()
        t0 = time.perf_counter()
        i = 0
        while i < len(arrivals) or eng.has_work():
            now = time.perf_counter() - t0
            while i < len(arrivals) and arrivals[i] <= now:
                p, mnt, cls, dl = reqs[i]
                kw = {"deadline_s": dl, "slo_class": cls}
                if submit_priority is not None:
                    kw["priority"] = submit_priority
                eng.submit(p, mnt, **kw)
                i += 1
            if eng.has_work():
                eng.step()
            else:
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.002))
        classes = eng.stats()["slo"]["classes"]
        return {c: {"attainment": s["attainment"],
                    "goodput_tokens": s["goodput_tokens"],
                    "met": s["met"], "missed": s["missed"],
                    "shed": s["shed"]} for c, s in classes.items()}

    pri_on = overload_point(build_engine(preemption=True))
    pri_off = overload_point(build_engine(preemption=False),
                             submit_priority=1)
    priority = {
        "arrival_multiplier": 2.0, "requests": n_req,
        "per_class": pri_on, "per_class_priority_off": pri_off,
        "interactive_attainment":
            pri_on.get("interactive", {}).get("attainment"),
        "interactive_attainment_priority_off":
            pri_off.get("interactive", {}).get("attainment"),
    }

    # ---- 3. preemption-resume greedy parity --------------------------
    # A preempt_storm plan evicts victims repeatedly; every output must
    # be token-identical to the clean twin's (the resume = prefix-hit
    # re-prefill continues the same fold_in(seed, position) stream).
    par_reqs = []
    for i in range(2 * num_slots):
        L = int(rng.integers(1, max_prompt))
        par_reqs.append((rng.integers(0, cfg.vocab_size, L).tolist(),
                         int(rng.integers(4, max_new + 1)),
                         "batch" if i % 2 else "interactive"))
    clean = build_engine()
    [clean.submit(p, m, slo_class=c) for p, m, c in par_reqs]
    want = [r.tokens for r in sorted(clean.drain(), key=lambda r: r.rid)]
    plan = FaultPlan.parse("preempt_storm@2x4")
    chaotic = build_engine(faults=plan)
    sup = EngineSupervisor(chaotic, backoff_base_s=0.0)
    [chaotic.submit(p, m, slo_class=c) for p, m, c in par_reqs]
    got_map = {}
    guard = 0
    while chaotic.has_work() and guard < 200_000:
        for r in sup.step():
            got_map[r.rid] = r
        guard += 1
    got = [got_map[rid].tokens for rid in sorted(got_map)]
    matches = sum(1 for a, b in zip(want, got) if a == b)
    parity = (matches / len(want)) if want else None

    return {"storm": storm, "priority": priority,
            "preempt_resume_parity": parity,
            "parity_probe_preemptions": chaotic.preemptions,
            "parity_probe_requests": len(par_reqs)}


def _bench_serve_disagg(model, params, *, cfg, num_slots, max_len,
                        chunk, quick, paged, kv_page) -> dict:
    """The ISSUE-16 disaggregation probe (--disagg): DisaggPair vs the
    chunked-colocated engine under the SAME prefill storm, in the same
    interleaved rotated rounds the chunked/unchunked twin uses.

    Chunking PACES the storm inside one engine (ISSUE 13 pinned the
    chunked/unchunked TPOT ratio); disaggregation REMOVES it — the
    decode tier never sees a prefill dispatch, so its inter-token gaps
    should beat even the chunked twin's. Also emits migration latency
    p50/p99 and the decode-tier dispatch ledger (the zero-prefill
    assertion CI pins), plus a greedy parity count between the
    disaggregated and colocated outputs."""
    import time

    import numpy as np

    from nanosandbox_tpu.serve import DisaggPair, Engine

    rounds = 3 if quick else 5
    n_dec = max(2, num_slots // 2)
    dec_budget = max(8, max_len - 12)
    storm_len = max_len - 2
    n_storm = num_slots
    missing = 0

    def build_pair():
        return DisaggPair(model, params, num_slots=num_slots,
                          max_len=max_len, pipeline=True, paged=True,
                          kv_page_size=kv_page)

    def build_chunked():
        return Engine(model, params, num_slots=num_slots,
                      max_len=max_len, pipeline=True, paged=paged,
                      kv_page_size=kv_page, prefill_chunk=chunk)

    engines = {"disagg": build_pair(), "chunked": build_chunked()}

    def storm_round(eng, seed):
        """One storm round against either harness (same submit/step/
        drain surface).  The TPOT being compared is 'wall time per
        token for an active decoder ON ITS TIER'S HARDWARE':

        - colocated twin: retire-timestamp gaps — each engine step is
          chunk prefill + decode dispatch sharing one device, and that
          whole step IS the decoder's inter-token gap.
        - disagg pair: the two tiers step SERIALLY in this in-process
          harness, so retire wall-gaps would charge the decode tier
          for prefill-tier storm work that on a dedicated decode pod
          runs concurrently.  Instead we time the decode engine's own
          step() — one retired token per active decoder per step, so
          its duration is exactly the decode tier's inter-token gap on
          dedicated hardware."""
        nonlocal missing
        r = np.random.default_rng(seed)
        eng.reset_latency_stats()
        if isinstance(eng, DisaggPair):
            eng.prefill.reset_prefix_cache()
            eng.decode.reset_prefix_cache()
        elif eng.paged:
            eng.reset_prefix_cache()
        gaps = []
        restore = None
        if isinstance(eng, DisaggPair):
            inner = eng.decode.step

            def timed_step():
                busy = bool(eng.decode._active)
                t0 = time.perf_counter()
                out = inner()
                if busy:     # steps that advance decoders, not no-ops
                    gaps.append(time.perf_counter() - t0)
                return out

            eng.decode.step, restore = timed_step, inner
        try:
            dec = [eng.submit(r.integers(0, cfg.vocab_size, 4).tolist(),
                              dec_budget, slo_class="interactive")
                   for _ in range(n_dec)]
            for _ in range(6):
                eng.step()
            storm = [eng.submit(
                r.integers(0, cfg.vocab_size, storm_len).tolist(), 2,
                slo_class="batch") for _ in range(n_storm)]
            results = {res.rid: res for res in eng.drain()}
        finally:
            if restore is not None:
                eng.decode.step = restore
        missing += sum(1 for rid in dec + storm if rid not in results)
        if not isinstance(eng, DisaggPair):
            events = eng.flight.events()
            for rid in dec:
                ts = [e["t"] for e in events
                      if e.get("rid") == rid and e["ev"] == "retire"]
                gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        return (float(np.percentile(gaps, 99)) if gaps else 0.0)

    for eng in engines.values():
        storm_round(eng, seed=123)       # untimed compile round
    p99s = {name: [] for name in engines}
    for i in range(rounds):
        order = list(engines)
        if i % 2:
            order.reverse()              # rotation: no fixed adjacency
        for name in order:
            p99s[name].append(storm_round(engines[name],
                                          seed=3000 + i))
    med = {n: float(np.median(v)) for n, v in p99s.items()}
    pair = engines["disagg"]

    # Greedy parity: disaggregated outputs == colocated outputs on a
    # fresh mixed mix (the acceptance criterion, measured not assumed).
    rng = np.random.default_rng(515)
    par_reqs = [(rng.integers(0, cfg.vocab_size,
                              int(rng.integers(2, storm_len))).tolist(),
                 int(rng.integers(2, 8)))
                for _ in range(2 * num_slots)]
    coloc = build_chunked()
    ref = [coloc.submit(p, m, temperature=0.0, seed=70 + i)
           for i, (p, m) in enumerate(par_reqs)]
    ref_map = {res.rid: res for res in coloc.drain()}
    par_pair = build_pair()
    got = [par_pair.submit(p, m, temperature=0.0, seed=70 + i)
           for i, (p, m) in enumerate(par_reqs)]
    got_map = {res.rid: res for res in par_pair.drain()}
    matches = sum(1 for a, b in zip(ref, got)
                  if ref_map[a].tokens == got_map[b].tokens)

    st = pair.stats()
    mig = st["migration_s"]
    decode_ledger = st["tiers"]["decode"]["host_dispatches"]
    return {
        "tpot_p99_under_storm_disagg": med["disagg"],
        "tpot_p99_under_storm_chunked": med["chunked"],
        "tpot_p99_ratio_disagg_vs_chunked": (
            med["disagg"] / med["chunked"] if med["chunked"] else None),
        "rounds": rounds, "per_round_p99_s": p99s,
        "prefill_chunk": chunk, "storm_size": n_storm,
        "active_decoders": n_dec,
        "unreached_terminals": missing,
        "migrations": st["migrations"],
        "fallbacks": st["fallbacks"],
        "migration_p50_s": mig.get("p50"),
        "migration_p99_s": mig.get("p99"),
        "decode_tier_dispatch_ledger": dict(decode_ledger),
        "decode_tier_prefill_dispatches": decode_ledger.get(
            "prefill", 0),
        "parity_matches": matches,
        "parity_requests": len(par_reqs),
        "parity": (matches / len(par_reqs)) if par_reqs else None,
    }


def bench_serve(kv: dict, *, quick: bool, on_tpu: bool) -> dict:
    """Closed-loop serving load generator: goodput under overload.

    Tokens/sec says how fast the engine CAN go; production cares how
    much of that survives a deadline at a given arrival rate. This mode
    (ISSUE 10, the ROADMAP-3 measurement harness) drives the real
    Engine with a paced arrival process instead of a saturating drain:

      1. CAPACITY PROBE — a saturated drain measures tokens/sec and a
         per-request base latency on THIS host (so deadlines and
         arrival rates scale with the machine, not hard-coded numbers).
      2. OVERLOAD SWEEP — for each arrival multiplier (default 1x and
         2x capacity; --load=a,b,c), requests arrive by a Poisson
         process (exponential gaps) with mixed prompt/budget lengths
         and per-class deadlines: ~70% 'interactive' (deadline
         3 x base latency), the rest 'batch' (12 x). The loop submits
         when arrivals come due and steps the engine in between —
         queueing, shedding and SLO attainment emerge from the same
         code paths production traffic exercises.
      3. BURST POINT — all-at-once arrivals at several times slot
         capacity under a tight deadline (2 x base latency), so the
         queue-expiry shed path is structurally exercised: the sweep
         JSON must show sheds somewhere or the shed machinery is dead
         (the CI smoke asserts the flight ledger agrees event-for-
         event).

    Every sweep point emits ``goodput_toks`` (tokens of requests that
    finished within deadline), ``goodput_toks_per_sec``,
    ``slo_attainment`` and ``shed_rate`` — the regression-pinned
    numbers goodput-under-overload turns into.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.sample import cast_params_for_serving
    from nanosandbox_tpu.serve import Engine

    if on_tpu:
        cfg = GPTConfig(n_layer=12, n_head=12, n_embd=768, block_size=1024,
                        vocab_size=50304, dropout=0.0,
                        compute_dtype="bfloat16", attention_impl="auto")
        max_len, max_new = 512, (64 if quick else 128)
    else:
        cfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=128,
                        vocab_size=256, dropout=0.0,
                        compute_dtype="float32", attention_impl="xla")
        max_len, max_new = (64, 8) if quick else (128, 16)

    num_slots = int(kv.get("num_slots", kv.get("slots", 8)))
    max_len = int(kv.get("max_len", max_len))
    max_new = int(kv.get("max_new_tokens", max_new))
    n_requests = int(kv.get("requests", (3 if quick else 6) * num_slots))
    interactive_share = float(kv.get("interactive_share", 0.7))
    loads = [float(x) for x in str(kv.get("load", "1,2")).split(",") if x]
    burst_mult = float(kv.get("burst", 6.0))   # 0 disables the burst point
    kv_page = int(kv.get("kv_page_size", 16))
    paged = kv.get("paged", "on") != "off"

    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    params = cast_params_for_serving(params, cfg.compute_dtype)
    # --faults: attach a (disabled) fault plan + the recovery
    # supervisor. The plan stays dark through warmup, the capacity
    # probe and the clean sweep points — it re-arms (relative step 0 =
    # now) only for the dedicated chaos point, so goodput-under-fault
    # has a clean twin to be a ratio OF.
    faults_spec = kv.get("faults")
    fault_plan = None
    if faults_spec:
        from nanosandbox_tpu.serve import EngineSupervisor, FaultPlan
        fault_plan = FaultPlan.parse(faults_spec)
        fault_plan.enabled = False
    prefill_chunk = int(kv.get("prefill_chunk", 0)) or None

    def build_engine(**kw):
        """One more engine with the sweep's layout — the scheduling
        probes build twins (chunked/unchunked, priority/FIFO, clean/
        chaotic) off the same baseline."""
        kw.setdefault("paged", paged)
        kw.setdefault("kv_page_size", kv_page)
        return Engine(model, params, num_slots=num_slots,
                      max_len=max_len, pipeline=True, **kw)

    engine = build_engine(faults=fault_plan, prefill_chunk=prefill_chunk)
    if fault_plan is not None:
        stepper = EngineSupervisor(engine, backoff_base_s=0.01,
                                   backoff_max_s=0.5)
    else:
        stepper = engine

    max_prompt = max(2, max_len - max_new)
    rng = np.random.default_rng(4242)

    def make_request(tight_deadline=None):
        L = int(rng.integers(1, max_prompt))
        mnt = int(rng.integers(max(1, max_new // 4), max_new + 1))
        prompt = rng.integers(0, cfg.vocab_size, L).tolist()
        if tight_deadline is not None:
            cls, dl = "interactive", tight_deadline
        elif rng.random() < interactive_share:
            cls, dl = "interactive", deadline_i
        else:
            cls, dl = "batch", deadline_b
        return prompt, mnt, cls, dl

    # Warmup: compile every reachable (rung, bucket) program (the
    # decode-bench discipline — a timed point must never eat an XLA
    # compile). Under --prefill_chunk the reachable set is smaller (big
    # buckets route through the chunk lane) and the warmup, going
    # through the same admission code, compiles exactly that set.
    _serve_warmup(engine, max_len)

    # Capacity probe: saturated drain, no deadlines.
    n_cap = 3 * num_slots
    for _ in range(n_cap):
        L = int(rng.integers(1, max_prompt))
        mnt = int(rng.integers(max(1, max_new // 4), max_new + 1))
        engine.submit(rng.integers(0, cfg.vocab_size, L).tolist(), mnt)
    t0 = time.perf_counter()
    cap_results = engine.drain()
    cap_dt = time.perf_counter() - t0
    cap_tokens = sum(len(r.tokens) for r in cap_results)
    cap_rate = cap_tokens / cap_dt
    mean_tokens = cap_tokens / n_cap
    # Time one full continuous batch takes to turn over — the natural
    # latency unit deadlines scale from (host-independent by
    # construction: a slower machine gets proportionally looser
    # deadlines and the same attainment shape).
    base_lat = cap_dt * num_slots / n_cap
    deadline_i = max(3.0 * base_lat, 0.02)
    deadline_b = max(12.0 * base_lat, 0.08)
    req_rate_1x = cap_rate / mean_tokens

    def run_point(name, arrivals, tight_deadline=None):
        """One sweep point: ``arrivals`` is the sorted list of offsets
        (seconds) at which requests become submittable."""
        engine.reset_latency_stats()
        reqs = [make_request(tight_deadline) for _ in arrivals]
        results = []
        t0 = time.perf_counter()
        i = 0
        while i < len(arrivals) or engine.has_work():
            now = time.perf_counter() - t0
            while i < len(arrivals) and arrivals[i] <= now:
                prompt, mnt, cls, dl = reqs[i]
                engine.submit(prompt, mnt, deadline_s=dl, slo_class=cls)
                i += 1
            if engine.has_work():
                results.extend(stepper.step())
            elif i < len(arrivals):
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.002))
        elapsed = time.perf_counter() - t0
        stats = engine.stats()
        slo = stats["slo"]["overall"]
        shed = [r for r in results if r.finish_reason == "shed"]
        flight_sheds = sum(1 for e in engine.flight.events()
                           if e["ev"] == "shed")
        return {
            "scenario": name,
            "requests": len(arrivals),
            "finished": len(results) - len(shed),
            "shed": len(shed),
            "shed_rate": len(shed) / max(len(arrivals), 1),
            "slo_attainment": slo["attainment"],
            "goodput_toks": slo["goodput_tokens"],
            "goodput_toks_per_sec": slo["goodput_tokens"] / elapsed,
            "late_toks": slo["late_tokens"],
            "slo_by_class": stats["slo"]["classes"],
            "elapsed_s": elapsed,
            "req_per_s_offered": (len(arrivals) / arrivals[-1]
                                  if len(arrivals) > 1 and arrivals[-1] > 0
                                  else None),
            "ttft_s": stats["ttft_s"],
            "queue_wait_steps_mean": stats["queue_wait_steps_mean"],
            # The ledger must agree with the results list event-for-
            # event: every shed Result has exactly one terminal `shed`
            # flight event (the CI smoke asserts this stays true).
            "flight_shed_events": flight_sheds,
            "block_stall_steps": (stats["kv_pool"].get(
                "block_stall_steps") if paged else None),
        }

    sweep = {}
    for mult in loads:
        rate = req_rate_1x * mult
        gaps = rng.exponential(1.0 / rate, n_requests)
        arrivals = np.cumsum(gaps).tolist()
        key = (f"{mult:g}x")
        sweep[key] = run_point(key, arrivals)
        sweep[key]["arrival_multiplier"] = mult
        sweep[key]["req_per_s_target"] = rate
    if burst_mult > 0:
        n_burst = max(2, int(round(burst_mult * num_slots)))
        sweep["burst"] = run_point("burst", [0.0] * n_burst,
                                   tight_deadline=2.0 * base_lat)
        sweep["burst"]["arrival_multiplier"] = None
        sweep["burst"]["burst_size"] = n_burst

    fault_extra = None
    if fault_plan is not None:
        # CHAOS point: the 1x arrival process again, with the plan
        # armed relative to NOW — recovery happens mid-point and the
        # point must still finish every request (run_point loops until
        # the engine is idle, so an unrecovered engine hangs the bench
        # rather than silently passing).
        fault_plan.rearm(engine.steps)
        fault_plan.enabled = True
        gaps = rng.exponential(1.0 / req_rate_1x, n_requests)
        sweep["fault"] = run_point("fault", np.cumsum(gaps).tolist())
        fault_plan.enabled = False
        if kv.get("flight_out"):
            # The fault run's black box as a CI artifact: reset_latency
            # at the point start cleared everything earlier, so this is
            # exactly the chaos window's ledger.
            engine.flight.dump(kv["flight_out"])
        clean_1x = sweep.get("1x", {}).get("goodput_toks_per_sec")
        under_fault = sweep["fault"]["goodput_toks_per_sec"]
        rec = engine.stats()["recovery"]
        sup_stats = stepper.stats()
        fault_extra = {
            "plan": fault_plan.describe(),
            "fired": fault_plan.stats()["fired"],
            "recoveries": engine.recoveries,
            "requeued": engine.requeued,
            "poisoned_steps": rec["poisoned_steps"],
            "recovery_s": rec["recovery_s"],
            "supervisor": sup_stats,
            "supervisor_state": sup_stats["state"],
            "goodput_under_fault_toks_per_sec": under_fault,
            "goodput_under_fault_ratio": (
                under_fault / clean_1x if clean_1x else None),
        }

    sched_extra = None
    if _flag(kv, "sched"):
        # Scheduling probes (ISSUE 13): storm twin, priority twin,
        # preemption-resume parity. Default chunk = the smallest
        # bucket (the finest interleave the compiled grid offers).
        chunk = prefill_chunk or min(engine.sched.buckets)
        sched_extra = _bench_serve_scheduling(
            build_engine, cfg=cfg, num_slots=num_slots,
            max_len=max_len, chunk=chunk, quick=quick,
            req_rate_1x=req_rate_1x, deadline_i=deadline_i,
            deadline_b=deadline_b, max_prompt=max_prompt,
            max_new=max_new)

    disagg_extra = None
    if _flag(kv, "disagg"):
        # Disaggregation probe (ISSUE 16): DisaggPair vs the chunked-
        # colocated engine under the same prefill storm. Same default
        # chunk choice as the scheduling twin so the two comparisons
        # share a baseline.
        chunk = prefill_chunk or min(engine.sched.buckets)
        disagg_extra = _bench_serve_disagg(
            model, params, cfg=cfg, num_slots=num_slots,
            max_len=max_len, chunk=chunk, quick=quick,
            paged=paged, kv_page=kv_page)

    one_x = sweep.get("1x") or next(iter(sweep.values()))
    from nanosandbox_tpu.analysis.shardcheck import provenance

    obs_extra = {"provenance": provenance()}
    if _flag(kv, "emit_obs"):
        from nanosandbox_tpu.obs import (global_registry,
                                         register_process_vitals)
        register_process_vitals()
        obs_extra["obs"] = {"engine": engine.metrics.snapshot(),
                            "process": global_registry().snapshot()}
    return {
        "metric": "gpt2_124m_serve_goodput_toks_per_sec" if on_tpu
        else "tiny_serve_goodput_toks_per_sec_cpu",
        "value": one_x["goodput_toks_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,   # no published serving baseline
        "extra": {
            "backend": jax.default_backend(),
            "num_slots": num_slots,
            "max_len": max_len,
            "max_new_tokens": max_new,
            "requests_per_point": n_requests,
            "paged": paged,
            "capacity_toks_per_sec": cap_rate,
            "mean_tokens_per_request": mean_tokens,
            "base_latency_s": base_lat,
            "deadline_interactive_s": deadline_i,
            "deadline_batch_s": deadline_b,
            "interactive_share": interactive_share,
            "req_per_s_1x": req_rate_1x,
            "prefill_chunk": prefill_chunk,
            "sweep": sweep,
            "fault": fault_extra,
            "scheduling": sched_extra,
            "disagg": disagg_extra,
            "watchdog_trips": engine.stats()["watchdog"]["trips"],
            "trace_counts": dict(engine.trace_counts),
        },
        **obs_extra,
    }


def bench_fleet(kv: dict, *, quick: bool, on_tpu: bool) -> dict:
    """Multi-replica fleet bench (ISSUE 15): does prefix-affinity
    routing actually move fleet TTFT, and does the fleet survive losing
    a replica?

      1. AFFINITY vs RANDOM — one in-process Fleet (serve/fleet.py),
         alternating the router between affinity scoring and its
         affinity-blind twin (seeded uniform-random over the ready
         set) across interleaved rounds on an
         IDENTICAL shared-prefix workload (G system prompts, each with
         many short-suffix followers, pool sized so one replica cannot
         cache every group: affinity partitions the groups across the
         fleet, random duplicates and thrashes). Reports the
         affinity/random mean-TTFT ratio (from the merged flight
         ledgers' submit->admit gaps — the same JSONL an operator
         would analyze) and both hit rates. CI pins ratio <= 0.85.
      2. PARITY — every request is greedy; every fleet result (both
         modes, every round) must match a solo reference engine
         token-for-token: routing must never change outputs.
      3. REPLICA KILL — a fresh fleet runs the same workload with a
         ``replica_down`` fault plan: one replica hard-dies
         mid-traffic, victims re-route with salvaged tokens. Pins
         zero unreached terminals (every submit -> exactly one fleet
         Result, one terminal per namespaced rid in the merged
         ledger) and goodput >= 0.4x the clean twin.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.obs import TERMINAL_EVENTS
    from nanosandbox_tpu.sample import cast_params_for_serving
    from nanosandbox_tpu.serve import Engine, FaultPlan, Fleet

    if on_tpu:
        cfg = GPTConfig(n_layer=12, n_head=12, n_embd=768, block_size=1024,
                        vocab_size=50304, dropout=0.0,
                        compute_dtype="bfloat16", attention_impl="auto")
        max_len, max_new = 512, 32
    else:
        # max_len 128 with ~6-block system prompts, and a model one
        # notch above the other CPU benches' tiny default: the regime
        # PR 9 measured hit TTFT ~0.5x miss in — shorter prompts (or
        # the 2-layer/64-wide model) are dispatch-bound on CPU and the
        # prefill savings affinity routes for would vanish into launch
        # overhead, measuring the router against noise.
        cfg = GPTConfig(n_layer=3, n_head=4, n_embd=128, block_size=128,
                        vocab_size=256, dropout=0.0,
                        compute_dtype="float32", attention_impl="xla")
        max_len, max_new = 128, 8

    n_replicas = int(kv.get("n_replicas", 2))
    num_slots = int(kv.get("num_slots", kv.get("slots", 4)))
    max_len = int(kv.get("max_len", max_len))
    max_new = int(kv.get("max_new_tokens", max_new))
    page = int(kv.get("kv_page_size", 16))
    rounds = int(kv.get("repeat", 3 if quick else 5))
    # Shared-prefix mix: G "system prompts" of prefix_blocks full pages
    # each, every request = one group's prefix + a short unique suffix.
    # The per-replica pool (the num_slots * slot_blocks default —
    # byte-parity with a dense pool) fits one replica's AFFINITY SHARE
    # of the chains (n_groups / n_replicas) next to its live rows, but
    # NOT every group's chain: under random routing each replica tries
    # to cache all of them and LRU-thrashes (round-robin group arrival
    # is LRU's worst case — the evicted chain is always the next one
    # back), which is exactly the fleet-capacity story affinity
    # routing exists to fix: N caches that partition the prefix set
    # instead of N copies of its most recent corner.
    n_groups = int(kv.get("groups", 3 * n_replicas))
    prefix_blocks = int(kv.get("prefix_blocks",
                               max(2, (max_len * 3 // 4) // page)))
    prefix_len = prefix_blocks * page
    n_requests = int(kv.get("requests", 8 * n_groups))
    slot_blocks = -(-max_len // page)
    pool_blocks = int(kv.get("kv_pool_blocks",
                             num_slots * slot_blocks
                             - slot_blocks // 2))

    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    params = cast_params_for_serving(params, cfg.compute_dtype)

    rng = np.random.default_rng(1515)
    groups = [rng.integers(0, cfg.vocab_size, prefix_len).tolist()
              for _ in range(n_groups)]
    budget_cap = max(2, min(max_new, max_len - prefix_len - page // 2))
    requests = []
    for i in range(n_requests):
        g = groups[i % n_groups]
        # Suffix ends with a request-index token so every prompt is
        # UNIQUE: the greedy-parity oracle maps prompt -> budget, and
        # two same-prompt requests with different budgets would
        # silently corrupt it (a latent CI trap, not a routing bug).
        sfx = rng.integers(0, cfg.vocab_size,
                           int(rng.integers(1, page // 2 - 1))).tolist()
        sfx.append(i % cfg.vocab_size)
        requests.append((g + sfx, int(rng.integers(2, budget_cap + 1))))
    budget_by_prompt = {tuple(p): m for p, m in requests}
    assert len(budget_by_prompt) == n_requests, (
        "workload prompts must be unique for the parity oracle "
        f"(--requests={n_requests} > vocab makes index tokens collide)")

    def build_fleet(**kw):
        fleet = Fleet(model, params, n_replicas=n_replicas,
                      num_slots=num_slots, max_len=max_len,
                      kv_page_size=page, kv_pool_blocks=pool_blocks,
                      **kw)
        for eng in fleet.replicas.values():
            _serve_warmup(eng, max_len)
        fleet.reset_prefix_caches()
        fleet.reset_latency_stats()
        return fleet

    def run_point(fleet):
        """Drive the workload with light pacing (submit a pair, step
        twice) so routing, admission and retirement interleave the way
        live traffic does while the queue stays SHALLOW — TTFT then
        reflects each request's own admission+prefill path, which is
        what affinity changes. (A saturating backlog instead batches
        the misses into shared big-bucket waves and equalizes the
        modes; goodput would show the difference there, TTFT not.)
        Returns per-point measurements from the merged flight ledger."""
        d0 = dict(fleet.router.decisions)   # delta: THIS point's routes
        t0 = time.perf_counter()
        it = iter(requests)
        pending = len(requests)
        results = []
        while pending or fleet.has_work():
            for _ in range(2):
                req = next(it, None)
                if req is None:
                    break
                prompt, mnt = req
                fleet.submit(prompt, mnt)
                pending -= 1
            for _ in range(2):
                results.extend(fleet.step())
        results.extend(fleet.drain())
        elapsed = time.perf_counter() - t0
        ttfts = []
        submits = {}
        terminals = {}
        for e in fleet.merged_flight_events():
            rid = e.get("rid")
            if e["ev"] == "submit":
                submits[rid] = e["t"]
            elif e["ev"] == "admit" and rid in submits:
                ttfts.append(e["t"] - submits.pop(rid))
            if e["ev"] in TERMINAL_EVENTS and rid is not None:
                terminals[rid] = terminals.get(rid, 0) + 1
        st = fleet.stats()
        hits = sum(v["prefix_hit_tokens"]
                   for v in st["replicas"].values())
        miss = sum(v["prefix_miss_tokens"]
                   for v in st["replicas"].values())
        ok_tokens = sum(len(r.tokens) for r in results
                        if r.finish_reason in ("length", "eos"))
        return {
            "results": results,
            "ttfts": ttfts,
            "ttft_mean_s": (sum(ttfts) / len(ttfts)) if ttfts else None,
            "hit_rate": hits / (hits + miss) if hits + miss else None,
            "goodput_toks_per_sec": ok_tokens / elapsed,
            "elapsed_s": elapsed,
            "decisions": {k: v - d0.get(k, 0)
                          for k, v in st["router"]["decisions"].items()},
            "multi_terminal_rids": sum(1 for n in terminals.values()
                                       if n != 1),
        }

    # ---- affinity vs random, interleaved rounds on ONE fleet ---------
    fleet = build_fleet()
    # One solo reference engine, each request run serially: greedy
    # outputs are batch-independent and prefix-hit-invariant (both
    # pinned elsewhere), so a single warm engine is a valid oracle for
    # every (prompt, budget) — routing must never change tokens.
    ref_eng = Engine(model, params, num_slots=num_slots,
                     max_len=max_len, kv_page_size=page)
    reference: dict = {}

    def ref_tokens(prompt: tuple):
        if prompt not in reference:
            ref_eng.submit(list(prompt), budget_by_prompt[prompt])
            reference[prompt] = ref_eng.drain()[-1].tokens
        return reference[prompt]

    aff_rounds, rand_rounds = [], []
    parity_ok = 0
    parity_total = 0
    for r in range(2 * rounds):
        # Alternate pair order (A R | R A | A R ...) so slow host
        # drift across the run cancels instead of biasing one mode —
        # the decode bench's engine-order rotation, mode-wise.
        affinity = (r % 2 == 0) ^ (r // 2 % 2 == 1)
        fleet.router.affinity = affinity
        fleet.reset_prefix_caches()
        fleet.reset_latency_stats()
        point = run_point(fleet)
        (aff_rounds if affinity else rand_rounds).append(point)
        for res in point["results"]:
            parity_total += 1
            parity_ok += ref_tokens(tuple(res.prompt)) == res.tokens

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    # Pool the per-request TTFT samples across every round of a mode
    # (hundreds of samples each) instead of a median of 3-5 per-round
    # means: the hit/miss mix per round is DETERMINISTIC (same arrival
    # order, same pool), so pooling only averages away host noise.
    # The PINNED ratio is the p75 one: TTFT is bimodal (hit cluster ~
    # 0.5x the miss cluster), affinity holds its hit share ABOVE 0.75
    # and random's structurally sits below it (duplication + LRU
    # thrash), so affinity's p75 lands in the hit cluster and random's
    # in the miss cluster — a separation set by the deterministic
    # hit-rate mix, not by how quiet the CI host felt today. The mean
    # ratio rides along for trend tracking.
    aff_all = sorted(t for p in aff_rounds for t in p["ttfts"])
    rand_all = sorted(t for p in rand_rounds for t in p["ttfts"])
    p75 = lambda xs: xs[(3 * len(xs)) // 4] if xs else None  # noqa: E731
    aff_ttft = sum(aff_all) / len(aff_all) if aff_all else None
    rand_ttft = sum(rand_all) / len(rand_all) if rand_all else None
    aff_p75, rand_p75 = p75(aff_all), p75(rand_all)
    clean_goodput = med([p["goodput_toks_per_sec"] for p in aff_rounds])

    # ---- replica kill point ------------------------------------------
    kill_step = int(kv.get("kill_step", 12))
    kfleet = build_fleet(
        faults=FaultPlan.parse(f"replica_down@{kill_step}"))
    kfleet.faults.rearm(kfleet.steps)
    kpoint = run_point(kfleet)
    unreached = n_requests - len(kpoint["results"])
    kill = {
        "goodput_toks_per_sec": kpoint["goodput_toks_per_sec"],
        "goodput_under_kill_ratio": (
            kpoint["goodput_toks_per_sec"] / clean_goodput
            if clean_goodput else None),
        "unreached_terminals": unreached,
        "multi_terminal_rids": kpoint["multi_terminal_rids"],
        "failovers": kfleet.failovers,
        "replica_downs": kfleet.replica_downs,
        "kill_parity_ok": all(
            ref_tokens(tuple(r.prompt)) == r.tokens
            for r in kpoint["results"]
            if r.finish_reason in ("length", "eos")),
    }
    if kv.get("flight_out"):
        with open(kv["flight_out"], "w") as f:
            f.write(kfleet.merged_flight_jsonl())

    from nanosandbox_tpu.analysis.shardcheck import provenance

    ratio = (aff_p75 / rand_p75
             if aff_p75 is not None and rand_p75 else None)
    mean_ratio = (aff_ttft / rand_ttft
                  if aff_ttft is not None and rand_ttft else None)
    return {
        "metric": ("gpt2_124m_fleet_affinity_vs_random_ttft" if on_tpu
                   else "tiny_fleet_affinity_vs_random_ttft_cpu"),
        "value": ratio,
        "unit": "ratio",
        "vs_baseline": None,
        "provenance": provenance(),
        "extra": {
            "backend": jax.default_backend(),
            "n_replicas": n_replicas,
            "num_slots": num_slots,
            "max_len": max_len,
            "kv_page_size": page,
            "kv_pool_blocks": pool_blocks,
            "groups": n_groups,
            "prefix_len": prefix_len,
            "requests": n_requests,
            "rounds_per_mode": rounds,
            "affinity_vs_random_ttft": ratio,
            "affinity_vs_random_ttft_mean": mean_ratio,
            "ttft_p75_affinity_s": aff_p75,
            "ttft_p75_random_s": rand_p75,
            "ttft_mean_affinity_s": aff_ttft,
            "ttft_mean_random_s": rand_ttft,
            "hit_rate_affinity": med([p["hit_rate"]
                                      for p in aff_rounds]),
            "hit_rate_random": med([p["hit_rate"]
                                    for p in rand_rounds]),
            "decisions_last_affinity_round": aff_rounds[-1]["decisions"],
            "fleet_greedy_parity": (parity_ok / parity_total
                                    if parity_total else None),
            "multi_terminal_rids": sum(
                p["multi_terminal_rids"]
                for p in aff_rounds + rand_rounds),
            "goodput_clean_toks_per_sec": clean_goodput,
            "kill": kill,
        },
    }


def main(argv: list[str]) -> dict:
    quick = "--quick" in argv
    kv = dict(a.lstrip("-").split("=", 1) for a in argv if "=" in a)
    if "--mixed" in argv:  # bare flag form, like --quick
        kv.setdefault("mixed", "1")
    if "--repetitive" in argv:
        kv.setdefault("repetitive", "1")
    if "--emit_obs" in argv:
        kv.setdefault("emit_obs", "1")
    if "--sched" in argv:
        kv.setdefault("sched", "1")
    if "--disagg" in argv:
        kv.setdefault("disagg", "1")
    if kv.get("mode") == "decode" and int(kv.get("tp", 1)) > 1 \
            and "jax" not in sys.modules:
        # --tp on a CPU-only install needs virtual host devices, and the
        # flag must land before jax initializes its backend. Harmless on
        # accelerators — it only sizes the host CPU platform, and the
        # engine shards over jax.devices() (the accelerator list there).
        import re

        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{max(8, int(kv['tp']))}").strip()
    import jax

    on_tpu = jax.default_backend() == "tpu"
    n_chips = len(jax.devices())

    mode = kv.get("mode", "train")
    if mode == "decode":
        result = bench_decode(kv, quick=quick, on_tpu=on_tpu)
        print(json.dumps(result))
        return result
    if mode == "serve":
        result = bench_serve(kv, quick=quick, on_tpu=on_tpu)
        print(json.dumps(result))
        return result
    if mode == "fleet":
        result = bench_fleet(kv, quick=quick, on_tpu=on_tpu)
        print(json.dumps(result))
        return result
    if mode != "train":
        raise SystemExit(
            f"--mode={mode!r}: expected train|decode|serve|fleet")
    impl_status = preflight_impls()

    tmp = tempfile.mkdtemp(prefix="bench_")
    data_dir = os.path.join(tmp, "data")
    from nanosandbox_tpu.data.prepare import prepare_char_dataset

    prepare_char_dataset(os.path.join(data_dir, "shakespeare_char"),
                         allow_synthetic=True,
                         url="http://invalid.localhost/offline")

    cfg, warmup, iters = build_config(kv, on_tpu=on_tpu, n_chips=n_chips,
                                      tmp=tmp, data_dir=data_dir, quick=quick)

    from nanosandbox_tpu.utils.benchmarking import measure_train_throughput

    m = measure_train_throughput(cfg, warmup, iters)
    toks_per_chip = m["tokens_per_sec_per_chip"]

    from nanosandbox_tpu.analysis.shardcheck import provenance

    result = {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip" if on_tpu
        else "tiny_train_tokens_per_sec_per_chip_cpu",
        "value": toks_per_chip,
        "unit": "tokens/sec/chip",
        "vs_baseline": round(toks_per_chip / A10_BASELINE_TOKS_PER_SEC, 3),
        # jax/jaxlib + device kind/count: cross-run perf/comms
        # comparisons (BENCH_rNN.json trend lines) are attributable to
        # the runtime that produced them.
        "provenance": provenance(),
        "extra": {
            "backend": jax.default_backend(),
            "n_chips": n_chips,
            "batch_size": cfg.batch_size,
            "batch_size_per_chip": cfg.batch_size // n_chips,
            "block_size": cfg.block_size,
            "attention_impl": cfg.attention_impl,
            "impl_status": impl_status,
            "step_ms": m["step_ms"],
            "mfu": m["mfu"],
            "loss": m["loss"],
        },
    }
    if _flag(kv, "emit_obs"):
        from nanosandbox_tpu.obs import global_registry
        result["obs"] = {"process": global_registry().snapshot()}
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main(sys.argv[1:])
