"""Benchmark: GPT-2 124M training throughput, tokens/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no numbers (SURVEY.md §6; BASELINE.json
"published": {}), so the parity target is nanoGPT GPT-2 124M tokens/sec on
one NVIDIA A10 — the reference's per-device hardware (README.md:5,13).
Public nanoGPT runs with torch.compile + flash attention put that at
~22k tokens/sec/A10 for the 124M/1024-ctx config; vs_baseline is measured
tokens/sec/chip divided by that estimate (>1.0 beats the reference's
per-device hardware).

Usage: python bench.py [--quick] [--batch_size=N] [--iters=N]
"""

from __future__ import annotations

import json
import sys
import time

A10_BASELINE_TOKS_PER_SEC = 22_000.0


def main(argv: list[str]) -> dict:
    quick = "--quick" in argv
    kv = dict(a.lstrip("-").split("=", 1) for a in argv if "=" in a)
    import numpy as np

    import jax

    on_tpu = jax.default_backend() == "tpu"
    n_chips = len(jax.devices())

    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.train import Trainer

    import os
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench_")
    data_dir = os.path.join(tmp, "data")
    from nanosandbox_tpu.data.prepare import prepare_char_dataset

    prepare_char_dataset(os.path.join(data_dir, "shakespeare_char"),
                         allow_synthetic=True,
                         url="http://invalid.localhost/offline")

    if on_tpu:
        cfg = TrainConfig(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char", vocab_size=50304,
            n_layer=12, n_head=12, n_embd=768, block_size=1024,
            batch_size=int(kv.get("batch_size", 16)) * n_chips,
            max_iters=0, eval_interval=0, log_interval=1,
            dropout=0.0, compute_dtype="bfloat16",
            attention_impl="auto", tensorboard=False)
        warmup, iters = (2, 5) if quick else (3, 20)
    else:  # CPU fallback keeps the bench runnable anywhere
        cfg = TrainConfig(
            out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
            dataset="shakespeare_char",
            n_layer=2, n_head=2, n_embd=64, block_size=128,
            batch_size=8, max_iters=0, eval_interval=0,
            dropout=0.0, compute_dtype="float32", tensorboard=False)
        warmup, iters = (1, 3)

    cfg = cfg.replace(batch_size=int(kv.get("batch_size", cfg.batch_size)))
    iters = int(kv.get("iters", iters))

    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=True)
    rng = jax.random.key(0)

    try:
        for i in range(warmup):
            xb, yb = next(loader)
            state, m = train_step(state, trainer.to_global(xb),
                                  trainer.to_global(yb), rng)
        float(m["loss"])  # hard sync: some PJRT transports make
        # block_until_ready a no-op; a scalar readback always waits.

        times = []
        loss = 0.0
        for i in range(iters):
            xb, yb = next(loader)
            t0 = time.perf_counter()
            state, m = train_step(state, trainer.to_global(xb),
                                  trainer.to_global(yb), rng)
            loss = float(m["loss"])
            times.append(time.perf_counter() - t0)
    finally:
        loader.close()

    med = float(np.median(times))
    toks_per_sec = cfg.tokens_per_iter / med
    toks_per_chip = toks_per_sec / n_chips
    mfu = trainer.flops_per_iter() / med / trainer.peak_flops()

    result = {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip" if on_tpu
        else "tiny_train_tokens_per_sec_per_chip_cpu",
        "value": round(toks_per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(toks_per_chip / A10_BASELINE_TOKS_PER_SEC, 3),
        "extra": {
            "backend": jax.default_backend(),
            "n_chips": n_chips,
            "batch_size": cfg.batch_size,
            "block_size": cfg.block_size,
            "median_step_ms": round(med * 1000, 2),
            "mfu": round(mfu, 4),
            "loss": round(loss, 4),
        },
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main(sys.argv[1:])
