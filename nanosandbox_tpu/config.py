"""Config system: dataclass defaults + nanoGPT-style "configurator".

The reference pins the exact CLI contract in its Colab notebook
(/root/reference/notebooks/colab_nanoGPT_companion.ipynb:71-78, 108-115):

    python train.py <config_file.py> --key=value --key=value ...

i.e. an optional positional python config file that overrides defaults, then
``--key=value`` overrides on top (SURVEY.md §2.3 #27). We keep that contract
exactly, but back it with a typed dataclass instead of module globals.

TPU-specific additions beyond the reference's 14 exercised keys: mesh axis
sizes (dp/fsdp/tp), dtype controls, and distributed-init settings. The
reference's ``--device={cpu,cuda}`` (ipynb:77) becomes ``--device={cpu,tpu}``
and maps to JAX platform selection; ``--compile`` maps to jax.jit on/off.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from dataclasses import dataclass, field, fields
from typing import Any


@dataclass
class TrainConfig:
    # -- I/O (reference ipynb:72 --out_dir; README.md:76 /data layout) --
    out_dir: str = "out"
    data_dir: str = "data"  # root holding <dataset>/{train,val}.bin + meta.pkl
    dataset: str = "shakespeare_char"
    eval_interval: int = 2000
    log_interval: int = 1
    eval_iters: int = 200
    eval_only: bool = False
    always_save_checkpoint: bool = True
    # 'scratch' | 'resume' | 'auto' (resume if ckpt exists) | 'gpt2' /
    # 'gpt2-medium' / 'gpt2-large' / 'gpt2-xl' (pretrained HF weights, the
    # reference's fine-tune path) | 'hf:<path>' (local save_pretrained dir)
    init_from: str = "scratch"
    keep_checkpoints: int = 3

    # -- model (reference ipynb:74-76: n_layer/n_head/n_embd/block_size/dropout) --
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024
    dropout: float = 0.0
    bias: bool = False
    vocab_size: int = 0  # 0 = take from dataset meta.pkl, else explicit

    # -- optimizer / schedule (nanoGPT contract: cosine decay, AdamW, clip) --
    learning_rate: float = 6e-4
    max_iters: int = 600000
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    decay_lr: bool = True
    warmup_iters: int = 2000
    lr_decay_iters: int = 600000
    min_lr: float = 6e-5

    # -- batch --
    batch_size: int = 12  # per-step GLOBAL batch in sequences
    gradient_accumulation_steps: int = 1

    # -- system / TPU --
    device: str = "auto"  # 'auto' | 'cpu' | 'tpu' (ref: --device={cpu,cuda})
    compile: bool = True  # jax.jit the train step (ref: --compile)
    seed: int = 1337
    # PRNG impl for the TRAINING rng stream (dropout masks). 'threefry2x32'
    # is jax's default (counter-based, splittable, slow on TPU — ~half
    # the e2e cost of dropout>0 configs is mask generation); 'rbg' uses
    # the hardware RNG path (the T5X/MaxText production choice). Same
    # statistics, different bits; loss trajectories under dropout differ
    # by mask realization only.
    rng_impl: str = "threefry2x32"
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"  # MXU-native
    attention_impl: str = "auto"  # 'auto' | 'pallas' | 'xla' | 'ring'
    # Flash-backward softmax-stat operand layout: 'replicated' broadcasts
    # per-row stats across the 128-lane minor dim (always lowers);
    # 'compact' stores them dense as (Tp/128, 128) rows and expands tiles
    # in-register — ~128x less stat HBM traffic (ops/attention.py).
    # Default compact: measured faster at the 124M bench shape once the
    # r5 backward-kernel changes removed the other overheads (110.8k vs
    # 108.9k tok/s), and strictly less memory; the compile probe covers
    # both layouts so 'auto' still degrades safely.
    attention_stat_layout: str = "compact"
    remat: bool = False  # jax.checkpoint each block (HBM <-> FLOPs trade)
    # What remat saves: 'save_attention' keeps each block's attention
    # output (tagged checkpoint_name) so the backward never re-runs the
    # O(T^2) kernel — attention is the one sub-computation whose recompute
    # cost dwarfs its activation size; 'full' recomputes everything.
    remat_policy: str = "save_attention"
    # Fused LM-head + cross-entropy, scanned over sequence chunks of this
    # many positions so full (B, T, vocab) logits never hit HBM. 0 disables
    # (plain full-logits loss); -1 (default) resolves per shape at Trainer
    # construction via resolve_loss_chunk_size() — full logits when the
    # per-device (B, T, vocab) f32 tensor fits the HBM budget (measured
    # ~8% faster at the 124M bench shape), chunked 512 when it doesn't or
    # under sequence parallelism. The old constant default of 128 silently
    # put every user config on the slower chunked path (r3 VERDICT weak #2).
    loss_chunk_size: int = -1

    # -- parallelism (mesh axes; SURVEY.md §2.5: DP required, FSDP stretch;
    #    seq = ring-attention context parallelism beyond the reference) --
    mesh_dp: int = -1  # -1 = all remaining devices on the data axis
    mesh_fsdp: int = 1
    mesh_tp: int = 1
    mesh_sp: int = 1  # sequence/context parallel (attention_impl='ring')
    # 'zigzag' balances per-device causal work (each device owns one early
    # + one late half-chunk); 'contiguous' keeps plain chunking. Zigzag
    # falls back to contiguous when block_size % (2*mesh_sp) != 0.
    ring_layout: str = "zigzag"
    # Per-block math inside the ring: 'auto' uses the Pallas flash kernel
    # when it compiles and the local chunk is 128-aligned (XLA einsum
    # otherwise); 'xla' | 'pallas' | 'pallas_interpret' pin it.
    ring_block_impl: str = "auto"
    shard_params: bool = False  # FSDP: shard params/opt-state over fsdp axis
    # Multi-slice (ICI x DCN) topology: 0 = flat mesh over all devices
    # (single slice / don't care); -1 = group devices by their hardware
    # slice_index; N>1 = split into N contiguous groups (scale-down
    # testing). When set, the data axis spans slices (allreduce on DCN)
    # and fsdp/seq/model are validated to stay inside one slice (ICI) —
    # see parallel/mesh.py:make_hybrid_mesh and docs/collectives.md.
    mesh_slices: int = 0

    # -- distributed bootstrap (SURVEY.md §2.6; entrypoint derives these).
    # Defaults mean "unset": the COORDINATOR_ADDRESS / NUM_PROCESSES /
    # PROCESS_ID env vars (container/entrypoint.sh) then take effect.
    coordinator_address: str = ""  # e.g. train-multipod-0.train-mp-headless:1234
    num_processes: int = 0
    process_id: int = -1

    # Print XLA's compile-time memory breakdown of the train step before
    # training (params/state/temp/total bytes per device) — the "will it
    # fit HBM" preflight. Costs one extra AOT compile, hence opt-in.
    memory_report: bool = False

    # -- logging --
    tensorboard: bool = True
    run_name: str = ""
    log_dir: str = ""  # default: <out_dir>/runs (README.md:86 /data/runs)
    # 'a:b' — capture a jax.profiler device trace of iters [a, b) to
    # <log_dir>/profile (view with tensorboard or xprof; main process only)
    profile_steps: str = ""

    def __post_init__(self) -> None:
        if self.lr_decay_iters <= 0:
            self.lr_decay_iters = self.max_iters
        if self.profile_steps:  # fail fast, before any resources exist
            self.profile_range()

    def profile_range(self) -> tuple[int, int] | None:
        """Parsed --profile_steps=a:b, validated. None when unset."""
        if not self.profile_steps:
            return None
        parts = self.profile_steps.split(":")
        try:
            a, b = (int(p) for p in parts)
        except ValueError:
            raise ValueError(
                f"profile_steps expects 'a:b' integers, got "
                f"{self.profile_steps!r}") from None
        if len(parts) != 2 or a < 0 or b <= a:
            raise ValueError(
                f"profile_steps expects 'a:b' with 0 <= a < b, got "
                f"{self.profile_steps!r}")
        return a, b

    @property
    def resolved_log_dir(self) -> str:
        """TB/JSONL log root; tracks out_dir unless set explicitly
        (README.md:86 contract: logs under /data/runs next to checkpoints)."""
        return self.log_dir or os.path.join(self.out_dir, "runs")

    @property
    def sequences_per_iter(self) -> int:
        """Sequences consumed per optimizer step (nanoGPT semantics:
        batch_size is the micro-batch; accumulation multiplies data)."""
        return self.gradient_accumulation_steps * self.batch_size

    @property
    def tokens_per_iter(self) -> int:
        return self.sequences_per_iter * self.block_size

    def replace(self, **kw: Any) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# Auto loss-chunk policy: full logits win ~8% at the 124M bench shape
# (BASELINE.md chunked-loss sweep rows) but cost B*T*V*4 bytes of f32 HBM
# per device — 3.3 GB at batch 16 (fine on 16 GB v5e), 13 GB at batch 64
# (OOM next to params+Adam). 4 GB is the measured comfortable ceiling.
AUTO_FULL_LOGITS_BUDGET_BYTES = 4 << 30
AUTO_CHUNK = 512  # the measured-best chunk when chunking is needed


def resolve_loss_chunk_size(loss_chunk_size: int, per_device_batch: int,
                            block_size: int, vocab_size: int,
                            seq_shards: int = 1) -> int:
    """Resolve the -1 (auto) sentinel to a concrete chunk size.

    Explicit values (>= 0) pass through untouched. Auto picks full logits
    (0) when the per-device (B, T, vocab) f32 logits tensor fits
    AUTO_FULL_LOGITS_BUDGET_BYTES, else chunk 512; under sequence
    parallelism it always chunks (full logits at long context defeat ring
    attention's memory story, models/gpt.py sharded loss docstring).
    """
    if loss_chunk_size >= 0:
        return loss_chunk_size
    if seq_shards > 1:
        return AUTO_CHUNK
    logits_bytes = 4 * per_device_batch * block_size * vocab_size
    return 0 if logits_bytes <= AUTO_FULL_LOGITS_BUDGET_BYTES else AUTO_CHUNK


_FIELD_TYPES = {f.name: f.type for f in fields(TrainConfig)}


def _coerce(key: str, raw: str) -> Any:
    """Coerce a --key=value string to the dataclass field's type.

    Mirrors nanoGPT's configurator behavior: literal_eval first, fall back to
    the raw string, and require bools to be spelled True/False.
    """
    try:
        val = ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        val = raw
    want = _FIELD_TYPES.get(key, "")
    if want == "bool" and not isinstance(val, bool):
        raise ValueError(f"--{key} expects True/False, got {raw!r}")
    if want == "int" and isinstance(val, bool):
        raise ValueError(f"--{key} expects int, got {raw!r}")
    if want == "int" and isinstance(val, float) and val.is_integer():
        val = int(val)
    if want == "float" and isinstance(val, int) and not isinstance(val, bool):
        val = float(val)
    return val


def load_config(argv: list[str] | None = None,
                defaults: TrainConfig | None = None) -> TrainConfig:
    """Build a TrainConfig from [config_file.py] --key=value... (ref ipynb:71).

    The optional positional .py file is exec'd with the current config values
    as globals; any names it (re)binds that match TrainConfig fields become
    overrides. ``--key=value`` args are applied after, winning over the file.
    Unknown keys raise, matching the configurator's strictness.
    """
    argv = list(argv or [])
    cfg = defaults or TrainConfig()
    overrides: dict[str, Any] = {}

    positional = [a for a in argv if not a.startswith("--")]
    flags = [a for a in argv if a.startswith("--")]
    if len(positional) > 1:
        raise ValueError(f"at most one config file allowed, got {positional}")

    if positional:
        path = positional[0]
        if not path.endswith(".py"):
            raise ValueError(f"config file must be .py, got {path!r}")
        ns: dict[str, Any] = dict(cfg.to_dict())
        with open(path, "r", encoding="utf-8") as f:
            exec(compile(f.read(), path, "exec"), ns)
        # Strictness must cover FILE bindings too, or a typo'd key in a
        # config ('learning_rte = ...') silently trains with the default.
        # Underscore-prefixed names are deliberate locals; modules (from
        # imports) and callables (helpers) are allowed scaffolding.
        import types
        for k, v in ns.items():
            if (k in _FIELD_TYPES or k.startswith("_")
                    or isinstance(v, types.ModuleType) or callable(v)):
                continue
            raise ValueError(
                f"unknown config key {k!r} in {path} (prefix helper "
                "variables with '_' to keep them — for imported constants, "
                "alias at import: 'from math import pi as _pi')")
        for k in _FIELD_TYPES:
            if k in ns and ns[k] != getattr(cfg, k):
                overrides[k] = ns[k]

    for arg in flags:
        body = arg[2:]
        if "=" not in body:
            raise ValueError(f"flag {arg!r} must be --key=value")
        key, raw = body.split("=", 1)
        if key not in _FIELD_TYPES:
            raise ValueError(f"unknown config key: {key!r}")
        overrides[key] = _coerce(key, raw)

    cfg = cfg.replace(**overrides)
    return cfg


@dataclass
class GPTConfig:
    """Model-only view of the config, passed to models.gpt.GPT."""

    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024
    vocab_size: int = 50304  # GPT-2 50257 padded up to a multiple of 64 for MXU
    dropout: float = 0.0
    bias: bool = False
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    attention_impl: str = "auto"
    attention_stat_layout: str = "compact"
    ring_layout: str = "zigzag"
    ring_block_impl: str = "auto"
    remat: bool = False
    remat_policy: str = "save_attention"
    # Cached-decode attention impl for the T=1 per-row hot path
    # (ops/flash_decode.py ladder): 'auto' = Pallas flash-decode when the
    # compile probe passes, XLA otherwise; 'pallas' / 'pallas_interpret'
    # / 'xla' pin it. Training never reads this field.
    decode_impl: str = "auto"

    def replace(self, **kw: Any) -> "GPTConfig":
        return dataclasses.replace(self, **kw)

    @classmethod
    def from_train_config(cls, cfg: TrainConfig, vocab_size: int) -> "GPTConfig":
        return cls(
            n_layer=cfg.n_layer,
            n_head=cfg.n_head,
            n_embd=cfg.n_embd,
            block_size=cfg.block_size,
            vocab_size=vocab_size,
            dropout=cfg.dropout,
            bias=cfg.bias,
            param_dtype=cfg.param_dtype,
            compute_dtype=cfg.compute_dtype,
            attention_impl=cfg.attention_impl,
            attention_stat_layout=cfg.attention_stat_layout,
            ring_layout=cfg.ring_layout,
            ring_block_impl=cfg.ring_block_impl,
            remat=cfg.remat,
            remat_policy=cfg.remat_policy,
        )


def field_names() -> set[str]:
    return set(_FIELD_TYPES)
