"""Model zoo: decoder-only GPT (the reference's single model family)."""

from nanosandbox_tpu.models.gpt import GPT, count_params, cross_entropy_loss  # noqa: F401
