"""Pretrained GPT-2 weight import: HF transformers -> this model's pytree.

The reference's training core supports `--init_from=gpt2*` (nanoGPT loads
the HF GPT-2 family and fine-tunes); this is the TPU-native counterpart.
The mapping is mechanical because the model was built name-compatible:

    transformer.wte.weight            -> wte.embedding   (tied lm_head)
    transformer.wpe.weight            -> wpe.embedding
    transformer.h.{i}.ln_1.weight     -> h_{i}.ln_1.scale     (+ bias)
    transformer.h.{i}.attn.c_attn.*   -> h_{i}.attn.c_attn.*  ([q|k|v] packed)
    transformer.h.{i}.attn.c_proj.*   -> h_{i}.attn.c_proj.*
    transformer.h.{i}.ln_2.weight     -> h_{i}.ln_2.scale
    transformer.h.{i}.mlp.c_fc.*      -> h_{i}.mlp.c_fc.*
    transformer.h.{i}.mlp.c_proj.*    -> h_{i}.mlp.c_proj.*
    transformer.ln_f.weight           -> ln_f.scale

No transposes anywhere: HF GPT-2 uses Conv1D with (in, out) weights, the
same orientation as flax Dense kernels (nanoGPT needed transposes only
because torch.nn.Linear stores (out, in)). Numerics that must line up and
do: gelu tanh-approx, LayerNorm eps 1e-5, [q|k|v] packing order, tied head.

Offline note: this environment cannot download pretrained weights; the
conversion is exercised against randomly initialized HF models saved
locally (tests/test_convert.py), and `init_from=hf:<path>` consumes any
local save_pretrained directory.
"""

from __future__ import annotations

from typing import Any

import numpy as np

HF_GPT2_NAMES = ("gpt2", "gpt2-medium", "gpt2-large", "gpt2-xl")


def gpt_config_from_hf(hf_config, *, compute_dtype: str = "bfloat16",
                       dropout: float = 0.0):
    """Our GPTConfig mirroring an HF GPT2Config (bias is always True in
    the pretrained family)."""
    from nanosandbox_tpu.config import GPTConfig

    # The flax model hard-codes two numerics the GPT-2 family uses:
    # tanh-approx gelu and LayerNorm eps 1e-5. hf: paths accept arbitrary
    # GPT2Configs, so a variant model must fail here, not convert into
    # silently-wrong forward passes.
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"unsupported activation_function {act!r}: this model "
            "implements GPT-2's tanh-approx gelu ('gelu_new') only")
    eps = float(getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if abs(eps - 1e-5) > 1e-7:
        raise ValueError(
            f"unsupported layer_norm_epsilon {eps}: this model hard-codes "
            "torch's 1e-5 (models/gpt.py _layer_norm)")
    return GPTConfig(
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        n_embd=hf_config.n_embd,
        block_size=hf_config.n_positions,
        vocab_size=hf_config.vocab_size,
        dropout=dropout,
        bias=True,
        compute_dtype=compute_dtype,
    )


def params_from_hf_state_dict(state_dict: dict, n_layer: int) -> dict:
    """Convert an HF GPT2LMHeadModel state_dict to this model's pytree
    (numpy float32 leaves; callers device_put with their shardings)."""

    def take(name):
        # Convert lazily, per referenced tensor: the state_dict also holds
        # entries this mapping never reads (the weight-tied lm_head.weight
        # duplicate — ~322 MB fp32 for gpt2-xl — and, on some transformers
        # versions, per-layer causal-mask buffers).
        v = state_dict[f"transformer.{name}"]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach")
                          else v, np.float32)

    params: dict[str, Any] = {
        "wte": {"embedding": take("wte.weight")},
        "wpe": {"embedding": take("wpe.weight")},
        "ln_f": {"scale": take("ln_f.weight"), "bias": take("ln_f.bias")},
    }
    for i in range(n_layer):
        h = f"h.{i}"
        params[f"h_{i}"] = {
            "ln_1": {"scale": take(f"{h}.ln_1.weight"),
                     "bias": take(f"{h}.ln_1.bias")},
            "ln_2": {"scale": take(f"{h}.ln_2.weight"),
                     "bias": take(f"{h}.ln_2.bias")},
            "attn": {
                "c_attn": {"kernel": take(f"{h}.attn.c_attn.weight"),
                           "bias": take(f"{h}.attn.c_attn.bias")},
                "c_proj": {"kernel": take(f"{h}.attn.c_proj.weight"),
                           "bias": take(f"{h}.attn.c_proj.bias")},
            },
            "mlp": {
                "c_fc": {"kernel": take(f"{h}.mlp.c_fc.weight"),
                         "bias": take(f"{h}.mlp.c_fc.bias")},
                "c_proj": {"kernel": take(f"{h}.mlp.c_proj.weight"),
                           "bias": take(f"{h}.mlp.c_proj.bias")},
            },
        }
    return params


def load_hf_gpt2(name_or_path: str):
    """(GPTConfig, params pytree) from an HF model name or local
    save_pretrained directory. Import of torch/transformers is deferred:
    both are CPU-only conversion dependencies, never on the train path."""
    from transformers import GPT2LMHeadModel

    model = GPT2LMHeadModel.from_pretrained(name_or_path)
    cfg = gpt_config_from_hf(model.config)
    params = params_from_hf_state_dict(model.state_dict(), cfg.n_layer)
    return cfg, params


def resolve_init_from(init_from: str) -> str | None:
    """'gpt2*' -> HF hub name; 'hf:<path>' -> local path; else None."""
    if init_from in HF_GPT2_NAMES:
        return init_from
    if init_from.startswith("hf:"):
        return init_from[3:]
    return None
