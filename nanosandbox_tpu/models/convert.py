"""GPT-2 weight conversion: HF transformers <-> this model's pytree.

Import: the reference's training core supports `--init_from=gpt2*`
(nanoGPT loads the HF GPT-2 family and fine-tunes); this is the
TPU-native counterpart. Export (export_hf_gpt2 / the module CLI) is the
inverse — a TPU-trained checkpoint becomes a save_pretrained directory
the HF ecosystem loads directly.
The mapping is mechanical because the model was built name-compatible:

    transformer.wte.weight            -> wte.embedding   (tied lm_head)
    transformer.wpe.weight            -> wpe.embedding
    transformer.h.{i}.ln_1.weight     -> h_{i}.ln_1.scale     (+ bias)
    transformer.h.{i}.attn.c_attn.*   -> h_{i}.attn.c_attn.*  ([q|k|v] packed)
    transformer.h.{i}.attn.c_proj.*   -> h_{i}.attn.c_proj.*
    transformer.h.{i}.ln_2.weight     -> h_{i}.ln_2.scale
    transformer.h.{i}.mlp.c_fc.*      -> h_{i}.mlp.c_fc.*
    transformer.h.{i}.mlp.c_proj.*    -> h_{i}.mlp.c_proj.*
    transformer.ln_f.weight           -> ln_f.scale

No transposes anywhere: HF GPT-2 uses Conv1D with (in, out) weights, the
same orientation as flax Dense kernels (nanoGPT needed transposes only
because torch.nn.Linear stores (out, in)). Numerics that must line up and
do: gelu tanh-approx, LayerNorm eps 1e-5, [q|k|v] packing order, tied head.

Offline note: this environment cannot download pretrained weights; the
conversion is exercised against randomly initialized HF models saved
locally (tests/test_convert.py), and `init_from=hf:<path>` consumes any
local save_pretrained directory.
"""

from __future__ import annotations

from typing import Any

import numpy as np

HF_GPT2_NAMES = ("gpt2", "gpt2-medium", "gpt2-large", "gpt2-xl")


def gpt_config_from_hf(hf_config, *, compute_dtype: str = "bfloat16",
                       dropout: float = 0.0):
    """Our GPTConfig mirroring an HF GPT2Config (bias is always True in
    the pretrained family)."""
    from nanosandbox_tpu.config import GPTConfig

    # The flax model hard-codes two numerics the GPT-2 family uses:
    # tanh-approx gelu and LayerNorm eps 1e-5. hf: paths accept arbitrary
    # GPT2Configs, so a variant model must fail here, not convert into
    # silently-wrong forward passes.
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"unsupported activation_function {act!r}: this model "
            "implements GPT-2's tanh-approx gelu ('gelu_new') only")
    eps = float(getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if abs(eps - 1e-5) > 1e-7:
        raise ValueError(
            f"unsupported layer_norm_epsilon {eps}: this model hard-codes "
            "torch's 1e-5 (models/gpt.py _layer_norm)")
    return GPTConfig(
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        n_embd=hf_config.n_embd,
        block_size=hf_config.n_positions,
        vocab_size=hf_config.vocab_size,
        dropout=dropout,
        bias=True,
        compute_dtype=compute_dtype,
    )


def params_from_hf_state_dict(state_dict: dict, n_layer: int) -> dict:
    """Convert an HF GPT2LMHeadModel state_dict to this model's pytree
    (numpy float32 leaves; callers device_put with their shardings)."""

    def take(name):
        # Convert lazily, per referenced tensor: the state_dict also holds
        # entries this mapping never reads (the weight-tied lm_head.weight
        # duplicate — ~322 MB fp32 for gpt2-xl — and, on some transformers
        # versions, per-layer causal-mask buffers).
        v = state_dict[f"transformer.{name}"]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach")
                          else v, np.float32)

    params: dict[str, Any] = {
        "wte": {"embedding": take("wte.weight")},
        "wpe": {"embedding": take("wpe.weight")},
        "ln_f": {"scale": take("ln_f.weight"), "bias": take("ln_f.bias")},
    }
    for i in range(n_layer):
        h = f"h.{i}"
        params[f"h_{i}"] = {
            "ln_1": {"scale": take(f"{h}.ln_1.weight"),
                     "bias": take(f"{h}.ln_1.bias")},
            "ln_2": {"scale": take(f"{h}.ln_2.weight"),
                     "bias": take(f"{h}.ln_2.bias")},
            "attn": {
                "c_attn": {"kernel": take(f"{h}.attn.c_attn.weight"),
                           "bias": take(f"{h}.attn.c_attn.bias")},
                "c_proj": {"kernel": take(f"{h}.attn.c_proj.weight"),
                           "bias": take(f"{h}.attn.c_proj.bias")},
            },
            "mlp": {
                "c_fc": {"kernel": take(f"{h}.mlp.c_fc.weight"),
                         "bias": take(f"{h}.mlp.c_fc.bias")},
                "c_proj": {"kernel": take(f"{h}.mlp.c_proj.weight"),
                           "bias": take(f"{h}.mlp.c_proj.bias")},
            },
        }
    return params


def load_hf_gpt2(name_or_path: str):
    """(GPTConfig, params pytree) from an HF model name or local
    save_pretrained directory. Import of torch/transformers is deferred:
    both are CPU-only conversion dependencies, never on the train path."""
    from transformers import GPT2LMHeadModel

    model = GPT2LMHeadModel.from_pretrained(name_or_path)
    cfg = gpt_config_from_hf(model.config)
    params = params_from_hf_state_dict(model.state_dict(), cfg.n_layer)
    return cfg, params


def resolve_init_from(init_from: str) -> str | None:
    """'gpt2*' -> HF hub name; 'hf:<path>' -> local path; else None."""
    if init_from in HF_GPT2_NAMES:
        return init_from
    if init_from.startswith("hf:"):
        return init_from[3:]
    return None


# ---------------------------------------------------------------------------
# Export: this model's pytree -> HF save_pretrained directory
# ---------------------------------------------------------------------------
#
# The inverse of the import above, completing the round trip a reference
# user expects: fine-tune on TPU, then hand the checkpoint to the HF
# ecosystem (generate/evaluate/serve with transformers). Same mechanical
# mapping, still no transposes.

def hf_config_from_gpt(cfg, vocab_size: int | None = None):
    """HF GPT2Config mirroring our GPTConfig. vocab_size crops the export
    (e.g. 50304 MXU-padded -> 50257 real GPT-2 entries)."""
    from transformers import GPT2Config

    v = vocab_size or cfg.vocab_size
    if v > cfg.vocab_size:
        raise ValueError(f"export vocab_size {v} exceeds model vocab "
                         f"{cfg.vocab_size}")
    return GPT2Config(
        vocab_size=v, n_positions=cfg.block_size, n_embd=cfg.n_embd,
        n_layer=cfg.n_layer, n_head=cfg.n_head,
        activation_function="gelu_new", layer_norm_epsilon=1e-5,
        # Mirror the source model's dropout instead of inheriting HF's
        # 0.1 defaults: eval-mode serving never notices, but fine-tuning
        # the exported checkpoint in the HF stack would otherwise
        # silently train under different regularization than the source
        # (round-4 ADVICE #4).
        resid_pdrop=cfg.dropout, embd_pdrop=cfg.dropout,
        attn_pdrop=cfg.dropout)


def hf_state_dict_from_params(params: dict, n_layer: int,
                              vocab_size: int) -> dict:
    """Our pytree -> HF GPT2LMHeadModel state_dict (torch fp32 tensors).

    bias=False checkpoints (the default config) export ZERO bias tensors:
    the HF format requires them, and zeros are mathematically identical
    to the bias-free forward."""
    import torch

    def t(arr) -> "torch.Tensor":
        return torch.from_numpy(np.array(arr, np.float32, copy=True))

    def dense(node, name, out_features):
        k = t(node["kernel"])
        b = t(node["bias"]) if "bias" in node else torch.zeros(out_features)
        return {f"{name}.weight": k, f"{name}.bias": b}

    def ln(node, name, width):
        return {f"{name}.weight": t(node["scale"]),
                f"{name}.bias": (t(node["bias"]) if "bias" in node
                                 else torch.zeros(width))}

    wte = t(params["wte"]["embedding"])[:vocab_size]
    C = wte.shape[1]
    sd = {"transformer.wte.weight": wte,
          "transformer.wpe.weight": t(params["wpe"]["embedding"]),
          "lm_head.weight": wte,  # weight-tied, same as training
          **{f"transformer.{k}": v
             for k, v in ln(params["ln_f"], "ln_f", C).items()}}
    for i in range(n_layer):
        p = params[f"h_{i}"]
        layer = {**ln(p["ln_1"], "ln_1", C), **ln(p["ln_2"], "ln_2", C),
                 **dense(p["attn"]["c_attn"], "attn.c_attn", 3 * C),
                 **dense(p["attn"]["c_proj"], "attn.c_proj", C),
                 **dense(p["mlp"]["c_fc"], "mlp.c_fc", 4 * C),
                 **dense(p["mlp"]["c_proj"], "mlp.c_proj", C)}
        sd.update({f"transformer.h.{i}.{k}": v for k, v in layer.items()})
    return sd


def export_hf_gpt2(params: dict, cfg, out_dir: str,
                   vocab_size: int | None = None) -> str:
    """Write an HF save_pretrained directory loadable by
    GPT2LMHeadModel.from_pretrained (and by this repo's own
    `--init_from=hf:<dir>`, which is the offline round-trip test)."""
    from transformers import GPT2LMHeadModel

    hf_cfg = hf_config_from_gpt(cfg, vocab_size)
    sd = hf_state_dict_from_params(params, cfg.n_layer, hf_cfg.vocab_size)
    model = GPT2LMHeadModel(hf_cfg)
    missing, unexpected = model.load_state_dict(sd, strict=False)
    # strict=False only to tolerate version-dependent non-persistent
    # buffers (per-layer causal masks); real weights must all match.
    bad = [m for m in missing if not m.endswith((".attn.bias",
                                                 ".attn.masked_bias"))]
    if bad or unexpected:
        raise ValueError(f"state_dict mismatch: missing={bad} "
                         f"unexpected={list(unexpected)}")
    model.save_pretrained(out_dir)
    return out_dir


def main(argv: list[str] | None = None) -> str:
    """CLI: export a trained checkpoint to an HF directory.

        python -m nanosandbox_tpu.models.convert \
            --out_dir=runs/gpt2_124m --to=exports/gpt2_124m_hf \
            [--vocab_size=50257] [--step=N]
    """
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--out_dir", required=True,
                    help="training out_dir holding ckpt/")
    ap.add_argument("--to", required=True, help="destination HF directory")
    ap.add_argument("--vocab_size", type=int, default=None,
                    help="crop the exported vocab (e.g. 50257 from a "
                         "50304 MXU-padded table)")
    ap.add_argument("--step", type=int, default=None)
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    from nanosandbox_tpu.train import restore_for_inference

    # device='cpu': export runs at checkpoint-handling speed and must not
    # contend for a TPU a training job already holds (the helper forces
    # the platform before any jax backend initializes).
    trainer, state, step = restore_for_inference(
        args.out_dir, step=args.step, device="cpu", attention_impl="xla")
    dest = export_hf_gpt2(state["params"], trainer.model_cfg, args.to,
                          vocab_size=args.vocab_size)
    print(f"exported step {step} -> {dest}")
    return dest


if __name__ == "__main__":
    main()
