"""Decoder-only GPT in flax.linen, bf16-MXU-first.

Reimplements the model contract the reference exercises from karpathy/nanoGPT
(/root/reference/notebooks/colab_nanoGPT_companion.ipynb:71-78, 108-115 and
SURVEY.md §2.3 #25): a decoder-only transformer configurable by
``n_layer / n_head / n_embd / block_size / dropout`` with learned positional
embeddings, pre-LayerNorm blocks, GELU MLP (4x), optional biases, weight
tying between the token embedding and the LM head, and GPT-2 initialization
(normal 0.02, residual projections scaled by 1/sqrt(2*n_layer)).

TPU-first choices: parameters kept in float32, matmuls run in bfloat16
(MXU-native) with float32 softmax/layernorm numerics; attention dispatches to
the Pallas flash kernel on TPU (ops/attention.py); optional per-block
jax.checkpoint (rematerialization) to trade FLOPs for HBM.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from nanosandbox_tpu.config import GPTConfig
from nanosandbox_tpu.ops.attention import causal_attention


def _dense_init(std: float = 0.02):
    return nn.initializers.normal(stddev=std)


def _layer_norm(cfg: GPTConfig, name: str) -> nn.LayerNorm:
    """LayerNorm in f32 with epsilon=1e-5 — torch.nn.LayerNorm's default
    (nanoGPT/HF GPT-2), not flax's 1e-6; pretrained-weight import
    (models/convert.py) relies on the match."""
    return nn.LayerNorm(use_bias=cfg.bias, dtype=jnp.float32, epsilon=1e-5,
                        param_dtype=cfg.param_dtype, name=name)


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig
    mesh: Any = None  # required for attention_impl='ring' (sequence parallel)

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool,
                 cache: Optional[tuple] = None, cache_index=None,
                 block_table=None):
        cfg = self.cfg
        B, T, C = x.shape
        assert C % cfg.n_head == 0
        head_dim = C // cfg.n_head
        dtype = jnp.dtype(cfg.compute_dtype)

        qkv = nn.Dense(3 * C, use_bias=cfg.bias, dtype=dtype,
                       param_dtype=cfg.param_dtype,
                       kernel_init=_dense_init(), name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # (B, T, C) -> (B, H, T, D)
        q = q.reshape(B, T, cfg.n_head, head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, cfg.n_head, head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, cfg.n_head, head_dim).transpose(0, 2, 1, 3)

        new_cache = None
        if cache is not None:
            # Incremental decode: write this call's K/V into the cache
            # buffer at cache_index and attend q against the buffer.
            # The T=1 per-row hot path dispatches to the fused flash-
            # decode Pallas kernel (ops/flash_decode.py) when the config
            # selects it; everything else (T = k+1 verify blocks, scalar-
            # index prefill, the XLA fallback) runs the masked-score
            # path below. Unwritten buffer tail is masked off by
            # position (kpos > qpos), so the zeros never contribute.
            # Falls through to the SHARED c_proj below — the projection
            # must be declared exactly once so decode can never desync
            # from the trained parameter's definition.
            if not deterministic and cfg.dropout > 0.0:
                raise ValueError("cached decode is inference-only; "
                                 "call with deterministic=True")
            from jax import lax

            from nanosandbox_tpu.ops.flash_decode import (
                flash_decode, flash_decode_paged, flash_prefill_paged,
                quantize_kv_rows, quantize_kv_rows_int4,
                resolve_decode_impl, unpack_int4,
                xla_decode_attention_paged)

            # int8/int4 KV mode (init_cache kv_dtype=): the layer cache
            # is (K, V, k_scale f32, v_scale f32) with one scale per
            # (row, head, position) — quantize-on-write, so quantized
            # K/V is the only representation the pool holds. int4 packs
            # two nibbles per byte along head_dim (uint8 storage, the
            # dtype that distinguishes the two modes).
            # Tensor-parallel serving (mesh with model > 1): heads are
            # sharded over the ``model`` axis — column-parallel c_attn
            # lands q/k/v pre-sharded by head, the KV pool (and its
            # per-position scales) lives row-sharded along its heads
            # dim, and attention is embarrassingly parallel across
            # heads. The constraints below are ANCHORS threaded through
            # every cached path (decode, prefill, scan body, spec
            # verify): each one is free when the sharding already
            # matches, and dropping any of them is exactly how GSPMD
            # quietly rebuilds the whole pool on every chip — the
            # full-pool all-gather the shardcheck ``frontier_slice``
            # fixture pins against the bounded exchange. The TP serve
            # budget (budgets/serve_tp_cpu8.json) CI-fails if that ever
            # happens.
            tp_mesh = (self.mesh if self.mesh is not None
                       and self.mesh.shape.get("model", 1) > 1 else None)

            def _tp(x, *spec):
                if tp_mesh is None or x is None:
                    return x
                from jax.sharding import NamedSharding, PartitionSpec

                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(tp_mesh, PartitionSpec(*spec)))

            q = _tp(q, None, "model", None, None)
            k = _tp(k, None, "model", None, None)
            v = _tp(v, None, "model", None, None)

            quantized = len(cache) == 4
            four_bit = quantized and cache[0].dtype == jnp.uint8
            _quantize = quantize_kv_rows_int4 if four_bit \
                else quantize_kv_rows
            if quantized:
                ck, cv, cks, cvs = cache
            else:
                ck, cv = cache
                cks = cvs = None
            if quantized and block_table is None:
                k_w, ks_w = _quantize(k)         # (B, H, T, D')->(B,H,T)
                v_w, vs_w = _quantize(v)
            elif not quantized:
                k_w, v_w = k.astype(ck.dtype), v.astype(cv.dtype)
            Tc = ck.shape[2]
            per_row = getattr(cache_index, "ndim", 0) == 1
            if block_table is not None:
                # Block-paged pool (init_paged_cache): the layer holds
                # GLOBAL (num_blocks, H, page, D) blocks and block_table
                # maps each row's i-th logical chunk to a pool block.
                # Write: position p of row b lands in pool block
                # table[b, p // page] at offset p % page — one flat
                # scatter over the (B*T) written positions, with the
                # engine's unallocated sentinel (>= num_blocks) dropped
                # so a parked/overrun row can never corrupt a block it
                # does not own. Read: the T=1 hot path pages the flash
                # kernel through the table (flash_decode_paged, same
                # fused int8 dequant); everything else gathers the
                # row's chain into contiguous (B, H, max_len, D) rows
                # and falls through to the shared masked-score path —
                # bit-identical math, the gather is the byte cost the
                # kernel exists to avoid.
                if not per_row:
                    raise ValueError(
                        "a paged cache is per-row by construction: "
                        "cache_index must be a (B,) frontier vector")
                n_blk, _, page, _ = ck.shape
                nb = block_table.shape[1]
                qpos = cache_index[:, None] + jnp.arange(T)[None, :]
                jblk = qpos // page
                blk = jnp.take_along_axis(block_table,
                                          jnp.minimum(jblk, nb - 1), axis=1)
                blk = jnp.where(jblk < nb, blk, n_blk)       # drop overruns
                bf, of = blk.reshape(-1), (qpos % page).reshape(-1)
                if quantized:
                    # Quantize AFTER the drop mask is known: positions
                    # destined for the sentinel block (ladder-padding
                    # rows, parked tables, frontier overruns) skip the
                    # amax/divide/round scale chain outright — that
                    # work fed a write the scatter drops on the floor
                    # anyway, a measurable lane-waste on every prefill
                    # wave.
                    w_valid = (blk < n_blk)[:, None, :]       # (B, 1, T)
                    k_w, ks_w = _quantize(k, valid=w_valid)
                    v_w, vs_w = _quantize(v, valid=w_valid)

                def _scatter_vals(buf, x):
                    vals = x.transpose(0, 2, 1, 3).reshape(
                        B * T, cfg.n_head, x.shape[-1])
                    return buf.at[bf, :, of, :].set(vals, mode="drop")

                ck = _scatter_vals(ck, k_w)
                cv = _scatter_vals(cv, v_w)
                if quantized:

                    def _scatter_scale(buf, s):
                        vals = s.transpose(0, 2, 1).reshape(B * T,
                                                            cfg.n_head)
                        return buf.at[bf, :, of].set(vals, mode="drop")

                    cks = _scatter_scale(cks, ks_w)
                    cvs = _scatter_scale(cvs, vs_w)
                Tc = nb * page
            elif per_row:
                # Per-row frontiers (serve engine's slot pool): each batch
                # row b writes its K/V at its OWN position cache_index[b]
                # and attends up to it. vmap over the batch dim turns the
                # single write into one write per row — the shapes stay
                # fixed, so one compiled decode step serves every mix of
                # in-flight request lengths.
                if T == 1:
                    # Decode hot path: a 1-column dynamic_update_slice per
                    # row, unchanged from the pre-speculative engine.
                    def _row_write(buf, x, i):
                        return lax.dynamic_update_slice(buf, x, (0, i, 0))

                    def _row_write_scale(buf, x, i):
                        return lax.dynamic_update_slice(buf, x, (0, i))
                else:
                    # Speculative-verify path: a fixed (T = k+1)-column
                    # block per row. Scatter with mode='drop', NOT
                    # dynamic_update_slice — for a row whose frontier sits
                    # within T of the buffer end, the slice CLAMP would
                    # shift the whole write backwards and overwrite valid
                    # history; drop discards only the out-of-range
                    # columns (masked off by position anyway).
                    def _row_write(buf, x, i):
                        cols = i + jnp.arange(T)
                        return buf.at[:, cols, :].set(x, mode="drop")

                    def _row_write_scale(buf, x, i):
                        cols = i + jnp.arange(T)
                        return buf.at[:, cols].set(x, mode="drop")
                ck = jax.vmap(_row_write)(ck, k_w, cache_index)
                cv = jax.vmap(_row_write)(cv, v_w, cache_index)
                if quantized:
                    cks = jax.vmap(_row_write_scale)(cks, ks_w, cache_index)
                    cvs = jax.vmap(_row_write_scale)(cvs, vs_w, cache_index)
                qpos = cache_index[:, None] + jnp.arange(T)[None, :]  # (B, T)
            else:
                ck = lax.dynamic_update_slice(ck, k_w, (0, 0, cache_index, 0))
                cv = lax.dynamic_update_slice(cv, v_w, (0, 0, cache_index, 0))
                if quantized:
                    cks = lax.dynamic_update_slice(cks, ks_w,
                                                   (0, 0, cache_index))
                    cvs = lax.dynamic_update_slice(cvs, vs_w,
                                                   (0, 0, cache_index))
                qpos = (cache_index + jnp.arange(T))[None, :]  # (1, T) global
            # Re-anchor the UPDATED pool layers: paged (N, H, page, D)
            # and dense (B, H, L, D) both carry heads at dim 1 (scales
            # drop the trailing D). Without this the jit's output
            # sharding is whatever the partitioner inferred — one
            # inference change away from returning the pool replicated,
            # i.e. all-gathering it every step.
            ck = _tp(ck, None, "model", None, None)
            cv = _tp(cv, None, "model", None, None)
            if quantized:
                cks = _tp(cks, None, "model", None)
                cvs = _tp(cvs, None, "model", None)
            decode_impl = resolve_decode_impl(
                getattr(cfg, "decode_impl", "auto"))

            sm_scale = 1.0 / head_dim ** 0.5
            interpret = decode_impl == "pallas_interpret"
            from jax.sharding import PartitionSpec as _P
            HP = _P(None, "model", None)         # q (B,H,D) / (.,H,.) scales
            PL = _P(None, "model", None, None)   # pool layers / (B,H,T,D) q

            def _heads_shard(fn, out_spec, args, in_specs):
                """Run a flash kernel per-shard over LOCAL heads under
                tensor parallelism: GSPMD cannot partition Mosaic custom
                calls, so under a model > 1 mesh the kernel body runs
                inside shard_map with the heads dim split over ``model``
                — the grid already iterates (B*H) rows, so each shard
                simply sees H_local rows and the kernel body is
                unchanged. Single-chip engines call the kernel direct."""
                if tp_mesh is None:
                    return fn(*args)
                from nanosandbox_tpu.parallel.mesh import shard_map

                return shard_map(fn, mesh=tp_mesh, in_specs=in_specs,
                                 out_specs=out_spec, check_vma=False)(*args)

            def _kernel(fn_kw, base, base_specs, out_spec):
                """One flash-kernel dispatch, TP-aware. Quantized pools
                append the scale planes as positional shard_map operands
                (a spec cannot describe a None leaf); fp pools call with
                the kernels' default None scales."""
                if quantized:
                    return _heads_shard(
                        lambda *a: fn_kw(*a[:-2], k_scale=a[-2],
                                         v_scale=a[-1]),
                        out_spec, base + (cks, cvs),
                        base_specs + (HP, HP))
                return _heads_shard(fn_kw, out_spec, base, base_specs)

            if per_row and T == 1 and decode_impl != "xla":
                # Fused single-query flash decode: one pass over each
                # row's K/V blocks up to its own frontier, int8 dequant
                # folded into scores/probs so quantized K/V never
                # materializes in fp (ops/flash_decode.py). A paged pool
                # routes the block-table variant: the same walk, with
                # each chunk's address an indirection through the table.
                if block_table is not None:
                    y = _kernel(
                        lambda *a, **kw: flash_decode_paged(
                            *a, sm_scale=sm_scale, interpret=interpret,
                            **kw),
                        (q[:, :, 0, :], ck, cv, block_table,
                         cache_index + 1),
                        (HP, PL, PL, _P(None, None), _P(None)),
                        HP)[:, :, None, :]
                else:
                    y = _kernel(
                        lambda *a, **kw: flash_decode(
                            *a, sm_scale=sm_scale, interpret=interpret,
                            **kw),
                        (q[:, :, 0, :], ck, cv, cache_index + 1),
                        (HP, PL, PL, _P(None)),
                        HP)[:, :, None, :]
            elif per_row and T == 1 and block_table is not None:
                # XLA fallback's paged DECODE fast path: masked
                # attention contracted straight against the block-
                # indexed (B, nb, H, page, D) gather — no chain
                # relayout into contiguous rows, which was a full
                # working-set transpose copy per layer per decode step
                # (the measured paged-vs-dense CPU decode gap, and
                # under scan_k it recurred every fused step).
                y = xla_decode_attention_paged(
                    q[:, :, 0, :], ck, cv, block_table, cache_index + 1,
                    k_scale=cks, v_scale=cvs,
                    sm_scale=1.0 / head_dim ** 0.5)[:, :, None, :]
            elif (per_row and block_table is not None
                  and decode_impl != "xla"):
                # Paged prefill / verify (T > 1) flash kernel: each
                # row's (T, D) suffix queries walk its block chain
                # through the scalar-prefetched table — the resident
                # prefix included — instead of the gathered-masked XLA
                # fallback below, which copies every row's whole chain
                # into contiguous rows per wave (the last non-kernel
                # hot path, and the known paged-vs-dense CPU TTFT gap).
                y = _kernel(
                    lambda *a, **kw: flash_prefill_paged(
                        *a, sm_scale=sm_scale, interpret=interpret, **kw),
                    (q, ck, cv, block_table, cache_index),
                    (PL, PL, PL, _P(None, None), _P(None)),
                    PL)
            else:
                # Masked-score XLA path. When cache_index is a STATIC int
                # (prefill / sample.generate's first pass) the attended
                # range is bounded to the known frontier instead of the
                # full buffer: positions past cache_index + T can only
                # ever be masked, so slicing them off saves their score
                # FLOPs and K/V bytes outright (bit-identical output —
                # the masked columns' softmax mass is exactly 0). Traced
                # indices (the per-row decode/verify paths) keep the full
                # buffer: their frontier is data, not shape.
                span = Tc
                if isinstance(cache_index, int):
                    span = min(cache_index + T, Tc)
                if block_table is not None:
                    # XLA fallback / T > 1 verify blocks over a paged
                    # pool: gather each row's block chain into the
                    # contiguous rows the shared masked path expects.
                    # Same values at the same positions as a dense row
                    # (garbage beyond the frontier is masked either
                    # way), so the math below is bit-identical.
                    gathered, = _gather_paged_layers(
                        [(ck, cv, cks, cvs) if quantized else (ck, cv)],
                        block_table)
                    ck_a, cv_a = gathered[0], gathered[1]
                    cks_a = gathered[2] if quantized else None
                    cvs_a = gathered[3] if quantized else None
                else:
                    ck_a, cv_a = ck[:, :, :span], cv[:, :, :span]
                    cks_a = cks[:, :, :span] if quantized else None
                    cvs_a = cvs[:, :, :span] if quantized else None
                if four_bit:
                    # Packed int4 unpacks to int8 for the reference
                    # math; scales then fold identically to int8 (the
                    # kernels unpack per-tile in-register instead).
                    ck_a, cv_a = unpack_int4(ck_a), unpack_int4(cv_a)
                # (B|1, 1, T, span): kpos <= qpos. The unwritten/stale
                # buffer tail beyond each row's frontier is masked off,
                # so garbage K/V from a previous slot occupant never
                # contributes.
                mask = (jnp.arange(span)[None, None, None, :]
                        <= qpos[:, None, :, None])
                scores = jnp.einsum(
                    "bhtd,bhsd->bhts", q,
                    ck_a.astype(q.dtype) if quantized else ck_a,
                    preferred_element_type=jnp.float32)
                scores = scores * (1.0 / head_dim ** 0.5)
                if quantized:
                    # Per-position scales fold into the score/probability
                    # tensors (scale is constant across the head_dim
                    # contraction) — the same dequant-by-folding contract
                    # as the flash kernel, so the two paths agree.
                    scores = scores * cks_a[:, :, None, :]
                scores = jnp.where(mask, scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                if quantized:
                    probs_v = (probs * cvs_a[:, :, None, :]).astype(q.dtype)
                    y = jnp.einsum("bhts,bhsd->bhtd", probs_v,
                                   cv_a.astype(q.dtype))
                else:
                    y = jnp.einsum("bhts,bhsd->bhtd", probs.astype(cv.dtype),
                                   cv_a)
            # Per-head attention output stays head-sharded into the
            # row-parallel c_proj below: its (B, T, C) reshape carries
            # the split on C, so the projection contracts locally and
            # XLA inserts exactly ONE model-axis all-reduce per block.
            y = _tp(y, None, "model", None, None)
            new_cache = (ck, cv, cks, cvs) if quantized else (ck, cv)
        elif cfg.attention_impl == "ring":
            # Sequence-parallel ring attention: T is sharded over the mesh's
            # seq axis; K/V chunks rotate over ICI (ops/ring_attention.py).
            from nanosandbox_tpu.ops.ring_attention import ring_attention_sharded
            from nanosandbox_tpu.parallel.mesh import current_mesh

            mesh = self.mesh if self.mesh is not None else current_mesh()
            if mesh is None:
                raise ValueError(
                    "attention_impl='ring' needs an active mesh — construct "
                    "the model via Trainer, or call "
                    "parallel.mesh.set_current_mesh(make_mesh(...)) first")
            dropout_seed = None
            ring_rate = 0.0
            if cfg.dropout > 0.0 and not deterministic:
                # Attention-prob dropout composes with the ring because
                # the keep-mask is keyed on GLOBAL (q_pos, k_pos)
                # coordinates (ops/ring_attention.py round-5) — same
                # regularization as the non-ring flash path.
                ring_rate = cfg.dropout
                dropout_seed = jax.random.bits(self.make_rng("dropout"),
                                               (1,), jnp.uint32)
            y = ring_attention_sharded(
                q, k, v, mesh=mesh, layout=cfg.ring_layout,
                block_impl=cfg.ring_block_impl,
                stat_layout=cfg.attention_stat_layout,
                dropout_rate=ring_rate, dropout_seed=dropout_seed)
        else:
            attn_rng = None
            if cfg.dropout > 0.0 and not deterministic:
                attn_rng = self.make_rng("dropout")
            # Only the EXPLICITLY bound mesh routes through the shard_map
            # wrapper — the current_mesh() global (a ring-path fallback)
            # must not leak into standalone-model use, where the caller's
            # arrays have no relation to whatever mesh a previous Trainer
            # registered.
            mesh = self.mesh
            if (mesh is not None and mesh.size > 1
                    and mesh.shape.get("seq", 1) == 1
                    and cfg.attention_impl in ("auto", "pallas",
                                               "pallas_interpret")):
                # seq-axis gate: with mesh_sp > 1 the ring branch above is
                # the only correct path (Trainer validates that); a
                # direct-model user with a seq-sharded mesh but a
                # non-ring impl falls through and gets GSPMD's own
                # error rather than a silently-contiguous ring that
                # ignores cfg.ring_layout/ring_block_impl.
                # GSPMD cannot auto-partition Mosaic custom calls ("Mosaic
                # kernels cannot be automatically partitioned") — on a
                # >1-device mesh the flash kernel must sit inside a
                # shard_map. The sp=1-degenerate ring wrapper IS that
                # shell: one local flash block per shard, batch over
                # (data, fsdp), heads over model, with the global-position
                # dropout offsets keeping per-shard masks decorrelated.
                from nanosandbox_tpu.ops.ring_attention import (
                    ring_attention_sharded)

                rate = 0.0 if deterministic else cfg.dropout
                seed = None
                if rate > 0.0:
                    seed = jax.random.bits(attn_rng, (1,), jnp.uint32)
                y = ring_attention_sharded(
                    q, k, v, mesh=mesh, layout="contiguous",
                    block_impl=cfg.attention_impl,
                    stat_layout=cfg.attention_stat_layout,
                    dropout_rate=rate, dropout_seed=seed)
            else:
                y = causal_attention(
                    q, k, v, impl=cfg.attention_impl,
                    dropout_rate=0.0 if deterministic else cfg.dropout,
                    dropout_rng=attn_rng,
                    stat_layout=cfg.attention_stat_layout)
        y = y.transpose(0, 2, 1, 3).reshape(B, T, C)
        if cache is not None and tp_mesh is not None:
            # The merged (H, D) -> C dim keeps the head split: this is
            # the Megatron row-parallel input layout for c_proj (kernel
            # sharded on its contraction dim by spec_for_param).
            y = _tp(y, None, None, "model")

        proj_std = 0.02 / (2 * cfg.n_layer) ** 0.5
        y = nn.Dense(C, use_bias=cfg.bias, dtype=dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=_dense_init(proj_std), name="c_proj")(y)
        if cfg.dropout > 0.0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return (y, new_cache) if cache is not None else y


class MLP(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool) -> jax.Array:
        cfg = self.cfg
        C = x.shape[-1]
        dtype = jnp.dtype(cfg.compute_dtype)
        proj_std = 0.02 / (2 * cfg.n_layer) ** 0.5
        h = nn.Dense(4 * C, use_bias=cfg.bias, dtype=dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=_dense_init(), name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(C, use_bias=cfg.bias, dtype=dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=_dense_init(proj_std), name="c_proj")(h)
        if cfg.dropout > 0.0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    cfg: GPTConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool,
                 cache: Optional[tuple] = None, cache_index=None,
                 block_table=None):
        cfg = self.cfg
        attn = CausalSelfAttention(cfg, mesh=self.mesh, name="attn")
        a_in = _layer_norm(cfg, "ln_1")(x).astype(cfg.compute_dtype)
        if cache is not None:
            y, new_cache = attn(a_in, deterministic, cache, cache_index,
                                block_table)
            x = x + y
        else:
            x = x + attn(a_in, deterministic)
            new_cache = None
        x = x + MLP(cfg, name="mlp")(
            _layer_norm(cfg, "ln_2")(x).astype(cfg.compute_dtype),
            deterministic)
        return (x, new_cache) if cache is not None else x


class GPT(nn.Module):
    cfg: GPTConfig
    mesh: Any = None  # bound by Trainer; needed for attention_impl='ring'

    def _constrain_acts(self, x: jax.Array) -> jax.Array:
        """Pin (B, T, C) activations to batch-over-(data, fsdp) /
        seq-over-seq / C-replicated at the embedding lookup and between
        blocks. Without the anchor at the wte gather, SPMD has to invert a
        sharding transition through a gather whose table is fsdp-sharded —
        a move it only solves by involuntary full rematerialization
        (replicate, then re-partition; MULTICHIP_r03.json tail warning).
        Free when the sharding already matches, which it does everywhere
        else, so this is an anchor, not a resharding."""
        if self.mesh is None or self.mesh.size == 1:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(("data", "fsdp"), "seq", None)))

    @nn.compact
    def __call__(self, idx: jax.Array, *, deterministic: bool = True,
                 return_hidden: bool = False,
                 cache: Optional[list] = None, cache_index=None,
                 block_table=None):
        """Returns logits (B, T, vocab) — or, with return_hidden=True, the
        final-layernorm hidden states (B, T, C) so the caller can fuse the
        LM head into a chunked loss (chunked_cross_entropy_loss) without
        ever materializing full logits in HBM.

        Incremental decode: pass ``cache`` (per-layer (K, V) buffers from
        init_cache) and ``cache_index`` (global position of idx[:, 0] —
        a scalar, or a (B,) int32 vector giving each row its OWN position,
        the serve engine's slot-pool contract where every row is an
        independent request at its own frontier); returns
        (logits, new_cache). Each call attends against everything
        written so far, so a prefill call (T = prompt length) followed by
        T=1 calls decodes in O(T) total attention reads instead of the
        windowed full-forward's O(T * block_size) recompute per token."""
        cfg = self.cfg
        B, T = idx.shape
        if T > cfg.block_size:
            raise ValueError(f"sequence length {T} > block_size {cfg.block_size}")

        wte = nn.Embed(cfg.vocab_size, cfg.n_embd,
                       embedding_init=_dense_init(),
                       param_dtype=cfg.param_dtype, name="wte")
        wpe = nn.Embed(cfg.block_size, cfg.n_embd,
                       embedding_init=_dense_init(),
                       param_dtype=cfg.param_dtype, name="wpe")

        if cache is not None:
            if getattr(cache_index, "ndim", 0) == 1:
                # Per-row decode positions (serve slot pool): row b's
                # tokens sit at cache_index[b] + [0, T).
                pos = cache_index[:, None] + jnp.arange(T)[None, :]
            else:
                pos = cache_index + jnp.arange(T)[None, :]
        else:
            pos = jnp.arange(T)[None, :]
        x = self._constrain_acts(wte(idx) + wpe(pos))
        if cfg.dropout > 0.0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        x = x.astype(cfg.compute_dtype)

        if cache is not None:
            if return_hidden:
                raise ValueError(
                    "return_hidden is a training-loss hook (chunked CE); "
                    "the cached decode path always returns (logits, cache)")
            # Contract: cache_index + T must stay within the cache buffer.
            # An overrun would not error — dynamic_update_slice clamps the
            # write offset and the wpe gather clamps positions — it would
            # silently produce wrong logits. Checkable only when the index
            # is a Python int (jit callers pass a traced scalar and must
            # enforce the bound themselves, as sample.generate does by
            # falling back to the windowed path when total > block_size).
            if isinstance(cache_index, int) and cache:
                cache_len = cache[0][0].shape[2]
                if cache_index + T > cache_len:
                    raise ValueError(
                        f"cached decode overrun: cache_index {cache_index} "
                        f"+ T {T} exceeds the cache length {cache_len}")
            # Decode path: no remat (inference has no backward to feed).
            new_cache = []
            for i in range(cfg.n_layer):
                x, layer_cache = Block(cfg, mesh=self.mesh, name=f"h_{i}")(
                    x, deterministic, cache[i], cache_index, block_table)
                new_cache.append(layer_cache)
            x = _layer_norm(cfg, "ln_f")(x)
            logits = wte.attend(x.astype(cfg.param_dtype))
            return logits, new_cache

        block_cls = Block
        if cfg.remat:
            # 'save_attention': save each block's attention output + the
            # flash kernel's logsumexp residual (tagged with
            # checkpoint_name inside ops/attention.py) so the backward
            # never re-runs the O(T^2) forward kernel — a remat region
            # discards custom_vjp residuals, so without the tags the
            # flash forward would execute twice in the backward. The
            # saved bytes are O(B*T*C) per block; everything else (qkv
            # dense, MLP) recomputes cheaply. 'full' is the classic
            # save-nothing trade.
            if cfg.remat_policy == "save_attention":
                policy = jax.checkpoint_policies.save_only_these_names(
                    "attn_out", "attn_lse")
            elif cfg.remat_policy == "full":
                policy = None
            else:
                raise ValueError(
                    f"unknown remat_policy: {cfg.remat_policy!r} "
                    "(expected 'save_attention' or 'full')")
            block_cls = nn.remat(Block, static_argnums=(2,), policy=policy)
        for i in range(cfg.n_layer):
            x = self._constrain_acts(
                block_cls(cfg, mesh=self.mesh, name=f"h_{i}")(x, deterministic))

        x = _layer_norm(cfg, "ln_f")(x)
        if return_hidden:
            return x
        # Weight-tied LM head (nanoGPT ties lm_head.weight = wte.weight).
        # Note on dtype: JAX's default matmul precision on TPU already
        # runs f32-input matmuls at the MXU's bf16 rate (measured: an
        # explicit bf16 cast of the embedding table changes nothing but
        # adds ~230 MB/step of cast traffic), so the f32 attend is
        # already the fast path.
        logits = wte.attend(x.astype(cfg.param_dtype))
        return logits


KV_DTYPES = ("fp32", "bf16", "int8", "int4")


def normalize_kv_dtype(kv_dtype) -> str | None:
    """Canonicalize a --kv_dtype flag value: None/''/'auto' -> None (use
    the compute dtype, the pre-int8 default), else one of KV_DTYPES."""
    if kv_dtype in (None, "", "auto"):
        return None
    alias = {"fp32": "fp32", "float32": "fp32",
             "bf16": "bf16", "bfloat16": "bf16", "int8": "int8",
             "int4": "int4"}
    norm = alias.get(str(kv_dtype))
    if norm is None:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r} "
                         f"(expected one of {KV_DTYPES})")
    return norm


def _quantized_layer_shapes(kvd: str, lead: tuple, n_head: int,
                            length: int, head_dim: int):
    """(value shape+dtype, scale shape) for an int8/int4 cache layer.
    int4 packs two nibbles per byte along head_dim (uint8 storage —
    the dtype is how every consumer tells the two modes apart); both
    keep one f32 scale per (row, head, position) block of lanes."""
    if kvd == "int4":
        if head_dim % 2:
            raise ValueError(
                f"int4 KV packs two lanes per byte; head_dim "
                f"{head_dim} must be even")
        vshape = lead + (n_head, length, head_dim // 2)
        vdtype = jnp.uint8
    else:
        vshape = lead + (n_head, length, head_dim)
        vdtype = jnp.int8
    return vshape, vdtype, lead + (n_head, length)


def init_cache(cfg: GPTConfig, batch_size: int, max_len: int,
               dtype: Any = None, kv_dtype=None) -> list:
    """Per-layer (K, V) decode buffers, shape (B, H, max_len, head_dim).

    max_len caps at block_size — the learned positional table (wpe) defines
    positions no further, matching nanoGPT's context-cropping contract.
    Stored in compute_dtype by default (bf16 on TPU): halves cache HBM and
    matches the dtype K/V are produced in, so writes are cast-free.

    kv_dtype ('fp32' | 'bf16' | 'int8' | 'int4', see normalize_kv_dtype)
    overrides the storage mode. 'int8' switches each layer to a 4-tuple
    (K int8, V int8, k_scale f32 (B, H, max_len), v_scale f32 likewise):
    per-(row, head, position) symmetric scales, quantize-on-write in the
    attention cache path (models above) and in scatter_cache_rows, so
    fp K/V never reaches the pool — 2x (vs bf16) / 4x (vs fp32) less HBM
    per cached token, i.e. 2x the concurrent slots at constant HBM and
    proportionally less decode read traffic. 'int4' halves the value
    bytes again: two nibbles per byte packed along head_dim (uint8
    storage), the SAME per-(row, head, position) f32 residual scales,
    round-trip error <= max|row|/7.5 per block of lanes."""
    if max_len > cfg.block_size:
        raise ValueError(
            f"cache length {max_len} > block_size {cfg.block_size}")
    kvd = normalize_kv_dtype(kv_dtype)
    head_dim = cfg.n_embd // cfg.n_head
    shape = (batch_size, cfg.n_head, max_len, head_dim)
    if kvd in ("int8", "int4"):
        vshape, vdtype, sshape = _quantized_layer_shapes(
            kvd, (batch_size,), cfg.n_head, max_len, head_dim)
        return [(jnp.zeros(vshape, vdtype), jnp.zeros(vshape, vdtype),
                 jnp.zeros(sshape, jnp.float32),
                 jnp.zeros(sshape, jnp.float32))
                for _ in range(cfg.n_layer)]
    if kvd == "fp32":
        dtype = jnp.float32
    elif kvd == "bf16":
        dtype = jnp.bfloat16
    else:
        dtype = jnp.dtype(dtype or cfg.compute_dtype)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.n_layer)]


def scatter_cache_rows(pool: list, rows: list, slots: jax.Array) -> list:
    """Write a prefill wave's per-layer (k, H, L, D) K/V rows into the
    slot rows of a (num_slots, H, max_len, D) pool at columns [0, L).

    The scatter uses mode='drop': a slot id >= num_slots (the serve
    engine's ladder-padding rows) writes nowhere, unlike
    dynamic_update_slice whose index CLAMP would silently overwrite the
    last real slot row. Stale columns past L are hidden by the per-row
    causal mask until the new occupant's decode overwrites them.

    An int8/int4 pool (4-tuple layers) accepts fp rows — they are
    quantized HERE, inside the compiled prefill program, so a prefill
    wave's K/V lands already-quantized (the prefill forward itself
    keeps full precision; only the pool representation narrows). Rows
    that are already quantized 4-tuples (a quantized temp cache)
    scatter as-is. Ladder-padding rows (slot id >= num_slots) skip the
    quantizer's scale chain entirely — their scatter drops anyway, so
    computing per-position amax/divide/round for them was wasted lane
    work on every prefill wave."""
    from nanosandbox_tpu.ops.flash_decode import (quantize_kv_rows,
                                                  quantize_kv_rows_int4)

    out = []
    num_slots = pool[0][0].shape[0]
    # (k, 1, 1) over the wave's (k, H, L) quantize rows.
    row_valid = (slots < num_slots)[:, None, None]
    for pool_layer, row_layer in zip(pool, rows):
        if len(pool_layer) == 4:
            pk, pv, pks, pvs = pool_layer
            qfn = (quantize_kv_rows_int4 if pk.dtype == jnp.uint8
                   else quantize_kv_rows)
            if len(row_layer) == 4:
                ck, cv, cks, cvs = row_layer
            else:
                ck, cv = row_layer
                ck, cks = qfn(ck, valid=row_valid)
                cv, cvs = qfn(cv, valid=row_valid)
            L = ck.shape[2]
            pk = pk.at[slots, :, :L, :].set(ck, mode="drop")
            pv = pv.at[slots, :, :L, :].set(cv, mode="drop")
            pks = pks.at[slots, :, :L].set(cks, mode="drop")
            pvs = pvs.at[slots, :, :L].set(cvs, mode="drop")
            out.append((pk, pv, pks, pvs))
            continue
        ck, cv = row_layer[0], row_layer[1]
        if len(row_layer) == 4:
            raise ValueError(
                "cannot scatter quantized rows into a full-precision "
                "pool; build the pool with init_cache(kv_dtype=...)")
        pk, pv = pool_layer
        L = ck.shape[2]
        pk = pk.at[slots, :, :L, :].set(ck.astype(pk.dtype), mode="drop")
        pv = pv.at[slots, :, :L, :].set(cv.astype(pv.dtype), mode="drop")
        out.append((pk, pv))
    return out


def init_paged_cache(cfg: GPTConfig, num_blocks: int, page: int,
                     kv_dtype=None) -> list:
    """Per-layer K/V BLOCK pools, shape (num_blocks, H, page, head_dim).

    The paged twin of init_cache: instead of one (B, H, max_len, D) row
    per slot, the pool is a global heap of fixed-size blocks of ``page``
    positions each, and a (num_slots, max_blocks) block table (serve
    engine slot state) maps each row's logical positions onto blocks —
    allocate-on-demand memory, refcount-shared prefixes
    (serve/paged.py). Same kv_dtype modes as init_cache; 'int8'/'int4'
    layers are 4-tuples with (num_blocks, H, page) f32 per-position
    scales (int4 values pack two nibbles per byte along head_dim)."""
    kvd = normalize_kv_dtype(kv_dtype)
    head_dim = cfg.n_embd // cfg.n_head
    shape = (num_blocks, cfg.n_head, page, head_dim)
    if kvd in ("int8", "int4"):
        vshape, vdtype, sshape = _quantized_layer_shapes(
            kvd, (num_blocks,), cfg.n_head, page, head_dim)
        return [(jnp.zeros(vshape, vdtype), jnp.zeros(vshape, vdtype),
                 jnp.zeros(sshape, jnp.float32),
                 jnp.zeros(sshape, jnp.float32))
                for _ in range(cfg.n_layer)]
    if kvd == "fp32":
        dtype = jnp.float32
    elif kvd == "bf16":
        dtype = jnp.bfloat16
    else:
        dtype = jnp.dtype(cfg.compute_dtype)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.n_layer)]


def _gather_paged_layers(pool: list, block_table: jax.Array) -> list:
    """Gather each row's block chain into contiguous per-layer rows:
    (num_blocks, H, page, D) pool + (B, nb) table -> (B, H, nb*page, D)
    rows (scales likewise). Sentinel table entries clamp to a real
    block — their positions sit beyond the row's frontier and every
    consumer masks them. This is the XLA fallback's per-step byte cost
    (a full row-copy) that flash_decode_paged's in-kernel indirection
    exists to avoid."""
    B, nb = block_table.shape
    out = []
    for layer in pool:
        pk, pv = layer[0], layer[1]
        _, H, page, D = pk.shape
        L = nb * page

        def _vals(p):
            return p[block_table].transpose(0, 2, 1, 3, 4).reshape(
                B, H, L, D)

        if len(layer) == 4:
            pks, pvs = layer[2], layer[3]

            def _scales(s):
                return s[block_table].transpose(0, 2, 1, 3).reshape(B, H, L)

            out.append((_vals(pk), _vals(pv), _scales(pks), _scales(pvs)))
        else:
            out.append((_vals(pk), _vals(pv)))
    return out


def gather_paged_rows(pool: list, block_table: jax.Array) -> list:
    """Public alias of the per-layer paged gather (tests use it to
    build the contiguous reference view of a paged pool)."""
    return _gather_paged_layers(pool, block_table)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       ignore_index: int = -1) -> jax.Array:
    """Mean next-token cross entropy; positions == ignore_index are masked.

    Written in logsumexp form — nll = logsumexp(logits) - logits[target] —
    rather than log_softmax + gather: identical math (log_softmax is
    logits - logsumexp, the gather distributes), but the (B, T, vocab)
    log-probability tensor never materializes. At the 124M bench shape
    that tensor is 3.3 GB of f32 HBM writes+reads per step; the lse form
    reduces the head+CE fwd+bwd from ~38.6 to ~25.8 ms on v5e
    (benchmarks/r5/roofline_124m.json, RTT-corrected)."""
    logits = logits.astype(jnp.float32)
    valid = targets != ignore_index
    safe_targets = jnp.where(valid, targets, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, lse - tgt, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def chunked_cross_entropy_loss(hidden: jax.Array, embedding: jax.Array,
                               targets: jax.Array, *, chunk_size: int = 128,
                               compute_dtype: str = "bfloat16",
                               ignore_index: int = -1) -> jax.Array:
    """Fused LM-head + cross entropy, scanned over sequence chunks.

    The full-logits path materializes a (B, T, vocab) float32 tensor —
    13 GB at batch 64 / 1024 ctx / 50304 vocab, the single largest HBM
    consumer of the whole train step and the reason batch size caps early.
    Here the weight-tied head matmul runs chunk-by-chunk inside a
    lax.scan whose body is jax.checkpoint'd: only (B, chunk, vocab) logits
    are ever alive, forward or backward (the backward recomputes the chunk
    matmul instead of saving it). The matmul feeds the MXU in
    ``compute_dtype`` with float32 accumulation, softmax math is float32.

    hidden: (B, T, C) from GPT(..., return_hidden=True); embedding: (V, C)
    (the tied wte table).

    Numerics note: the full-logits path (GPT.__call__ -> wte.attend) casts
    hidden to param_dtype (float32) before the head matmul; this path
    deliberately feeds the MXU in compute_dtype instead (bf16 inputs,
    f32 accumulation — the reference trains its head under torch autocast
    bf16 too). With compute_dtype=float32 the two paths agree to float
    rounding (tests/test_model.py pins this); under bf16 training they
    differ by bf16 input rounding, a worthwhile trade for the ~2x MXU rate
    and the 128x logits-memory saving.
    """
    tot, cnt = _chunked_nll_sums(hidden, embedding, targets,
                                 chunk_size=chunk_size,
                                 compute_dtype=compute_dtype,
                                 ignore_index=ignore_index)
    return tot / jnp.maximum(cnt, 1)


def _chunked_nll_sums(hidden, embedding, targets, *, chunk_size: int,
                      compute_dtype: str, ignore_index: int = -1):
    """(sum of nll, count of valid targets) via the chunked scan — the
    reduction core shared by the single-device mean above and the
    sequence-parallel psum variant below."""
    from jax import lax

    B, T, C = hidden.shape
    cs = min(chunk_size, T)
    while T % cs:
        cs -= 1  # largest divisor <= chunk_size; worst case 1
    n = T // cs
    dtype = jnp.dtype(compute_dtype)
    h = hidden.reshape(B, n, cs, C).transpose(1, 0, 2, 3)
    y = targets.reshape(B, n, cs).transpose(1, 0, 2)
    emb = embedding.astype(dtype)

    @jax.checkpoint
    def body(carry, xy):
        h_c, y_c = xy
        logits = lax.dot_general(
            h_c.astype(dtype), emb,
            (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (B, cs, V)
        valid = y_c != ignore_index
        safe = jnp.where(valid, y_c, 0)
        # logsumexp form, same as cross_entropy_loss: the (B, cs, V)
        # log-prob tensor never materializes (here it would also be
        # recomputed by the checkpoint during backward, doubling the
        # waste).
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = lse - tgt
        tot, cnt = carry
        return (tot + jnp.where(valid, nll, 0.0).sum()[None],
                cnt + valid.sum()[None]), None

    # Shape-(1,) carries, not scalars: under the sequence-parallel
    # shard_map wrapper below, jax 0.4.x cannot transpose a scan whose
    # residuals are rank-0 (the scalar-residual promotion that fixes
    # this landed after 0.4.37, _SpecError from grad-of-shard_map), and
    # a trailing squeeze is free either way.
    (tot, cnt), _ = lax.scan(
        body, (jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32)),
        (h, y))
    return tot[0], cnt[0]


def sharded_chunked_cross_entropy_loss(hidden: jax.Array,
                                       embedding: jax.Array,
                                       targets: jax.Array, *, mesh,
                                       chunk_size: int = 128,
                                       compute_dtype: str = "bfloat16",
                                       ignore_index: int = -1) -> jax.Array:
    """Chunked loss under sequence parallelism (attention_impl='ring').

    A plain lax.scan over a T-sharded hidden would make the partitioner
    gather the full sequence onto every device; and the full-logits
    fallback materializes (B, T, vocab) f32 — 1.6 GB per sequence at
    8k/50304, defeating ring attention's whole memory story. Instead
    each device runs the chunked scan over its LOCAL T shard inside
    shard_map (only (B, T_local/chunks, vocab) logits alive anywhere)
    and the scalar (nll_sum, count) pairs psum across the batch- and
    sequence-sharding axes.
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    hspec = P(("data", "fsdp"), "seq", None)
    yspec = P(("data", "fsdp"), "seq")

    def body(h, emb, y):
        tot, cnt = _chunked_nll_sums(h, emb, y, chunk_size=chunk_size,
                                     compute_dtype=compute_dtype,
                                     ignore_index=ignore_index)
        tot = lax.psum(tot, ("data", "fsdp", "seq"))
        cnt = lax.psum(cnt, ("data", "fsdp", "seq"))
        return tot / jnp.maximum(cnt, 1)

    from nanosandbox_tpu.parallel.mesh import shard_map

    fn = shard_map(body, mesh=mesh,
                   in_specs=(hspec, P(None, None), yspec),
                   out_specs=P(), check_vma=False)
    return fn(hidden, embedding, targets)


def count_params(params: Any, include_embeddings: bool = True) -> int:
    total = sum(x.size for x in jax.tree.leaves(params))
    if not include_embeddings:
        emb = params.get("params", params)
        for name in ("wpe",):
            node = emb.get(name)
            if node is not None:
                total -= sum(x.size for x in jax.tree.leaves(node))
    return total
