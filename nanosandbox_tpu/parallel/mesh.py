"""Device mesh construction and batch sharding.

Axes: ``data`` (pure data parallel), ``fsdp`` (data parallel + parameter
sharding — ZeRO-3 style), ``seq`` (sequence/context parallel — ring
attention over long sequences, ops/ring_attention.py), ``model`` (tensor
parallel). The batch dim is sharded over (data, fsdp) jointly and the
sequence dim over ``seq``; params are replicated over ``data``/``seq``,
sharded over ``fsdp`` when cfg.shard_params, and sharded over ``model``
per the TP rules in sharding.py.

Replaces the reference's torchrun process-group topology (SURVEY.md §2.5):
workflow A (1 pod × 3 GPU) maps to a single-host mesh over local devices;
workflow B (3 pods × 1 GPU) maps to the same mesh spanning hosts after
jax.distributed.initialize. The ``seq`` and ``model`` axes go beyond the
reference's DDP-only envelope.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "seq", "model")

# The axis-name registry: every PartitionSpec in the stack may only name
# these axes. jaxlint's `axis-mismatch` rule enforces the same set
# statically (analysis/rules_sharding.py mirrors it — jax-free — and a
# test pins the two in sync), and sharding.spec_for_param validates it
# at runtime.
REGISTERED_AXES = frozenset(AXES)

_CURRENT_MESH: Mesh | None = None


def axis_sizes(mesh: Mesh) -> dict[str, int]:
    """{axis name: size} in mesh order — the shape dict shardcheck's
    replica-group attribution and the budget files key on."""
    return {name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def replicated_abstract(mesh: Mesh, tree):
    """Abstract twin of a pytree with every leaf REPLICATED over the
    mesh — the lowering helper for AOT-analyzing today's single-chip
    serve programs under a declared mesh (shardcheck): lowering with
    these shardings makes the SPMD partitioner run for real, so any
    collective it inserts is by definition accidental."""
    import jax

    rep = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep),
        tree)


def make_mesh(mesh_dp: int = -1, mesh_fsdp: int = 1, mesh_tp: int = 1,
              mesh_sp: int = 1, devices: list | None = None) -> Mesh:
    """Build a (data, fsdp, seq, model) mesh over all devices.

    mesh_dp = -1 means "all devices not claimed by fsdp/seq/model". Axis
    order puts ``model`` innermost so TP collectives ride the fastest ICI
    links, then ``seq`` (ring neighbor exchanges), then ``fsdp``, then
    ``data`` outermost (its allreduce tolerates DCN).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_fsdp <= 0 or mesh_tp <= 0 or mesh_sp <= 0:
        raise ValueError("mesh_fsdp, mesh_tp, and mesh_sp must be positive")
    claimed = mesh_fsdp * mesh_tp * mesh_sp
    if mesh_dp == -1:
        if n % claimed:
            raise ValueError(
                f"{n} devices not divisible by fsdp*sp*tp={claimed}")
        mesh_dp = n // claimed
    if mesh_dp * claimed != n:
        raise ValueError(
            f"mesh {mesh_dp}x{mesh_fsdp}x{mesh_sp}x{mesh_tp} != {n} devices")
    dev_array = np.asarray(devices).reshape(mesh_dp, mesh_fsdp, mesh_sp,
                                            mesh_tp)
    return Mesh(dev_array, AXES)


def make_hybrid_mesh(mesh_dp: int = -1, mesh_fsdp: int = 1,
                     mesh_tp: int = 1, mesh_sp: int = 1, *,
                     num_slices: int = -1,
                     devices: list | None = None) -> Mesh:
    """(data, fsdp, seq, model) mesh over a MULTI-SLICE topology: the
    ``data`` axis spans slices (its allreduce rides DCN, the only
    cross-slice fabric), while fsdp/seq/model are constrained to live
    INSIDE one slice so their chattier collectives (reduce-scatter /
    all-gather per step, ring ppermute per layer) stay on ICI — the
    placement rule docs/collectives.md teaches, now enforced by
    construction (round-4 VERDICT missing #4: the doc existed, the
    constructor didn't).

    num_slices = -1 groups devices by their ``slice_index`` attribute
    (real multi-slice TPU); an explicit count splits the device list into
    that many contiguous groups (the no-hardware test path — virtual CPU
    devices carry no slice ids). Slice grouping is VALIDATED: every
    (fsdp, seq, model) block must fall entirely within one slice, and
    the dp axis is laid out slice-major so adjacent dp indices within a
    slice stay on ICI.
    """
    if mesh_fsdp <= 0 or mesh_tp <= 0 or mesh_sp <= 0:
        raise ValueError("mesh_fsdp, mesh_tp, and mesh_sp must be positive")
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if num_slices == -1:
        ids = {getattr(d, "slice_index", 0) for d in devices}
        num_slices = len(ids)
        groups = [[d for d in devices if getattr(d, "slice_index", 0) == i]
                  for i in sorted(ids)]
    else:
        if num_slices <= 0 or n % num_slices:
            raise ValueError(
                f"{n} devices cannot split into {num_slices} slices")
        per = n // num_slices
        groups = [devices[i * per:(i + 1) * per] for i in range(num_slices)]
    per_slice = len(groups[0])
    if any(len(g) != per_slice for g in groups):
        raise ValueError(
            f"unequal slice sizes {[len(g) for g in groups]}: a mesh "
            "needs homogeneous slices")
    claimed = mesh_fsdp * mesh_tp * mesh_sp
    if per_slice % claimed:
        raise ValueError(
            f"fsdp*sp*tp={claimed} must divide the per-slice device count "
            f"{per_slice}: those axes' collectives must stay on ICI — "
            "only the data axis may span slices (DCN)")
    dp_per_slice = per_slice // claimed
    dp = num_slices * dp_per_slice
    if mesh_dp not in (-1, dp):
        raise ValueError(
            f"mesh_dp={mesh_dp} inconsistent with {num_slices} slices x "
            f"{dp_per_slice} in-slice dp (= {dp})")
    # Slice-major dp: dev_array[s * dp_per_slice + i] is slice s's i-th
    # (fsdp, seq, model) block, so dp neighbors within a slice are on ICI
    # and only the slice-crossing hop pays DCN.
    dev_array = np.stack([
        np.asarray(g).reshape(dp_per_slice, mesh_fsdp, mesh_sp, mesh_tp)
        for g in groups]).reshape(dp, mesh_fsdp, mesh_sp, mesh_tp)
    return Mesh(dev_array, AXES)


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the jax versions this repo spans: the
    top-level binding (with ``check_vma``) only exists from jax 0.5; on
    older runtimes the same thing is ``jax.experimental.shard_map`` with
    the pre-rename ``check_rep`` flag. Every shard_map in the package
    goes through here so a version bump is a one-line audit."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim over data+fsdp jointly; sequence dim over seq."""
    return NamedSharding(mesh, P(("data", "fsdp"), "seq"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def set_current_mesh(mesh: Mesh | None) -> None:
    """Record the active training mesh so mesh-aware ops (ring attention)
    can be reached from inside model code without threading the mesh
    through every module signature.

    Switching to a DIFFERENT mesh drops ring attention's cached shard_map
    closures: jax interns Mesh objects forever, so this hook is the
    deterministic release point for retired-mesh closures in long-lived
    processes (ADVICE.md round-1 item 5)."""
    global _CURRENT_MESH
    if mesh is not _CURRENT_MESH and _CURRENT_MESH is not None:
        from nanosandbox_tpu.ops.ring_attention import clear_sharded_cache

        clear_sharded_cache()
    _CURRENT_MESH = mesh


def current_mesh() -> Mesh | None:
    return _CURRENT_MESH
