"""Device mesh construction and batch sharding.

Axes: ``data`` (pure data parallel), ``fsdp`` (data parallel + parameter
sharding — ZeRO-3 style), ``seq`` (sequence/context parallel — ring
attention over long sequences, ops/ring_attention.py), ``model`` (tensor
parallel). The batch dim is sharded over (data, fsdp) jointly and the
sequence dim over ``seq``; params are replicated over ``data``/``seq``,
sharded over ``fsdp`` when cfg.shard_params, and sharded over ``model``
per the TP rules in sharding.py.

Replaces the reference's torchrun process-group topology (SURVEY.md §2.5):
workflow A (1 pod × 3 GPU) maps to a single-host mesh over local devices;
workflow B (3 pods × 1 GPU) maps to the same mesh spanning hosts after
jax.distributed.initialize. The ``seq`` and ``model`` axes go beyond the
reference's DDP-only envelope.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "seq", "model")

_CURRENT_MESH: Mesh | None = None


def make_mesh(mesh_dp: int = -1, mesh_fsdp: int = 1, mesh_tp: int = 1,
              mesh_sp: int = 1, devices: list | None = None) -> Mesh:
    """Build a (data, fsdp, seq, model) mesh over all devices.

    mesh_dp = -1 means "all devices not claimed by fsdp/seq/model". Axis
    order puts ``model`` innermost so TP collectives ride the fastest ICI
    links, then ``seq`` (ring neighbor exchanges), then ``fsdp``, then
    ``data`` outermost (its allreduce tolerates DCN).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_fsdp <= 0 or mesh_tp <= 0 or mesh_sp <= 0:
        raise ValueError("mesh_fsdp, mesh_tp, and mesh_sp must be positive")
    claimed = mesh_fsdp * mesh_tp * mesh_sp
    if mesh_dp == -1:
        if n % claimed:
            raise ValueError(
                f"{n} devices not divisible by fsdp*sp*tp={claimed}")
        mesh_dp = n // claimed
    if mesh_dp * claimed != n:
        raise ValueError(
            f"mesh {mesh_dp}x{mesh_fsdp}x{mesh_sp}x{mesh_tp} != {n} devices")
    dev_array = np.asarray(devices).reshape(mesh_dp, mesh_fsdp, mesh_sp,
                                            mesh_tp)
    return Mesh(dev_array, AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim over data+fsdp jointly; sequence dim over seq."""
    return NamedSharding(mesh, P(("data", "fsdp"), "seq"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def set_current_mesh(mesh: Mesh | None) -> None:
    """Record the active training mesh so mesh-aware ops (ring attention)
    can be reached from inside model code without threading the mesh
    through every module signature.

    Switching to a DIFFERENT mesh drops ring attention's cached shard_map
    closures: jax interns Mesh objects forever, so this hook is the
    deterministic release point for retired-mesh closures in long-lived
    processes (ADVICE.md round-1 item 5)."""
    global _CURRENT_MESH
    if mesh is not _CURRENT_MESH and _CURRENT_MESH is not None:
        from nanosandbox_tpu.ops.ring_attention import clear_sharded_cache

        clear_sharded_cache()
    _CURRENT_MESH = mesh


def current_mesh() -> Mesh | None:
    return _CURRENT_MESH
