"""Device mesh construction and batch sharding.

Axes: ``data`` (pure data parallel), ``fsdp`` (data parallel + parameter
sharding — ZeRO-3 style), ``model`` (tensor parallel, open for scale-up).
The batch is sharded over (data, fsdp) jointly; params are replicated over
``data``, sharded over ``fsdp`` when cfg.shard_params, and sharded over
``model`` per the TP rules in sharding.py.

Replaces the reference's torchrun process-group topology (SURVEY.md §2.5):
workflow A (1 pod × 3 GPU) maps to a single-host mesh over local devices;
workflow B (3 pods × 1 GPU) maps to the same mesh spanning hosts after
jax.distributed.initialize.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "model")


def make_mesh(mesh_dp: int = -1, mesh_fsdp: int = 1, mesh_tp: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (data, fsdp, model) mesh over all devices.

    mesh_dp = -1 means "all devices not claimed by fsdp/model". Axis order
    puts ``model`` innermost so TP collectives ride the fastest ICI links,
    then ``fsdp``, then ``data`` outermost (its allreduce tolerates DCN).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_fsdp <= 0 or mesh_tp <= 0:
        raise ValueError("mesh_fsdp and mesh_tp must be positive")
    if mesh_dp == -1:
        if n % (mesh_fsdp * mesh_tp):
            raise ValueError(
                f"{n} devices not divisible by fsdp*tp={mesh_fsdp * mesh_tp}")
        mesh_dp = n // (mesh_fsdp * mesh_tp)
    if mesh_dp * mesh_fsdp * mesh_tp != n:
        raise ValueError(
            f"mesh {mesh_dp}x{mesh_fsdp}x{mesh_tp} != {n} devices")
    dev_array = np.asarray(devices).reshape(mesh_dp, mesh_fsdp, mesh_tp)
    return Mesh(dev_array, AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim sharded over data+fsdp jointly; sequence dim replicated."""
    return NamedSharding(mesh, P(("data", "fsdp"), None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
