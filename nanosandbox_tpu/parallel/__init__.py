"""Parallelism: device mesh, sharding rules, multi-host bootstrap.

SURVEY.md §2.5-2.6: the reference's parallelism is DDP-only (NCCL allreduce
via torchrun); the TPU build expresses DP as a sharded batch axis under jit
over a `jax.sharding.Mesh`, FSDP (BASELINE config 5) as parameter sharding
on an `fsdp` axis, and leaves a `model` (TP) axis open. Collectives are
inserted by XLA's SPMD partitioner and ride ICI within a slice / DCN across
slices — there is no NCCL analogue to tune (README.md:101's NCCL env notes
map to nothing; documented in docs/playbook.md).
"""

from nanosandbox_tpu.parallel.mesh import make_mesh, batch_sharding  # noqa: F401
from nanosandbox_tpu.parallel.sharding import param_shardings  # noqa: F401
from nanosandbox_tpu.parallel.distributed import maybe_initialize_distributed  # noqa: F401
