"""Multi-host bootstrap: jax.distributed.initialize from pod environment.

Replaces the reference's torchrun rendezvous (SURVEY.md §2.6): there,
container/entrypoint.sh derived NODE_RANK from the StatefulSet pod ordinal
and MASTER_ADDR from the headless Service DNS (README.md:21, 102, 120). The
same mechanism survives here with different names: the entrypoint exports

  COORDINATOR_ADDRESS  e.g. train-multipod-0.train-mp-headless:12355
  NUM_PROCESSES        StatefulSet replica count
  PROCESS_ID           pod ordinal (parsed from hostname)

and every host runs the *same* program (SPMD — no launcher forking
workers). A missing pod hangs initialize(), the analogue of the reference's
rendezvous-DNS failure mode (README.md:120); initialization_timeout turns
that hang into a diagnosable error.
"""

from __future__ import annotations

import os
import re

import jax

_INITIALIZED = False


def derive_process_id_from_hostname(hostname: str | None = None) -> int | None:
    """StatefulSet pods are named <name>-<ordinal> (README.md:69-71)."""
    hostname = hostname if hostname is not None else os.environ.get(
        "HOSTNAME", "")
    m = re.search(r"-(\d+)$", hostname)
    return int(m.group(1)) if m else None


def maybe_initialize_distributed(coordinator_address: str = "",
                                 num_processes: int = 0,
                                 process_id: int = -1,
                                 timeout_s: int = 300) -> bool:
    """Initialize multi-host JAX if configured; no-op for single-process.

    Resolution order per field: explicit arg > env var > hostname-derived.
    Returns True when running multi-process.
    """
    global _INITIALIZED
    coord = coordinator_address or os.environ.get("COORDINATOR_ADDRESS", "")
    nproc = num_processes if num_processes > 0 else int(
        os.environ.get("NUM_PROCESSES", "0"))
    pid = process_id
    if pid < 0:
        pid = int(os.environ.get("PROCESS_ID", "-1"))
    if pid < 0:
        derived = derive_process_id_from_hostname()
        pid = derived if derived is not None else 0

    if not coord or nproc <= 1:
        return False
    if _INITIALIZED:
        return True
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
        initialization_timeout=timeout_s,
    )
    _INITIALIZED = True
    return True


def process_info() -> tuple[int, int]:
    return jax.process_index(), jax.process_count()
