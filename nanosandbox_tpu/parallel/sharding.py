"""Parameter sharding rules: path-pattern -> PartitionSpec.

DP replicates parameters; FSDP (cfg.shard_params, BASELINE config 5) shards
each parameter's largest eligible dim over the ``fsdp`` axis (ZeRO-3 under
jit: XLA all-gathers params for compute and reduce-scatters grads); TP
shards attention/MLP kernels over ``model`` (column-parallel c_attn/c_fc,
row-parallel c_proj — the classic Megatron layout, expressed purely as
sharding annotations for XLA's SPMD partitioner rather than explicit
collectives).

A dim is only sharded when divisible by the axis size, so tiny test models
fall back to replication rather than erroring.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", str(p))
        parts.append(str(key))
    return "/".join(parts)


def _tp_dim(path: str, ndim: int) -> int | None:
    """Megatron placement: column-parallel then row-parallel per block."""
    if ndim != 2:
        return None
    if path.endswith("c_attn/kernel") or path.endswith("c_fc/kernel"):
        return 1  # output dim
    if path.endswith("c_proj/kernel"):
        return 0  # input dim
    if path.endswith("wte/embedding"):
        return None  # keep vocab replicated over model (weight-tied head)
    return None


def spec_for_param(path: str, shape: tuple[int, ...], *, axis_sizes: dict,
                   shard_params: bool, tp: bool) -> P:
    from nanosandbox_tpu.parallel.mesh import REGISTERED_AXES

    unknown = set(axis_sizes) - REGISTERED_AXES
    if unknown:
        # The rule table below only places registered axes, but the
        # mesh handed in must speak the same axis vocabulary or the
        # P() fallbacks would silently replicate what the caller
        # thought was sharded (jaxlint's axis-mismatch rule is the
        # static twin of this check).
        raise ValueError(
            f"mesh axis names {sorted(unknown)} are not in the "
            f"registered set {sorted(REGISTERED_AXES)}")
    ndim = len(shape)
    placement: list[Any] = [None] * ndim

    if tp and axis_sizes["model"] > 1:
        d = _tp_dim(path, ndim)
        if d is not None and shape[d] % axis_sizes["model"] == 0:
            placement[d] = "model"

    if shard_params and axis_sizes["fsdp"] > 1:
        # Shard the largest still-free, divisible dim over fsdp — except
        # embedding tables, which may only shard their ROW (vocab/position)
        # dim: a feature-dim-sharded table turns every lookup into a gather
        # whose output is C-sharded, and SPMD can only move that back to
        # the C-replicated activation layout via involuntary full
        # rematerialization (replicate-then-repartition; the
        # MULTICHIP_r03.json spmd_partitioner.cc warning). Row-sharded
        # gathers lower to the clean masked-gather + psum pattern.
        allowed = ((0,) if path.endswith("wte/embedding")
                   or path.endswith("wpe/embedding") else range(ndim))
        candidates = sorted(
            (i for i in allowed
             if placement[i] is None and shape[i] % axis_sizes["fsdp"] == 0
             and shape[i] >= axis_sizes["fsdp"]),
            key=lambda i: shape[i], reverse=True)
        if candidates:
            placement[candidates[0]] = "fsdp"

    return P(*placement) if any(p is not None for p in placement) else P()


def param_shardings(mesh: Mesh, abstract_params: Any, *,
                    shard_params: bool = False, tp: bool = True) -> Any:
    """Tree of NamedSharding matching an abstract param tree."""
    axis_sizes = {name: int(size)
                  for name, size in zip(mesh.axis_names, mesh.devices.shape)}

    def one(path, leaf):
        spec = spec_for_param(_path_str(path), tuple(leaf.shape),
                              axis_sizes=axis_sizes,
                              shard_params=shard_params, tp=tp)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, abstract_params)
