"""CLI: ``python -m nanosandbox_tpu.analysis [options] <paths>``.

Exit status is the CI gate: 0 clean, 1 findings, 2 usage error. The
JSON report (``--format=json``, optionally ``--out=FILE`` so CI can
upload it as an artifact while the text summary still lands in the
log) is schema-versioned — see docs/playbook.md "Static analysis".
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nanosandbox_tpu.analysis",
        description="jaxlint: static analysis for the stack's JAX/TPU "
                    "invariants (host syncs, tracer leaks, shape "
                    "bucketing, donation, trace purity)")
    ap.add_argument("paths", nargs="*", default=["nanosandbox_tpu"],
                    help="files or directories to lint "
                         "(default: nanosandbox_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the report to FILE (JSON when "
                         "--format=json; CI uploads this as an artifact)")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    from nanosandbox_tpu.analysis.core import (all_rules, analyze_paths,
                                               render_json, render_text)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}: {rule.doc}")
        return 0

    select = ([r.strip() for r in args.select.split(",") if r.strip()]
              if args.select else None)
    try:
        report = analyze_paths(args.paths, select=select)
    except ValueError as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2
    if report["summary"]["files_scanned"] == 0:
        print(f"jaxlint: no Python files under {args.paths!r}",
              file=sys.stderr)
        return 2

    rendered = (render_json(report) if args.format == "json"
                else render_text(report))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(rendered + "\n")
        # The log still gets the human-readable summary.
        print(render_text(report))
    else:
        print(rendered)
    return 1 if report["summary"]["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
