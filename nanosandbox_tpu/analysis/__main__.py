"""CLI: ``python -m nanosandbox_tpu.analysis [options] <paths>``.

Three tools, one entry point:

  * jaxlint (default) — the jax-free AST linter. Exit status is the CI
    gate: 0 clean, 1 findings, 2 usage error. The JSON report
    (``--format=json``, optionally ``--out=FILE`` so CI can upload it
    as an artifact while the text summary still lands in the log) is
    schema-versioned — see docs/playbook.md "Static analysis".
  * ``shardcheck`` subcommand — the IR-level comms analyzer
    (``python -m nanosandbox_tpu.analysis shardcheck --help``); this
    one compiles programs and therefore imports jax. See
    docs/playbook.md "Sharding analysis".
  * ``lockcheck`` subcommand — the jax-free concurrency analyzer for
    the serving host layer (``python -m nanosandbox_tpu.analysis
    lockcheck --help``); same flags and exit codes as jaxlint plus a
    committed lock-ordering file. See docs/playbook.md "Concurrency
    analysis".
"""

from __future__ import annotations

import argparse
import subprocess
import sys


def changed_only_paths(paths, base: str, cwd=None):
    """Resolve the lint set from ``git diff --name-only <base>`` —
    staged + unstaged changes vs the base commit, the fast pre-commit
    path (CI keeps the full run). Returns the changed .py files that
    live under one of ``paths``. Untracked files are invisible to
    ``git diff``; ``git add`` them first (as a pre-commit run has)."""
    from pathlib import Path

    proc = subprocess.run(
        ["git", "diff", "--name-only", base],
        capture_output=True, text=True, cwd=cwd)
    if proc.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {base} failed: "
            f"{proc.stderr.strip() or 'not a git checkout?'}")
    # git prints REPO-ROOT-relative paths regardless of where it ran;
    # resolving them against the cwd would silently drop every changed
    # file when invoked from a subdirectory.
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True, cwd=cwd)
    if top.returncode != 0:
        raise RuntimeError("git rev-parse --show-toplevel failed: "
                           f"{top.stderr.strip()}")
    root_dir = Path(top.stdout.strip())
    base_dir = Path(cwd) if cwd else Path.cwd()
    roots = [(base_dir / p).resolve() for p in paths]
    missing = [str(p) for p, r in zip(paths, roots) if not r.exists()]
    if missing:
        # A root that resolves to nothing (e.g. the default
        # 'nanosandbox_tpu' run from a subdirectory) must fail loudly
        # like the plain run does — not degrade into an empty changed
        # set and a green exit.
        raise RuntimeError(
            f"path(s) {missing} do not exist relative to {base_dir}")
    out = []
    for line in proc.stdout.splitlines():
        f = root_dir / line.strip()
        if not f.suffix == ".py" or not f.exists():
            continue           # deleted files have nothing to lint
        r = f.resolve()
        if any(r == root or root in r.parents for root in roots):
            out.append(str(f))
    return out


def main(argv=None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    if argv and argv[0] == "shardcheck":
        from nanosandbox_tpu.analysis.shardcheck.cli import main as sc_main

        return sc_main(argv[1:])
    if argv and argv[0] == "lockcheck":
        from nanosandbox_tpu.analysis.lockcheck.cli import main as lc_main

        return lc_main(argv[1:])

    ap = argparse.ArgumentParser(
        prog="python -m nanosandbox_tpu.analysis",
        description="jaxlint: static analysis for the stack's JAX/TPU "
                    "invariants (host syncs, tracer leaks, shape "
                    "bucketing, donation, trace purity, sharding "
                    "annotations). For the IR-level comms analyzer run "
                    "the `shardcheck` subcommand.")
    ap.add_argument("paths", nargs="*", default=["nanosandbox_tpu"],
                    help="files or directories to lint "
                         "(default: nanosandbox_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the report to FILE (JSON when "
                         "--format=json; CI uploads this as an artifact)")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs --base (from "
                         "`git diff --name-only`) — the fast pre-commit "
                         "run; CI keeps the full tree")
    ap.add_argument("--base", default="HEAD", metavar="REF",
                    help="git ref --changed-only diffs against "
                         "(default: HEAD)")
    ap.add_argument("--strict-suppressions", action="store_true",
                    help="a reasoned suppression that no longer matches "
                         "any finding becomes a finding itself (rot "
                         "gate)")
    args = ap.parse_args(argv)

    from nanosandbox_tpu.analysis.core import (all_rules, analyze_paths,
                                               render_json, render_text)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}: {rule.doc}")
        return 0

    paths = args.paths
    if args.changed_only:
        try:
            paths = changed_only_paths(args.paths, args.base)
        except RuntimeError as e:
            print(f"jaxlint: {e}", file=sys.stderr)
            return 2
        if not paths:
            print(f"jaxlint: no changed Python files vs {args.base} "
                  f"under {args.paths!r} — nothing to lint")
            return 0

    select = ([r.strip() for r in args.select.split(",") if r.strip()]
              if args.select else None)
    try:
        report = analyze_paths(paths, select=select,
                               strict_suppressions=args.strict_suppressions)
    except ValueError as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2
    if report["summary"]["files_scanned"] == 0:
        print(f"jaxlint: no Python files under {paths!r}",
              file=sys.stderr)
        return 2

    rendered = (render_json(report) if args.format == "json"
                else render_text(report))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(rendered + "\n")
        # The log still gets the human-readable summary.
        print(render_text(report))
    else:
        print(rendered)
    return 1 if report["summary"]["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
