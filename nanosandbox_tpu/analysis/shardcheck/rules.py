"""Manifest rules: the *accidental*-communication findings.

The budget layer (budget.py) pins how much a program communicates; this
layer judges WHAT it communicates against the program's declared
Expectations. Four rules, each a structural accident class:

  comms-free-violation     any collective in a program declared
                           comms-free — the serve engine's decode /
                           prefill / verify programs run replicated
                           today, so a dp-axis collective appearing in
                           one means an annotation leaked or a future
                           TP change forgot to update the declaration.
  accidental-all-gather    an all-gather materializing the FULL global
                           bytes of an input that had a non-replicated
                           NamedSharding, on an axis where full gathers
                           are not expected (fsdp's ZeRO-3 param
                           gathers ARE expected — declared via
                           gather_ok_axes). This is the "dropped
                           with_sharding_constraint" signature: GSPMD
                           could not keep the value sharded and quietly
                           rebuilt the whole tensor on every device.
  unexpected-dp-collective a gather/scatter/permute on an axis declared
                           all-reduce-only (the data axis: gradient
                           sync is the ONLY traffic that should ride
                           it; anything else means batch-dim sharding
                           broke inside the step).
  unfused-grad-allreduce   more all-reduce instances on the
                           all-reduce-only axes than the declared
                           fusion bound — per-leaf gradient reductions
                           that XLA failed to combine serialize the
                           interconnect with launch latency.
  donated-reshard          a collective consuming a donated argument
                           directly: the donation aliased the buffer,
                           and resharding it at the call boundary buys
                           a copy exactly where the donation promised
                           none.

Rules are pure functions of (manifest entry, Expectations) so the unit
tests feed synthetic manifests — no compile in the loop.
"""

from __future__ import annotations

from typing import Any, Dict, List

from nanosandbox_tpu.analysis.shardcheck.manifest import Expectations


def _finding(program: str, rule: str, message: str,
             bytes_: int = 0) -> Dict[str, Any]:
    return {"program": program, "rule": rule, "message": message,
            "bytes": int(bytes_)}


def check_program(name: str, entry: Dict[str, Any],
                  expect: Expectations) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    colls = entry.get("collectives", {})

    if expect.comms_free and colls:
        for slot in colls.values():
            axes = "+".join(slot["axes"]) or "none"
            out.append(_finding(
                name, "comms-free-violation",
                f"{slot['count']}x {slot['kind']} on axes [{axes}] moving "
                f"{slot['bytes_moved']} bytes in a program declared "
                "comms-free — an annotation leaked (or the declaration "
                "is stale: update the program's Expectations AND its "
                "budget explicitly)", slot["bytes_moved"]))

    ok_gather = set(expect.gather_ok_axes)
    for fg in entry.get("full_input_gathers", ()):
        axes = set(fg["axes"])
        if axes and axes <= ok_gather:
            continue
        out.append(_finding(
            name, "accidental-all-gather",
            f"all-gather on axes [{'+'.join(fg['axes']) or 'none'}] "
            f"materializes the full {fg['bytes']} bytes of sharded input "
            f"`{fg['materializes']}` — a NamedSharding was declared but "
            "the program rebuilds the whole tensor on every device "
            "(typical cause: a dropped with_sharding_constraint, or an "
            "op like a traced-offset dynamic_slice on the sharded dim)",
            fg["bytes"]))

    ar_only = set(expect.allreduce_only_axes)
    if ar_only:
        n_ar = 0
        for slot in colls.values():
            axes = set(slot["axes"])
            if not (axes & ar_only):
                continue
            if slot["kind"] != "all-reduce":
                out.append(_finding(
                    name, "unexpected-dp-collective",
                    f"{slot['count']}x {slot['kind']} on "
                    f"[{'+'.join(slot['axes'])}] — this axis is declared "
                    "all-reduce-only (gradient sync); any other "
                    "collective there means batch-dim sharding broke "
                    "inside the step", slot["bytes_moved"]))
            else:
                n_ar += slot["count"]
        if expect.max_axis_allreduces is not None \
                and n_ar > expect.max_axis_allreduces:
            out.append(_finding(
                name, "unfused-grad-allreduce",
                f"{n_ar} all-reduce instances on "
                f"[{'+'.join(sorted(ar_only))}] exceed the declared "
                f"fusion bound {expect.max_axis_allreduces} — per-leaf "
                "gradient reductions are not being combined"))

    for dc in entry.get("donated_param_comms", ()):
        out.append(_finding(
            name, "donated-reshard",
            f"{dc['kind']} on [{'+'.join(dc['axes']) or 'none'}] consumes "
            f"donated argument(s) {dc['params']} directly — the donation "
            "aliased this buffer, and resharding it at the call boundary "
            "costs the copy the donation was supposed to save",
            dc["bytes"]))
    return out
