"""``python -m nanosandbox_tpu.analysis shardcheck`` — the CLI driver.

Exit status mirrors jaxlint's: 0 clean, 1 findings or budget
violations, 2 usage error. Unlike jaxlint this half of the analysis
package DOES import jax (it compiles programs); the subcommand
bootstraps its own virtual device fleet (JAX_PLATFORMS=cpu +
--xla_force_host_platform_device_count) BEFORE the first jax import,
exactly like tests/conftest.py, so it runs identically on a laptop and
in CI.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys


def _bootstrap_devices(n: int) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # REPLACE any pre-existing device-count flag (a stale N=2 from a
    # README repro session would otherwise win and surface as an
    # opaque mesh-reshape crash deep inside jax) — same scrub the
    # dryrun bootstrap applies.
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized; caller chose the platform


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nanosandbox_tpu.analysis shardcheck",
        description="shardcheck: AOT-lower the compiled-program fleet "
                    "under a declared mesh, extract every collective "
                    "with bytes + mesh axes, flag accidental "
                    "communication, and pin the result against a CI "
                    "comms budget")
    ap.add_argument("--fleet", default="train,serve",
                    help="comma-separated fleets to analyze "
                         "(train, serve; default: both)")
    ap.add_argument("--mesh", default="1,2,2,2", metavar="DP,FSDP,SP,TP",
                    help="mesh axis sizes (default 1,2,2,2 over 8 "
                         "virtual CPU devices)")
    ap.add_argument("--devices", type=int, default=0,
                    help="virtual CPU device count (default: the mesh "
                         "size)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the report to FILE (CI uploads it "
                         "as the shardcheck-manifest artifact)")
    ap.add_argument("--budget", default=None, metavar="FILE",
                    help="check the manifest against this pinned budget "
                         "(any new collective / count growth / bytes "
                         "growth past its tolerance exits 1)")
    ap.add_argument("--write-budget", default=None, metavar="FILE",
                    help="write a fresh budget pinning this manifest "
                         "(the explicit ratchet/adopt step)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="bytes tolerance fraction for --write-budget "
                         "(default 0.10)")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    try:
        mesh_spec = tuple(int(x) for x in args.mesh.split(","))
        if len(mesh_spec) != 4 or any(m < 1 for m in mesh_spec):
            raise ValueError
    except ValueError:
        print(f"shardcheck: bad --mesh {args.mesh!r} (want DP,FSDP,SP,TP)",
              file=sys.stderr)
        return 2
    fleets = [f.strip() for f in args.fleet.split(",") if f.strip()]

    _bootstrap_devices(args.devices or math.prod(mesh_spec))

    from nanosandbox_tpu.analysis.shardcheck import budget as budget_mod
    from nanosandbox_tpu.analysis.shardcheck.fleet import (FLEETS,
                                                           build_mesh,
                                                           fleet_programs)
    from nanosandbox_tpu.analysis.shardcheck.manifest import (
        build_manifest, render_manifest_text)

    unknown = sorted(set(fleets) - set(FLEETS))
    if unknown:
        print(f"shardcheck: unknown fleet(s) {', '.join(unknown)}; "
              f"known: {', '.join(FLEETS)}", file=sys.stderr)
        return 2

    mesh = build_mesh(mesh_spec)
    specs = []
    for fleet in fleets:
        specs.extend(fleet_programs(fleet, mesh))
    manifest = build_manifest(
        specs, mesh,
        progress=lambda name: print(f"shardcheck: lowering {name} ...",
                                    file=sys.stderr))

    failed = bool(manifest["findings"])
    if args.budget:
        try:
            budget = budget_mod.load_budget(args.budget)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"shardcheck: cannot load budget {args.budget}: {e}",
                  file=sys.stderr)
            return 2
        violations, notes = budget_mod.check_budget(manifest, budget)
        manifest["budget"] = {"file": args.budget,
                              "violations": violations, "notes": notes}
        failed = failed or bool(violations)

    rendered = (json.dumps(manifest, indent=1) if args.format == "json"
                else render_manifest_text(manifest))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(rendered + "\n")
        print(render_manifest_text(manifest))
    else:
        print(rendered)
    if args.budget:
        for note in manifest["budget"]["notes"]:
            print(f"shardcheck: note: {note}")
        for v in manifest["budget"]["violations"]:
            print(f"shardcheck: BUDGET VIOLATION [{v['kind']}] "
                  f"{v['message']}")
        if not manifest["budget"]["violations"]:
            print(f"shardcheck: budget {args.budget} OK")

    if args.write_budget:
        tol = (args.tolerance if args.tolerance is not None
               else budget_mod.DEFAULT_TOLERANCE)
        budget_mod.write_budget(
            args.write_budget,
            budget_mod.budget_from_manifest(manifest, tolerance=tol))
        print(f"shardcheck: wrote budget {args.write_budget}")

    return 1 if failed else 0
