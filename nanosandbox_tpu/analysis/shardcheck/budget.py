"""CI-pinned comms budgets: the TraceBudgetRegistry move, one level down.

A budget file freezes a fleet's manifest the way tracecheck freezes
trace counts: each program's (collective kind, mesh axes) entries with
their counts and bytes. The check fails on anything that GREW — a new
program nobody budgeted, a new (kind, axes) pair, a count increase, or
bytes up by more than the file's tolerance — while shrinkage is
reported as a stale note (ratchet down by regenerating, see
``scripts/update_shardcheck_budgets.sh``). This makes "this program now
moves 3x more bytes over ICI" a red CI check a PR must answer for,
instead of a mystery MULTICHIP regression two rounds later; ROADMAP
item 1's TP-serving work must rewrite the serve budget EXPLICITLY.

Pure stdlib on dicts: the budget tests run without jax, mirroring
hlo.py's grammar tests.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple

BUDGET_SCHEMA_VERSION = 1
DEFAULT_TOLERANCE = 0.10


def budget_from_manifest(manifest: Dict[str, Any],
                         tolerance: float = DEFAULT_TOLERANCE,
                         ) -> Dict[str, Any]:
    programs: Dict[str, Any] = {}
    for name, entry in manifest["programs"].items():
        programs[name] = {
            key: {"kind": slot["kind"], "axes": list(slot["axes"]),
                  "count": int(slot["count"]),
                  "bytes": int(slot["bytes_moved"])}
            for key, slot in entry["collectives"].items()
        }
    return {
        "version": BUDGET_SCHEMA_VERSION,
        "tool": "shardcheck",
        "tolerance_bytes_frac": tolerance,
        "provenance": manifest.get("provenance", {}),
        "mesh": manifest.get("mesh", {}),
        "programs": programs,
    }


def check_budget(manifest: Dict[str, Any], budget: Dict[str, Any],
                 ) -> Tuple[List[Dict[str, Any]], List[str]]:
    """(violations, stale_notes). Violations fail CI; stale notes mean
    the live fleet communicates LESS than budgeted (regenerate to
    ratchet down) or the environment changed (provenance drift)."""
    violations: List[Dict[str, Any]] = []
    notes: List[str] = []
    tol = float(budget.get("tolerance_bytes_frac", DEFAULT_TOLERANCE))

    if budget.get("mesh") and manifest.get("mesh") \
            and budget["mesh"] != manifest["mesh"]:
        violations.append({
            "kind": "mesh-mismatch", "program": None,
            "message": f"budget pinned mesh {budget['mesh']} but the "
                       f"manifest ran on {manifest['mesh']} — budgets "
                       "are per-mesh contracts"})
        return violations, notes

    bp = budget.get("provenance", {})
    mp = manifest.get("provenance", {})
    for k in ("jax", "jaxlib"):
        if bp.get(k) and mp.get(k) and bp[k] != mp[k]:
            notes.append(f"provenance drift: budget pinned {k} {bp[k]}, "
                         f"running {mp[k]} — partitioner decisions may "
                         "differ; regenerate if the check fails")

    b_programs = budget.get("programs", {})
    m_programs = manifest.get("programs", {})
    for name in sorted(set(m_programs) - set(b_programs)):
        violations.append({
            "kind": "unbudgeted-program", "program": name,
            "message": f"program `{name}` is not in the budget — every "
                       "compiled program in the fleet must be pinned "
                       "(regenerate with --write-budget to adopt it "
                       "deliberately)"})
    for name in sorted(set(b_programs) - set(m_programs)):
        violations.append({
            "kind": "missing-program", "program": name,
            "message": f"budgeted program `{name}` is gone from the "
                       "fleet — removing a program is a contract change; "
                       "regenerate the budget explicitly"})

    for name in sorted(set(b_programs) & set(m_programs)):
        b_entry = b_programs[name]
        m_entry = m_programs[name]["collectives"]
        for key in sorted(set(m_entry) - set(b_entry)):
            slot = m_entry[key]
            violations.append({
                "kind": "new-collective", "program": name,
                "message": f"`{name}` grew a new collective "
                           f"{slot['kind']} on "
                           f"[{'+'.join(slot['axes']) or 'none'}] "
                           f"({slot['count']}x, {slot['bytes_moved']} "
                           "bytes) not in the budget"})
        for key in sorted(set(b_entry) - set(m_entry)):
            notes.append(f"stale: `{name}` no longer emits {key} "
                         "(budget can ratchet down)")
        for key in sorted(set(b_entry) & set(m_entry)):
            b_slot, m_slot = b_entry[key], m_entry[key]
            if m_slot["count"] > b_slot["count"]:
                violations.append({
                    "kind": "count-growth", "program": name,
                    "message": f"`{name}` {key}: {m_slot['count']} "
                               f"instances vs budgeted "
                               f"{b_slot['count']}"})
            elif m_slot["count"] < b_slot["count"]:
                notes.append(f"stale: `{name}` {key} count "
                             f"{m_slot['count']} < budgeted "
                             f"{b_slot['count']}")
            limit = b_slot["bytes"] * (1.0 + tol)
            if m_slot["bytes_moved"] > limit:
                violations.append({
                    "kind": "bytes-growth", "program": name,
                    "message": f"`{name}` {key}: {m_slot['bytes_moved']} "
                               f"bytes moved vs budgeted "
                               f"{b_slot['bytes']} "
                               f"(+{tol:.0%} tolerance = "
                               f"{int(limit)})"})
            elif m_slot["bytes_moved"] < b_slot["bytes"] * (1.0 - tol):
                # A budget left far above the live number is silently
                # loose — a later regression back up would stay green.
                notes.append(f"stale: `{name}` {key} moves "
                             f"{m_slot['bytes_moved']} bytes, well under "
                             f"the budgeted {b_slot['bytes']} (ratchet "
                             "down by regenerating)")
    return violations, notes


def load_budget(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        budget = json.load(f)
    if budget.get("tool") != "shardcheck":
        raise ValueError(f"{path} is not a shardcheck budget file")
    if budget.get("version") != BUDGET_SCHEMA_VERSION:
        raise ValueError(
            f"{path} has budget schema version {budget.get('version')}, "
            f"this tool speaks {BUDGET_SCHEMA_VERSION}")
    return budget


def write_budget(path: str, budget: Dict[str, Any]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budget, f, indent=1, sort_keys=False)
        f.write("\n")
