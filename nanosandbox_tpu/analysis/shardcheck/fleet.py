"""The analyzed program fleets + the known-bad fixture pair.

Two committed fleets, one per budget file:

  * ``train`` — a dryrun-shaped tiny Trainer (2L/64d, the
    __graft_entry__ mesh factoring dp x fsdp x sp x tp) exercising
    every parallelism axis: ZeRO-3 param gathers, ring attention
    permutes, TP activation collectives. Budget:
    ``budgets/train_cpu8.json``.
  * ``serve`` — a tiny Engine with a ModelDrafter: decode, the
    prefill ladder x bucket grid, spec verify, drafter draft +
    draft_prefill grid, everything REPLICATED on the mesh (the
    single-chip contract stated explicitly) so the budget pins zero
    collectives. Budget: ``budgets/serve_cpu8.json``.
  * ``serve_tp`` — the tensor-parallel serve contract (ISSUE 14): a
    tp=2 Engine sharded over the ``model`` axis, lowered with its live
    placements, pinning the bounded model-axis collectives (and zero
    everywhere else). Budget: ``budgets/serve_tp_cpu8.json``, mesh
    ``--mesh=1,1,1,2 --devices=8``.

``frontier_slice_programs`` is the proof fixture: a decode-frontier
gather (``dynamic_slice`` at a traced offset) over a row-sharded pool.
The constrained twin reshards OFF the sliced dim first
(``with_sharding_constraint``) and lowers to a bounded all-to-all; the
unconstrained twin silently all-gathers the ENTIRE pool on every
device — the exact accident class shardcheck exists to catch, pinned
by tests/test_shardcheck.py with nonzero byte attribution.
"""

from __future__ import annotations

import atexit
import os
import shutil
import tempfile
from typing import List, Tuple

DEFAULT_MESH = (1, 2, 2, 2)          # (dp, fsdp, sp, tp) over 8 devices
SERVE_TP_MESH = (1, 1, 1, 2)         # serve_tp: pure model-axis mesh
FLEETS = ("train", "serve", "serve_tp")


def build_mesh(mesh_spec: Tuple[int, int, int, int] = DEFAULT_MESH):
    import jax

    from nanosandbox_tpu.parallel.mesh import make_mesh

    dp, fsdp, sp, tp = mesh_spec
    # A mesh smaller than the bootstrapped device fleet takes the first
    # prod(mesh) devices — the serve_tp fleet states its contract on a
    # pure (1, 1, 1, tp) mesh (a spectator data axis would collect
    # partitioner layout noise into the budget) while the process still
    # runs the standard 8-virtual-device CI bootstrap.
    devices = list(jax.devices())
    n = dp * fsdp * sp * tp
    if len(devices) > n:
        devices = devices[:n]
    return make_mesh(dp, fsdp, tp, sp, devices=devices)


def train_programs(mesh) -> List:
    """Tiny-Trainer train/eval ProgramSpecs on ``mesh`` (the dryrun
    shapes: ring+dropout+rbg+remat live so the analyzed compile surface
    is the one the production long-context configs ship)."""
    from nanosandbox_tpu.config import TrainConfig
    from nanosandbox_tpu.data.prepare import prepare_char_dataset
    from nanosandbox_tpu.parallel.mesh import axis_sizes
    from nanosandbox_tpu.train import Trainer

    sizes = axis_sizes(mesh)
    tmp = tempfile.mkdtemp(prefix="shardcheck_train_")
    # The ProgramSpecs close over the Trainer (lazy .lower()), so the
    # dataset must outlive this call — reap at process exit instead of
    # leaking one synthetic-corpus dir per analysis run.
    atexit.register(shutil.rmtree, tmp, ignore_errors=True)
    data_dir = os.path.join(tmp, "data")
    prepare_char_dataset(os.path.join(data_dir, "shakespeare_char"),
                         allow_synthetic=True,
                         url="http://invalid.localhost/offline")
    cfg = TrainConfig(
        out_dir=os.path.join(tmp, "out"), data_dir=data_dir,
        dataset="shakespeare_char",
        n_layer=2, n_head=4, n_embd=64, block_size=64,
        batch_size=2 * mesh.devices.size, gradient_accumulation_steps=2,
        max_iters=1, eval_interval=0, log_interval=1,
        warmup_iters=1, lr_decay_iters=1,
        dropout=0.1, compute_dtype="float32",
        mesh_dp=sizes["data"], mesh_fsdp=sizes["fsdp"],
        mesh_tp=sizes["model"], mesh_sp=sizes["seq"],
        attention_impl="ring" if sizes["seq"] > 1 else "auto",
        rng_impl="rbg", shard_params=sizes["fsdp"] > 1, remat=True,
        tensorboard=False, device="auto")
    trainer = Trainer(cfg, mesh_devices=list(mesh.devices.flat))
    return trainer.shardcheck_programs()


def serve_programs(mesh) -> List:
    """Tiny-Engine ProgramSpecs (decode + prefill grid + spec verify +
    ModelDrafter draft/draft_prefill) on ``mesh``, all replicated — in
    BOTH KV-pool modes: the default full-precision engine and an
    int8-KV twin (kv_dtype='int8', flash-decode in interpret mode so
    the analyzed decode program contains this kernel's actual ops).
    The *_kv8 programs pin that quantize-on-write, fused-dequant decode
    stays comms-free exactly like the fp pool.

    As of ISSUE 9 the unsuffixed programs are the BLOCK-PAGED engine —
    decode/prefill/spec_verify/drafter programs all paging reads and
    writes through the (num_slots, max_blocks) block table — which is
    the layout the committed budget pins (still zero collectives: the
    table gather/scatter partitions trivially under replication, the
    contract ROADMAP-1 TP serving must rewrite). A dense fp32 engine
    (no spec) keeps the pre-paged layout pinned under *_dense names —
    the bench comparison baseline stays budgeted too.

    ISSUE 12 grows the fleet two ways: an int4-KV twin (*_kv4 —
    packed-nibble pool, interpret-mode kernels, so the analyzed decode
    AND paged-prefill programs contain the real unpack/fold ops) and
    the multi-token scan megaprogram ladder (decode_scan2/decode_scan4
    from a scan_k=4 engine — each rung is its own compiled surface the
    budget must name; the scan engine's prefill/rung-1 programs are
    identical to the default engine's and are filtered out rather than
    double-pinned)."""
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.serve.drafters import ModelDrafter
    from nanosandbox_tpu.serve.engine import Engine

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=64,
                    vocab_size=256, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    dcfg = GPTConfig(n_layer=1, n_head=2, n_embd=32, block_size=64,
                     vocab_size=256, dropout=0.0, compute_dtype="float32",
                     attention_impl="xla")
    dmodel = GPT(dcfg)
    dparams = dmodel.init(jax.random.key(1),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    engine = Engine(model, params, num_slots=4, max_len=32,
                    prefill_buckets=(16, 32),
                    spec=ModelDrafter(dmodel, dparams, k=3))
    engine_kv8 = Engine(model, params, num_slots=4, max_len=32,
                        prefill_buckets=(16, 32),
                        spec=ModelDrafter(dmodel, dparams, k=3),
                        kv_dtype="int8", decode_impl="pallas_interpret")
    engine_kv4 = Engine(model, params, num_slots=4, max_len=32,
                        prefill_buckets=(16, 32),
                        kv_dtype="int4", decode_impl="pallas_interpret")
    engine_dense = Engine(model, params, num_slots=4, max_len=32,
                          prefill_buckets=(16, 32), paged=False)
    engine_scan = Engine(model, params, num_slots=4, max_len=32,
                         prefill_buckets=(16, 32), scan_k=4)
    scan_specs = [s for s in engine_scan.shardcheck_programs(mesh)
                  if "decode_scan" in s.name]
    return (engine.shardcheck_programs(mesh)
            + engine_kv8.shardcheck_programs(mesh)
            + engine_kv4.shardcheck_programs(mesh)
            + engine_dense.shardcheck_programs(mesh)
            + scan_specs)


def serve_tp_programs(mesh) -> List:
    """The TENSOR-PARALLEL serve fleet (ISSUE 14) — the rewrite of the
    zero-collectives serve contract ROADMAP 1 called for: a tp=2
    Engine sharded over the mesh's ``model`` axis (Megatron weights,
    heads-sharded paged int8 KV pool, replicated slot state), lowered
    with its LIVE placements so the partitioner inserts the real
    collectives. The committed budget (budgets/serve_tp_cpu8.json) pins
    them: bounded model-axis all-reduces/permutes on decode, every
    prefill rung x bucket, spec verify and the scan megaprogram rungs —
    and ZERO collectives anywhere else. gather_ok_axes stays empty, so
    a dropped with_sharding_constraint that all-gathers the full pool
    (the frontier_slice accident, on the serving pool) is a CI finding
    with exact bytes, not a budget line item.

    Run with ``--mesh=1,1,1,2 --devices=8``: the engine shards over a
    pure model-axis mesh (the first 2 of the 8 bootstrapped CI
    devices). A spectator data axis would let the partitioner park
    layout choices on it and leak data-axis noise into the contract —
    on this mesh every collective is model-axis by construction, and
    the budget enforces exactly that."""
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.parallel.mesh import axis_sizes
    from nanosandbox_tpu.serve.drafters import NGramDrafter
    from nanosandbox_tpu.serve.engine import Engine

    tp = axis_sizes(mesh)["model"]
    if tp < 2:
        raise ValueError(
            f"serve_tp fleet needs a mesh with model >= 2, got "
            f"{axis_sizes(mesh)} (run with --mesh=1,1,1,2 --devices=8)")
    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=64, block_size=64,
                    vocab_size=256, dropout=0.0, compute_dtype="float32",
                    attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # The default TP serve shape: paged + int8 pool, a host drafter so
    # the spec_verify program is in the pinned set (a device drafter
    # would need its own sharded pool — engine rejects that for now).
    engine = Engine(model, params, num_slots=4, max_len=32,
                    prefill_buckets=(16, 32), kv_dtype="int8",
                    spec=NGramDrafter(k=3), tp=tp, tp_mesh=mesh)
    # The scan megaprogram ladder under TP: each rung is its own comms
    # surface (k model-axis all-reduce rounds fused into one program).
    # Its prefill/rung-1 programs are identical to the base engine's
    # and are filtered rather than double-pinned.
    engine_scan = Engine(model, params, num_slots=4, max_len=32,
                         prefill_buckets=(16, 32), kv_dtype="int8",
                         scan_k=4, tp=tp, tp_mesh=mesh)
    scan_specs = [s for s in engine_scan.shardcheck_programs(mesh)
                  if "decode_scan" in s.name]
    return engine.shardcheck_programs(mesh) + scan_specs


def frontier_slice_programs(mesh, constrained: bool) -> List:
    """The fixture pair (see module docstring). ``constrained=False``
    drops the with_sharding_constraint — the injected accident."""
    import jax
    import jax.numpy as jnp
    from jax.lax import dynamic_slice_in_dim, with_sharding_constraint
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nanosandbox_tpu.analysis.shardcheck.manifest import (Expectations,
                                                              ProgramSpec)

    rep = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("fsdp", None))
    pool = jax.ShapeDtypeStruct((256, 64), jnp.float32,
                                sharding=row_sharded)
    start = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)

    def frontier_good(pool, start):
        # Reshard OFF the sliced dim before the traced-offset slice:
        # each device then owns full rows and the exchange is a bounded
        # all-to-all instead of a full materialization.
        pool = with_sharding_constraint(
            pool, NamedSharding(mesh, P(None, "fsdp")))
        return dynamic_slice_in_dim(pool, start, 8, axis=0)

    def frontier_bad(pool, start):
        # The dropped constraint: a traced-offset dynamic_slice on the
        # sharded dim forces GSPMD to all-gather the ENTIRE pool.
        return dynamic_slice_in_dim(pool, start, 8, axis=0)  # jaxlint: disable=unconstrained-frontier-slice -- the deliberate bad twin the fixture test pins

    if constrained:
        name = "frontier_slice"

        def lower():
            return jax.jit(frontier_good,
                           in_shardings=(row_sharded, rep)).lower(pool,
                                                                  start)
    else:
        name = "frontier_slice_unconstrained"

        def lower():
            # jaxlint: disable=unconstrained-output -- the deliberate bad twin the acceptance test pins
            return jax.jit(frontier_bad,
                           in_shardings=(row_sharded, rep)).lower(pool,
                                                                  start)

    return [ProgramSpec(name=name, lower=lower,
                        abstract_args=(pool, start),
                        expect=Expectations(), tags=("fixture",))]


def fleet_programs(fleet: str, mesh) -> List:
    if fleet == "train":
        return train_programs(mesh)
    if fleet == "serve":
        return serve_programs(mesh)
    if fleet == "serve_tp":
        return serve_tp_programs(mesh)
    raise ValueError(f"unknown fleet {fleet!r}; known: {FLEETS}")
