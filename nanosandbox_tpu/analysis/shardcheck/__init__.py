"""shardcheck — IR-level sharding & collective-communication analysis.

jaxlint (the rest of ``nanosandbox_tpu.analysis``) reads SOURCE; this
subpackage reads what XLA actually DECIDED: it AOT-lowers every
compiled program in the fleet under a declared mesh, walks the
optimized (post-GSPMD) HLO for collectives — kind, operand/result
bytes, mesh axes recovered from replica groups — and emits a
per-program comms manifest. On top of the manifest sit a rule layer
for *accidental* communication (an all-gather materializing a tensor
that had a NamedSharding, collectives in a declared comms-free decode
step, non-all-reduce traffic on the data axis, resharding at a
donation boundary) and a budget layer that pins the manifest in CI the
way tracecheck pins retrace counts.

    python -m nanosandbox_tpu.analysis shardcheck \
        --fleet=train --budget=budgets/train_cpu8.json

Layout: hlo.py (jax-free HLO text grammar), manifest.py (axis
attribution + ProgramSpec + analyzer), rules.py (accident rules),
budget.py (jax-free pin/check), fleet.py (the committed program
fleets + the frontier_slice fixture pair), cli.py (the subcommand).
Program enumeration lives WITH the owners: ``Trainer`` /
``Engine`` / ``SpecRunner`` / ``ModelDrafter`` each export
``shardcheck_programs()``.
"""

from nanosandbox_tpu.analysis.shardcheck.budget import (budget_from_manifest,
                                                        check_budget,
                                                        load_budget,
                                                        write_budget)
from nanosandbox_tpu.analysis.shardcheck.manifest import (
    Expectations, ProgramSpec, analyze_program, axis_groups,
    build_manifest, export_collective_bytes_per_token,
    export_manifest_metrics, provenance, render_manifest_text)

__all__ = ["Expectations", "ProgramSpec", "analyze_program", "axis_groups",
           "build_manifest", "render_manifest_text", "provenance",
           "export_manifest_metrics", "export_collective_bytes_per_token",
           "budget_from_manifest",
           "check_budget", "load_budget", "write_budget"]
