"""Post-partitioning HLO text parser: collectives, bytes, groups.

shardcheck reads the OPTIMIZED HLO of a compiled program
(``jax.jit(f).lower(...).compile().as_text()``) because that is the only
layer where XLA's SPMD partitioner has already made its communication
decisions — the StableHLO a ``lower()`` emits still carries abstract
``sharding`` annotations, not the all-gathers GSPMD will insert for a
missing or inconsistent one. Parsing is line-oriented and deliberately
jax-free (plain ``re``/stdlib): the unit tests pin the grammar against
literal instruction lines, so an XLA text-format drift breaks a fast
pure-Python test instead of a compile-heavy integration run.

Grammar covered (the forms XLA:CPU/TPU emit today):

  %ag = f32[8,64]{1,0} all-gather(f32[8,32]{1,0} %p), channel_id=1,
        replica_groups={{0,2},{1,3}}, dimensions={2}, ...
  %ar = f32[] all-reduce(f32[] %x), replica_groups=[4,2]<=[8], ...
  %rs = f32[4,8]{1,0} reduce-scatter(...), replica_groups=[2,4]<=[4,2]T(1,0)
  %cp = f32[8]{0} collective-permute(...), source_target_pairs={{0,1},{1,0}}
  %aa = (f32[...], f32[...]) all-to-all(f32[...] %a, f32[...] %b), ...

``replica_groups`` comes in two spellings: explicit nested braces, and
the iota form ``[G,S]<=[d0,d1,...]`` with an optional transpose
``T(p...)`` — reshape iota(prod(d)) to ``d``, transpose by ``p``,
flatten, then reshape to (G, S) rows. Async pairs (``all-gather-start``
/ ``-done``) count once, on the start.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")

# dtype -> itemsize in bytes (sub-byte types round up to 1).
_ITEMSIZE = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "f8e3m4": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.*)$")
_KIND_RE = re.compile(
    r"\b(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?P<async>-start)?\(")
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[0-9,{} ]*\})\}")
# A parameter definition has no parens before "parameter(N)" — this
# cannot match a collective line or a metadata op_name string (both put
# parens/quotes first).
_PARAM_RE = re.compile(r"^[^()\"]*\bparameter\((\d+)\)")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


@dataclass
class Collective:
    """One collective instruction in the optimized HLO."""
    kind: str
    name: str
    bytes_in: int                 # summed operand tensor bytes
    bytes_out: int                # summed result tensor bytes
    groups: Optional[FrozenSet[FrozenSet[int]]] = None   # replica groups
    pairs: Tuple[Tuple[int, int], ...] = ()              # permute pairs
    operand_params: Tuple[int, ...] = ()   # parameter numbers fed directly
    line: str = ""

    @property
    def bytes_moved(self) -> int:
        """The materialized-tensor convention the budgets pin: a gather
        is charged its (larger) result, everything else its operand —
        a stable ratchet quantity, not a link-level byte count."""
        if self.kind in ("all-gather", "all-to-all"):
            return max(self.bytes_out, self.bytes_in)
        return self.bytes_in


@dataclass
class HloCollectives:
    collectives: List[Collective] = field(default_factory=list)
    # parameter-instruction name -> parameter(N) index, for the
    # donation-boundary rule.
    params: Dict[str, int] = field(default_factory=dict)


def _shape_bytes(text: str) -> int:
    """Summed byte size of every ``dtype[dims]`` shape token in text."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        if dtype not in _ITEMSIZE:
            continue           # token/tuple/opaque
        n = 1
        if dims:
            n = math.prod(int(d) for d in dims.split(","))
        total += n * _ITEMSIZE[dtype]
    return total


def parse_replica_groups(attrs: str) -> Optional[FrozenSet[FrozenSet[int]]]:
    m = _GROUPS_EXPLICIT_RE.search(attrs)
    if m:
        groups = []
        for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            if ids:
                groups.append(frozenset(ids))
        return frozenset(groups) if groups else None
    m = _GROUPS_IOTA_RE.search(attrs)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        total = math.prod(dims)
        ids = list(range(total))
        if m.group(4):
            perm = [int(p) for p in m.group(4).split(",")]
            # reshape to dims, transpose by perm, flatten — index math
            # without numpy (this module stays stdlib-pure).
            strides = [0] * len(dims)
            acc = 1
            for i in range(len(dims) - 1, -1, -1):
                strides[i] = acc
                acc *= dims[i]
            tdims = [dims[p] for p in perm]
            tstrides = [strides[p] for p in perm]
            out = []
            idx = [0] * len(tdims)
            for _ in range(total):
                out.append(sum(i * s for i, s in zip(idx, tstrides)))
                for ax in range(len(tdims) - 1, -1, -1):
                    idx[ax] += 1
                    if idx[ax] < tdims[ax]:
                        break
                    idx[ax] = 0
            ids = out
        if n_groups * group_size != total:
            return None
        return frozenset(
            frozenset(ids[g * group_size:(g + 1) * group_size])
            for g in range(n_groups))
    return None


def parse_permute_pairs(attrs: str) -> Tuple[Tuple[int, int], ...]:
    m = _PAIRS_RE.search(attrs)
    if not m:
        return ()
    pairs = []
    for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
        ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
        if len(ids) == 2:
            pairs.append((ids[0], ids[1]))
    return tuple(pairs)


def _split_operands(rest: str, open_idx: int) -> Tuple[str, str]:
    """(operand text, trailing attrs) by paren balance from open_idx."""
    depth = 0
    for i in range(open_idx, len(rest)):
        if rest[i] == "(":
            depth += 1
        elif rest[i] == ")":
            depth -= 1
            if depth == 0:
                return rest[open_idx + 1:i], rest[i + 1:]
    return rest[open_idx + 1:], ""


def parse_hlo_collectives(text: str) -> HloCollectives:
    out = HloCollectives()
    for line in text.splitlines():
        im = _INSTR_RE.match(line)
        if im is None:
            continue
        rest = im.group("rest")
        pm = _PARAM_RE.match(rest)
        if pm is not None:
            out.params[im.group("name")] = int(pm.group(1))
            continue
        km = _KIND_RE.search(rest)
        if km is None or rest[:km.start()].count('"') % 2:
            continue           # kind name inside a metadata string
        if f"{km.group('kind')}-done(" in rest:
            continue           # async completion: counted at -start
        result_text = rest[:km.start()]
        operands, attrs = _split_operands(rest, km.end() - 1)
        bytes_out = _shape_bytes(result_text)
        if km.group("async"):
            # An async start returns a tuple whose FIRST element echoes
            # the operand buffer (all-gather-start: (input, output);
            # permute-start adds u32 context scalars) — summing the
            # tuple would charge the operand twice and break the
            # full-input-gather byte match. The true result is the
            # second tuple element.
            shapes = _SHAPE_RE.findall(result_text)
            if len(shapes) >= 2:
                dtype, dims = shapes[1]
                if dtype in _ITEMSIZE:
                    n = (math.prod(int(d) for d in dims.split(","))
                         if dims else 1)
                    bytes_out = n * _ITEMSIZE[dtype]
        out.collectives.append(Collective(
            kind=km.group("kind"),
            name=im.group("name"),
            bytes_in=_shape_bytes(operands),
            bytes_out=bytes_out,
            groups=parse_replica_groups(attrs),
            pairs=parse_permute_pairs(attrs),
            operand_params=tuple(
                out.params[n] for n in _OPERAND_NAME_RE.findall(operands)
                if n in out.params),
            line=line.strip()))
    return out
