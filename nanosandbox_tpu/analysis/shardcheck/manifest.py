"""shardcheck manifest: AOT-lower a program fleet, attribute collectives.

The pipeline per program: ``ProgramSpec.lower()`` (an AOT
``jax.jit(...).lower(...)``) -> ``compile()`` -> optimized HLO text ->
``hlo.parse_hlo_collectives`` -> replica groups mapped back to MESH AXES
(``axis_groups`` below) -> aggregated per (kind, axes) with the byte
convention ``Collective.bytes_moved`` documents -> one manifest dict the
budget layer (budget.py) pins and the rule layer (rules.py) judges.

Axis attribution: a replica group set like ``{{0,2},{1,3},{4,6},{5,7}}``
is exactly "the device positions that vary the ``fsdp`` coordinate with
everything else fixed" for some mesh — so each group set is matched
against the group sets of every non-trivial axis subset of the declared
mesh (positions = indices into ``mesh.devices.flat``, which is what
XLA's flattened device assignment numbers). collective-permute carries
source/target pairs instead; those are attributed to the single axis
whose coordinate every pair steps along.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

from nanosandbox_tpu.analysis.shardcheck.hlo import (Collective,
                                                     parse_hlo_collectives)

MANIFEST_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class Expectations:
    """Per-program declarations the rule layer judges the manifest
    against. The defaults assume nothing; a program that SHOULD
    communicate must say where (and a comms-free one must say so)."""
    comms_free: bool = False          # any collective at all is a finding
    gather_ok_axes: Tuple[str, ...] = ()   # full-input gathers expected here
    allreduce_only_axes: Tuple[str, ...] = ()  # only all-reduce allowed here
    max_axis_allreduces: Optional[int] = None  # fusion bound on those axes
    donated_flat_args: Tuple[int, ...] = ()    # flattened donated positions


@dataclass
class ProgramSpec:
    """One compiled program of the fleet: a name, a lazy AOT lower, the
    abstract args (their ``.sharding`` attributes drive the sharded /
    replicated byte accounting), and the expectations."""
    name: str
    lower: Callable[[], Any]          # () -> jax.stages.Lowered
    abstract_args: Tuple[Any, ...] = ()
    expect: Expectations = field(default_factory=Expectations)
    tags: Tuple[str, ...] = ()


def mesh_axis_sizes(mesh) -> Dict[str, int]:
    # Delegates to the mesh-side helper (lazily: this module must stay
    # importable without jax) so budget keys and attribution can never
    # diverge from the mesh's own flattening semantics.
    from nanosandbox_tpu.parallel.mesh import axis_sizes

    return axis_sizes(mesh)


def axis_groups(axis_sizes: Dict[str, int],
                ) -> List[Tuple[Tuple[str, ...],
                                FrozenSet[FrozenSet[int]]]]:
    """(axes subset, replica-group set) for every non-trivial subset of
    mesh axes, smallest subsets first so a match reports the MINIMAL
    axis set (size-1 axes add nothing and are excluded). Positions are
    flat indices into the mesh's device array — the numbering XLA's
    device assignment uses for a jit over that mesh."""
    names = [n for n, s in axis_sizes.items() if s > 1]
    sizes = [axis_sizes[n] for n in axis_sizes]
    total = math.prod(sizes) if sizes else 1
    all_names = list(axis_sizes)
    # coordinate strides in the flattened order
    strides = {}
    acc = 1
    for n in reversed(all_names):
        strides[n] = acc
        acc *= axis_sizes[n]
    out = []
    for r in range(1, len(names) + 1):
        for subset in itertools.combinations(names, r):
            fixed = [n for n in all_names if n not in subset]
            groups = set()
            fixed_ranges = [range(axis_sizes[n]) for n in fixed]
            sub_ranges = [range(axis_sizes[n]) for n in subset]
            for fixed_coords in itertools.product(*fixed_ranges):
                base = sum(c * strides[n]
                           for c, n in zip(fixed_coords, fixed))
                groups.add(frozenset(
                    base + sum(c * strides[n]
                               for c, n in zip(sub_coords, subset))
                    for sub_coords in itertools.product(*sub_ranges)))
            out.append((subset, frozenset(groups)))
    assert all(len(g) * len(next(iter(g))) == total
               for _, g in out if g), "axis group cover must partition"
    return out


def _axis_coords(axis_sizes: Dict[str, int], pos: int) -> Dict[str, int]:
    coords = {}
    for n in reversed(list(axis_sizes)):
        coords[n] = pos % axis_sizes[n]
        pos //= axis_sizes[n]
    return coords


def attribute_axes(coll: Collective, axis_sizes: Dict[str, int],
                   groups_index) -> Tuple[str, ...]:
    """Mesh axes a collective communicates over; ("unknown",) when the
    group structure matches no axis subset (e.g. a hand-rolled group)."""
    if coll.groups is not None:
        # Groups of size 1 move nothing across devices.
        if all(len(g) == 1 for g in coll.groups):
            return ()
        for axes, gset in groups_index:
            if coll.groups == gset:
                return axes
        return ("unknown",)
    if coll.pairs:
        stepped: set = set()
        for src, dst in coll.pairs:
            cs, cd = (_axis_coords(axis_sizes, src),
                      _axis_coords(axis_sizes, dst))
            diff = tuple(n for n in axis_sizes if cs[n] != cd[n])
            if not diff:
                continue
            stepped.add(diff)
        if not stepped:
            return ()
        if len(stepped) == 1:
            return next(iter(stepped))
        return ("unknown",)
    return ("unknown",)


def agg_key(kind: str, axes: Tuple[str, ...]) -> str:
    return f"{kind}|{'+'.join(axes) if axes else 'none'}"


def _leaf_entries(abstract_args) -> List[Tuple[str, Any]]:
    import jax

    leaves = []
    for i, arg in enumerate(abstract_args):
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        for path, leaf in flat:
            name = f"arg{i}" + "".join(str(p) for p in path)
            leaves.append((name, leaf))
    return leaves


def _input_byte_split(abstract_args, axis_sizes) -> Dict[str, Any]:
    """Replicated vs sharded input accounting from the declared
    shardings: full bytes of replicated leaves, per-device bytes of
    sharded ones, and the {full bytes -> leaf name} index the
    accidental-all-gather rule matches gathers against."""
    import numpy as np

    replicated = 0
    sharded_per_device = 0
    # Byte size -> ALL sharded leaves of that size: matching a gather
    # back to "which input" by byte count is a heuristic, and
    # same-shaped leaves (per-layer kernels) are the common case — a
    # finding must name every candidate, not just the first.
    sharded_full: Dict[int, List[str]] = {}
    for name, leaf in _leaf_entries(abstract_args):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        axes = [a for entry in (spec or ()) if entry
                for a in ((entry,) if isinstance(entry, str) else entry)]
        shards = math.prod(axis_sizes.get(a, 1) for a in axes)
        if shards <= 1:
            replicated += nbytes
        else:
            sharded_per_device += nbytes // shards
            sharded_full.setdefault(nbytes, []).append(name)
    return {"replicated_input_bytes": replicated,
            "sharded_input_bytes_per_device": sharded_per_device,
            "sharded_input_full_bytes": sharded_full}


def analyze_program(spec: ProgramSpec, mesh) -> Dict[str, Any]:
    """Compile one ProgramSpec and return its manifest entry."""
    axis_sizes = mesh_axis_sizes(mesh)
    groups_index = axis_groups(axis_sizes)
    compiled = spec.lower().compile()
    parsed = parse_hlo_collectives(compiled.as_text())

    split = _input_byte_split(spec.abstract_args, axis_sizes)
    sharded_full = split.pop("sharded_input_full_bytes")

    agg: Dict[str, Dict[str, int]] = {}
    full_gathers: List[Dict[str, Any]] = []
    donated_comms: List[Dict[str, Any]] = []
    for coll in parsed.collectives:
        axes = attribute_axes(coll, axis_sizes, groups_index)
        key = agg_key(coll.kind, axes)
        slot = agg.setdefault(key, {"kind": coll.kind,
                                    "axes": list(axes), "count": 0,
                                    "bytes_moved": 0, "max_bytes_out": 0})
        slot["count"] += 1
        slot["bytes_moved"] += coll.bytes_moved
        slot["max_bytes_out"] = max(slot["max_bytes_out"], coll.bytes_out)
        if coll.kind == "all-gather" and coll.bytes_out in sharded_full:
            candidates = sharded_full[coll.bytes_out]
            full_gathers.append({
                "axes": list(axes), "bytes": coll.bytes_out,
                # Size-match heuristic: one candidate is an attribution,
                # several are a shortlist (and a same-sized unrelated
                # intermediate can false-match — gather_ok_axes is the
                # knob for declaring those expected).
                "materializes": (candidates[0] if len(candidates) == 1
                                 else f"one of {candidates}"),
                "candidates": list(candidates),
                "instr": coll.name})
        if coll.operand_params:
            donated = sorted(set(coll.operand_params)
                             & set(spec.expect.donated_flat_args))
            if donated:
                donated_comms.append({
                    "kind": coll.kind, "axes": list(axes),
                    "bytes": coll.bytes_moved, "params": donated})

    by_axis: Dict[str, int] = {}
    for slot in agg.values():
        for a in (slot["axes"] or ["none"]):
            by_axis[a] = by_axis.get(a, 0) + slot["bytes_moved"]

    mem = {}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            mem = {"argument_bytes": int(ma.argument_size_in_bytes),
                   "output_bytes": int(ma.output_size_in_bytes),
                   "temp_bytes": int(ma.temp_size_in_bytes),
                   "alias_bytes": int(ma.alias_size_in_bytes)}
    except Exception:          # backends without buffer assignment info
        mem = {}

    return {
        "collectives": {k: agg[k] for k in sorted(agg)},
        "totals": {
            "count": sum(s["count"] for s in agg.values()),
            "bytes_moved": sum(s["bytes_moved"] for s in agg.values()),
            "by_axis": dict(sorted(by_axis.items())),
        },
        "full_input_gathers": full_gathers,
        "donated_param_comms": donated_comms,
        **split,
        "memory": mem,
    }


def provenance() -> Dict[str, Any]:
    """jax/jaxlib versions + device kind/count: the attribution block
    every comms/perf artifact (manifest, BENCH, MULTICHIP) carries so
    cross-run comparisons know what produced them."""
    import jax
    import jaxlib

    devices = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "none",
        "device_count": len(devices),
    }


def build_manifest(specs: List[ProgramSpec], mesh,
                   progress: Optional[Callable[[str], None]] = None,
                   ) -> Dict[str, Any]:
    from nanosandbox_tpu.analysis.shardcheck.rules import check_program

    programs: Dict[str, Any] = {}
    findings: List[Dict[str, Any]] = []
    for spec in specs:
        if progress:
            progress(spec.name)
        entry = analyze_program(spec, mesh)
        entry["findings"] = check_program(spec.name, entry, spec.expect)
        findings.extend(entry["findings"])
        programs[spec.name] = entry
    return {
        "version": MANIFEST_SCHEMA_VERSION,
        "tool": "shardcheck",
        "provenance": provenance(),
        "mesh": mesh_axis_sizes(mesh),
        "programs": programs,
        "findings": findings,
        "summary": {
            "programs": len(programs),
            "collectives_total": sum(
                p["totals"]["count"] for p in programs.values()),
            "bytes_moved_total": sum(
                p["totals"]["bytes_moved"] for p in programs.values()),
            "findings": len(findings),
        },
    }


def render_manifest_text(manifest: Dict[str, Any]) -> str:
    """The human table: one line per (program, kind, axes)."""
    lines = []
    mesh = "x".join(f"{k}={v}" for k, v in manifest["mesh"].items())
    prov = manifest["provenance"]
    lines.append(f"shardcheck: mesh {mesh} on {prov['device_count']}x "
                 f"{prov['device_kind']} (jax {prov['jax']})")
    header = (f"{'program':<24} {'collective':<20} {'axes':<12} "
              f"{'count':>5} {'bytes':>12}")
    lines.append(header)
    lines.append("-" * len(header))
    for name, entry in manifest["programs"].items():
        if not entry["collectives"]:
            lines.append(f"{name:<24} {'(comms-free)':<20} {'-':<12} "
                         f"{0:>5} {0:>12}")
        for slot in entry["collectives"].values():
            axes = "+".join(slot["axes"]) or "none"
            lines.append(f"{name:<24} {slot['kind']:<20} {axes:<12} "
                         f"{slot['count']:>5} {slot['bytes_moved']:>12}")
    for f in manifest["findings"]:
        lines.append(f"FINDING [{f['rule']}] {f['program']}: {f['message']}")
    s = manifest["summary"]
    lines.append(f"shardcheck: {s['programs']} program(s), "
                 f"{s['collectives_total']} collective(s), "
                 f"{s['bytes_moved_total']} bytes moved, "
                 f"{s['findings']} finding(s)")
    return "\n".join(lines)


def export_manifest_metrics(manifest_or_budget: Dict[str, Any],
                            registry) -> None:
    """Publish per-program collective counts as
    ``shardcheck_collectives_total{program=,kind=}`` gauges on an
    obs.MetricRegistry — the serve frontend calls this at startup with
    the committed budget so a /metrics scrape carries the comms
    contract the engine is currently running under."""
    g = registry.gauge(
        "shardcheck_collectives_total",
        "Pinned collective count per compiled program (shardcheck).",
        labelnames=("program", "kind"))
    gb = registry.gauge(
        "shardcheck_bytes_moved_total",
        "Pinned bytes moved per compiled program (shardcheck).",
        labelnames=("program",))
    for name, entry in manifest_or_budget.get("programs", {}).items():
        # A manifest entry wraps its table in "collectives"; a budget
        # entry IS the table.
        table = entry.get("collectives", entry) if isinstance(entry, dict) \
            else {}
        by_kind: Dict[str, int] = {}
        total_bytes = 0
        for slot in table.values():
            by_kind[slot["kind"]] = by_kind.get(slot["kind"], 0) \
                + int(slot["count"])
            total_bytes += int(slot.get("bytes_moved", slot.get("bytes", 0)))
        if not by_kind:
            g.labels(program=name, kind="none").set(0)
        for kind, count in sorted(by_kind.items()):
            g.labels(program=name, kind=kind).set(count)
        gb.labels(program=name).set(total_bytes)


def _bytes_per_token(name: str, static_bytes: float) -> float:
    """Runtime collective bytes per emitted token for one serve
    program, from its STATIC manifest/budget bytes and the program-name
    conventions Engine.shardcheck_programs pins. Two corrections meet
    here: a decode_scan<r> megaprogram's collectives live in a lax.scan
    BODY the manifest counts ONCE but the dispatch executes r times
    while emitting r tokens — the r's cancel, so bytes/token equals the
    static body bytes (rung-1 decode's wire cost: scan amortizes HOST
    DISPATCH, not collectives). A prefill_*_k<K>_L* wave's static bytes
    already scale with the (K, L) operand shapes and the dispatch
    samples K first tokens, so it normalizes by K. Everything else
    (decode, spec_verify, drafter programs) is 1 token per dispatch —
    verify emits a variable 1..k+1, so 1 is the conservative floor."""
    import re

    if re.search(r"^decode_scan\d+", name):
        return static_bytes
    m = re.search(r"_k(\d+)_L\d+", name)
    if m:
        return static_bytes / int(m.group(1))
    return static_bytes


def export_collective_bytes_per_token(manifest_or_budget: Dict[str, Any],
                                      registry) -> None:
    """Publish ``serve_collective_bytes_per_token{program=}`` gauges
    from a shardcheck budget/manifest: the pinned collective bytes one
    dispatch of each serve program moves, normalized by the tokens that
    dispatch emits — the wire cost of tensor-parallel serving on the
    same scrape as the throughput it buys. The serve frontend calls
    this at startup alongside export_manifest_metrics when running
    under a TP budget."""
    g = registry.gauge(
        "serve_collective_bytes_per_token",
        "Pinned collective bytes per generated token per compiled "
        "program (shardcheck budget; prefill waves normalize by their "
        "K sampled tokens, scan rungs by their r-times-executed body).",
        labelnames=("program",))
    for name, entry in manifest_or_budget.get("programs", {}).items():
        table = entry.get("collectives", entry) if isinstance(entry, dict) \
            else {}
        total_bytes = sum(
            int(slot.get("bytes_moved", slot.get("bytes", 0)))
            for slot in table.values())
        g.labels(program=name).set(_bytes_per_token(name, total_bytes))
