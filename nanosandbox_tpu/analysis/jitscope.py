"""Jit-scope index: which functions are traced, which drive compiled code.

Every jaxlint rule needs the same two questions answered about a module:

  * TRACED set — functions whose bodies run under a jax trace: anything
    referenced from a ``jax.jit``/``@jit``/``partial(jax.jit, ...)``
    root (or a scan/grad/vmap-style trace wrapper), closed transitively
    over in-module references, plus functions nested inside traced ones.
    Host syncs here are trace-time bugs; Python control flow on traced
    arrays is a tracer leak; side effects replay once per retrace.

  * DISPATCHER set — host functions that CALL compiled programs (the
    hot loops AROUND the jit): a function calling a name bound to a
    ``jax.jit(...)`` result (``self._decode = jax.jit(...)`` anywhere in
    the class counts class-wide), or one of the KNOWN_COMPILED entry
    points the stack threads through opaque plumbing (``train_step`` /
    ``eval_step`` from ``Trainer.compiled_steps``), closed over the
    private helpers they reference (``Engine.step -> Engine._retire``).
    Host syncs here serialize the device pipeline — the perf bug class.

Analysis is per-module and purely syntactic: no imports are resolved,
no types inferred. The DeviceTracker below is the same spirit — a value
is "device" when the source SAYS so (result of a jnp/lax call, of a
compiled callable, of ``.apply``; propagated through assignments,
unpacking, arithmetic and comprehension targets) and a parameter counts
once the body treats it like an array (``x.shape``, ``x.astype``,
``x.at[...]`` ...). Heuristic by design: the rules only fire where the
evidence is written down, which keeps false positives near zero at the
cost of missing what plumbing hides (documented in the playbook).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set

# Callees whose function-valued arguments are traced by jax.
TRACE_WRAPPERS = {
    "jit", "grad", "value_and_grad", "vmap", "pmap", "scan", "cond",
    "while_loop", "fori_loop", "switch", "shard_map", "remat",
    "checkpoint", "eval_shape", "custom_vjp", "custom_jvp",
}

# Compiled entry points threaded through plumbing the per-module
# analysis cannot see (Trainer.compiled_steps returns these). Extend
# when you add a compiled entry point that travels through a tuple.
KNOWN_COMPILED = {"train_step", "eval_step"}

# Attribute accesses that mark a name as array-like (evidence).
ARRAY_EVIDENCE_ATTRS = {
    "shape", "ndim", "dtype", "astype", "at", "item", "reshape", "sum",
    "mean", "T", "transpose", "take", "squeeze", "ravel", "flatten",
    "block_until_ready", "sharding",
}

# Attribute reads that are STATIC under a trace (never a tracer).
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}

# Calls that launder a value into a static/host fact.
STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "type",
                "callable", "id", "repr"}

_DEVICE_ROOTS = {"jnp", "lax"}
_DEVICE_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.")
_DEVICE_EXACT = {"jax.device_put", "jax.make_array_from_process_local_data"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's own statements, NOT nested def/class bodies
    (those are indexed as their own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


@dataclass
class FunctionInfo:
    qualname: str
    bare_name: str
    node: ast.AST
    parent_class: Optional[str] = None
    parent_fn: Optional[str] = None     # qualname of enclosing function
    params: List[str] = field(default_factory=list)

    @property
    def is_private(self) -> bool:
        return (self.bare_name.startswith("_")
                and not self.bare_name.startswith("__"))


@dataclass
class JitCallInfo:
    """One jax.jit(...) call site — the donation rule's raw material."""
    node: ast.Call
    donate: Optional[ast.expr]          # the donate_argnums value, if any
    target: Optional[str]               # bound name ('_decode', 'gen'), if any
    enclosing: Optional[str]            # qualname of the enclosing function
    lineno: int = 0


class ModuleIndex:
    """Per-module jit-scope facts; built once, shared by every rule."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.functions: Dict[str, FunctionInfo] = {}
        self._by_bare: Dict[str, List[str]] = {}
        self.jit_calls: List[JitCallInfo] = []
        self.compiled_names: Set[str] = set(KNOWN_COMPILED)
        self.jit_roots: Set[str] = set()
        self.traced: Set[str] = set()
        self.dispatchers: Set[str] = set()

        self._collect_functions(tree)
        self._collect_jit_sites()
        self._close_traced()
        self._close_dispatchers()

    # ------------------------------------------------------------ collection

    def _collect_functions(self, tree: ast.Module) -> None:
        def visit(node: ast.AST, cls: Optional[str], fn: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join(p for p in (cls, fn, child.name) if p)
                    a = child.args
                    params = [x.arg for x in
                              (a.posonlyargs + a.args + a.kwonlyargs)]
                    if a.vararg:
                        params.append(a.vararg.arg)
                    if a.kwarg:
                        params.append(a.kwarg.arg)
                    info = FunctionInfo(qualname=qual, bare_name=child.name,
                                        node=child, parent_class=cls,
                                        parent_fn=fn, params=params)
                    self.functions[qual] = info
                    self._by_bare.setdefault(child.name, []).append(qual)
                    visit(child, cls, qual)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name, None)
                else:
                    visit(child, cls, fn)
        visit(tree, None, None)

    def enclosing_function(self, lineno: int) -> Optional[FunctionInfo]:
        best = None
        for info in self.functions.values():
            n = info.node
            if n.lineno <= lineno <= (n.end_lineno or n.lineno):
                if best is None or n.lineno > best.node.lineno:
                    best = info
        return best

    def _fn_refs(self, expr: ast.AST,
                 enclosing: Optional[FunctionInfo],
                 _depth: int = 0) -> Set[str]:
        """Function qualnames referenced by ``expr`` — following one or
        two levels of local-variable indirection (``step = partial(f)``;
        ``step = guard(step)``; ``jax.jit(step)``)."""
        refs: Set[str] = set()
        local_names: Set[str] = set()
        for node in ast.walk(expr):
            name = None
            if isinstance(node, ast.Attribute):
                # Only `self.<method>` references count: a deeper chain
                # like `self.cfg.memory_report` is data, and matching
                # its terminal against a method name poisons the root
                # set through local-variable resolution.
                if isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            if not name:
                continue
            for qual in self._by_bare.get(name, ()):
                refs.add(qual)
            if isinstance(node, ast.Name) and name not in self._by_bare:
                local_names.add(name)
        if enclosing is not None and _depth < 2 and local_names:
            for stmt in walk_body(enclosing.node):
                if not isinstance(stmt, ast.Assign):
                    continue
                targets = {t.id for t in stmt.targets
                           if isinstance(t, ast.Name)}
                if targets & local_names:
                    refs |= self._fn_refs(stmt.value, enclosing, _depth + 1)
        return refs

    def _collect_jit_sites(self) -> None:
        # Decorator roots: @jax.jit / @jit / @partial(jax.jit, ...).
        for info in self.functions.values():
            for dec in getattr(info.node, "decorator_list", []):
                names = {terminal_name(n) for n in ast.walk(dec)
                         if isinstance(n, (ast.Name, ast.Attribute))}
                if "jit" in names or "pmap" in names:
                    self.jit_roots.add(info.qualname)
                    # A decorated def IS the compiled callable: calling
                    # it by name dispatches a compiled program.
                    self.compiled_names.add(info.bare_name)

        # Call-site roots: jax.jit(f, ...), lax.scan(body, ...), etc.
        # ast.walk yields an Assign before its value Call, so the seen
        # set keeps `x = jax.jit(...)` from being indexed twice.
        seen: Set[int] = set()
        for node in ast.walk(self.tree):
            target = None
            call = None
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                seen.add(id(call))
                if len(node.targets) == 1:
                    target = terminal_name(node.targets[0])
            elif isinstance(node, ast.Call):
                if id(node) in seen:
                    continue
                call = node
            if call is None:
                continue
            callee = terminal_name(call.func)
            if callee not in TRACE_WRAPPERS:
                continue
            enclosing = self.enclosing_function(call.lineno)
            refs: Set[str] = set()
            for arg in list(call.args) + [k.value for k in call.keywords
                                          if k.arg != "donate_argnums"]:
                refs |= self._fn_refs(arg, enclosing)
            self.jit_roots |= refs
            if callee == "jit":
                donate = next((k.value for k in call.keywords
                               if k.arg == "donate_argnums"), None)
                if target:
                    self.compiled_names.add(target)
                enc_qual = enclosing.qualname if enclosing else None
                # Only record direct jax.jit assignments/calls (the
                # donation rule keys on these; nested wrappers came in
                # through refs already).
                if isinstance(node, ast.Assign) or donate is not None:
                    self.jit_calls.append(JitCallInfo(
                        node=call, donate=donate, target=target,
                        enclosing=enc_qual, lineno=call.lineno))

    # -------------------------------------------------------------- closures

    def _referenced_names(self, info: FunctionInfo) -> Iterator[str]:
        """Bare names a function body references (plain Name loads and
        `self.<attr>` — the two forms the per-module index can bind)."""
        for node in walk_body(info.node):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name) and node.value.id == "self":
                yield node.attr
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                yield node.id

    def _close_over(self, roots: Set[str], follow) -> Set[str]:
        """Transitive closure of ``roots`` over in-module references
        whose bare name passes ``follow``; nested defs always join
        their parent (they only exist inside it)."""
        done: Set[str] = set()
        pending = list(roots)
        while pending:
            qual = pending.pop()
            if qual in done or qual not in self.functions:
                continue
            done.add(qual)
            for other in self.functions.values():
                if other.parent_fn == qual:
                    pending.append(other.qualname)
            for name in self._referenced_names(self.functions[qual]):
                if follow(name):
                    pending.extend(self._by_bare.get(name, ()))
        return done

    def _close_traced(self) -> None:
        self.traced = self._close_over(self.jit_roots, lambda name: True)

    def _close_dispatchers(self) -> None:
        direct: Set[str] = set()
        for info in self.functions.values():
            for node in walk_body(info.node):
                if (isinstance(node, ast.Call)
                        and terminal_name(node.func) in self.compiled_names):
                    direct.add(info.qualname)
                    break
        # Close over PRIVATE helpers only: the hot loop's internals are
        # underscore-named by convention; public siblings (restore,
        # pretrained import...) are setup code, not the loop.
        self.dispatchers = self._close_over(
            direct,
            lambda name: name.startswith("_") and not name.startswith("__"))

    # ------------------------------------------------------------- utilities

    def traced_closure(self, expr: ast.AST,
                       enclosing: Optional[FunctionInfo]) -> Set[str]:
        """Qualnames of every in-module function reachable from the
        function-valued expression ``expr`` (e.g. the first argument of
        a ``jax.jit`` call), closed transitively — the public form of
        the root-resolution the index itself uses, for rules that need
        to inspect a specific traced closure (rules_sharding)."""
        return self._close_over(self._fn_refs(expr, enclosing),
                                lambda name: True)

    def hot_scope(self) -> Set[str]:
        """Functions where a host sync is a finding: traced bodies plus
        the host loops that drive compiled programs."""
        return self.traced | self.dispatchers


class DeviceTracker:
    """Syntactic device-value propagation inside ONE function body."""

    def __init__(self, info: FunctionInfo, index: ModuleIndex,
                 params_are_device: bool = False):
        self.info = info
        self.index = index
        self.device: Set[str] = set()
        if params_are_device:
            self.device |= {p for p in info.params if p != "self"}
        else:
            self.device |= self._evidenced_params()
        # Two passes: later assignments can feed earlier uses in loops.
        for _ in range(2):
            self._propagate()

    def _evidenced_params(self) -> Set[str]:
        out: Set[str] = set()
        params = set(self.info.params) - {"self"}
        for node in walk_body(self.info.node):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in params
                    and node.attr in ARRAY_EVIDENCE_ATTRS):
                out.add(node.value.id)
        return out

    # -------------------------------------------------------------- plumbing

    def _mark(self, target: ast.AST, device: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._mark(el, device)
            return
        name = dotted_name(target)
        if not name:
            return
        if device:
            self.device.add(name)
        else:
            self.device.discard(name)

    def _propagate(self) -> None:
        for node in walk_body(self.info.node):
            if isinstance(node, ast.Assign):
                dev = self.is_device(node.value)
                host = self._is_host_call(node.value)
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)) and isinstance(
                            node.value, (ast.Tuple, ast.List)):
                        for el, v in zip(t.elts, node.value.elts):
                            self._mark(el, self.is_device(v))
                    elif dev:
                        self._mark(t, True)
                    elif host:
                        self._mark(t, False)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if self.is_device(node.value):
                    self._mark(node.target, True)
            elif isinstance(node, ast.AugAssign):
                if self.is_device(node.value):
                    self._mark(node.target, True)
            elif isinstance(node, ast.For):
                if self.is_device(node.iter):
                    self._mark(node.target, True)
            elif isinstance(node, ast.comprehension):
                if self.is_device(node.iter):
                    self._mark(node.target, True)
            elif isinstance(node, (ast.NamedExpr,)):
                if self.is_device(node.value):
                    self._mark(node.target, True)

    def _is_host_call(self, expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        name = dotted_name(expr.func) or ""
        root = name.split(".")[0]
        return root in {"np", "numpy"} or name == "jax.device_get"

    # ------------------------------------------------------------ the oracle

    def is_device(self, expr: ast.AST) -> bool:
        """Does this expression produce (or contain) a device value?"""
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name:
                root = name.split(".")[0]
                term = name.split(".")[-1]
                if root in {"np", "numpy"}:
                    return False
                if name == "jax.device_get":
                    return False
                if root in _DEVICE_ROOTS or name in _DEVICE_EXACT \
                        or name.startswith(_DEVICE_PREFIXES):
                    return True
                if term in self.index.compiled_names:
                    return True
                if term == "apply":     # flax Module.apply
                    return True
            # method call on a device value: jnp.stack(x).mean()
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr not in STATIC_ATTRS \
                    and self.is_device(expr.func.value):
                return True
            return False
        if isinstance(expr, (ast.Name, ast.Attribute)):
            name = dotted_name(expr)
            if name in self.device:
                return True
            if isinstance(expr, ast.Attribute):
                if expr.attr in STATIC_ATTRS:
                    return False
                return self.is_device(expr.value)
            return False
        if isinstance(expr, ast.Subscript):
            return self.is_device(expr.value)
        if isinstance(expr, ast.BinOp):
            return self.is_device(expr.left) or self.is_device(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.is_device(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self.is_device(v) for v in expr.values)
        if isinstance(expr, ast.Compare):
            return self.is_device(expr.left) or any(
                self.is_device(c) for c in expr.comparators)
        if isinstance(expr, ast.IfExp):
            return self.is_device(expr.body) or self.is_device(expr.orelse)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_device(e) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self.is_device(expr.value)
        return False

    def test_is_dynamic(self, test: ast.expr) -> bool:
        """True when a condition depends on a traced value at RUNTIME —
        static introspection (``x.shape``, ``len(x)``, ``x is None``,
        ``isinstance``) is stripped before the device check."""
        def dynamic(node: ast.AST) -> bool:
            if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
                return False
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                term = (name or "").split(".")[-1]
                if term in STATIC_CALLS:
                    return False
                return self.is_device(node) or any(
                    dynamic(a) for a in node.args)
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
                return False                     # `x is None` is static
            if isinstance(node, (ast.Name,)):
                return node.id in self.device
            if isinstance(node, ast.Subscript):
                return dynamic(node.value)
            for child in ast.iter_child_nodes(node):
                if dynamic(child):
                    return True
            return False
        return dynamic(test)
