"""donation-misuse: donated buffers are gone; donation is TPU-only.

Two failure modes around ``jax.jit(..., donate_argnums=...)``:

  * Reuse after donation — a donated argument's buffer is aliased into
    the output; reading the old handle after the call returns garbage
    (or a deleted-buffer error) on accelerators while silently WORKING
    on CPU, where jit ignores donation. The engine's discipline is to
    rebind in the same statement (``self._pool, toks =
    self._prefill(params, self._pool, ...)``) — anything else is a
    latent TPU-only bug.

  * Unguarded donation — CPU jit ignores ``donate_argnums`` and warns
    on every compile; the stack's convention is the engine's
    accelerator gate: ``donate_argnums=(1,) if on_accel else ()`` with
    ``on_accel = jax.default_backend() != "cpu"``. A bare literal tuple
    means every CPU test run churns warnings and documents the wrong
    contract.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from nanosandbox_tpu.analysis.core import (Finding, ModuleContext, Rule,
                                           register)
from nanosandbox_tpu.analysis.jitscope import (dotted_name, terminal_name,
                                               walk_body)


def _donated_positions(donate: ast.expr) -> Tuple[int, ...]:
    """Static positions from the donate_argnums expression; for the
    guarded form ``(...) if on_accel else ()`` the accelerator branch
    is the contract."""
    if isinstance(donate, ast.IfExp):
        donate = donate.body
    if isinstance(donate, (ast.Tuple, ast.List)):
        return tuple(e.value for e in donate.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    if isinstance(donate, ast.Constant) and isinstance(donate.value, int):
        return (donate.value,)
    return ()


@register
class DonationMisuseRule(Rule):
    id = "donation-misuse"
    doc = ("reuse of a donated argument after the jit call, and "
           "donate_argnums without the accelerator guard (CPU jit "
           "ignores donation and warns)")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        idx = ctx.index
        for jc in idx.jit_calls:
            if jc.donate is None:
                continue
            if not self._is_guarded(jc, idx):
                out.append(Finding(
                    ctx.path, jc.lineno, jc.node.col_offset, self.id,
                    "donate_argnums without an accelerator guard: CPU "
                    "jit ignores donation and warns every compile — "
                    "write `donate_argnums=(...) if on_accel else ()` "
                    "with `on_accel = jax.default_backend() != \"cpu\"`"))
            if jc.target:
                out.extend(self._check_reuse(ctx, jc))
        return out

    # ---------------------------------------------------------------- guards

    def _is_guarded(self, jc, idx) -> bool:
        donate = jc.donate
        if isinstance(donate, ast.IfExp):
            return True
        if isinstance(donate, (ast.Tuple, ast.List)) and not donate.elts:
            return True                          # () donates nothing
        if isinstance(donate, ast.Name):
            # A name bound to a guarded expression counts; an unresolved
            # name is given the benefit of the doubt (no type info).
            enc = idx.functions.get(jc.enclosing) if jc.enclosing else None
            if enc is None:
                return True
            for node in walk_body(enc.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == donate.id
                        for t in node.targets):
                    if not isinstance(node.value, ast.IfExp):
                        return False
            return True
        return False

    # ----------------------------------------------------------- reuse check

    def _check_reuse(self, ctx: ModuleContext, jc) -> List[Finding]:
        out: List[Finding] = []
        positions = _donated_positions(jc.donate)
        if not positions:
            return out
        idx = ctx.index
        for info in idx.functions.values():
            for node in walk_body(info.node):
                if not (isinstance(node, ast.Call)
                        and terminal_name(node.func) == jc.target):
                    continue
                donated: List[str] = []
                for pos in positions:
                    if pos < len(node.args):
                        name = dotted_name(node.args[pos])
                        if name:
                            donated.append(name)
                if not donated:
                    continue
                rebound = self._rebound_by(info.node, node)
                for name in donated:
                    if name in rebound:
                        continue
                    reuse = self._load_after(info.node, node, name)
                    if reuse is not None:
                        out.append(Finding(
                            ctx.path, reuse.lineno, reuse.col_offset,
                            self.id,
                            f"`{name}` was donated to compiled "
                            f"`{jc.target}` on line {node.lineno} — its "
                            "buffer is aliased into the output and this "
                            "read returns garbage on accelerators "
                            "(rebind the result over the donated "
                            "operand in the same statement)"))
        return out

    def _rebound_by(self, fn: ast.AST, call: ast.Call) -> Set[str]:
        """Targets of the assignment whose value is this call."""
        for node in walk_body(fn):
            if isinstance(node, ast.Assign) and node.value is call:
                names: Set[str] = set()
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            n = dotted_name(el)
                            if n:
                                names.add(n)
                    else:
                        n = dotted_name(t)
                        if n:
                            names.add(n)
                return names
        return set()

    def _load_after(self, fn: ast.AST, call: ast.Call,
                    name: str) -> Optional[ast.AST]:
        """First Load of ``name`` after the call line, stopping at a
        rebind (a Store of the same name ends the donated lifetime)."""
        candidates = []
        for node in walk_body(fn):
            lineno = getattr(node, "lineno", None)
            if lineno is None or lineno <= call.end_lineno:
                continue
            if isinstance(node, (ast.Name, ast.Attribute)) \
                    and dotted_name(node) == name:
                candidates.append(node)
        if not candidates:
            return None
        candidates.sort(key=lambda n: (n.lineno, n.col_offset))
        for node in candidates:
            ctx_ = getattr(node, "ctx", None)
            if isinstance(ctx_, ast.Store):
                return None
            if isinstance(ctx_, ast.Load):
                return node
        return None
