"""Sharding-annotation rules: the AST-side feeders for shardcheck.

shardcheck (analysis/shardcheck/) finds the collectives XLA actually
inserted; these three rules catch the ANNOTATION mistakes that cause
them, at the source level, before anything compiles:

  unconstrained-output   a ``jax.jit`` that declares ``in_shardings``
                         but neither declares ``out_shardings`` nor
                         calls ``with_sharding_constraint`` anywhere in
                         the traced closure — the partitioner is free
                         to pick the output layout, and "free" is how a
                         mesh-sized result quietly comes back
                         replicated (the frontier_slice fixture's
                         all-gather is this rule's runtime twin).
  implicit-replication   ``jax.device_put(x)`` with no
                         sharding/device argument in a module that
                         works with meshes: the value lands REPLICATED
                         (or on one device), and the first compiled
                         consumer pays a reshard — placement in
                         multi-device paths must be spelled out.
  axis-mismatch          a ``PartitionSpec``/``P(...)`` naming an axis
                         outside the registered mesh axis set
                         (data/fsdp/seq/model — parallel/mesh.py
                         ``AXES``): GSPMD treats an unknown name as
                         just another axis label until mesh-bind time,
                         when it fails far from the typo (or worse,
                         a stale name silently stops sharding).
  unconstrained-frontier-slice
                         a traced-offset ``lax.dynamic_slice`` /
                         ``dynamic_slice_in_dim`` whose operand was
                         never REBOUND through
                         ``with_sharding_constraint`` in the same
                         function, in a mesh-aware module: if that
                         operand is sharded along the sliced dim,
                         GSPMD can only satisfy the data-dependent
                         offset by all-gathering the WHOLE operand on
                         every device — the shardcheck
                         ``frontier_slice`` fixture's accident, and
                         the exact footgun a sharded KV pool is one
                         dropped constraint away from. Constrain the
                         operand off the sliced dim first (the fixture
                         shows the idiom).

Like every jaxlint rule this file is pure ast — the axis registry is
MIRRORED here (jaxlint must run without jax installed) and a test pins
the mirror against ``parallel.mesh.AXES``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from nanosandbox_tpu.analysis.core import (Finding, ModuleContext, Rule,
                                           register)
from nanosandbox_tpu.analysis.jitscope import dotted_name, terminal_name

# Mirror of parallel.mesh.AXES (jax-free by design; pinned by
# tests/test_analysis.py against the real registry).
REGISTERED_AXIS_NAMES = ("data", "fsdp", "seq", "model")


def _jit_call_nodes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and terminal_name(node.func) == "jit":
            yield node


def _kw(call: ast.Call, name: str):
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


@register
class UnconstrainedOutputRule(Rule):
    id = "unconstrained-output"
    doc = ("jax.jit with in_shardings but no out_shardings and no "
           "with_sharding_constraint in the traced closure — the "
           "partitioner freely picks the output layout, which is how "
           "mesh-sized results come back replicated (accidental "
           "all-gathers)")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        idx = ctx.index
        for call in _jit_call_nodes(ctx.tree):
            if _kw(call, "in_shardings") is None:
                continue       # meshless jit: nothing declared to lose
            if _kw(call, "out_shardings") is not None:
                continue
            enclosing = idx.enclosing_function(call.lineno)
            closure: Set[str] = set()
            for arg in call.args[:1]:
                closure |= idx.traced_closure(arg, enclosing)
            constrained = False
            for qual in closure:
                info = idx.functions.get(qual)
                if info is None:
                    continue
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Call) and terminal_name(
                            node.func) == "with_sharding_constraint":
                        constrained = True
                        break
                if constrained:
                    break
            if not constrained:
                out.append(Finding(
                    ctx.path, call.lineno, call.col_offset, self.id,
                    "jit declares in_shardings but neither out_shardings "
                    "nor any with_sharding_constraint in the traced "
                    "closure — pin the output layout (or constrain the "
                    "intermediate) so the partitioner cannot replicate "
                    "a mesh-sized result behind your back"))
        return out


@register
class ImplicitReplicationRule(Rule):
    id = "implicit-replication"
    doc = ("jax.device_put without a sharding/device argument in a "
           "mesh-aware module — the value lands replicated or "
           "single-device and the first sharded consumer pays a "
           "reshard; spell the placement out")

    _MESH_MARKERS = ("NamedSharding", "make_mesh", "make_hybrid_mesh",
                     "Mesh(")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        # Only mesh-aware modules: a single-chip script's device_put has
        # exactly one sensible placement and naming it would be noise.
        if not any(m in ctx.source for m in self._MESH_MARKERS):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in ("jax.device_put", "device_put"):
                continue
            has_placement = len(node.args) >= 2 or any(
                k.arg in ("device", "sharding") for k in node.keywords)
            if not has_placement:
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id,
                    "device_put without an explicit sharding in a "
                    "mesh-aware module — this lands the value "
                    "replicated/single-device and the first sharded "
                    "consumer pays the reshard; pass a NamedSharding"))
        return out


@register
class UnconstrainedFrontierSliceRule(Rule):
    id = "unconstrained-frontier-slice"
    doc = ("a traced-offset dynamic_slice/dynamic_slice_in_dim on a "
           "value never rebound through with_sharding_constraint in a "
           "mesh-aware module — on an operand sharded along the sliced "
           "dim GSPMD satisfies the data-dependent offset by "
           "all-gathering the WHOLE operand (the shardcheck "
           "frontier_slice fixture's accident); constrain the operand "
           "off the sliced dim first")

    # Same scope heuristic as implicit-replication: only modules that
    # visibly work with meshes — a single-chip script's dynamic_slice
    # has nothing to gather.
    _MESH_MARKERS = ("NamedSharding", "make_mesh", "make_hybrid_mesh",
                     "Mesh(")
    _SLICE_NAMES = ("dynamic_slice", "dynamic_slice_in_dim")

    @staticmethod
    def _own_nodes(fn) -> List[ast.AST]:
        """Nodes belonging to ``fn`` itself, nested function bodies
        excluded — a constraint applied inside a sibling closure must
        not launder a slice in this one (the fixture pair lives as two
        nested functions of one builder, and only ONE of them
        constrains)."""
        out: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            out.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not any(m in ctx.source for m in self._MESH_MARKERS):
            return []
        out: List[Finding] = []
        funcs = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        def is_wsc(value) -> bool:
            return (isinstance(value, ast.Call) and terminal_name(
                value.func) == "with_sharding_constraint")

        def is_static(a) -> bool:
            return (isinstance(a, ast.Constant)
                    or (isinstance(a, (ast.Tuple, ast.List))
                        and all(isinstance(e, ast.Constant)
                                for e in a.elts)))

        for fn in funcs:
            nodes = self._own_nodes(fn)
            constrained: Set[str] = set()
            for node in nodes:
                # with_sharding_constraint is FUNCTIONAL — the
                # constrained value is its RESULT, so credit the
                # assignment TARGET (`pool = wsc(pool, ...)` or the
                # rebind `pool_c = wsc(pool, ...)`), never the argument:
                # a discarded-result call constrains nothing.
                targets = []
                if isinstance(node, ast.Assign) and is_wsc(node.value):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None and is_wsc(node.value):
                    targets = [node.target]
                elif isinstance(node, ast.NamedExpr) and is_wsc(node.value):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        constrained.add(t.id)
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                if terminal_name(node.func) not in self._SLICE_NAMES:
                    continue
                if not node.args or not isinstance(node.args[0], ast.Name):
                    continue       # only bare names are trackable
                operand = node.args[0].id
                if operand in constrained:
                    continue
                # Static start indices slice a fixed window — GSPMD
                # partitions those without materializing anything; only
                # a TRACED offset forces the gather. The offset may
                # arrive positionally or as a keyword (start_index /
                # start_indices); an empty candidate list means we
                # could not FIND the offset — treat as traced, never
                # vacuously static.
                starts = list(node.args[1:2]) + [
                    kw.value for kw in node.keywords
                    if kw.arg in ("start_index", "start_indices")]
                if starts and all(is_static(a) for a in starts):
                    continue
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id,
                    f"traced-offset {terminal_name(node.func)} on "
                    f"`{operand}`, which no with_sharding_constraint "
                    "touched in this function — if it is sharded along "
                    "the sliced dim, GSPMD all-gathers the whole "
                    "operand on every device to satisfy the offset "
                    "(the shardcheck frontier_slice accident); "
                    "constrain it off the sliced dim first"))
        return out


@register
class AxisMismatchRule(Rule):
    id = "axis-mismatch"
    doc = ("PartitionSpec axis names outside the registered mesh axis "
           "set (parallel.mesh.AXES: data/fsdp/seq/model) — unknown "
           "names fail at mesh-bind time far from the typo, or "
           "silently stop sharding")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        registered = set(REGISTERED_AXIS_NAMES)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) not in ("P", "PartitionSpec"):
                continue
            for arg in node.args:
                entries = (arg.elts if isinstance(arg, (ast.Tuple, ast.List))
                           else [arg])
                for e in entries:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str) \
                            and e.value not in registered:
                        out.append(Finding(
                            ctx.path, e.lineno, e.col_offset, self.id,
                            f"PartitionSpec names axis {e.value!r}, not "
                            "in the registered mesh axis set "
                            f"{REGISTERED_AXIS_NAMES} "
                            "(parallel.mesh.AXES)"))
        return out
