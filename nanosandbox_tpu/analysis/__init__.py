"""jaxlint — first-party static analysis for the JAX/TPU invariants.

The codebase's hardest-won performance invariants — a CLOSED compile
set (``Engine.max_programs``), zero host syncs inside the decode/train
hot loops, donation only on accelerators, no tracers leaking into
Python control flow — are structural properties of the source, not of
any one run. This package checks them with plain ``ast`` (no jax
import, so the CI lint job needs nothing but a Python), as
``python -m nanosandbox_tpu.analysis [--format=json] <paths>``.

Rules (see docs/playbook.md "Static analysis" for the full catalogue):

  host-sync        .item()/float()/int()/np.asarray/jax.device_get/
                   print on device values in jit-traced code or in the
                   host functions that drive compiled programs
  tracer-leak      Python if/while/for/bool() conditioned on traced
                   array values inside jit-traced functions
  nonstatic-shape  arguments to compiled callables whose array shapes
                   derive from unbucketed runtime values (len(...))
  donation-misuse  reuse of a donated argument after the jit call;
                   donate_argnums without an accelerator guard
  impure-trace     np.random/time/global-state mutation inside
                   jit-traced functions (side effects replay per trace)
  unconstrained-output  jit with in_shardings but no out_shardings and
                   no with_sharding_constraint in the traced closure
  implicit-replication  device_put without an explicit sharding in a
                   mesh-aware module
  axis-mismatch    PartitionSpec axis names outside the registered
                   mesh axis set (parallel.mesh.AXES)

The IR-level half lives one package down:
``python -m nanosandbox_tpu.analysis shardcheck`` (analysis/shardcheck/)
AOT-lowers the compiled-program fleet under a declared mesh, extracts
every collective from the optimized HLO with bytes + mesh axes, flags
accidental communication, and pins the result against the committed
``budgets/*.json`` in CI.

The host-concurrency surface has its own pass:
``python -m nanosandbox_tpu.analysis lockcheck`` (analysis/lockcheck/)
classifies functions by execution context (stepping thread, HTTP
handlers, asyncio loop, executors, timers, main), tracks ``with
self._lock:`` regions and ``# guarded-by:`` declarations, and enforces
shared-write guarding, the committed lock order
(``budgets/lock_order.json``), no blocking under a lock, no sync I/O on
the event loop, and no leaked acquires. Its runtime witness is
``nanosandbox_tpu.utils.schedcheck`` (seeded schedule-fuzz harness).

Suppress a deliberate violation with a REASONED comment (the reason is
mandatory; a bare disable is itself a finding)::

    x = np.asarray(toks)  # jaxlint: disable=host-sync -- readback feeds results

The runtime half of the same contract lives in
``nanosandbox_tpu.utils.tracecheck`` (retrace budgets + the blessed
``host_sync`` readback wrapper, which this linter recognizes).
"""

from nanosandbox_tpu.analysis.core import (Finding, Rule, all_rules,
                                           analyze_paths, analyze_source,
                                           render_json, render_text)

__all__ = ["Finding", "Rule", "all_rules", "analyze_paths",
           "analyze_source", "render_json", "render_text"]
