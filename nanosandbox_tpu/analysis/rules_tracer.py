"""tracer-leak and nonstatic-shape: what jit specializes on must be static.

tracer-leak — Python ``if``/``while``/``for``/``bool()`` on a traced
array forces concretization: at best a ConcretizationTypeError at trace
time, at worst (via a Python scalar that jit re-specializes on) a fresh
compile per distinct value. Static introspection is fine and stripped
before the check: ``x.shape``/``x.ndim``/``x.dtype``, ``len(x)``,
``isinstance``, ``x is None``.

nonstatic-shape — the bug class the prefill bucket ladder exists to
prevent: a compiled program's operand shapes must come from a CLOSED
set, so any shape that reaches a jitted call site carrying a raw
``len(...)`` of runtime data (a queue, a wave, a batch list) is an
unbounded compile family. The rule follows shape expressions through
local assignments and accepts values laundered through a bucketing
function (callee name containing bucket/rung/ladder/pad — e.g.
``scheduler.rung_for``/``bucket_for``), which is exactly the engine's
admission discipline.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from nanosandbox_tpu.analysis.core import (Finding, ModuleContext, Rule,
                                           register)
from nanosandbox_tpu.analysis.jitscope import (DeviceTracker, dotted_name,
                                               terminal_name, walk_body)

_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange"}
_BUCKET_WORDS = ("bucket", "rung", "ladder", "pad", "pow2", "next_power")
_RESOLVE_DEPTH = 8


@register
class TracerLeakRule(Rule):
    id = "tracer-leak"
    doc = ("Python if/while/for/bool() conditioned on traced array "
           "values inside jit-traced functions")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        idx = ctx.index
        out: List[Finding] = []
        for qual in sorted(idx.traced & set(idx.functions)):
            info = idx.functions[qual]
            tracker = DeviceTracker(info, idx)
            for node in walk_body(info.node):
                if isinstance(node, (ast.If, ast.While)) \
                        and tracker.test_is_dynamic(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"Python `{kind}` on a traced value in {qual}: "
                        "use lax.cond/lax.select/jnp.where (shapes, "
                        "dtypes and `is None` checks stay static)"))
                elif isinstance(node, ast.IfExp) \
                        and tracker.test_is_dynamic(node.test):
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"conditional expression on a traced value in "
                        f"{qual}: use jnp.where/lax.select"))
                elif isinstance(node, ast.For) \
                        and tracker.is_device(node.iter):
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"Python `for` over a traced array in {qual} "
                        "unrolls per element at trace time: use "
                        "lax.scan/lax.fori_loop"))
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Name)
                      and node.func.id == "bool" and node.args
                      and tracker.is_device(node.args[0])):
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"bool() on a traced array in {qual} forces "
                        "concretization at trace time"))
        return out


@register
class NonstaticShapeRule(Rule):
    id = "nonstatic-shape"
    doc = ("arguments to compiled callables whose array shapes derive "
           "from unbucketed runtime values (raw len(...) reaching a "
           "jitted call site)")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        idx = ctx.index
        out: List[Finding] = []
        for info in idx.functions.values():
            for node in walk_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = terminal_name(node.func)
                if callee not in idx.compiled_names:
                    continue
                for arg in node.args:
                    bad = self._dynamic_shape_source(arg, info.node,
                                                     node.lineno)
                    if bad is not None:
                        out.append(Finding(
                            ctx.path, node.lineno, node.col_offset,
                            self.id,
                            f"argument `{ast.unparse(arg)}` to compiled "
                            f"`{callee}` has a shape derived from "
                            f"`{ast.unparse(bad)}` — every distinct "
                            "value is a fresh XLA compile; pad through "
                            "a bucket ladder (scheduler.bucket_for/"
                            "rung_for)"))
        return out

    # ------------------------------------------------------------- resolvers

    def _last_assign(self, fn: ast.AST, name: str,
                     before: int) -> Optional[Tuple[ast.expr, int]]:
        best: Optional[Tuple[ast.expr, int]] = None
        for node in walk_body(fn):
            if not isinstance(node, ast.Assign) or node.lineno >= before:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    if best is None or node.lineno > best[1]:
                        best = (node.value, node.lineno)
        return best

    def _constructor_of(self, expr: ast.expr, fn: ast.AST, before: int,
                        depth: int = 0) -> Optional[Tuple[ast.Call, int]]:
        """The np/jnp.zeros|ones|full|empty call an argument expression
        bottoms out in, following asarray() wraps and local assignment
        chains (returns the call plus the lineno context to resolve its
        shape names at)."""
        if depth > _RESOLVE_DEPTH:
            return None
        if isinstance(expr, ast.Call):
            term = terminal_name(expr.func)
            if term in _CONSTRUCTORS:
                return expr, before
            if term == "asarray" and expr.args:
                return self._constructor_of(expr.args[0], fn, before,
                                            depth + 1)
            return None
        if isinstance(expr, ast.Name):
            got = self._last_assign(fn, expr.id, before)
            if got is None:
                return None
            return self._constructor_of(got[0], fn, got[1], depth + 1)
        return None

    def _dynamic_shape_source(self, arg: ast.expr, fn: ast.AST,
                              before: int) -> Optional[ast.expr]:
        got = self._constructor_of(arg, fn, before)
        if got is None:
            return None
        ctor, lineno = got
        if not ctor.args:
            return None
        shape = ctor.args[0]
        elems = shape.elts if isinstance(shape, (ast.Tuple, ast.List)) \
            else [shape]
        for el in elems:
            bad = self._offender(el, fn, lineno, 0)
            if bad is not None:
                return bad
        return None

    def _offender(self, el: ast.expr, fn: ast.AST, before: int,
                  depth: int) -> Optional[ast.expr]:
        """The unlaundered len(...) feeding a shape element, if any."""
        if depth > _RESOLVE_DEPTH:
            return None
        if isinstance(el, ast.Constant) or isinstance(el, ast.Attribute):
            return None
        if isinstance(el, ast.Call):
            term = terminal_name(el.func) or ""
            if any(w in term for w in _BUCKET_WORDS):
                return None                      # laundered: bucketed
            if term == "len":
                return el
            for a in el.args:                    # e.g. max(len(q), 1)
                bad = self._offender(a, fn, before, depth + 1)
                if bad is not None:
                    return bad
            return None
        if isinstance(el, ast.BinOp):
            return (self._offender(el.left, fn, before, depth + 1)
                    or self._offender(el.right, fn, before, depth + 1))
        if isinstance(el, ast.Name):
            got = self._last_assign(fn, el.id, before)
            if got is None:
                return None
            return self._offender(got[0], fn, got[1], depth + 1)
        return None
