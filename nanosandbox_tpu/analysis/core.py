"""jaxlint framework: findings, suppressions, rule registry, reports.

Deliberately jax-free (pure ``ast`` + stdlib): the CI lint job runs on
a bare Python, and importing jax just to read source would drag the
whole accelerator runtime into a linter. Rules get a ``ModuleContext``
(parsed tree + the jit-scope index from jitscope.py) and yield
``Finding``s; this module owns everything around them — file walking,
``# jaxlint: disable=<rule> -- <reason>`` suppression comments (the
reason is mandatory), and the text/JSON renderers the CI gate consumes.
"""

from __future__ import annotations

import ast
import json
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

JSON_SCHEMA_VERSION = 1

# Syntax: `jaxlint: disable=host-sync,tracer-leak -- why this is
# deliberate` after a `#` (spelled without the leading hash here so the
# unused-suppression check doesn't see this very comment as one).
_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*?))?\s*$")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    file: str
    line: int
    col: int
    rule: str
    message: str

    def key(self) -> Tuple:
        return (self.file, self.line, self.col, self.rule, self.message)


@dataclass
class Suppression:
    line: int               # line the comment sits on
    rules: Tuple[str, ...]  # rule ids; ("all",) disables every rule
    reason: str             # mandatory — empty means the disable is void
    standalone: bool        # comment-only line: applies to the NEXT stmt line
    used: bool = False

    def covers(self, rule: str) -> bool:
        return "all" in self.rules or rule in self.rules


@dataclass
class ModuleContext:
    """Everything a rule needs about one source file."""
    path: str
    source: str
    tree: ast.Module
    index: object            # jitscope.ModuleIndex (typed loosely: no cycle)
    lines: List[str] = field(default_factory=list)


class Rule:
    """Base class: subclasses set ``id``/``doc`` and implement check().

    Adding a rule = subclass + register() — see docs/playbook.md
    "Static analysis: adding a rule".
    """

    id: str = ""
    doc: str = ""

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls: Callable[[], Rule]):
    """Class decorator: instantiate and add to the global rule registry."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"rule {rule_cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    _load_builtin_rules()
    return dict(_REGISTRY)


_BUILTINS_LOADED = False


def _load_builtin_rules() -> None:
    # Import for the @register side effect; deferred so `import
    # nanosandbox_tpu.analysis.core` alone never half-registers.
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    from nanosandbox_tpu.analysis import (rules_donation,  # noqa: F401
                                          rules_sharding, rules_sync,
                                          rules_tracer)


# ---------------------------------------------------------------- suppression

def parse_suppressions(source: str) -> List[Suppression]:
    """Extract jaxlint disable comments via tokenize (not regex over raw
    lines: a '# jaxlint:' inside a string literal must not suppress)."""
    out: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        standalone = tok.line.strip().startswith("#")
        out.append(Suppression(line=tok.start[0], rules=rules,
                               reason=reason, standalone=standalone))
    return out


def _suppression_for(sup: List[Suppression], finding: Finding,
                     lines: List[str]) -> Optional[Suppression]:
    for s in sup:
        if not s.covers(finding.rule):
            continue
        # Same-line, or a standalone comment above with NOTHING but
        # comments/blank lines in between (stacked disables + prose are
        # fine; a code line in between would let the disable silently
        # swallow a later, unaudited violation on it).
        if s.line == finding.line:
            return s
        if s.standalone and s.line < finding.line:
            between = lines[s.line:finding.line - 1]
            if all(not ln.strip() or ln.lstrip().startswith("#")
                   for ln in between):
                return s
    return None


# ------------------------------------------------------------------ analysis

def analyze_source(source: str, path: str = "<string>",
                   select: Optional[Sequence[str]] = None,
                   strict_suppressions: bool = False,
                   ) -> Tuple[List[Finding], int]:
    """Lint one source string. Returns (findings, suppressed_count).

    ``select`` restricts to a subset of rule ids (the fixture tests use
    it to pin each rule to its known-bad twin in isolation).

    Unused suppressions: a REASONED disable whose line no longer
    triggers any of its rules has rotted — the audited violation is
    gone but the audit comment still vouches for one. They are always
    collected (``unused_suppressions`` in the report, notes in the text
    render); ``strict_suppressions`` promotes them to findings so CI
    can refuse the rot outright. Under ``select`` the check only
    applies to suppressions naming a selected rule — the others never
    got a chance to match.
    """
    from nanosandbox_tpu.analysis.jitscope import ModuleIndex

    rules = all_rules()
    if select:
        unknown = sorted(set(select) - set(rules))
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(unknown)}; "
                             f"known: {', '.join(sorted(rules))}")
        rules = {k: v for k, v in rules.items() if k in select}

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, e.offset or 0, "parse-error",
                        f"could not parse: {e.msg}")], 0

    ctx = ModuleContext(path=path, source=source, tree=tree,
                        index=ModuleIndex(tree), lines=source.splitlines())
    raw: List[Finding] = []
    for rule in rules.values():
        raw.extend(rule.check(ctx))

    suppressions = parse_suppressions(source)
    findings: List[Finding] = []
    suppressed = 0
    for f in sorted(set(raw), key=lambda f: f.key()):
        s = _suppression_for(suppressions, f, ctx.lines)
        if s is None:
            findings.append(f)
        elif not s.reason:
            # A bare disable is void AND a finding (below): the tool's
            # contract is that every deliberate violation carries its why.
            s.used = True
            findings.append(f)
        else:
            s.used = True
            suppressed += 1
    # Malformed suppressions are findings whether or not they matched
    # anything — a typo'd rule id or a bare disable must not sit inert
    # while the author believes the violation is audited.
    known = set(all_rules()) | {"all", "parse-error", "bad-suppression",
                                "unused-suppression"}
    for s in suppressions:
        if not s.reason:
            findings.append(Finding(
                path, s.line, 0, "bad-suppression",
                "suppression without a reason — write "
                "'# jaxlint: disable=<rule> -- <why this is deliberate>'"))
        for r in s.rules:
            if r not in known:
                findings.append(Finding(
                    path, s.line, 0, "bad-suppression",
                    f"unknown rule id {r!r} in suppression — known: "
                    f"{', '.join(sorted(set(all_rules())))}"))
        # Unused reasoned suppressions (the rot check): only judged
        # when every rule it names actually ran this pass — and a
        # `disable=all` only under a FULL run (any unselected rule
        # could be what it suppresses).
        if (s.reason and not s.used
                and (select is None
                     or ("all" not in s.rules
                         and all(r in select for r in s.rules)))):
            _UNUSED_LOG.append({
                "file": path, "line": s.line,
                "rules": list(s.rules), "reason": s.reason})
            if strict_suppressions:
                findings.append(Finding(
                    path, s.line, 0, "unused-suppression",
                    f"suppression for {', '.join(s.rules)} no longer "
                    "matches any finding — the audited violation is "
                    "gone; delete the comment (reason was: "
                    f"{s.reason!r})"))
    return sorted(set(findings), key=lambda f: f.key()), suppressed


# analyze_source appends here so analyze_paths can report unused
# suppressions without changing the (findings, suppressed) signature
# every caller and test pins; single-threaded like the rest of the CLI.
_UNUSED_LOG: List[dict] = []


def drain_unused_suppressions() -> List[dict]:
    """Take (and clear) the unused-suppression records accumulated by
    analyze_source calls since the last drain."""
    out, _UNUSED_LOG[:] = list(_UNUSED_LOG), []
    return out


def iter_python_files(paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    # De-dup while preserving order (a file listed and inside a dir).
    seen = set()
    out = []
    for f in files:
        r = f.resolve()
        if r not in seen and "__pycache__" not in f.parts:
            seen.add(r)
            out.append(f)
    return out


def analyze_paths(paths: Sequence[str],
                  select: Optional[Sequence[str]] = None,
                  strict_suppressions: bool = False) -> dict:
    """Lint files/directories; returns the report dict render_json dumps."""
    findings: List[Finding] = []
    suppressed = 0
    drain_unused_suppressions()
    files = iter_python_files(paths)
    for f in files:
        try:
            src = f.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(str(f), 1, 0, "parse-error",
                                    f"could not read: {e}"))
            continue
        fs, sup = analyze_source(src, str(f), select=select,
                                 strict_suppressions=strict_suppressions)
        findings.extend(fs)
        suppressed += sup
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "version": JSON_SCHEMA_VERSION,
        "tool": "jaxlint",
        "findings": [vars(f) for f in findings],
        "unused_suppressions": drain_unused_suppressions(),
        "summary": {
            "files_scanned": len(files),
            "findings": len(findings),
            "suppressed": suppressed,
            "by_rule": dict(sorted(by_rule.items())),
        },
    }


# ------------------------------------------------------------------- reports

def render_text(report: dict) -> str:
    lines = [f"{f['file']}:{f['line']}:{f['col']}: {f['rule']}: "
             f"{f['message']}" for f in report["findings"]]
    unused = report.get("unused_suppressions", [])
    lines.extend(
        f"{u['file']}:{u['line']}: note: unused suppression for "
        f"{', '.join(u['rules'])} (use --strict-suppressions to fail "
        "on these)" for u in unused)
    s = report["summary"]
    lines.append(f"jaxlint: {s['findings']} finding(s) in "
                 f"{s['files_scanned']} file(s), "
                 f"{s['suppressed']} suppressed"
                 + (f", {len(unused)} unused suppression(s)" if unused
                    else ""))
    return "\n".join(lines)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=False)
