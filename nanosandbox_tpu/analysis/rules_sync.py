"""host-sync and impure-trace: the hot loop must not talk to the host.

host-sync — the ROADMAP's "as fast as the hardware allows" dies the
first time a ``.item()`` / ``float()`` / ``np.asarray`` sneaks into the
decode or train hot loop: under JAX async dispatch each readback is a
host<->device round trip (~100ms+ on a tunneled PJRT transport) that
serializes with device compute. The rule fires inside jit-traced code
AND inside the host functions that drive compiled programs (the
jitscope dispatcher set). Deliberate syncs go through the blessed
``utils.tracecheck.host_sync`` wrapper (which this rule recognizes and
counts at runtime) or carry a reasoned
``# jaxlint: disable=host-sync -- <why>``.

impure-trace — a jit-traced function's body replays once per compile,
not once per call: ``np.random``/``time`` reads bake one trace-time
value into the program forever, and mutation of ``self``/globals counts
retraces, not steps (the exact bug class the engine's old hand-rolled
``self.trace_counts[...] += 1`` counters exploited deliberately — now
owned by ``utils.tracecheck.compile_budget`` OUTSIDE the traced body).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from nanosandbox_tpu.analysis.core import (Finding, ModuleContext, Rule,
                                           register)
from nanosandbox_tpu.analysis.jitscope import (DeviceTracker, dotted_name,
                                               walk_body)

_HOST_SYNC_CALLS = {"jax.device_get", "np.asarray", "numpy.asarray"}
_IMPURE_PREFIXES = ("np.random.", "numpy.random.", "random.")
_IMPURE_EXACT = {
    "time.time", "time.monotonic", "time.perf_counter", "time.sleep",
    "time.time_ns", "time.process_time", "datetime.datetime.now",
}
_MUTATORS = {"append", "add", "extend", "update", "pop", "setdefault",
             "remove", "insert", "clear", "appendleft", "popleft", "write"}


def _is_blessed(name: str) -> bool:
    """utils.tracecheck APIs are the sanctioned way to sync/count."""
    return "tracecheck" in name or name.split(".")[-1] == "host_sync"


@register
class HostSyncRule(Rule):
    id = "host-sync"
    doc = (".item()/float()/int()/np.asarray/jax.device_get/print on "
           "device values in jit-traced code or in the host loops that "
           "drive compiled programs")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        idx = ctx.index
        out: List[Finding] = []
        for qual in sorted(idx.hot_scope() & set(idx.functions)):
            info = idx.functions[qual]
            tracker = DeviceTracker(info, idx)
            traced = qual in idx.traced
            where = ("jit-traced code" if traced
                     else "a hot path driving compiled programs")
            for node in walk_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                if name and _is_blessed(name):
                    continue
                msg = None
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    msg = (f".item() in {where} ({qual}) forces a "
                           "device->host readback")
                elif name in _HOST_SYNC_CALLS:
                    msg = (f"{name}() in {where} ({qual}) forces a "
                           "device->host readback (route deliberate "
                           "syncs through utils.tracecheck.host_sync)")
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in ("float", "int") and node.args
                      and tracker.is_device(node.args[0])):
                    msg = (f"{node.func.id}() on a device value in "
                           f"{where} ({qual}) blocks on the async "
                           "dispatch queue (route deliberate syncs "
                           "through utils.tracecheck.host_sync)")
                elif (isinstance(node.func, ast.Name)
                      and node.func.id == "bool" and not traced
                      and node.args and tracker.is_device(node.args[0])):
                    msg = (f"bool() on a device value in {where} "
                           f"({qual}) forces a device->host readback")
                elif (isinstance(node.func, ast.Name)
                      and node.func.id == "print"
                      and any(tracker.is_device(a) for a in node.args)):
                    msg = (f"print() of a device value in {where} "
                           f"({qual}) forces a device->host readback")
                if msg:
                    out.append(Finding(ctx.path, node.lineno,
                                       node.col_offset, self.id, msg))
        return out


@register
class ImpureTraceRule(Rule):
    id = "impure-trace"
    doc = ("np.random/time reads and self/global mutation inside "
           "jit-traced functions (side effects replay per trace, "
           "values freeze at trace time)")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        idx = ctx.index
        out: List[Finding] = []
        module_globals = {
            t.id for stmt in ctx.tree.body if isinstance(stmt, ast.Assign)
            for t in stmt.targets if isinstance(t, ast.Name)
        }
        for qual in sorted(idx.traced & set(idx.functions)):
            info = idx.functions[qual]
            for node in walk_body(info.node):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"{type(node).__name__.lower()} statement in "
                        f"jit-traced {qual}: the rebind happens once per "
                        "trace, not once per call"))
                elif isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    if (name.startswith(_IMPURE_PREFIXES)
                            or name in _IMPURE_EXACT):
                        out.append(Finding(
                            ctx.path, node.lineno, node.col_offset,
                            self.id,
                            f"{name}() inside jit-traced {qual}: the "
                            "value is baked in at trace time (use "
                            "jax.random / pass times in as operands)"))
                elif isinstance(node, ast.Expr) \
                        and isinstance(node.value, ast.Call):
                    # Mutator heuristic fires only on BARE statement
                    # calls: `self.seen.append(x)` is a side effect,
                    # while `a, b = self.tx.update(...)` is functional
                    # (optax) and must not match.
                    call = node.value
                    if (isinstance(call.func, ast.Attribute)
                            and call.func.attr in _MUTATORS):
                        recv = dotted_name(call.func.value) or ""
                        if (recv.startswith("self.")
                                or recv.split(".")[0] in module_globals):
                            out.append(Finding(
                                ctx.path, call.lineno, call.col_offset,
                                self.id,
                                f"mutation of {recv} inside jit-traced "
                                f"{qual} runs once per RETRACE, not per "
                                "call (use utils.tracecheck for trace "
                                "counting; thread state functionally)"))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        root = t
                        while isinstance(root, (ast.Subscript,
                                                ast.Attribute)):
                            if (isinstance(root, ast.Attribute)
                                    and dotted_name(root) is not None
                                    and dotted_name(root)
                                    .startswith("self.")):
                                out.append(Finding(
                                    ctx.path, node.lineno,
                                    node.col_offset, self.id,
                                    f"assignment to {dotted_name(root)} "
                                    f"inside jit-traced {qual} mutates "
                                    "host state once per RETRACE (use "
                                    "utils.tracecheck.compile_budget "
                                    "for trace counting)"))
                                break
                            root = root.value
        return out
