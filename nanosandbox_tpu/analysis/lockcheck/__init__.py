"""lockcheck — concurrency static analysis for the serving host layer.

The third static-analysis surface after jaxlint (traced/compiled
boundary) and shardcheck (collective-comms contract): the serve stack
is a concurrent host program — a stepping thread, stdlib HTTP handler
threads, the asyncio RouterFrontend and its executor pools, watchdog
dumps, the disagg migration pump — and this pass checks its thread
discipline without running it. Pure ast + stdlib; no jax import.

Rules (see ``rules.py`` for semantics, docs/playbook.md "Concurrency
analysis" for the catalogue):

  * unguarded-shared-write  — attr written from ≥2 execution contexts
    with no common lock (and ``# guarded-by:`` enforcement)
  * lock-order-inversion    — cycle in the acquired-while-holding
    graph, or a violation of the committed tier ordering in
    ``budgets/lock_order.json``
  * blocking-under-lock     — host sync / readback / I/O / sleep /
    join inside a lock region
  * asyncio-blocking-call   — sync I/O in an ``async def`` not routed
    through ``run_in_executor``
  * leaked-acquire          — ``acquire()`` without with/try-finally

Run: ``python -m nanosandbox_tpu.analysis lockcheck [--format=json]``.
Suppress with ``# lockcheck: disable=<rule> -- <why>`` (reason
mandatory). The runtime half is ``nanosandbox_tpu.utils.schedcheck``:
a deterministic schedule-fuzz harness giving every static claim a
dynamic witness.
"""

from nanosandbox_tpu.analysis.lockcheck.core import (  # noqa: F401
    DEFAULT_LOCK_ORDER, LockOrder, ModuleContext, Rule, all_rules,
    analyze_paths, analyze_source, load_lock_order, parse_suppressions,
    register, render_json, render_text)
from nanosandbox_tpu.analysis.lockcheck.contexts import (  # noqa: F401
    ConcurrencyIndex)


def export_report_metrics(report: dict, registry) -> None:
    """Publish a lockcheck report into a MetricRegistry: the scrape
    surface obs_smoke asserts (lockcheck_findings_total by rule,
    lockcheck_files_scanned, lockcheck_suppressed_total)."""
    g = registry.gauge("lockcheck_files_scanned",
                       "Files scanned by the last lockcheck run.")
    g.set(report["summary"]["files_scanned"])
    s = registry.gauge("lockcheck_suppressed_total",
                       "Findings suppressed with a reasoned disable.")
    s.set(report["summary"]["suppressed"])
    c = registry.gauge("lockcheck_findings_total",
                       "Open lockcheck findings by rule.",
                       labelnames=("rule",))
    # Render a 0 sample even when clean so the scrape assertion has a
    # line to match (mirrors the shardcheck budget export).
    if not report["summary"]["by_rule"]:
        c.labels(rule="none").set(0)
    for rule, n in report["summary"]["by_rule"].items():
        c.labels(rule=rule).set(n)
