"""CLI: ``python -m nanosandbox_tpu.analysis lockcheck [options] <paths>``.

Flag-for-flag compatible with the jaxlint CLI (same exit codes: 0
clean, 1 findings, 2 usage error; same --format/--out/--select/
--list-rules/--changed-only/--base/--strict-suppressions) plus one
extra input: ``--lock-order=FILE``, the committed tier ordering the
lock-order-inversion rule enforces (default ``budgets/lock_order.json``
when it exists next to the repo root; absent file = cycle check only).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def main(argv=None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m nanosandbox_tpu.analysis lockcheck",
        description="lockcheck: concurrency static analysis for the "
                    "serving host layer (shared-write guards, lock "
                    "ordering, blocking-under-lock, asyncio blocking, "
                    "leaked acquires).")
    ap.add_argument("paths", nargs="*", default=["nanosandbox_tpu"],
                    help="files or directories to lint "
                         "(default: nanosandbox_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the report to FILE (JSON when "
                         "--format=json; CI uploads this as an artifact)")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs --base (from "
                         "`git diff --name-only`) — the fast pre-commit "
                         "run; CI keeps the full tree")
    ap.add_argument("--base", default="HEAD", metavar="REF",
                    help="git ref --changed-only diffs against "
                         "(default: HEAD)")
    ap.add_argument("--strict-suppressions", action="store_true",
                    help="a reasoned suppression that no longer matches "
                         "any finding becomes a finding itself (rot "
                         "gate)")
    ap.add_argument("--lock-order", default=None, metavar="FILE",
                    help="committed lock ordering JSON for the "
                         "lock-order-inversion rule (default: "
                         "budgets/lock_order.json when present)")
    args = ap.parse_args(argv)

    from nanosandbox_tpu.analysis.lockcheck.core import (
        DEFAULT_LOCK_ORDER, all_rules, analyze_paths, load_lock_order,
        render_json, render_text)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}: {rule.doc}")
        return 0

    lock_order = None
    order_path = args.lock_order
    if order_path is None and Path(DEFAULT_LOCK_ORDER).exists():
        order_path = DEFAULT_LOCK_ORDER
    if order_path is not None:
        try:
            lock_order = load_lock_order(order_path)
        except (OSError, ValueError) as e:
            print(f"lockcheck: bad --lock-order file {order_path}: {e}",
                  file=sys.stderr)
            return 2

    paths = args.paths
    if args.changed_only:
        from nanosandbox_tpu.analysis.__main__ import changed_only_paths
        try:
            paths = changed_only_paths(args.paths, args.base)
        except RuntimeError as e:
            print(f"lockcheck: {e}", file=sys.stderr)
            return 2
        if not paths:
            print(f"lockcheck: no changed Python files vs {args.base} "
                  f"under {args.paths!r} — nothing to lint")
            return 0

    select = ([r.strip() for r in args.select.split(",") if r.strip()]
              if args.select else None)
    try:
        report = analyze_paths(paths, select=select,
                               strict_suppressions=args.strict_suppressions,
                               lock_order=lock_order)
    except ValueError as e:
        print(f"lockcheck: {e}", file=sys.stderr)
        return 2
    if report["summary"]["files_scanned"] == 0:
        print(f"lockcheck: no Python files under {paths!r}",
              file=sys.stderr)
        return 2

    rendered = (render_json(report) if args.format == "json"
                else render_text(report))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(rendered + "\n")
        print(render_text(report))
    else:
        print(rendered)
    return 1 if report["summary"]["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
